"""Live introspection server (docs/observability.md).

A stdlib :class:`ThreadingHTTPServer` that makes the process's
observability surface — PR 3's monitor registry and flight recorder,
the XLA program accounting (core/program_accounting.py), pool queue
depths, KV-block occupancy, mesh topology — reachable from OUTSIDE the
process, so a load balancer, autoscaler, Prometheus scraper, or a
human with curl can read the signals that until now only in-process
code could. Endpoints:

- ``/metrics``   Prometheus text exposition (monitor.to_prometheus)
- ``/healthz``   liveness: 200 while the server thread runs
- ``/readyz``    readiness: 200 only when every registered warmup
                 probe passes (PredictorPool / GenerationPool register
                 on start(), flip on warmup()) and, when a process-
                 global ShardingPlan is active, it has placed state
- ``/statusz``   JSON: uptime, jax/backend/devices, mesh topology +
                 per-axis collective counters, program accounting
                 totals, pool queue depths, KV block-pool occupancy
- ``/flightz``   flight-recorder tail (text; ``?format=json`` for the
                 raw records)
- ``/programz``  per-program XLA cost/memory accounting
- ``/tracez``    request-lifecycle traces (tracing.py): rolling
                 TTFT/TPOT/stage-decomposition latencies, recently
                 completed traces, and the slow/errored exemplar ring
                 (text; ``?format=json`` for the raw payload;
                 ``?tenant=`` filters recent/exemplars to one tenant)
- ``/sloz``      SLO engine (slo.py, FLAGS_slo): objectives with
                 windowed good-ratios, error-budget remaining,
                 fast/slow burn rates and alert state, autoscaling
                 signals, per-tenant accounting (text;
                 ``?format=json`` for the raw payload)
- ``/modelz``    serving front door (frontdoor.py, FLAGS_frontdoor):
                 per-model versions, routing state, quota/shed/scale
                 counters, recent autoscale decisions (text;
                 ``?format=json`` for the raw payload)
- ``/failpointz`` fault injection (failpoints.py, docs/robustness.md):
                 GET lists every known site with its armed spec and
                 calls/fires hit counts; POST arms
                 (``?arm=site%3Draise%40once`` or a spec-string body)
                 and disarms (``?disarm=site`` or ``?disarm=all``)
- ``/workerz``   gang supervisors (launch.py, docs/robustness.md
                 "Multi-host fault model"): per-worker state,
                 last-heartbeat age, step progress, restart budget —
                 read from the supervisor process
- ``/gangz``     gang observability plane (docs/observability.md
                 "Gang-wide observability"): per-rank step-phase p50s
                 from the heartbeat-piggybacked digests, straggler
                 scores, collective-wait fractions, KV occupancy
                 (text; ``?format=json`` for the raw payload)

Lifecycle: **off by default, zero overhead when off.**
``FLAGS_introspect_port`` is 0 → :func:`maybe_start` (called from
Executor construction and pool ``start()``) is one dict lookup; no
thread, no socket. Set the flag to a positive port (host via
``FLAGS_introspect_host``, default 127.0.0.1) and the first
``maybe_start()`` brings the server up. Tests and tooling call
``start(port=0)`` for an OS-assigned ephemeral port and ``stop()`` to
tear it down.

Readiness semantics: with no registered probes and no active plan,
``/readyz`` is trivially ready — a bare Executor process serves
traffic the moment it can compile. Each serving pool registers an
"unready until warmed" probe on ``start()`` and unregisters on
``close()``, so a scraping load balancer only routes to a process
whose compile-ahead actually finished. ``/readyz`` and ``/statusz``
read the *process-global* plan (``mesh.install_plan`` /
``FLAGS_mesh_spec``); thread-local ``use_plan`` scopes on other
threads are invisible to the server thread by design.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

__all__ = ["start", "stop", "maybe_start", "server",
           "register_readiness", "unregister_readiness", "readiness"]

_T0 = time.time()

_SERVER_LOCK = threading.Lock()
_SERVER: Optional["IntrospectServer"] = None

_READY_LOCK = threading.Lock()
_READY_PROBES: Dict[str, Callable[[], bool]] = {}


# ---------------------------------------------------------------------------
# readiness registry
# ---------------------------------------------------------------------------

def register_readiness(name: str, probe: Callable[[], bool]) -> None:
    """Register a named readiness probe (re-registering replaces).
    /readyz returns 200 only when every registered probe is truthy."""
    with _READY_LOCK:
        _READY_PROBES[name] = probe


def unregister_readiness(name: str) -> None:
    with _READY_LOCK:
        _READY_PROBES.pop(name, None)


def readiness() -> Tuple[bool, Dict[str, bool]]:
    """(ready, per-check dict). A probe that raises reads as unready.
    When a process-global ShardingPlan is active, it must have placed
    state at least once (Executor.run under the plan does this on its
    first step)."""
    with _READY_LOCK:
        items = list(_READY_PROBES.items())
    checks: Dict[str, bool] = {}
    for name, probe in items:
        try:
            checks[name] = bool(probe())
        except Exception:
            checks[name] = False
    try:
        from .mesh.plan import current_plan
        plan = current_plan()
    except Exception:
        plan = None
    if plan is not None:
        checks["mesh_plan_placed"] = bool(getattr(plan, "_placed", False))
    return (all(checks.values()) if checks else True), checks


# ---------------------------------------------------------------------------
# payload builders (shared by the handler and tests)
# ---------------------------------------------------------------------------

def statusz() -> Dict[str, Any]:
    import jax
    from . import telemetry
    from .core import program_accounting
    from .monitor import gauge_get, snapshot

    snap = snapshot()
    counters = snap["counters"]

    try:
        devices = jax.devices()
        jax_info = {
            "version": jax.__version__,
            "backend": jax.default_backend(),
            "device_count": len(devices),
            "device_kinds": sorted({d.platform for d in devices}),
        }
    except Exception as e:  # pre-init / wedged backend: report, don't die
        jax_info = {"error": repr(e)}

    mesh: Dict[str, Any] = {"active": False}
    try:
        from .mesh.plan import current_plan
        plan = current_plan()
    except Exception:
        plan = None
    if plan is not None:
        mesh = {
            "active": True,
            "topology": [list(t) if isinstance(t, tuple) else t
                         for t in plan.topology()],
            "devices": int(plan.spec.size),
            "data_axis": plan.data_axis,
            "placed": bool(getattr(plan, "_placed", False)),
        }
    # per-axis collective census rides along even without a live plan
    # (parallel/collective.py and TrainStep count process-globally)
    mesh["collectives"] = _collectives_status(counters)

    program_accounting.refresh_throughput()
    programs = dict(program_accounting.totals())
    programs["achieved_flops_per_s"] = gauge_get(
        "GAUGE_programs_achieved_flops_per_s")

    ready, checks = readiness()
    return {
        "uptime_s": round(time.time() - _T0, 3),
        "pid": __import__("os").getpid(),
        "jax": jax_info,
        "mesh": mesh,
        "programs": programs,
        "program_cache": {
            k: v for k, v in sorted(counters.items())
            if k.startswith("STAT_program_cache_")
            or k == "STAT_executor_compile"},
        "serving": {
            "queue_depth": gauge_get("GAUGE_serving_queue_depth"),
            "last_batch_rows": gauge_get("GAUGE_serving_last_batch_rows"),
        },
        "generation": {
            "queue_depth": gauge_get("GAUGE_generation_queue_depth"),
            "active_seqs": gauge_get("GAUGE_generation_active_seqs"),
            "kv_blocks": {
                "free": gauge_get("GAUGE_generation_blocks_free"),
                "used": gauge_get("GAUGE_generation_blocks_used"),
                "total": gauge_get("GAUGE_generation_blocks_free")
                + gauge_get("GAUGE_generation_blocks_used"),
                # shared-vs-private occupancy (PR 14 prefix cache):
                # shared = blocks referenced more than once, saved =
                # duplicate allocations sharing avoided, private =
                # used blocks nothing shares
                "shared": gauge_get("GAUGE_kv_shared_blocks"),
                "saved": gauge_get("GAUGE_kv_blocks_saved"),
                "private": gauge_get("GAUGE_generation_blocks_used")
                - gauge_get("GAUGE_kv_shared_blocks"),
            },
            "prefix_cache": {
                "entries": gauge_get("GAUGE_generation_prefix_entries"),
                "blocks": gauge_get("GAUGE_generation_prefix_blocks"),
            },
            # quantized serving (ISSUE 15): modes read from the flags
            # (the configured deployment); the numeric gauges are
            # published by the live engine, so a per-engine ctor
            # override shows up in the numbers
            "quant": _quant_status(counters),
        },
        "flight_recorder_steps": len(telemetry.flight_records()),
        "autotune": _autotune_status(counters),
        "gangs": _gang_status(),
        "tracing": _tracing_status(counters),
        "slo": _slo_status(),
        "frontdoor": _frontdoor_status(),
        "failpoints_armed": _armed_failpoints(),
        "readiness": {"ready": ready, "checks": checks},
    }


def _collectives_status(counters: Dict[str, Any]) -> Dict[str, Any]:
    """The /statusz mesh.collectives section (docs/spmd.md "Quantized
    collectives"): per-axis op counts, payload bytes on the wire by
    (axis, dtype) under the ring model documented in monitor.py, and
    the quantized-collective health numbers — configured mode, live
    bucket geometry gauges, cumulative bucket exchanges and fp32
    fallbacks."""
    import re
    from .flags import get_flag
    from .monitor import gauge_get
    ops: Dict[str, Any] = {}
    by_axis: Dict[str, Dict[str, Any]] = {}
    for k, v in sorted(counters.items()):
        if not k.startswith("STAT_mesh_collective_"):
            continue
        rest = k[len("STAT_mesh_collective_"):]
        m = re.match(r'bytes\{axis="([^"]*)",dtype="([^"]*)"\}$', rest)
        if m:
            by_axis.setdefault(m.group(1), {})[m.group(2)] = v
        elif "{" not in rest:
            ops[rest] = v
    return {
        "ops": ops,
        "bytes": by_axis,
        "quant": {
            "mode": str(get_flag("FLAGS_collective_quant")),
            "buckets": gauge_get("GAUGE_collective_quant_buckets"),
            "small_tensors": gauge_get("GAUGE_collective_quant_small"),
            "wire_bytes_per_exchange": gauge_get(
                "GAUGE_collective_quant_wire_bytes"),
            "bucket_exchanges": counters.get(
                "STAT_collective_quant_buckets", 0),
            "fallbacks": counters.get(
                "STAT_collective_quant_fallbacks", 0),
            # mp-axis composition (ISSUE 19): the sharded-param wire
            # mode, quantized gathers per plan (gauge) and cumulative
            # (counter), builds demoted to legacy GSPMD, and mp
            # failpoint fp32 fallbacks
            "mode_mp": str(get_flag("FLAGS_collective_quant_mp")),
            "gathers": gauge_get("GAUGE_collective_quant_gathers"),
            "gather_exchanges": counters.get(
                "STAT_collective_quant_mp_gathers", 0),
            "demotions": counters.get(
                "STAT_collective_quant_demotions", 0),
            "mp_fallbacks": counters.get(
                "STAT_collective_quant_mp_fallbacks", 0),
        },
    }


def _quant_status(counters: Dict[str, Any]) -> Dict[str, Any]:
    """The /statusz generation.quant section (docs/quantization.md):
    quant mode + KV pool dtype, pool capacity in max-length sequences,
    and the byte-saving gauges the engine publishes."""
    from .flags import get_flag
    from .monitor import gauge_get
    return {
        "mode": str(get_flag("FLAGS_quant_mode")),
        "kv_dtype": str(get_flag("FLAGS_generation_kv_quant")),
        "kv_capacity_seqs": gauge_get("GAUGE_kv_capacity_seqs"),
        "kv_bytes_per_seq": gauge_get("GAUGE_kv_bytes_per_seq"),
        "weight_bytes_saved": gauge_get(
            "GAUGE_quant_weight_bytes_saved"),
        "kv_quant_blocks": counters.get(
            "STAT_generation_kv_quant_blocks", 0),
    }


def _autotune_status(counters: Dict[str, Any]) -> Dict[str, Any]:
    """The /statusz "autotune" section (docs/autotune.md): one line
    per resolved policy key — winning form, geometry label, measured
    step time, trial count, source (tuned this process vs reloaded
    from disk) — plus the tuning counters. Steady state should show
    cache_hits growing and trials flat; the opposite is the re-tuning
    loop tools/stat_diff.py flags as a cost regression."""
    from .flags import get_flag
    from . import autotune
    return {
        "enabled": bool(get_flag("FLAGS_autotune")),
        "policies": autotune.policies(),
        "trials": counters.get("STAT_autotune_trials", 0),
        "wins": counters.get("STAT_autotune_wins", 0),
        "cache_hits": counters.get("STAT_autotune_cache_hits", 0),
        "fallbacks": counters.get("STAT_autotune_fallbacks", 0),
    }


def _gang_status() -> list:
    """The /statusz "gangs" section: one compact line per supervised
    gang (/workerz has the full per-worker table, /gangz the digest
    view). `max_straggler` is the worst per-rank skew score from the
    heartbeat digests — the one number a dashboard needs to decide
    whether to click through to /gangz."""
    from . import launch
    out = []
    for g in launch.workerz()["gangs"]:
        scores = [(w.get("straggler_score"), w.get("rank"))
                  for w in g["workers"]
                  if w.get("straggler_score") is not None]
        row = {"name": g["name"], "state": g["state"],
               "restarts": g["restarts"], "workers": len(g["workers"])}
        if scores:
            worst, rank = max(scores)
            row["max_straggler"] = {"rank": rank, "score": worst}
        out.append(row)
    return out


def _slo_status() -> Dict[str, Any]:
    """The /statusz "slo" section (slo.status_summary: enabled +
    objective count + firing alerts + autoscaling signals)."""
    from . import slo
    return slo.status_summary()


def _frontdoor_status() -> Dict[str, Any]:
    """The /statusz "frontdoor" section (frontdoor.status_summary:
    enabled + per-model routing/worker/queue one-liners; /modelz has
    the full view)."""
    from . import frontdoor
    return frontdoor.status_summary()


def _armed_failpoints() -> Dict[str, str]:
    """site -> armed spec, armed sites only (/failpointz has the full
    table with hit counts)."""
    from . import failpoints
    return {s: info["armed"] for s, info in failpoints.sites().items()
            if info["armed"]}


def _tracing_status(counters: Dict[str, Any]) -> Dict[str, Any]:
    """The /statusz "tracing" section: completion counters + rolling
    TTFT/TPOT/total latencies from the request-trace decomposition
    timers (tracing.rolling)."""
    from . import tracing
    from .flags import get_flag
    return {
        "enabled": bool(get_flag("FLAGS_request_tracing")),
        "completed": counters.get("STAT_trace_completed", 0),
        "errored": counters.get("STAT_trace_errored", 0),
        "deadline_missed": {
            k[len("STAT_"):-len("_deadline_missed")]: v
            for k, v in sorted(counters.items())
            if k.endswith("_deadline_missed")},
        "exemplars": len(tracing.exemplars()),
        "rolling_us": tracing.rolling(),
    }


def programz() -> Dict[str, Any]:
    from .core import program_accounting
    program_accounting.refresh_throughput()
    from .monitor import gauge_get
    totals = dict(program_accounting.totals())
    totals["achieved_flops_per_s"] = gauge_get(
        "GAUGE_programs_achieved_flops_per_s")
    return {
        "uptime_s": round(time.time() - _T0, 3),
        "totals": totals,
        "programs": program_accounting.programs(),
    }


# ---------------------------------------------------------------------------
# HTTP plumbing
# ---------------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-introspect/1.0"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # no stderr spam per scrape
        pass

    def _send(self, code: int, body: str, ctype: str) -> None:
        payload = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _json(self, obj: Any, code: int = 200) -> None:
        self._send(code, json.dumps(obj, indent=1, default=str) + "\n",
                   "application/json; charset=utf-8")

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler contract)
        url = urlsplit(self.path)
        try:
            if url.path == "/metrics":
                from .core import program_accounting
                from .monitor import to_prometheus
                program_accounting.refresh_throughput()
                self._send(
                    200, to_prometheus(),
                    "text/plain; version=0.0.4; charset=utf-8")
            elif url.path == "/healthz":
                self._send(200, "ok\n", "text/plain; charset=utf-8")
            elif url.path == "/readyz":
                ready, checks = readiness()
                self._json({"ready": ready, "checks": checks},
                           code=200 if ready else 503)
            elif url.path == "/statusz":
                self._json(statusz())
            elif url.path == "/programz":
                self._json(programz())
            elif url.path == "/tracez":
                from . import tracing
                q = parse_qs(url.query)
                tenant = q.get("tenant", [None])[0]
                if q.get("format", [""])[0] == "json":
                    self._json(tracing.tracez(tenant=tenant))
                else:
                    self._send(
                        200, tracing.tracez_text(tenant=tenant) + "\n",
                        "text/plain; charset=utf-8")
            elif url.path == "/sloz":
                from . import slo
                q = parse_qs(url.query)
                if q.get("format", [""])[0] == "json":
                    self._json(slo.sloz())
                else:
                    self._send(200, slo.sloz_text(),
                               "text/plain; charset=utf-8")
            elif url.path == "/modelz":
                from . import frontdoor
                q = parse_qs(url.query)
                if q.get("format", [""])[0] == "json":
                    self._json(frontdoor.modelz())
                else:
                    self._send(200, frontdoor.modelz_text(),
                               "text/plain; charset=utf-8")
            elif url.path == "/flightz":
                from . import telemetry
                q = parse_qs(url.query)
                if q.get("format", [""])[0] == "json":
                    self._json(telemetry.flight_records())
                else:
                    self._send(200, telemetry.flight_dump() + "\n",
                               "text/plain; charset=utf-8")
            elif url.path == "/failpointz":
                from . import failpoints
                self._json({"sites": failpoints.sites()})
            elif url.path == "/workerz":
                from . import launch
                self._json(launch.workerz())
            elif url.path == "/gangz":
                from . import launch
                q = parse_qs(url.query)
                if q.get("format", [""])[0] == "json":
                    self._json(launch.gangz())
                else:
                    self._send(200, launch.gangz_text(),
                               "text/plain; charset=utf-8")
            elif url.path == "/":
                self._send(
                    200,
                    "paddle_tpu introspection: /metrics /healthz "
                    "/readyz /statusz /flightz /programz /tracez "
                    "/sloz /modelz /failpointz /workerz /gangz\n",
                    "text/plain; charset=utf-8")
            else:
                self._send(404, "not found: %s\n" % url.path,
                           "text/plain; charset=utf-8")
        except BrokenPipeError:
            pass  # scraper went away mid-response
        except Exception as e:
            try:
                self._json({"error": repr(e)}, code=500)
            except Exception:
                pass

    def do_POST(self):  # noqa: N802 (BaseHTTPRequestHandler contract)
        url = urlsplit(self.path)
        try:
            if url.path != "/failpointz":
                self._send(404, "not found: %s\n" % url.path,
                           "text/plain; charset=utf-8")
                return
            from . import failpoints
            q = parse_qs(url.query)
            armed_now: list = []
            disarmed: list = []
            try:
                for spec in q.get("arm", []):
                    armed_now += failpoints.arm_spec(spec)
                for site in q.get("disarm", []):
                    failpoints.disarm(site)
                    disarmed.append(site)
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    body = self.rfile.read(n).decode("utf-8").strip()
                    if body:
                        armed_now += failpoints.arm_spec(body)
            except ValueError as e:
                self._json({"error": str(e),
                            "sites": failpoints.sites()}, code=400)
                return
            self._json({"armed": armed_now, "disarmed": disarmed,
                        "sites": failpoints.sites()})
        except BrokenPipeError:
            pass
        except Exception as e:
            try:
                self._json({"error": repr(e)}, code=500)
            except Exception:
                pass


class IntrospectServer:
    """Handle on a running server: .port, .host, .url, .stop()."""

    def __init__(self, httpd: ThreadingHTTPServer,
                 thread: threading.Thread):
        self._httpd = httpd
        self._thread = thread
        self.host, self.port = httpd.server_address[:2]

    @property
    def url(self) -> str:
        return "http://%s:%d" % (self.host, self.port)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10.0)


def server() -> Optional[IntrospectServer]:
    return _SERVER


def maybe_start() -> Optional[IntrospectServer]:
    """Start the server iff FLAGS_introspect_port is a positive port.
    The disabled path is one flag lookup — no imports beyond flags, no
    thread, no socket. Idempotent; call sites are Executor
    construction and pool start()."""
    if _SERVER is not None:
        return _SERVER
    from .flags import get_flag
    try:
        port = int(get_flag("FLAGS_introspect_port", 0) or 0)
    except (TypeError, ValueError):
        return None
    if port <= 0:
        return None
    return start(port=port)


def start(port: Optional[int] = None,
          host: Optional[str] = None) -> IntrospectServer:
    """Start the server (idempotent — returns the running one). `port`
    None reads FLAGS_introspect_port; 0 binds an OS-assigned ephemeral
    port (tests/tooling — the flag value 0 still means *off* through
    maybe_start)."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None:
            return _SERVER
        from .flags import get_flag
        if port is None:
            port = int(get_flag("FLAGS_introspect_port", 0) or 0)
        if host is None:
            host = str(get_flag("FLAGS_introspect_host",
                                "127.0.0.1") or "127.0.0.1")
        httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        httpd.daemon_threads = True
        thread = threading.Thread(target=httpd.serve_forever,
                                  kwargs={"poll_interval": 0.2},
                                  name="pt-introspect", daemon=True)
        thread.start()
        _SERVER = IntrospectServer(httpd, thread)
        return _SERVER


def stop() -> None:
    """Shut the server down and release the socket (idempotent)."""
    global _SERVER
    with _SERVER_LOCK:
        srv, _SERVER = _SERVER, None
    if srv is not None:
        srv.stop()
