"""Profiler: host event spans + device traces + chrome-tracing export.

Analog of /root/reference/paddle/fluid/platform/profiler.{h,cc}
(RecordEvent:126 scoped host spans, ProfilerState CPU/GPU/All:39,
start_profiler/stop_profiler + report tables) and device_tracer.cc
(CUPTI kernel capture -> profiler.proto -> tools/timeline.py chrome
trace). The device side maps onto jax.profiler (XPlane/TensorBoard
traces capture the real TPU timeline); the host side keeps the
RecordEvent span tree, aggregate tables, and a chrome://tracing JSON
exporter so tools/timeline.py-style workflows keep working.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

__all__ = ["RecordEvent", "profiler", "start_profiler", "stop_profiler",
           "reset_profiler", "export_chrome_tracing", "summary",
           "start_device_trace", "stop_device_trace"]

_lock = threading.Lock()
_enabled = False
_events: List[dict] = []
_tls = threading.local()


def _now_us() -> float:
    return time.perf_counter() * 1e6


class RecordEvent:
    """Scoped host span (profiler.h:126). Usable as context manager or
    decorator; nests via a thread-local stack."""

    def __init__(self, name: str, event_type: str = "op"):
        self.name = name
        self.event_type = event_type
        self._t0 = None

    def __enter__(self):
        if _enabled:
            self._t0 = _now_us()
            stack = getattr(_tls, "stack", None)
            if stack is None:
                stack = _tls.stack = []
            stack.append(self.name)
        return self

    def __exit__(self, *exc):
        if _enabled and self._t0 is not None:
            t1 = _now_us()
            stack = _tls.stack
            full = "/".join(stack)
            stack.pop()
            with _lock:
                _events.append({
                    "name": self.name, "full_name": full,
                    "cat": self.event_type, "ts": self._t0,
                    "dur": t1 - self._t0,
                    "tid": threading.get_ident() % 100000,
                })
        return False

    def __call__(self, fn):
        def wrapped(*a, **k):
            with RecordEvent(self.name, self.event_type):
                return fn(*a, **k)
        return wrapped


def start_profiler(state: str = "CPU", tracer_option: str = "Default"):
    """fluid/profiler.py start_profiler. state 'All'/'GPU' additionally
    starts a jax.profiler device trace when a trace dir is configured via
    start_device_trace()."""
    global _enabled
    _enabled = True


def stop_profiler(sorted_key: Optional[str] = "total",
                  profile_path: Optional[str] = None):
    global _enabled
    _enabled = False
    if profile_path:
        export_chrome_tracing(profile_path)
    return summary(sorted_key)


def reset_profiler():
    with _lock:
        _events.clear()


class profiler:
    """Context manager: `with profiler.profiler('CPU', ...)` parity
    (fluid/profiler.py:context)."""

    def __init__(self, state: str = "CPU", sorted_key: str = "total",
                 profile_path: Optional[str] = None):
        self._path = profile_path
        self._key = sorted_key

    def __enter__(self):
        reset_profiler()
        start_profiler()
        return self

    def __exit__(self, *exc):
        stop_profiler(self._key, self._path)
        return False


def summary(sorted_key: Optional[str] = "total") -> List[dict]:
    """Aggregate table like the reference's profiler report: per name
    {calls, total_us, avg_us, max_us}."""
    agg: Dict[str, dict] = defaultdict(
        lambda: {"calls": 0, "total_us": 0.0, "max_us": 0.0})
    with _lock:
        for e in _events:
            a = agg[e["name"]]
            a["calls"] += 1
            a["total_us"] += e["dur"]
            a["max_us"] = max(a["max_us"], e["dur"])
    rows = [{"name": k, **v, "avg_us": v["total_us"] / v["calls"]}
            for k, v in agg.items()]
    if sorted_key in ("total", None):
        rows.sort(key=lambda r: -r["total_us"])
    elif sorted_key == "calls":
        rows.sort(key=lambda r: -r["calls"])
    elif sorted_key == "max":
        rows.sort(key=lambda r: -r["max_us"])
    return rows


def export_chrome_tracing(path: str):
    """tools/timeline.py analog: write chrome://tracing JSON."""
    with _lock:
        trace = {
            "traceEvents": [
                {"name": e["name"], "cat": e["cat"], "ph": "X",
                 "ts": e["ts"], "dur": e["dur"], "pid": 0, "tid": e["tid"],
                 "args": {"full_name": e["full_name"]}}
                for e in _events
            ]
        }
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)


# --- device (XLA) tracing — the CUPTI/device_tracer analog ---------------

_device_trace_dir = None


def start_device_trace(log_dir: str):
    """jax.profiler.start_trace: captures the real TPU timeline (XPlane)
    viewable in TensorBoard/Perfetto — the device_tracer.cc replacement."""
    global _device_trace_dir
    import jax
    jax.profiler.start_trace(log_dir)
    _device_trace_dir = log_dir


def stop_device_trace():
    global _device_trace_dir
    import jax
    jax.profiler.stop_trace()
    d = _device_trace_dir
    _device_trace_dir = None
    return d
