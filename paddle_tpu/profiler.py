"""Profiler: host event spans + device traces + chrome-tracing export.

Analog of /root/reference/paddle/fluid/platform/profiler.{h,cc}
(RecordEvent:126 scoped host spans, ProfilerState CPU/GPU/All:39,
start_profiler/stop_profiler + report tables) and device_tracer.cc
(CUPTI kernel capture -> profiler.proto -> tools/timeline.py chrome
trace). The device side maps onto jax.profiler (XPlane/TensorBoard
traces capture the real TPU timeline); the host side keeps the
RecordEvent span tree, aggregate tables, and a chrome://tracing JSON
exporter so tools/timeline.py-style workflows keep working.

Beyond RecordEvent's synchronous thread-stack spans, the telemetry
layer (telemetry.py, docs/observability.md) records *step-correlated*
events here: spans carry a `step` id and a named `track` (dispatch /
feed-stage / drain / sync), so a pipelined `train_from_dataset` trace
shows dispatch N, feed-stage N+1, and drain N−window as separate rows
of one chrome://tracing timeline, correlated by `args.step` and by a
shared async id. Monitor counters ride along as chrome counter events
("C" phase) via add_counter_event.
"""
from __future__ import annotations

import functools
import json
import os
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

__all__ = ["RecordEvent", "profiler", "start_profiler", "stop_profiler",
           "reset_profiler", "export_chrome_tracing", "summary",
           "start_device_trace", "stop_device_trace",
           "set_device_trace_dir", "add_trace_event", "add_counter_event",
           "maybe_export_rank_trace"]

_lock = threading.Lock()
_enabled = False
_events: List[dict] = []
_tls = threading.local()

# hard bound on buffered events: a telemetry-on service loop must not
# grow host memory without limit; overflow drops new events and counts
# them (STAT_profiler_events_dropped)
_MAX_EVENTS = 200_000


def _now_us() -> float:
    return time.perf_counter() * 1e6


def _append_event(e: dict) -> bool:
    with _lock:
        if len(_events) >= _MAX_EVENTS:
            dropped = True
        else:
            _events.append(e)
            dropped = False
    if dropped:
        from .monitor import stat_add
        stat_add("STAT_profiler_events_dropped")
    return not dropped


class RecordEvent:
    """Scoped host span (profiler.h:126). Usable as context manager or
    decorator; nests via a thread-local stack."""

    def __init__(self, name: str, event_type: str = "op"):
        self.name = name
        self.event_type = event_type
        self._t0 = None

    def __enter__(self):
        if _enabled:
            self._t0 = _now_us()
            stack = getattr(_tls, "stack", None)
            if stack is None:
                stack = _tls.stack = []
            stack.append(self.name)
        return self

    def __exit__(self, *exc):
        if _enabled and self._t0 is not None:
            t1 = _now_us()
            stack = _tls.stack
            full = "/".join(stack)
            stack.pop()
            _append_event({
                "name": self.name, "full_name": full,
                "cat": self.event_type, "ts": self._t0,
                "dur": t1 - self._t0,
                "tid": threading.get_ident() % 100000,
            })
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapped(*a, **k):
            with RecordEvent(self.name, self.event_type):
                return fn(*a, **k)
        return wrapped


# --- step-correlated telemetry events (gated by the CALLER, not by
# start_profiler: telemetry.py records whenever FLAGS_telemetry is on) ----

def add_trace_event(name: str, ts_us: float, dur_us: float, *,
                    cat: str = "telemetry", track: Optional[str] = None,
                    step: Optional[int] = None,
                    args: Optional[dict] = None) -> None:
    """Record one complete span on a named track. `track` becomes its
    own chrome-trace row (thread_name metadata); `step` lands in
    args.step AND as the event id, which is what lets chrome://tracing
    highlight every span of one pipeline step together."""
    e = {"name": name, "cat": cat, "ts": ts_us, "dur": dur_us,
         "tid": threading.get_ident() % 100000}
    if track is not None:
        e["track"] = track
    if step is not None:
        e["step"] = int(step)
    if args:
        e["args"] = dict(args)
    _append_event(e)


def add_counter_event(name: str, value: float,
                      ts_us: Optional[float] = None) -> None:
    """Chrome counter event ("C" phase): monitor counters sampled into
    the same timeline as the spans."""
    _append_event({"name": name, "cat": "counter", "ph": "C",
                   "ts": _now_us() if ts_us is None else ts_us,
                   "value": float(value)})


# --- start/stop (ProfilerState honored — ISSUE 3 satellite) --------------

# where state='All'/'GPU' sends the device trace; configured via
# set_device_trace_dir() or $PADDLE_TPU_DEVICE_TRACE_DIR. No dir
# configured -> host-only profiling, exactly the old behavior.
_device_trace_dir_cfg: Optional[str] = None
_device_trace_started_here = False


def set_device_trace_dir(log_dir: Optional[str]) -> None:
    """Configure where start_profiler(state='All'/'GPU') writes the jax
    device trace. None disables the device tier again."""
    global _device_trace_dir_cfg
    _device_trace_dir_cfg = log_dir


def start_profiler(state: str = "CPU", tracer_option: str = "Default"):
    """fluid/profiler.py start_profiler. `state` selects the tiers
    (ProfilerState, profiler.h:39): 'CPU' records host spans only;
    'All'/'GPU' ADDITIONALLY starts a jax.profiler device trace when a
    trace dir is configured (set_device_trace_dir /
    $PADDLE_TPU_DEVICE_TRACE_DIR) — stop_profiler stops it again."""
    global _enabled, _device_trace_started_here
    _enabled = True
    if str(state) in ("All", "GPU"):
        d = _device_trace_dir_cfg or \
            os.environ.get("PADDLE_TPU_DEVICE_TRACE_DIR")
        if d and _device_trace_dir is None:
            try:
                start_device_trace(d)
                _device_trace_started_here = True
            except Exception:
                # device tracing is an optimization tier, never a hard
                # dependency (e.g. no profiler plugin on this backend)
                _device_trace_started_here = False


def stop_profiler(sorted_key: Optional[str] = "total",
                  profile_path: Optional[str] = None):
    global _enabled, _device_trace_started_here
    _enabled = False
    if _device_trace_started_here:
        # symmetric with start_profiler(state='All'/'GPU'); a trace the
        # USER started via start_device_trace stays theirs to stop
        _device_trace_started_here = False
        try:
            stop_device_trace()
        except Exception:
            pass
    if profile_path:
        export_chrome_tracing(profile_path)
    return summary(sorted_key)


def reset_profiler():
    with _lock:
        _events.clear()


class profiler:
    """Context manager: `with profiler.profiler('All', ...)` parity
    (fluid/profiler.py:context). `state` is forwarded to
    start_profiler, so 'All'/'GPU' capture the device tier too."""

    def __init__(self, state: str = "CPU", sorted_key: str = "total",
                 profile_path: Optional[str] = None):
        self._state = state
        self._path = profile_path
        self._key = sorted_key

    def __enter__(self):
        reset_profiler()
        start_profiler(self._state)
        return self

    def __exit__(self, *exc):
        stop_profiler(self._key, self._path)
        return False


def summary(sorted_key: Optional[str] = "total") -> List[dict]:
    """Aggregate table like the reference's profiler report: per name
    {calls, total_us, avg_us, max_us}. Counter events carry no
    duration and stay out of the table."""
    agg: Dict[str, dict] = defaultdict(
        lambda: {"calls": 0, "total_us": 0.0, "max_us": 0.0})
    with _lock:
        for e in _events:
            if e.get("ph") == "C":
                continue
            a = agg[e["name"]]
            a["calls"] += 1
            a["total_us"] += e["dur"]
            a["max_us"] = max(a["max_us"], e["dur"])
    rows = [{"name": k, **v, "avg_us": v["total_us"] / v["calls"]}
            for k, v in agg.items()]
    if sorted_key in ("total", None):
        rows.sort(key=lambda r: -r["total_us"])
    elif sorted_key == "calls":
        rows.sort(key=lambda r: -r["calls"])
    elif sorted_key == "max":
        rows.sort(key=lambda r: -r["max_us"])
    return rows


def export_chrome_tracing(path: str, pid: int = 0,
                          process_name: Optional[str] = None):
    """tools/timeline.py analog: write chrome://tracing JSON.

    Track-tagged telemetry events render as named rows (thread_name
    metadata per track) and keep their step id both in args.step and as
    the event id; counter events export as "C" phases.

    `pid`/`process_name` tag every event with a process row — a gang
    worker exports with pid=rank so tools/trace_merge.py can overlay N
    rank files in one chrome://tracing view without tid collisions."""
    with _lock:
        events = list(_events)
    track_tids: Dict[str, int] = {}
    trace_events: List[dict] = []
    for e in events:
        if e.get("ph") == "C":
            trace_events.append({
                "name": e["name"], "cat": e.get("cat", "counter"),
                "ph": "C", "ts": e["ts"], "pid": pid, "tid": 0,
                "args": {"value": e["value"]}})
            continue
        track = e.get("track")
        if track is not None:
            tid = track_tids.get(track)
            if tid is None:
                # track rows get stable small tids well clear of the
                # hashed thread ids RecordEvent spans use
                tid = track_tids[track] = 1 + len(track_tids)
        else:
            tid = e["tid"]
        out = {"name": e["name"], "cat": e.get("cat", "op"), "ph": "X",
               "ts": e["ts"], "dur": e["dur"], "pid": pid, "tid": tid,
               "args": dict(e.get("args") or ())}
        if "full_name" in e:
            out["args"]["full_name"] = e["full_name"]
        if "step" in e:
            out["args"]["step"] = e["step"]
            out["id"] = str(e["step"])
        trace_events.append(out)
    meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": track}}
            for track, tid in sorted(track_tids.items(), key=lambda kv:
                                     kv[1])]
    if process_name is not None:
        meta.insert(0, {"name": "process_name", "ph": "M", "pid": pid,
                        "tid": 0, "args": {"name": process_name}})
    trace = {"traceEvents": meta + trace_events}
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)


# --- device (XLA) tracing — the CUPTI/device_tracer analog ---------------

_device_trace_dir = None


def start_device_trace(log_dir: str):
    """jax.profiler.start_trace: captures the real TPU timeline (XPlane)
    viewable in TensorBoard/Perfetto — the device_tracer.cc replacement."""
    global _device_trace_dir
    import jax
    jax.profiler.start_trace(log_dir)
    _device_trace_dir = log_dir


def stop_device_trace():
    global _device_trace_dir
    import jax
    jax.profiler.stop_trace()
    d = _device_trace_dir
    _device_trace_dir = None
    return d


# --- per-rank trace export (gang observability plane) --------------------

def maybe_export_rank_trace(dir_path: Optional[str] = None
                            ) -> Optional[str]:
    """Export this worker's buffered events as `trace_rank<k>.json` in
    `dir_path` (default $PADDLE_TPU_TRACE_DIR), tagged pid=rank so
    tools/trace_merge.py can overlay the gang's files. Registered as an
    atexit hook by launch.maybe_start_worker_heartbeat when the env var
    is set; a no-op (returns None) when the dir is unset or there are
    no events — must never raise on the worker exit path."""
    try:
        d = dir_path or os.environ.get("PADDLE_TPU_TRACE_DIR")
        if not d:
            return None
        with _lock:
            if not _events:
                return None
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0") or "0")
        path = os.path.join(d, "trace_rank%d.json" % rank)
        export_chrome_tracing(path, pid=rank,
                              process_name="rank %d" % rank)
        return path
    except Exception:
        return None
