"""LayerHelper: shared machinery for graph-building layer functions.

Analog of /root/reference/python/paddle/fluid/layer_helper.py — creates
parameters (appending their initializer ops to the startup program, like the
reference's Initializer __call__ appending to startup), temp vars, and ops
on the current default main program.
"""
from __future__ import annotations

from typing import Optional

from ..core.program import (VarDesc, default_main_program,
                            default_startup_program)
from ..core import dtypes


class ParamAttr:
    """Parameter attribute (fluid.ParamAttr, param_attr.py:29)."""

    def __init__(self, name: Optional[str] = None, initializer=None,
                 learning_rate: float = 1.0, regularizer=None,
                 trainable: bool = True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable

    @staticmethod
    def to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return False
        return ParamAttr()


# --- initializers (fluid/initializer.py) -----------------------------------
class Initializer:
    def desc(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def desc(self, shape, dtype):
        return {"type": "fill_constant",
                "attrs": {"shape": list(shape), "value": self.value,
                          "dtype": dtypes.convert_dtype(dtype)}}


class Normal(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0):
        self.loc, self.scale = loc, scale

    def desc(self, shape, dtype):
        return {"type": "gaussian_random",
                "attrs": {"shape": list(shape), "mean": self.loc,
                          "std": self.scale,
                          "dtype": dtypes.convert_dtype(dtype)}}


class TruncatedNormal(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0):
        self.loc, self.scale = loc, scale

    def desc(self, shape, dtype):
        return {"type": "truncated_gaussian_random",
                "attrs": {"shape": list(shape), "mean": self.loc,
                          "std": self.scale,
                          "dtype": dtypes.convert_dtype(dtype)}}


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def desc(self, shape, dtype):
        return {"type": "uniform_random",
                "attrs": {"shape": list(shape), "min": self.low,
                          "max": self.high,
                          "dtype": dtypes.convert_dtype(dtype)}}


class Xavier(Initializer):
    """XavierInitializer (initializer.py:422) — fan-based uniform/normal."""

    def __init__(self, uniform: bool = True, fan_in=None, fan_out=None):
        self.uniform, self.fan_in, self.fan_out = uniform, fan_in, fan_out

    def desc(self, shape, dtype):
        import numpy as np
        fan_in = self.fan_in
        fan_out = self.fan_out
        if fan_in is None:
            fan_in = int(np.prod(shape[1:])) if len(shape) > 2 else shape[0]
        if fan_out is None:
            if len(shape) > 2:
                fan_out = int(shape[0] * np.prod(shape[2:]))
            else:
                fan_out = shape[1] if len(shape) > 1 else shape[0]
        if self.uniform:
            limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
            return {"type": "uniform_random",
                    "attrs": {"shape": list(shape), "min": -limit,
                              "max": limit,
                              "dtype": dtypes.convert_dtype(dtype)}}
        std = float(np.sqrt(2.0 / (fan_in + fan_out)))
        return {"type": "gaussian_random",
                "attrs": {"shape": list(shape), "mean": 0.0, "std": std,
                          "dtype": dtypes.convert_dtype(dtype)}}


class MSRA(Initializer):
    """MSRAInitializer / Kaiming (initializer.py:577)."""

    def __init__(self, uniform: bool = True, fan_in=None):
        self.uniform, self.fan_in = uniform, fan_in

    def desc(self, shape, dtype):
        import numpy as np
        fan_in = self.fan_in
        if fan_in is None:
            fan_in = int(np.prod(shape[1:])) if len(shape) > 1 else shape[0]
        if self.uniform:
            limit = float(np.sqrt(6.0 / fan_in))
            return {"type": "uniform_random",
                    "attrs": {"shape": list(shape), "min": -limit,
                              "max": limit,
                              "dtype": dtypes.convert_dtype(dtype)}}
        std = float(np.sqrt(2.0 / fan_in))
        return {"type": "gaussian_random",
                "attrs": {"shape": list(shape), "mean": 0.0, "std": std,
                          "dtype": dtypes.convert_dtype(dtype)}}


def _init_desc(initializer, shape, dtype, default=None):
    if initializer is None:
        initializer = default or Xavier()
    if isinstance(initializer, Initializer):
        return initializer.desc(shape, dtype)
    return initializer


class LayerHelper:
    def __init__(self, layer_type: str, name: Optional[str] = None):
        self.layer_type = layer_type
        self.name = name
        self.main_program = default_main_program()
        self.startup_program = default_startup_program()

    @property
    def block(self):
        # current (possibly sub-) block — control-flow layers build into
        # sub-blocks under Program.block_guard
        return self.main_program.current_block()

    def unique_name(self, suffix: str = "") -> str:
        base = self.name or self.layer_type
        return self.main_program._unique_name(
            f"{base}{('.' + suffix) if suffix else ''}")

    def create_parameter(self, attr, shape, dtype="float32",
                         default_initializer=None, is_bias=False) -> VarDesc:
        attr = ParamAttr.to_attr(attr)
        if attr is False:
            return None
        name = attr.name or self.unique_name("b" if is_bias else "w")
        default = default_initializer or \
            (Constant(0.0) if is_bias else Xavier())
        init = _init_desc(attr.initializer, shape, dtype, default)
        # parameters ALWAYS live in the global block, even when the
        # helper is building a control-flow sub-block (framework.py
        # create_parameter: "global_block().create_parameter") — a
        # StaticRNN/while step must share weights across iterations
        param = self.main_program.global_block.create_parameter(
            name, shape, dtype, initializer=init, trainable=attr.trainable)
        # mirror into startup program with its init op (reference
        # initializer.py appends ops to startup)
        sblock = self.startup_program.global_block
        if name not in sblock.vars:
            sblock.create_parameter(name, shape, dtype, initializer=init,
                                    trainable=attr.trainable)
            sblock.append_op(init["type"], inputs={},
                             outputs={"Out": [name]}, attrs=init["attrs"])
        if hasattr(attr, "dim") and not is_bias:
            # WeightNormParamAttr: reparameterize as w = g * v/||v||
            # (reference layer_helper.py _create_weight_normalize — it
            # builds the same norm/div/scale op chain). v takes the
            # requested init; g starts at ||v|| so the initial w equals
            # the plain init. The ops live in the MAIN block, so the
            # backward meta-op differentiates into g and v.
            return self._weight_normalize(param, shape, dtype, attr.dim)
        return param

    def _weight_normalize(self, v_param, shape, dtype, dim):
        # dim=None (the reference default): one scalar g over the whole
        # tensor; dim=k: one g per slice of axis k
        if dim is None:
            axes, g_shape, reduce_all = [], [], True
        else:
            axes = [i for i in range(len(shape)) if i != dim]
            g_shape = [shape[dim]]
            reduce_all = False
        g_name = v_param.name + "@wn_g"
        self.main_program.global_block.create_parameter(
            g_name, g_shape, dtype,
            initializer={"type": "fill_constant",
                         "attrs": {"shape": g_shape, "value": 1.0,
                                   "dtype": dtype}},
            trainable=True)
        # g is initialized to ||v|| computed in the startup program from
        # the freshly-initialized v, so training starts at w == v
        sblock = self.startup_program.global_block
        if g_name not in sblock.vars:
            sblock.create_parameter(g_name, g_shape, dtype)
            sq0 = sblock.create_var(g_name + "@sq0", shape=list(shape),
                                    dtype=dtype)
            ssum = sblock.create_var(g_name + "@sum", shape=g_shape,
                                     dtype=dtype)
            sblock.append_op("elementwise_mul",
                             {"X": [v_param.name], "Y": [v_param.name]},
                             {"Out": [sq0.name]}, {})
            sblock.append_op("reduce_sum", {"X": [sq0.name]},
                             {"Out": [ssum.name]},
                             {"dim": axes, "keep_dim": False,
                              "reduce_all": reduce_all})
            sblock.append_op("sqrt", {"X": [ssum.name]},
                             {"Out": [g_name]}, {})
        # main block: w = v * (g / ||v||) broadcast along dim
        sq = self.create_tmp_variable(dtype)
        self.append_op("elementwise_mul",
                       inputs={"X": [v_param.name], "Y": [v_param.name]},
                       outputs={"Out": [sq.name]})
        nrm = self.create_tmp_variable(dtype)
        self.append_op("reduce_sum", inputs={"X": [sq.name]},
                       outputs={"Out": [nrm.name]},
                       attrs={"dim": axes, "keep_dim": False,
                              "reduce_all": reduce_all})
        nrm_s = self.create_tmp_variable(dtype)
        self.append_op("sqrt", inputs={"X": [nrm.name]},
                       outputs={"Out": [nrm_s.name]})
        ratio = self.create_tmp_variable(dtype)
        self.append_op("elementwise_div",
                       inputs={"X": [g_name], "Y": [nrm_s.name]},
                       outputs={"Out": [ratio.name]})
        w = self.create_tmp_variable(dtype)
        self.append_op("elementwise_mul",
                       inputs={"X": [v_param.name], "Y": [ratio.name]},
                       outputs={"Out": [w.name]},
                       attrs={} if dim is None else {"axis": dim})
        w.shape = tuple(shape)
        return w

    def create_tmp_variable(self, dtype="float32", shape=None,
                            stop_gradient=False) -> VarDesc:
        return self.block.create_var(
            self.unique_name("tmp"), shape=shape, dtype=dtype,
            stop_gradient=stop_gradient)

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        op = self.block.append_op(type, inputs, outputs, attrs)
        from ..core.shape_inference import infer_op_shapes
        infer_op_shapes(self.block, op)
        return op

    def append_activation(self, out: VarDesc, act: Optional[str]) -> VarDesc:
        if act is None:
            return out
        act_out = self.create_tmp_variable(out.dtype)
        self.append_op(act, inputs={"X": [out.name]},
                       outputs={"Out": [act_out.name]})
        return act_out
