from .helper import (Constant, Initializer, LayerHelper, MSRA, Normal,  # noqa: F401
                     ParamAttr, TruncatedNormal, Uniform, Xavier)
from .nn import *  # noqa: F401,F403
from . import nn  # noqa: F401
from .control_flow import (While, Assert, Print, StaticRNN, Switch,  # noqa: F401
                           case, switch_case, while_loop, array_length,
                           array_read, array_write, cond, create_array,
                           increment)
from . import control_flow  # noqa: F401
from .auto import *  # noqa: F401,F403  (generated layer builders)
from .auto import generate_layer_fn  # noqa: F401
