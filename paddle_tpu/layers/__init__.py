from .helper import (Constant, Initializer, LayerHelper, MSRA, Normal,  # noqa: F401
                     ParamAttr, TruncatedNormal, Uniform, Xavier)
from .nn import *  # noqa: F401,F403
from . import nn  # noqa: F401
