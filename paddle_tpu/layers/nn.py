"""Graph-building layer functions (static mode).

Analog of /root/reference/python/paddle/fluid/layers/nn.py (214 defs, fc:190,
conv2d:1575, embedding:397, batch_norm, layer_norm, dropout, ...) — each
function creates vars + appends ops through LayerHelper exactly like the
reference's append_op pattern (layer_helper.py), but the ops lower to jax.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core.program import VarDesc, default_main_program
from ..core import dtypes
from .helper import Constant, LayerHelper, Normal, ParamAttr, Xavier


def data(name: str, shape: Sequence[int], dtype="float32",
         lod_level: int = 0, append_batch_size: bool = True) -> VarDesc:
    """fluid.layers.data / fluid.data (layers/io.py) — feed placeholder.
    shape may include -1 for batch; with append_batch_size a leading -1 is
    added like the v1 API."""
    shape = list(shape)
    if append_batch_size and (not shape or shape[0] != -1):
        shape = [-1] + shape
    prog = default_main_program()
    return prog.global_block.create_var(
        name, shape=shape, dtype=dtype, stop_gradient=True,
        lod_level=lod_level)


def fc(input: VarDesc, size: int, num_flatten_dims: int = 1,
       param_attr=None, bias_attr=None, act: Optional[str] = None,
       name: Optional[str] = None) -> VarDesc:
    """fluid.layers.fc (nn.py:190): mul + elementwise_add + activation."""
    helper = LayerHelper("fc", name)
    in_dim = int(np.prod(input.shape[num_flatten_dims:]))
    w = helper.create_parameter(param_attr, [in_dim, size], input.dtype)
    pre = helper.create_tmp_variable(input.dtype)
    helper.append_op("mul", inputs={"X": [input.name], "Y": [w.name]},
                     outputs={"Out": [pre.name]},
                     attrs={"x_num_col_dims": num_flatten_dims,
                            "y_num_col_dims": 1})
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [size], input.dtype,
                                    is_bias=True)
        tmp = helper.create_tmp_variable(input.dtype)
        helper.append_op("elementwise_add",
                         inputs={"X": [pre.name], "Y": [b.name]},
                         outputs={"Out": [tmp.name]},
                         attrs={"axis": num_flatten_dims})
        pre = tmp
    return helper.append_activation(pre, act)


def embedding(input: VarDesc, size: Sequence[int], is_sparse: bool = False,
              is_distributed: bool = False, padding_idx: Optional[int] = None,
              param_attr=None, dtype="float32",
              name: Optional[str] = None) -> VarDesc:
    """fluid.layers.embedding (nn.py:397). is_sparse/is_distributed are
    accepted for parity; on TPU the gradient is an XLA scatter-add and
    distributed tables shard over the mesh (parallel/embedding.py)."""
    helper = LayerHelper("embedding", name)
    w = helper.create_parameter(param_attr, list(size), dtype,
                                default_initializer=Xavier())
    out = helper.create_tmp_variable(dtype)
    helper.append_op("lookup_table",
                     inputs={"W": [w.name], "Ids": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"padding_idx": -1 if padding_idx is None
                            else padding_idx,
                            "is_sparse": is_sparse,
                            "is_distributed": is_distributed})
    return out


def conv2d(input: VarDesc, num_filters: int, filter_size, stride=1,
           padding=0, dilation=1, groups: int = 1, param_attr=None,
           bias_attr=None, act: Optional[str] = None,
           data_format: str = "NCHW", name: Optional[str] = None) -> VarDesc:
    """fluid.layers.conv2d (nn.py:1575)."""
    helper = LayerHelper("conv2d", name)
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if isinstance(dilation, int):
        dilation = [dilation, dilation]
    c_in = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    w_shape = [num_filters, c_in // groups] + list(filter_size)
    import math
    fan_in = (c_in // groups) * int(np.prod(filter_size))
    std = math.sqrt(2.0 / fan_in)
    w = helper.create_parameter(param_attr, w_shape, input.dtype,
                                default_initializer=Normal(0.0, std))
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("conv2d",
                     inputs={"Input": [input.name], "Filter": [w.name]},
                     outputs={"Output": [out.name]},
                     attrs={"strides": list(stride),
                            "paddings": list(padding),
                            "dilations": list(dilation), "groups": groups,
                            "data_format": data_format})
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_filters], input.dtype,
                                    is_bias=True)
        tmp = helper.create_tmp_variable(input.dtype)
        helper.append_op("elementwise_add",
                         inputs={"X": [out.name], "Y": [b.name]},
                         outputs={"Out": [tmp.name]},
                         attrs={"axis": 1 if data_format == "NCHW" else 3})
        out = tmp
    return helper.append_activation(out, act)


def conv2d_transpose(input: VarDesc, num_filters: int, filter_size,
                     stride=1, padding=0, dilation=1, groups: int = 1,
                     param_attr=None, bias_attr=None,
                     act: Optional[str] = None,
                     name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("conv2d_transpose", name)
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if isinstance(dilation, int):
        dilation = [dilation, dilation]
    c_in = input.shape[1]
    w_shape = [c_in, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(param_attr, w_shape, input.dtype)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("conv2d_transpose",
                     inputs={"Input": [input.name], "Filter": [w.name]},
                     outputs={"Output": [out.name]},
                     attrs={"strides": list(stride),
                            "paddings": list(padding),
                            "dilations": list(dilation), "groups": groups})
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_filters], input.dtype,
                                    is_bias=True)
        tmp = helper.create_tmp_variable(input.dtype)
        helper.append_op("elementwise_add",
                         inputs={"X": [out.name], "Y": [b.name]},
                         outputs={"Out": [tmp.name]}, attrs={"axis": 1})
        out = tmp
    return helper.append_activation(out, act)


def pool2d(input: VarDesc, pool_size=2, pool_type: str = "max",
           pool_stride=1, pool_padding=0, global_pooling: bool = False,
           ceil_mode: bool = False, exclusive: bool = True,
           name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("pool2d", name)
    if isinstance(pool_size, int):
        pool_size = [pool_size, pool_size]
    if isinstance(pool_stride, int):
        pool_stride = [pool_stride, pool_stride]
    if isinstance(pool_padding, int):
        pool_padding = [pool_padding, pool_padding]
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("pool2d", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"ksize": list(pool_size),
                            "pooling_type": pool_type,
                            "strides": list(pool_stride),
                            "paddings": list(pool_padding),
                            "global_pooling": global_pooling,
                            "ceil_mode": ceil_mode,
                            "exclusive": exclusive,
                            "adaptive": False})
    return out


def adaptive_pool2d(input: VarDesc, pool_size, pool_type: str = "max",
                    name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("adaptive_pool2d", name)
    if isinstance(pool_size, int):
        pool_size = [pool_size, pool_size]
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("pool2d", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"ksize": list(pool_size),
                            "pooling_type": pool_type,
                            "strides": [1, 1], "paddings": [0, 0],
                            "global_pooling": False, "ceil_mode": False,
                            "exclusive": True, "adaptive": True})
    return out


def batch_norm(input: VarDesc, act: Optional[str] = None,
               is_test: bool = False, momentum: float = 0.9,
               epsilon: float = 1e-5, param_attr=None, bias_attr=None,
               data_layout: str = "NCHW", moving_mean_name=None,
               moving_variance_name=None, use_global_stats: bool = False,
               name: Optional[str] = None) -> VarDesc:
    """fluid.layers.batch_norm (nn.py:2716)."""
    helper = LayerHelper("batch_norm", name)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(param_attr, [c], input.dtype,
                                    default_initializer=Constant(1.0))
    bias = helper.create_parameter(bias_attr, [c], input.dtype, is_bias=True)
    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name or helper.unique_name("mean"),
                  initializer=Constant(0.0), trainable=False),
        [c], input.dtype)
    var = helper.create_parameter(
        ParamAttr(name=moving_variance_name or helper.unique_name("var"),
                  initializer=Constant(1.0), trainable=False),
        [c], input.dtype)
    mean.stop_gradient = True
    var.stop_gradient = True
    y = helper.create_tmp_variable(input.dtype)
    saved_mean = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    saved_var = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    helper.append_op(
        "batch_norm",
        inputs={"X": [input.name], "Scale": [scale.name],
                "Bias": [bias.name], "Mean": [mean.name],
                "Variance": [var.name]},
        outputs={"Y": [y.name], "MeanOut": [mean.name],
                 "VarianceOut": [var.name], "SavedMean": [saved_mean.name],
                 "SavedVariance": [saved_var.name]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout,
               "use_global_stats": use_global_stats})
    return helper.append_activation(y, act)


def layer_norm(input: VarDesc, scale: bool = True, shift: bool = True,
               begin_norm_axis: int = 1, epsilon: float = 1e-5,
               param_attr=None, bias_attr=None, act: Optional[str] = None,
               name: Optional[str] = None) -> VarDesc:
    """fluid.layers.layer_norm (nn.py:3297)."""
    helper = LayerHelper("layer_norm", name)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input.name]}
    if scale:
        s = helper.create_parameter(param_attr, norm_shape, input.dtype,
                                    default_initializer=Constant(1.0))
        inputs["Scale"] = [s.name]
    if shift:
        b = helper.create_parameter(bias_attr, norm_shape, input.dtype,
                                    is_bias=True)
        inputs["Bias"] = [b.name]
    y = helper.create_tmp_variable(input.dtype)
    mean = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    var = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    helper.append_op("layer_norm", inputs=inputs,
                     outputs={"Y": [y.name], "Mean": [mean.name],
                              "Variance": [var.name]},
                     attrs={"epsilon": epsilon,
                            "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(y, act)


def dropout(x: VarDesc, dropout_prob: float, is_test: bool = False,
            dropout_implementation: str = "downgrade_in_infer",
            name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("dropout", name)
    out = helper.create_tmp_variable(x.dtype)
    mask = helper.create_tmp_variable(x.dtype, stop_gradient=True)
    helper.append_op("dropout", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "Mask": [mask.name]},
                     attrs={"dropout_prob": dropout_prob, "is_test": is_test,
                            "dropout_implementation": dropout_implementation})
    return out


def _unary(op_type):
    def f(x: VarDesc, name: Optional[str] = None, **attrs) -> VarDesc:
        helper = LayerHelper(op_type, name)
        out = helper.create_tmp_variable(x.dtype)
        helper.append_op(op_type, inputs={"X": [x.name]},
                         outputs={"Out": [out.name]}, attrs=attrs)
        return out
    f.__name__ = op_type
    return f


relu = _unary("relu")
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
gelu = _unary("gelu")
exp = _unary("exp")
sqrt = _unary("sqrt")
abs = _unary("abs")  # noqa: A001
square = _unary("square")
log = _unary("log")
leaky_relu = _unary("leaky_relu")
relu6 = _unary("relu6")
softplus = _unary("softplus")
softsign = _unary("softsign")
sign = _unary("sign")
erf = _unary("erf")
swish = _unary("swish")
hard_swish = _unary("hard_swish")
hard_sigmoid = _unary("hard_sigmoid")


def softmax(input: VarDesc, axis: int = -1,
            name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("softmax", name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("softmax", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": axis})
    return out


def log_softmax(input: VarDesc, axis: int = -1,
                name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("log_softmax", name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("log_softmax", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": axis})
    return out


def cross_entropy(input: VarDesc, label: VarDesc, soft_label: bool = False,
                  ignore_index: int = -100,
                  name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("cross_entropy", name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("cross_entropy",
                     inputs={"X": [input.name], "Label": [label.name]},
                     outputs={"Y": [out.name]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits: VarDesc, label: VarDesc,
                               soft_label: bool = False,
                               ignore_index: int = -100, axis: int = -1,
                               return_softmax: bool = False,
                               name: Optional[str] = None):
    helper = LayerHelper("softmax_with_cross_entropy", name)
    softmax_out = helper.create_tmp_variable(logits.dtype)
    loss = helper.create_tmp_variable(logits.dtype)
    helper.append_op("softmax_with_cross_entropy",
                     inputs={"Logits": [logits.name], "Label": [label.name]},
                     outputs={"Softmax": [softmax_out.name],
                              "Loss": [loss.name]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index, "axis": axis})
    if return_softmax:
        return loss, softmax_out
    return loss


def square_error_cost(input: VarDesc, label: VarDesc,
                      name: Optional[str] = None) -> VarDesc:
    """(input - label)^2 elementwise (layers/loss.py square_error_cost)."""
    helper = LayerHelper("square_error_cost", name)
    out = helper.create_tmp_variable(input.dtype, shape=input.shape)
    helper.append_op("square_error_cost",
                     inputs={"X": [input.name], "Y": [label.name]},
                     outputs={"Out": [out.name]})
    return out


def mean(x: VarDesc, name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("mean", name)
    out = helper.create_tmp_variable(x.dtype, shape=())
    helper.append_op("mean", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]})
    return out


def reduce_sum(x: VarDesc, dim=None, keep_dim: bool = False,
               name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("reduce_sum", name)
    out = helper.create_tmp_variable(x.dtype)
    attrs = {"keep_dim": keep_dim}
    if dim is None:
        attrs["reduce_all"] = True
    else:
        attrs["dim"] = [dim] if isinstance(dim, int) else list(dim)
    helper.append_op("reduce_sum", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs=attrs)
    return out


def reduce_mean(x: VarDesc, dim=None, keep_dim: bool = False,
                name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("reduce_mean", name)
    out = helper.create_tmp_variable(x.dtype)
    attrs = {"keep_dim": keep_dim}
    if dim is None:
        attrs["reduce_all"] = True
    else:
        attrs["dim"] = [dim] if isinstance(dim, int) else list(dim)
    helper.append_op("reduce_mean", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs=attrs)
    return out


def _reduce_layer(op_type):
    def f(x: VarDesc, dim=None, keep_dim: bool = False,
          name: Optional[str] = None) -> VarDesc:
        helper = LayerHelper(op_type, name)
        out = helper.create_tmp_variable(x.dtype)
        attrs = {"keep_dim": keep_dim}
        if dim is None:
            attrs["reduce_all"] = True
        else:
            attrs["dim"] = [dim] if isinstance(dim, int) else list(dim)
        helper.append_op(op_type, inputs={"X": [x.name]},
                         outputs={"Out": [out.name]}, attrs=attrs)
        return out
    f.__name__ = op_type
    return f


reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")
reduce_any = _reduce_layer("reduce_any")
reduce_all = _reduce_layer("reduce_all")


def concat(input, axis: int = 0, name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("concat", name)
    out = helper.create_tmp_variable(input[0].dtype)
    helper.append_op("concat", inputs={"X": [v.name for v in input]},
                     outputs={"Out": [out.name]}, attrs={"axis": axis})
    return out


def reshape(x: VarDesc, shape, name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("reshape", name)
    out = helper.create_tmp_variable(x.dtype)
    xshape = helper.create_tmp_variable(x.dtype, stop_gradient=True)
    helper.append_op("reshape2", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "XShape": [xshape.name]},
                     attrs={"shape": list(shape)})
    return out


def transpose(x: VarDesc, perm, name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("transpose", name)
    out = helper.create_tmp_variable(x.dtype)
    xshape = helper.create_tmp_variable(x.dtype, stop_gradient=True)
    helper.append_op("transpose2", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "XShape": [xshape.name]},
                     attrs={"axis": list(perm)})
    return out


def flatten(x: VarDesc, axis: int = 1, name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("flatten", name)
    out = helper.create_tmp_variable(x.dtype)
    xshape = helper.create_tmp_variable(x.dtype, stop_gradient=True)
    helper.append_op("flatten2", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "XShape": [xshape.name]},
                     attrs={"axis": axis})
    return out


def cast(x: VarDesc, dtype) -> VarDesc:
    helper = LayerHelper("cast")
    out = helper.create_tmp_variable(dtype)
    helper.append_op("cast", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"out_dtype": dtypes.convert_dtype(dtype)})
    return out


def _binary(op_type):
    def f(x: VarDesc, y: VarDesc, axis: int = -1,
          act: Optional[str] = None, name: Optional[str] = None) -> VarDesc:
        helper = LayerHelper(op_type, name)
        out = helper.create_tmp_variable(x.dtype)
        helper.append_op(op_type, inputs={"X": [x.name], "Y": [y.name]},
                         outputs={"Out": [out.name]}, attrs={"axis": axis})
        return helper.append_activation(out, act)
    f.__name__ = op_type
    return f


def _cmp(op_type):
    def f(x: VarDesc, y: VarDesc, name: Optional[str] = None) -> VarDesc:
        helper = LayerHelper(op_type, name)
        out = helper.create_tmp_variable("bool", shape=x.shape)
        helper.append_op(op_type, inputs={"X": [x.name], "Y": [y.name]},
                         outputs={"Out": [out.name]})
        return out
    f.__name__ = op_type
    return f


less_than = _cmp("less_than")
less_equal = _cmp("less_equal")
greater_than = _cmp("greater_than")
greater_equal = _cmp("greater_equal")
equal = _cmp("equal")
not_equal = _cmp("not_equal")
logical_and = _cmp("logical_and")
logical_or = _cmp("logical_or")
logical_xor = _cmp("logical_xor")


def assign(input: VarDesc, output: Optional[VarDesc] = None) -> VarDesc:
    """layers.assign (tensor.py:560): copy input into output var."""
    helper = LayerHelper("assign")
    if output is None:
        output = helper.create_tmp_variable(input.dtype, shape=input.shape)
    helper.append_op("assign", inputs={"X": [input.name]},
                     outputs={"Out": [output.name]})
    return output


elementwise_add = _binary("elementwise_add")
elementwise_sub = _binary("elementwise_sub")
elementwise_mul = _binary("elementwise_mul")
elementwise_div = _binary("elementwise_div")
elementwise_max = _binary("elementwise_max")
elementwise_min = _binary("elementwise_min")
elementwise_pow = _binary("elementwise_pow")


def matmul(x: VarDesc, y: VarDesc, transpose_x: bool = False,
           transpose_y: bool = False, alpha: float = 1.0,
           name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("matmul", name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("matmul", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]},
                     attrs={"transpose_X": transpose_x,
                            "transpose_Y": transpose_y, "alpha": alpha})
    return out


def mul(x: VarDesc, y: VarDesc, x_num_col_dims: int = 1,
        y_num_col_dims: int = 1, name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("mul", name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("mul", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


def scale(x: VarDesc, scale: float = 1.0, bias: float = 0.0,
          bias_after_scale: bool = True,
          name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("scale", name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("scale", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"scale": scale, "bias": bias,
                            "bias_after_scale": bias_after_scale})
    return out


def accuracy(input: VarDesc, label: VarDesc, k: int = 1,
             name: Optional[str] = None) -> VarDesc:
    """fluid.layers.accuracy (metric_op.py) — top_k + accuracy op."""
    helper = LayerHelper("accuracy", name)
    topk_out = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    topk_idx = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op("top_k", inputs={"X": [input.name]},
                     outputs={"Out": [topk_out.name],
                              "Indices": [topk_idx.name]},
                     attrs={"k": k})
    acc = helper.create_tmp_variable("float32", stop_gradient=True)
    correct = helper.create_tmp_variable("int32", stop_gradient=True)
    total = helper.create_tmp_variable("int32", stop_gradient=True)
    helper.append_op("accuracy",
                     inputs={"Out": [topk_out.name],
                             "Indices": [topk_idx.name],
                             "Label": [label.name]},
                     outputs={"Accuracy": [acc.name],
                              "Correct": [correct.name],
                              "Total": [total.name]})
    return acc


def fill_constant(shape, dtype, value, name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("fill_constant", name)
    out = helper.create_tmp_variable(dtype, stop_gradient=True)
    helper.append_op("fill_constant", inputs={},
                     outputs={"Out": [out.name]},
                     attrs={"shape": list(shape), "value": value,
                            "dtype": dtypes.convert_dtype(dtype)})
    return out


def one_hot(input: VarDesc, depth: int, name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("one_hot", name)
    out = helper.create_tmp_variable("float32")
    helper.append_op("one_hot", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs={"depth": depth})
    return out


def topk(input: VarDesc, k: int, name: Optional[str] = None):
    helper = LayerHelper("top_k", name)
    out = helper.create_tmp_variable(input.dtype)
    idx = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op("top_k", inputs={"X": [input.name]},
                     outputs={"Out": [out.name], "Indices": [idx.name]},
                     attrs={"k": k})
    return out, idx


def clip(x: VarDesc, min: float, max: float,
         name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("clip", name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("clip", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"min": min, "max": max})
    return out


def sums(input, name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("sum", name)
    out = helper.create_tmp_variable(input[0].dtype)
    helper.append_op("sum", inputs={"X": [v.name for v in input]},
                     outputs={"Out": [out.name]})
    return out


# ---------------------------------------------------------------------------
# sequence / RNN / CRF layer builders (fluid.layers book-model surface)
# ---------------------------------------------------------------------------

def sequence_pool(input: VarDesc, pool_type: str = "sum",
                  seq_len: Optional[VarDesc] = None,
                  name: Optional[str] = None) -> VarDesc:
    """fluid.layers.sequence_pool (sequence_ops; ragged repr is
    padded + lengths, ops/sequence.py)."""
    helper = LayerHelper("sequence_pool", name)
    out = helper.create_tmp_variable(input.dtype)
    ins = {"X": [input.name]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len.name]
    outs = {"Out": [out.name]}
    if pool_type.upper() == "MAX":  # MaxIndex only exists for max pool
        outs["MaxIndex"] = [helper.create_tmp_variable("int32").name]
    helper.append_op("sequence_pool", inputs=ins, outputs=outs,
                     attrs={"pooltype": pool_type.upper()})
    return out


def sequence_conv(input: VarDesc, num_filters: int, filter_size: int = 3,
                  act: Optional[str] = None, param_attr=None,
                  bias_attr=None, name: Optional[str] = None) -> VarDesc:
    """fluid.layers.sequence_conv (nn.py:2462)."""
    helper = LayerHelper("sequence_conv", name)
    d = input.shape[-1]
    w = helper.create_parameter(param_attr,
                                [filter_size * d, num_filters],
                                input.dtype)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("sequence_conv",
                     inputs={"X": [input.name], "Filter": [w.name]},
                     outputs={"Out": [out.name]},
                     attrs={"contextLength": filter_size,
                            "contextStart": -(filter_size // 2),
                            "contextStride": 1})
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_filters], input.dtype,
                                    is_bias=True)
        bout = helper.create_tmp_variable(input.dtype)
        helper.append_op("elementwise_add",
                         inputs={"X": [out.name], "Y": [b.name]},
                         outputs={"Out": [bout.name]}, attrs={"axis": -1})
        out = bout
    return helper.append_activation(out, act)


def dynamic_lstm(input: VarDesc, size: int, h_0=None, c_0=None,
                 param_attr=None, bias_attr=None, use_peepholes=False,
                 is_reverse=False, seq_len: Optional[VarDesc] = None,
                 name: Optional[str] = None):
    """fluid.layers.dynamic_lstm (nn.py:466): input is the
    PRE-PROJECTED [.., 4*hidden] sequence (the fc lives outside, like
    the reference); returns (hidden, cell) full sequences. The ragged
    repr is padded + lengths, so pass seq_len for variable-length
    batches — REQUIRED with is_reverse, where the flip relies on the
    length mask to skip front padding."""
    if is_reverse and seq_len is None:
        raise ValueError(
            "dynamic_lstm(is_reverse=True) needs seq_len: without the "
            "length mask the time flip feeds padding first")
    helper = LayerHelper("dynamic_lstm", name)
    d = size // 4
    wh = helper.create_parameter(param_attr, [d, 4 * d], input.dtype)
    bias = helper.create_parameter(bias_attr, [4 * d], input.dtype,
                                   is_bias=True)
    hidden = helper.create_tmp_variable(input.dtype)
    cell = helper.create_tmp_variable(input.dtype)
    last_h = helper.create_tmp_variable(input.dtype)
    last_c = helper.create_tmp_variable(input.dtype)
    ins = {"Input": [input.name], "WeightH": [wh.name],
           "Bias": [bias.name]}
    if h_0 is not None:
        ins["H0"] = [h_0.name]
    if c_0 is not None:
        ins["C0"] = [c_0.name]
    if seq_len is not None:
        ins["SeqLen"] = [seq_len.name]
    helper.append_op("lstm", inputs=ins,
                     outputs={"Hidden": [hidden.name],
                              "Cell": [cell.name],
                              "LastH": [last_h.name],
                              "LastC": [last_c.name]},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse})
    return hidden, cell


def dynamic_gru(input: VarDesc, size: int, h_0=None, param_attr=None,
                bias_attr=None, is_reverse=False, origin_mode=False,
                seq_len: Optional[VarDesc] = None,
                name: Optional[str] = None) -> VarDesc:
    """fluid.layers.dynamic_gru (nn.py:850): input pre-projected
    [.., 3*hidden]. Pass seq_len for variable-length batches —
    REQUIRED with is_reverse (see dynamic_lstm)."""
    if is_reverse and seq_len is None:
        raise ValueError(
            "dynamic_gru(is_reverse=True) needs seq_len: without the "
            "length mask the time flip feeds padding first")
    helper = LayerHelper("dynamic_gru", name)
    wh = helper.create_parameter(param_attr, [size, 3 * size],
                                 input.dtype)
    bias = helper.create_parameter(bias_attr, [3 * size], input.dtype,
                                   is_bias=True)
    hidden = helper.create_tmp_variable(input.dtype)
    last_h = helper.create_tmp_variable(input.dtype)
    ins = {"Input": [input.name], "WeightH": [wh.name],
           "Bias": [bias.name]}
    if h_0 is not None:
        ins["H0"] = [h_0.name]
    if seq_len is not None:
        ins["SeqLen"] = [seq_len.name]
    helper.append_op("gru", inputs=ins,
                     outputs={"Hidden": [hidden.name],
                              "LastH": [last_h.name]},
                     attrs={"origin_mode": origin_mode,
                            "is_reverse": is_reverse})
    return hidden


def linear_chain_crf(input: VarDesc, label: VarDesc, param_attr=None,
                     length: Optional[VarDesc] = None,
                     name: Optional[str] = None) -> VarDesc:
    """fluid.layers.linear_chain_crf (nn.py:1590): returns the negative
    log-likelihood [B, 1]; the Transition parameter ([D+2, D], rows
    0/1 start/stop) is created here."""
    helper = LayerHelper("linear_chain_crf", name)
    d = input.shape[-1]
    transition = helper.create_parameter(
        ParamAttr.to_attr(param_attr) or ParamAttr(),
        [d + 2, d], input.dtype, default_initializer=Constant(0.0))
    alpha = helper.create_tmp_variable(input.dtype)
    eexp = helper.create_tmp_variable(input.dtype)
    texp = helper.create_tmp_variable(input.dtype)
    ll = helper.create_tmp_variable(input.dtype)
    ins = {"Emission": [input.name], "Transition": [transition.name],
           "Label": [label.name]}
    if length is not None:
        ins["Length"] = [length.name]
    helper.append_op("linear_chain_crf", inputs=ins,
                     outputs={"Alpha": [alpha.name],
                              "EmissionExps": [eexp.name],
                              "TransitionExps": [texp.name],
                              "LogLikelihood": [ll.name]})
    return ll


def crf_decoding(input: VarDesc, param_attr, label=None, length=None,
                 name: Optional[str] = None) -> VarDesc:
    """fluid.layers.crf_decoding (nn.py:1699): Viterbi path (or the
    per-token correctness indicator when label is given). param_attr
    must NAME the transition parameter created by linear_chain_crf."""
    helper = LayerHelper("crf_decoding", name)
    attr = ParamAttr.to_attr(param_attr)
    tname = attr.name if attr is not None and attr.name else None
    if tname is None:
        raise ValueError("crf_decoding needs param_attr naming the "
                         "transition parameter of linear_chain_crf")
    out = helper.create_tmp_variable("int64")
    ins = {"Emission": [input.name], "Transition": [tname]}
    if label is not None:
        ins["Label"] = [label.name]
    if length is not None:
        ins["Length"] = [length.name]
    helper.append_op("crf_decoding", inputs=ins,
                     outputs={"ViterbiPath": [out.name]})
    return out


# ---------------------------------------------------------------------------
# fundamental var builders + misc surface (fluid.layers tail)
# ---------------------------------------------------------------------------

def create_tensor(dtype: str = "float32", name: Optional[str] = None,
                  persistable: bool = False) -> VarDesc:
    """fluid.layers.create_tensor (tensor.py:66)."""
    helper = LayerHelper("create_tensor", name)
    return helper.block.create_var(
        name or helper.unique_name("tensor"), dtype=dtype,
        persistable=persistable)


def create_global_var(shape, value, dtype="float32",
                      persistable: bool = False, force_cpu: bool = False,
                      name: Optional[str] = None) -> VarDesc:
    """fluid.layers.create_global_var (tensor.py:120): a persistable
    var initialized by fill_constant in the startup program."""
    helper = LayerHelper("global_var", name)
    vname = name or helper.unique_name("gvar")
    var = helper.block.create_var(vname, shape=list(shape), dtype=dtype,
                                  persistable=persistable,
                                  stop_gradient=True)
    sblock = helper.startup_program.global_block
    if vname not in sblock.vars:
        sblock.create_var(vname, shape=list(shape), dtype=dtype,
                          persistable=persistable)
        sblock.append_op("fill_constant", inputs={},
                         outputs={"Out": [vname]},
                         attrs={"shape": list(shape),
                                "value": float(value),
                                "dtype": dtypes.convert_dtype(dtype)})
    return var


def create_parameter(shape, dtype="float32", name: Optional[str] = None,
                     attr=None, is_bias: bool = False,
                     default_initializer=None) -> VarDesc:
    """fluid.layers.create_parameter (tensor.py:34)."""
    helper = LayerHelper("create_parameter", name)
    attr = ParamAttr.to_attr(attr) or ParamAttr()
    if name is not None and attr.name is None:
        attr.name = name
    return helper.create_parameter(attr, list(shape), dtype,
                                   default_initializer, is_bias=is_bias)


def autoincreased_step_counter(counter_name: Optional[str] = None,
                               begin: int = 1, step: int = 1) -> VarDesc:
    """layers.autoincreased_step_counter (tensor.py:155): a persistable
    int64 counter incremented once per executor run. Repeated calls
    with the same name share ONE increment (the reference's
    counter.op-is-None guard) — otherwise two callers would double-step
    every schedule keyed on it."""
    helper = LayerHelper("step_counter")
    vname = counter_name or "@STEP_COUNTER@"
    var = create_global_var([1], float(begin - step), "int64",
                            persistable=True, name=vname)
    prog = helper.main_program
    seen = getattr(prog, "_step_counters", None)
    if seen is None:
        seen = prog._step_counters = set()
    if vname not in seen:
        seen.add(vname)
        helper.append_op("increment", inputs={"X": [vname]},
                         outputs={"Out": [vname]},
                         attrs={"step": float(step)})
    return var


def image_resize(input: VarDesc, out_shape=None, scale=None,
                 resample: str = "BILINEAR", align_corners: bool = True,
                 align_mode: int = 1, data_format: str = "NCHW",
                 name: Optional[str] = None) -> VarDesc:
    """fluid.layers.image_resize (nn.py:7556) — routes to the interp
    op family."""
    if resample.upper() == "TRILINEAR":
        # 5-D path owns out_d and the NCDHW layout
        return resize_trilinear(
            input, out_shape, scale, name, align_corners, align_mode,
            "NCDHW" if data_format == "NCHW" else data_format)
    op = {"BILINEAR": "bilinear_interp", "NEAREST": "nearest_interp",
          "BICUBIC": "bicubic_interp"}.get(resample.upper())
    if op is None:
        raise ValueError("image_resize: unknown resample %r" % resample)
    if data_format not in ("NCHW",):
        # the interp ops are NCHW; transpose around them rather than
        # silently resizing the wrong axes
        if data_format != "NHWC":
            raise ValueError("image_resize: data_format must be NCHW "
                             "or NHWC")
    helper = LayerHelper(op, name)
    src = input
    if data_format == "NHWC":
        t_in = helper.create_tmp_variable(input.dtype)
        helper.append_op("transpose2", inputs={"X": [input.name]},
                         outputs={"Out": [t_in.name]},
                         attrs={"axis": [0, 3, 1, 2]})
        src = t_in
    out = helper.create_tmp_variable(input.dtype)
    attrs = {"align_corners": align_corners, "align_mode": align_mode}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), \
            int(out_shape[1])
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op(op, inputs={"X": [src.name]},
                     outputs={"Out": [out.name]}, attrs=attrs)
    if data_format == "NHWC":
        t_out = helper.create_tmp_variable(input.dtype)
        helper.append_op("transpose2", inputs={"X": [out.name]},
                         outputs={"Out": [t_out.name]},
                         attrs={"axis": [0, 2, 3, 1]})
        out = t_out
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    align_corners=True, align_mode=1,
                    data_format="NCHW"):
    return image_resize(input, out_shape, scale, "BILINEAR",
                        align_corners, align_mode, data_format, name)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   align_corners=True, data_format="NCHW"):
    return image_resize(input, out_shape, scale, "NEAREST",
                        align_corners, 1, data_format, name)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    """3-d variant; out_shape is [D, H, W]."""
    helper = LayerHelper("trilinear_interp", name)
    out = helper.create_tmp_variable(input.dtype)
    attrs = {"align_corners": align_corners, "align_mode": align_mode,
             "data_layout": data_format}
    if out_shape is not None:
        attrs["out_d"], attrs["out_h"], attrs["out_w"] = \
            [int(v) for v in out_shape]
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op("trilinear_interp", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs=attrs)
    return out


def has_inf(x: VarDesc, name: Optional[str] = None) -> VarDesc:
    """layers.has_inf (tensor.py:940)."""
    helper = LayerHelper("has_inf", name)
    out = helper.create_tmp_variable("bool")
    helper.append_op("isinf", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]})
    return out


def has_nan(x: VarDesc, name: Optional[str] = None) -> VarDesc:
    helper = LayerHelper("has_nan", name)
    out = helper.create_tmp_variable("bool")
    helper.append_op("isnan", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]})
    return out


def is_empty(x: VarDesc, name: Optional[str] = None) -> VarDesc:
    """layers.is_empty (control_flow.py:3406)."""
    helper = LayerHelper("is_empty", name)
    out = helper.create_tmp_variable("bool")
    helper.append_op("is_empty", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]})
    return out


def rank(input: VarDesc) -> VarDesc:
    """layers.rank (nn.py:11587): static rank as a 0-d int constant."""
    return fill_constant([1], value=len(input.shape), dtype="int32")


def multi_head_attention(queries: VarDesc, num_heads: int,
                         attn_mask: Optional[VarDesc] = None,
                         param_prefix: Optional[str] = None,
                         name: Optional[str] = None) -> VarDesc:
    """Canonical UNFUSED self-attention subgraph: three mul+add
    projections, reshape2/transpose2 into heads, scaled q@k^T (+mask),
    softmax, @v, transpose2/reshape2 back — exactly the op pattern the
    reference's multihead_matmul_fuse_pass matches
    (/root/reference/paddle/fluid/framework/ir/multihead_matmul_fuse_pass.cc)
    and this repo's `multihead_matmul_fuse` IR pass rewrites onto the
    fused flash-attention op. queries: [B, S, H]."""
    import math as _math
    helper = LayerHelper(param_prefix or "mha", name)
    B_S_H = queries.shape
    H = int(B_S_H[-1])
    assert H % num_heads == 0, (H, num_heads)
    d = H // num_heads

    def proj(tag):
        w = helper.create_parameter(helper.unique_name(tag + "_w"),
                                    [H, H], queries.dtype)
        b = helper.create_parameter(helper.unique_name(tag + "_b"),
                                    [H], queries.dtype, is_bias=True)
        out = mul(queries, w, x_num_col_dims=2)
        return elementwise_add(out, b), w, b

    q, wq, bq = proj("q")
    k, wk, bk = proj("k")
    v, wv, bv = proj("v")

    def heads(x):
        return transpose(reshape(x, [0, 0, num_heads, d]), [0, 2, 1, 3])

    qh, kh, vh = heads(q), heads(k), heads(v)
    score = matmul(qh, kh, transpose_y=True,
                   alpha=1.0 / _math.sqrt(d))
    if attn_mask is not None:
        score = elementwise_add(score, attn_mask)
    weights = softmax(score)
    ctx = matmul(weights, vh)
    ctx = transpose(ctx, [0, 2, 1, 3])
    return reshape(ctx, [0, 0, H])
