"""Auto-generated layer builders — the layer_function_generator analog.

The reference fills most of fluid.layers from op metadata
(/root/reference/python/paddle/fluid/layers/layer_function_generator.py:
generate_layer_fn builds a python wrapper from an OpProto's inputs/
outputs; layers/ops.py registers one per listed op). Here the same
generator reads the op registry's slot metadata, so every registered op
with a plain tensor-in/tensor-out contract gets a fluid-style builder
for free — dual-mode through nn.functional's dispatch.

Functions the v2 tensor namespace already implements dual-mode
(zeros/argmax/gather/...) are re-exported rather than regenerated.
"""
from __future__ import annotations

from typing import Optional

from ..core.registry import REGISTRY
from ..nn.functional import _run, _run_multi

__all__ = ["generate_layer_fn"]


def generate_layer_fn(op_type: str, out_slots=None):
    """layer_function_generator.py:generate_layer_fn: positional args
    map onto the op's input slots in declared order (lists allowed for
    duplicable slots), keyword args matching slot names feed inputs,
    everything else becomes op attrs. Returns one var, or a tuple in
    declared output order when the op has several outputs."""
    opdef = REGISTRY.get(op_type)
    in_slots = list(opdef.input_slots)
    all_out = list(out_slots or opdef.output_slots)

    def fn(*args, name: Optional[str] = None, **kwargs):
        ins = {}
        for slot, arg in zip(in_slots, args):
            if arg is None:
                continue
            ins[slot] = list(arg) if isinstance(arg, (list, tuple)) \
                else [arg]
        if len(args) > len(in_slots):
            raise TypeError("%s takes at most %d tensor args (%s)"
                            % (op_type, len(in_slots), in_slots))
        attrs = {}
        for k, v in kwargs.items():
            if k in in_slots:
                if v is not None:
                    ins[k] = list(v) if isinstance(v, (list, tuple)) \
                        else [v]
            else:
                attrs[k] = v
        if len(all_out) == 1:
            return _run(op_type, ins, attrs, out_slot=all_out[0])
        outs = _run_multi(op_type, ins, attrs, all_out)
        return tuple(outs)

    fn.__name__ = op_type
    fn.__doc__ = ("Auto-generated builder for op %r (inputs %s, "
                  "outputs %s) — layer_function_generator analog."
                  % (op_type, in_slots, all_out))
    return fn


# --- fluid.layers names backed 1:1 by a registered op ----------------------
# (name -> (op_type, out_slots or None)); out_slots trims multi-output
# ops whose extra outputs are intermediates in the reference builder
_OP_BACKED = {
    "affine_channel": ("affine_channel", None),
    "affine_grid": ("affine_grid", None),
    "anchor_generator": ("anchor_generator", None),
    "add_position_encoding": ("add_position_encoding", None),
    "bilinear_tensor_product": ("bilinear_tensor_product", None),
    "bipartite_match": ("bipartite_match", None),
    "box_clip": ("box_clip", None),
    "box_coder": ("box_coder", None),
    "box_decoder_and_assign": ("box_decoder_and_assign", None),
    "bpr_loss": ("bpr_loss", None),
    "center_loss": ("center_loss", None),
    "chunk_eval": ("chunk_eval", None),
    "clip_by_norm": ("clip_by_norm", None),
    "collect_fpn_proposals": ("collect_fpn_proposals", None),
    "continuous_value_model": ("cvm", None),
    "cos_sim": ("cos_sim", None),
    "crop": ("crop", None),
    "crop_tensor": ("crop_tensor", None),
    "ctc_greedy_decoder": ("ctc_greedy_decoder", None),
    "data_norm": ("data_norm", None),
    "deformable_conv": ("deformable_conv", None),
    "density_prior_box": ("density_prior_box", None),
    "detection_output": ("detection_output", None),
    "ssd_loss": ("ssd_loss", None),
    "dice_loss": ("dice_loss", None),
    "distribute_fpn_proposals": ("distribute_fpn_proposals", None),
    "edit_distance": ("edit_distance", None),
    "elementwise_floordiv": ("elementwise_floordiv", None),
    "elementwise_mod": ("elementwise_mod", None),
    "elu": ("elu", None),
    "expand": ("expand", None),
    "expand_as": ("expand_as", None),
    "fill_constant_batch_size_like": ("fill_constant_batch_size_like",
                                      None),
    "filter_by_instag": ("filter_by_instag", None),
    "fsp_matrix": ("fsp", None),
    "gather_tree": ("gather_tree", None),
    "gaussian_random": ("gaussian_random", None),
    "generate_mask_labels": ("generate_mask_labels", None),
    "generate_proposal_labels": ("generate_proposal_labels", None),
    "generate_proposals": ("generate_proposals", None),
    "get_tensor_from_selected_rows": ("get_tensor_from_selected_rows",
                                      None),
    "grid_sampler": ("grid_sampler", None),
    "group_norm": ("group_norm", None),
    "hash": ("hash", None),
    "huber_loss": ("huber_loss", None),
    "im2sequence": ("im2sequence", None),
    "inplace_abn": ("inplace_abn", None),
    "instance_norm": ("instance_norm", None),
    "iou_similarity": ("iou_similarity", None),
    "isfinite": ("isfinite", None),
    "kldiv_loss": ("kldiv_loss", None),
    "l2_normalize": ("l2_normalize", None),
    "label_smooth": ("label_smooth", None),
    "locality_aware_nms": ("locality_aware_nms", None),
    "lod_reset": ("lod_reset", None),
    "log_loss": ("log_loss", None),
    "logical_not": ("logical_not", None),
    "lrn": ("lrn", None),
    "lstm_unit": ("lstm_unit", None),
    "margin_rank_loss": ("margin_rank_loss", None),
    "matrix_nms": ("matrix_nms", None),
    "maxout": ("maxout", None),
    "mean_iou": ("mean_iou", None),
    "merge_selected_rows": ("merge_selected_rows", None),
    "mish": ("mish", None),
    "mse_loss": ("square_error_cost", None),
    "multiclass_nms": ("multiclass_nms", None),
    "multiplex": ("multiplex", None),
    "nce": ("nce", None),
    "npair_loss": ("npair_loss", None),
    "soft_relu": ("soft_relu", None),
    "uniform_random_batch_size_like":
        ("uniform_random_batch_size_like", None),
    "gaussian_random_batch_size_like":
        ("gaussian_random_batch_size_like", None),
    "pad": ("pad", None),
    "pad2d": ("pad2d", None),
    "pad_constant_like": ("pad_constant_like", None),
    "pixel_shuffle": ("pixel_shuffle", None),
    "polygon_box_transform": ("polygon_box_transform", None),
    "prelu": ("prelu", None),
    "prior_box": ("prior_box", None),
    "prroi_pool": ("prroi_pool", None),
    "psroi_pool": ("psroi_pool", None),
    "random_crop": ("random_crop", None),
    "rank_loss": ("rank_loss", None),
    "retinanet_detection_output": ("retinanet_detection_output", None),
    "reverse": ("reverse", None),
    "roi_align": ("roi_align", None),
    "roi_perspective_transform": ("roi_perspective_transform", None),
    "roi_pool": ("roi_pool", None),
    "row_conv": ("row_conv", None),
    "retinanet_target_assign": ("retinanet_target_assign", None),
    "rpn_target_assign": ("rpn_target_assign", None),
    "deformable_roi_pooling": ("deformable_roi_pooling", None),
    "sampling_id": ("sampling_id", None),
    "scatter_nd": ("scatter_nd", None),
    "selu": ("selu", None),
    "sequence_concat": ("sequence_concat", None),
    "sequence_enumerate": ("sequence_enumerate", None),
    "sequence_expand": ("sequence_expand", None),
    "sequence_expand_as": ("sequence_expand_as", None),
    "sequence_mask": ("sequence_mask", None),
    "sequence_pad": ("sequence_pad", None),
    "sequence_reshape": ("sequence_reshape", None),
    "sequence_reverse": ("sequence_reverse", None),
    "sequence_scatter": ("sequence_scatter", None),
    "sequence_slice": ("sequence_slice", None),
    "sequence_softmax": ("sequence_softmax", None),
    "sequence_unpad": ("sequence_unpad", None),
    "shard_index": ("shard_index", None),
    "shuffle_channel": ("shuffle_channel", None),
    "sigmoid_cross_entropy_with_logits":
        ("sigmoid_cross_entropy_with_logits", None),
    "sigmoid_focal_loss": ("sigmoid_focal_loss", None),
    "similarity_focus": ("similarity_focus", None),
    "smooth_l1": ("smooth_l1_loss", None),
    "space_to_depth": ("space_to_depth", None),
    "spectral_norm": ("spectral_norm", None),
    "stanh": ("stanh", None),
    "target_assign": ("target_assign", None),
    "teacher_student_sigmoid_loss": ("teacher_student_sigmoid_loss",
                                     None),
    "temporal_shift": ("temporal_shift", None),
    "unbind": ("unbind", None),
    "unfold": ("unfold", None),
    "uniform_random": ("uniform_random", None),
    "warpctc": ("warpctc", None),
    "yolo_box": ("yolo_box", None),
    "yolov3_loss": ("yolov3_loss", None),
}


def _install():
    import sys
    installed = []
    for name, (op_type, outs) in sorted(_OP_BACKED.items()):
        if not REGISTRY.has(op_type):
            continue
        globals()[name] = generate_layer_fn(op_type, outs)
        installed.append(name)
    __all__.extend(installed)

    # names the v2 tensor namespace implements dual-mode already
    from .. import tensor as _T
    reexport = [
        "argmax", "argmin", "argsort", "diag", "eye", "gather",
        "gather_nd", "linspace", "ones", "ones_like", "pow", "range",
        "scatter", "scatter_nd_add", "shape", "slice", "split",
        "squeeze", "stack", "strided_slice", "triu", "unique",
        "unique_with_counts", "unsqueeze", "unstack", "where", "zeros",
        "zeros_like",
    ]
    alias = {"range": "arange", "unique_with_counts": "unique"}
    for name in reexport:
        src = alias.get(name, name)
        if hasattr(_T, src):
            globals()[name] = getattr(_T, src)
            __all__.append(name)


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                        num_true=1,
                                        remove_accidental_hits=True,
                                        use_customized_samples=False,
                                        customized_samples=None,
                                        customized_probabilities=None,
                                        seed=0, name=None):
    """Reference signature (loss.py:1051): num_samples is a required
    POSITIONAL parameter, so the generic slot-mapping wrapper does not
    fit."""
    return _run("sampled_softmax_with_cross_entropy",
                {"Logits": [logits], "Label": [label]},
                {"num_samples": int(num_samples),
                 "remove_accidental_hits": bool(remove_accidental_hits)},
                out_slot="Loss")


__all__.append("sampled_softmax_with_cross_entropy")


def sum(x, name=None):  # noqa: A001
    """fluid.layers.sum: elementwise sum of a LIST of tensors (sum_op)
    — NOT the v2 reduction (that is paddle.sum / tensor.sum)."""
    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    return _run("sum", {"X": xs}, {})


__all__.append("sum")

_install()
