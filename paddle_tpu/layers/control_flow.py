"""Control-flow layers: While, cond, tensor arrays, Print/Assert.

Analog of /root/reference/python/paddle/fluid/layers/control_flow.py
(While:1021, array_write:1370, array_read:1575, increment:1315,
less_than:1723, Print:231) over the structural op lowerings in
core/control_flow.py.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..core.program import VarDesc, default_main_program
from .helper import LayerHelper

__all__ = ["While", "cond", "increment", "array_write", "array_read",
           "array_length", "create_array", "Print", "Assert"]


class While:
    """layers/control_flow.py:1021:

        i = fill_constant(...); cond = less_than(i, n)
        w = While(cond)
        with w.block():
            ...
            increment(i, in_place=True)
            assign(less_than(i, n), cond)   # update the condition var

    Loop-carried vars are discovered from the sub-block's reads/writes
    (core/control_flow.py lower_while); forward-only under XLA.
    """

    def __init__(self, cond: VarDesc, is_test: bool = False,
                 name: Optional[str] = None):
        self.helper = LayerHelper("while", name)
        self.cond_var = cond
        self.program = default_main_program()

    class _Guard:
        def __init__(self, w: "While"):
            self._w = w
            self._sub = w.program.create_block()
            self._guard = w.program.block_guard(self._sub)

        def __enter__(self):
            self._guard.__enter__()
            return self._sub

        def __exit__(self, *exc):
            self._guard.__exit__(*exc)
            if exc and exc[0] is not None:
                return False
            w = self._w
            # outputs: every var the sub-block writes that exists in the
            # parent too (in-place loop vars)
            parent = w.program.current_block()
            writes = []
            for op in self._sub.ops:
                for ns in op.outputs.values():
                    for n in ns:
                        if parent.has_var(n) and n not in writes:
                            writes.append(n)
            parent.append_op(
                "while",
                inputs={"Condition": [w.cond_var.name]},
                outputs={"Out": writes},
                attrs={"sub_block": self._sub.idx})
            return False

    def block(self) -> "_Guard":
        return While._Guard(self)


def cond(pred: VarDesc, true_fn, false_fn=None, name: Optional[str] = None):
    """layers.cond (control_flow.py:2214): run true_fn/false_fn graphs,
    merge outputs. Built as two conditional_block ops + select_input per
    output, exactly the reference's lowering shape."""
    helper = LayerHelper("cond", name)
    program = default_main_program()
    parent = program.current_block()

    def _build(fn):
        sub = program.create_block()
        with program.block_guard(sub):
            out = fn() if fn is not None else None
        outs = out if isinstance(out, (tuple, list)) else \
            ([] if out is None else [out])
        return sub, list(outs)

    true_sub, true_outs = _build(true_fn)
    false_sub, false_outs = _build(false_fn)
    if len(true_outs) != len(false_outs):
        raise ValueError(
            "cond: true_fn and false_fn must return the same number of "
            "outputs (%d vs %d)" % (len(true_outs), len(false_outs)))

    # one structural op holding both branch blocks -> lax.cond (the
    # reference builds two conditional_blocks + select_input per output;
    # lax.cond expresses the same merge natively and differentiably)
    merged = [helper.create_tmp_variable(t_o.dtype, shape=t_o.shape)
              for t_o in true_outs]
    parent.append_op(
        "cond_block_pair",
        inputs={"Cond": [pred.name]},
        outputs={"Out": [m.name for m in merged]},
        attrs={"true_block": true_sub.idx,
               "false_block": false_sub.idx,
               "true_outs": [v.name for v in true_outs],
               "false_outs": [v.name for v in false_outs]})
    if not merged:
        return None
    return merged[0] if len(merged) == 1 else merged


def increment(x: VarDesc, value: float = 1.0, in_place: bool = True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_tmp_variable(x.dtype,
                                                        shape=x.shape)
    helper.append_op("increment", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"step": value})
    return out


def create_array(dtype: str = "float32", name: Optional[str] = None):
    """control_flow.py create_array: declare a TENSOR_ARRAY var."""
    from ..core.program import TENSOR_ARRAY
    helper = LayerHelper("array", name)
    return helper.block.create_var(
        helper.unique_name("array"), dtype=dtype, type=TENSOR_ARRAY)


def array_write(x: VarDesc, i: VarDesc, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    helper.append_op("write_to_array",
                     inputs={"X": [x.name], "I": [i.name]},
                     outputs={"Out": [array.name]})
    return array


def array_read(array: VarDesc, i: VarDesc):
    helper = LayerHelper("array_read")
    out = helper.create_tmp_variable()
    helper.append_op("read_from_array",
                     inputs={"X": [array.name], "I": [i.name]},
                     outputs={"Out": [out.name]})
    return out


def array_length(array: VarDesc):
    helper = LayerHelper("array_length")
    out = helper.create_tmp_variable("int64", shape=(1,))
    helper.append_op("array_length", inputs={"X": [array.name]},
                     outputs={"Out": [out.name]})
    return out


def Print(input: VarDesc, first_n: int = -1, message: Optional[str] = None,
          summarize: int = 20, **kw):
    helper = LayerHelper("print")
    out = helper.create_tmp_variable(input.dtype, shape=input.shape)
    helper.append_op("print", inputs={"In": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"message": message or "", "first_n": first_n,
                            "summarize": summarize})
    return out


def Assert(cond: VarDesc, data: Optional[Sequence[VarDesc]] = None,
           summarize: int = 20, name: Optional[str] = None):
    helper = LayerHelper("assert", name)
    helper.append_op(
        "assert",
        inputs={"Cond": [cond.name],
                "Data": [d.name for d in (data or [])]},
        outputs={}, attrs={"summarize": summarize})
