"""Control-flow layers: While, cond, tensor arrays, Print/Assert.

Analog of /root/reference/python/paddle/fluid/layers/control_flow.py
(While:1021, array_write:1370, array_read:1575, increment:1315,
less_than:1723, Print:231) over the structural op lowerings in
core/control_flow.py.
"""
from __future__ import annotations

import numpy as np
from typing import Optional, Sequence

from ..core.program import VarDesc, default_main_program
from .helper import LayerHelper

__all__ = ["While", "cond", "increment", "array_write", "array_read",
           "while_loop", "case", "switch_case", "Switch", "StaticRNN",
           "array_length", "create_array", "Print", "Assert"]




def _parent_writes(sub, parent):
    """Output vars a sub-block writes that exist in the parent — the
    structural op's Out list (shared by While and conditional blocks)."""
    writes = []
    for op in sub.ops:
        for ns in op.outputs.values():
            for n in ns:
                if parent.has_var(n) and n not in writes:
                    writes.append(n)
    return writes

class While:
    """layers/control_flow.py:1021:

        i = fill_constant(...); cond = less_than(i, n)
        w = While(cond)
        with w.block():
            ...
            increment(i, in_place=True)
            assign(less_than(i, n), cond)   # update the condition var

    Loop-carried vars are discovered from the sub-block's reads/writes
    (core/control_flow.py lower_while); forward-only under XLA.
    """

    def __init__(self, cond: VarDesc, is_test: bool = False,
                 name: Optional[str] = None):
        self.helper = LayerHelper("while", name)
        self.cond_var = cond
        self.program = default_main_program()

    class _Guard:
        def __init__(self, w: "While"):
            self._w = w
            self._sub = w.program.create_block()
            self._guard = w.program.block_guard(self._sub)

        def __enter__(self):
            self._guard.__enter__()
            return self._sub

        def __exit__(self, *exc):
            self._guard.__exit__(*exc)
            if exc and exc[0] is not None:
                return False
            w = self._w
            parent = w.program.current_block()
            writes = _parent_writes(self._sub, parent)
            parent.append_op(
                "while",
                inputs={"Condition": [w.cond_var.name]},
                outputs={"Out": writes},
                attrs={"sub_block": self._sub.idx})
            return False

    def block(self) -> "_Guard":
        return While._Guard(self)


def cond(pred: VarDesc, true_fn, false_fn=None, name: Optional[str] = None):
    """layers.cond (control_flow.py:2214): run true_fn/false_fn graphs,
    merge outputs. Built as two conditional_block ops + select_input per
    output, exactly the reference's lowering shape."""
    helper = LayerHelper("cond", name)
    program = default_main_program()
    parent = program.current_block()

    def _build(fn):
        sub = program.create_block()
        with program.block_guard(sub):
            out = fn() if fn is not None else None
        outs = out if isinstance(out, (tuple, list)) else \
            ([] if out is None else [out])
        return sub, list(outs)

    true_sub, true_outs = _build(true_fn)
    false_sub, false_outs = _build(false_fn)
    if len(true_outs) != len(false_outs):
        raise ValueError(
            "cond: true_fn and false_fn must return the same number of "
            "outputs (%d vs %d)" % (len(true_outs), len(false_outs)))

    # one structural op holding both branch blocks -> lax.cond (the
    # reference builds two conditional_blocks + select_input per output;
    # lax.cond expresses the same merge natively and differentiably)
    merged = [helper.create_tmp_variable(t_o.dtype, shape=t_o.shape)
              for t_o in true_outs]
    parent.append_op(
        "cond_block_pair",
        inputs={"Cond": [pred.name]},
        outputs={"Out": [m.name for m in merged]},
        attrs={"true_block": true_sub.idx,
               "false_block": false_sub.idx,
               "true_outs": [v.name for v in true_outs],
               "false_outs": [v.name for v in false_outs]})
    if not merged:
        return None
    return merged[0] if len(merged) == 1 else merged


def increment(x: VarDesc, value: float = 1.0, in_place: bool = True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_tmp_variable(x.dtype,
                                                        shape=x.shape)
    helper.append_op("increment", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"step": value})
    return out


def create_array(dtype: str = "float32", name: Optional[str] = None):
    """control_flow.py create_array: declare a TENSOR_ARRAY var."""
    from ..core.program import TENSOR_ARRAY
    helper = LayerHelper("array", name)
    return helper.block.create_var(
        helper.unique_name("array"), dtype=dtype, type=TENSOR_ARRAY)


def array_write(x: VarDesc, i: VarDesc, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    helper.append_op("write_to_array",
                     inputs={"X": [x.name], "I": [i.name]},
                     outputs={"Out": [array.name]})
    return array


def array_read(array: VarDesc, i: VarDesc):
    helper = LayerHelper("array_read")
    out = helper.create_tmp_variable()
    helper.append_op("read_from_array",
                     inputs={"X": [array.name], "I": [i.name]},
                     outputs={"Out": [out.name]})
    return out


def array_length(array: VarDesc):
    helper = LayerHelper("array_length")
    out = helper.create_tmp_variable("int64", shape=(1,))
    helper.append_op("array_length", inputs={"X": [array.name]},
                     outputs={"Out": [out.name]})
    return out


def Print(input: VarDesc, first_n: int = -1, message: Optional[str] = None,
          summarize: int = 20, **kw):
    helper = LayerHelper("print")
    out = helper.create_tmp_variable(input.dtype, shape=input.shape)
    helper.append_op("print", inputs={"In": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"message": message or "", "first_n": first_n,
                            "summarize": summarize})
    return out


def Assert(cond: VarDesc, data: Optional[Sequence[VarDesc]] = None,
           summarize: int = 20, name: Optional[str] = None):
    helper = LayerHelper("assert", name)
    helper.append_op(
        "assert",
        inputs={"Cond": [cond.name],
                "Data": [d.name for d in (data or [])]},
        outputs={}, attrs={"summarize": summarize})


def while_loop(cond_fn, body_fn, loop_vars, is_test: bool = False,
               name: Optional[str] = None):
    """layers.while_loop (control_flow.py:1111): functional while over
    graph-built cond/body. Dygraph runs the python loop directly (the
    reference does the same in imperative mode); static mode builds the
    while op's sub-block from body_fn and lowers to lax.while_loop."""
    from ..core.program import in_dygraph_mode
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise ValueError("while_loop: loop_vars must be a non-empty "
                         "list")
    loop_vars = list(loop_vars)
    if in_dygraph_mode():
        while True:
            c = cond_fn(*loop_vars)
            if not bool(np.asarray(c.value if hasattr(c, "value")
                                   else c)):
                break
            out = body_fn(*loop_vars)
            loop_vars = list(out) if isinstance(out, (tuple, list)) \
                else [out]
        return loop_vars

    helper = LayerHelper("while_loop", name)
    cond_var = cond_fn(*loop_vars)
    w = While(cond_var, is_test=is_test)
    with w.block():
        out = body_fn(*loop_vars)
        out = list(out) if isinstance(out, (tuple, list)) else [out]
        if len(out) != len(loop_vars):
            raise ValueError("while_loop: body returned %d vars for %d "
                             "loop vars" % (len(out), len(loop_vars)))
        for res, var in zip(out, loop_vars):
            if res.name != var.name:
                helper.append_op("assign", inputs={"X": [res.name]},
                                 outputs={"Out": [var.name]})
        new_cond = cond_fn(*loop_vars)
        helper.append_op("assign", inputs={"X": [new_cond.name]},
                         outputs={"Out": [cond_var.name]})
    return loop_vars


def case(pred_fn_pairs, default=None, name: Optional[str] = None):
    """layers.case (control_flow.py:2026): first true predicate wins;
    `default` (or the LAST branch, like the reference) handles the
    fall-through. Composed from nested cond calls."""
    if not pred_fn_pairs:
        raise ValueError("case: pred_fn_pairs must be non-empty")
    pairs = list(pred_fn_pairs)
    if default is None:
        _, default = pairs[-1]
        pairs = pairs[:-1]
        if not pairs:
            return default()

    def build(i):
        if i == len(pairs):
            return default
        pred, fn = pairs[i]
        return lambda: cond(pred, fn, build(i + 1))

    return build(0)()


def switch_case(branch_index, branch_fns, default=None,
                name: Optional[str] = None):
    """layers.switch_case (control_flow.py:2387): integer-indexed
    branch selection (dict or list of fns)."""
    from .nn import equal as _eq, fill_constant
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    if default is None:
        default = items[-1][1]
        items = items[:-1]
        if not items:
            return default()
    pairs = []
    for idx, fn in items:
        pairs.append((_eq(branch_index,
                          fill_constant([1], value=int(idx),
                                        dtype=branch_index.dtype)),
                      fn))
    return case(pairs, default=default)


class Switch:
    """fluid.layers.Switch (control_flow.py:1524):

        with Switch() as switch:
            with switch.case(cond1): ...assign...
            with switch.default(): ...assign...

    Builds the same first-match semantics as `case`; each with-block
    appends ops into a conditional_block guarded by the accumulated
    not-any-previous predicate."""

    def __init__(self, name: Optional[str] = None):
        self._helper = LayerHelper("switch", name)
        self._prev = None  # OR of earlier case predicates

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    class _CaseGuard:
        def __init__(self, sw, pred):
            from .nn import logical_and
            from .auto import logical_not
            if sw._prev is not None:
                pred = logical_and(pred, logical_not(sw._prev))
            self._block = _ConditionalBlock(pred)
            from .nn import logical_or
            sw._prev = pred if sw._prev is None else \
                logical_or(sw._prev, pred)

        def __enter__(self):
            return self._block.__enter__()

        def __exit__(self, *exc):
            return self._block.__exit__(*exc)

    def case(self, condition):
        return Switch._CaseGuard(self, condition)

    def default(self):
        from .auto import logical_not
        if self._prev is None:
            raise ValueError("Switch.default before any case")
        guard = Switch._CaseGuard.__new__(Switch._CaseGuard)
        guard._block = _ConditionalBlock(logical_not(self._prev))
        return guard


class _ConditionalBlock:
    """with-block appending ops under a conditional_block op whose
    outputs are the vars assigned inside (IfElse/Switch building
    block, control_flow.py ConditionalBlock)."""

    def __init__(self, pred: VarDesc):
        self._pred = pred
        self._program = default_main_program()

    def __enter__(self):
        self._sub = self._program.create_block()
        self._guard = self._program.block_guard(self._sub)
        self._guard.__enter__()
        return self

    def __exit__(self, *exc):
        self._guard.__exit__(*exc)
        if exc and exc[0] is not None:
            return False
        parent = self._program.current_block()
        writes = _parent_writes(self._sub, parent)
        parent.append_op(
            "conditional_block",
            inputs={"Cond": [self._pred.name]},
            outputs={"Out": writes},
            attrs={"sub_block": self._sub.idx})
        return False


class StaticRNN:
    """fluid.layers.StaticRNN (control_flow.py:449): build a per-step
    block with step_input / memory / update_memory / step_output, then
    call the rnn to get time-stacked outputs.

        rnn = StaticRNN()
        with rnn.step():
            word = rnn.step_input(x_t_major)       # x: [T, B, D]
            prev = rnn.memory(init=h0)             # [B, H]
            h = layers.fc(concat([word, prev]), H, act="tanh")
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        out = rnn()                                # [T, B, H]

    Lowering: ONE structural static_rnn op whose sub-block scans under
    lax.scan (core/control_flow.py lower_static_rnn) — the reference
    unrolls per-step ops; XLA gets a rolled loop instead."""

    def __init__(self, name: Optional[str] = None):
        self.helper = LayerHelper("static_rnn", name)
        self.program = default_main_program()
        self._step_ins = []    # (outer_name, inner_name)
        self._mems = []        # (init_name, pre_name, post_name or None)
        self._outs = []        # (inner_name, outer_name)
        self._sub = None
        self._built = False

    class _StepGuard:
        def __init__(self, rnn):
            self._rnn = rnn
            rnn._sub = rnn.program.create_block()
            self._guard = rnn.program.block_guard(rnn._sub)

        def __enter__(self):
            self._guard.__enter__()
            return self._rnn

        def __exit__(self, *exc):
            self._guard.__exit__(*exc)
            if exc and exc[0] is not None:
                return False
            self._rnn._finalize()
            return False

    def step(self):
        return StaticRNN._StepGuard(self)

    def _require_building(self):
        if self._sub is None or self.program.current_block() is not \
                self._sub:
            raise RuntimeError("StaticRNN: call inside `with rnn.step()`")

    def step_input(self, x: VarDesc) -> VarDesc:
        self._require_building()
        inner = self._sub.create_var(
            self.helper.unique_name("step_in"),
            shape=tuple(x.shape[1:]) if x.shape else None,
            dtype=x.dtype, stop_gradient=x.stop_gradient)
        self._step_ins.append((x.name, inner.name))
        return inner

    def memory(self, init: Optional[VarDesc] = None, shape=None,
               batch_ref=None, init_value: float = 0.0) -> VarDesc:
        self._require_building()
        if init is None:
            raise ValueError(
                "StaticRNN.memory needs init= (value-initialized "
                "memories: create the init var with fill_constant "
                "outside the step block)")
        pre = self._sub.create_var(
            self.helper.unique_name("mem_pre"), shape=init.shape,
            dtype=init.dtype, stop_gradient=False)
        self._mems.append([init.name, pre.name, None])
        return pre

    def update_memory(self, mem: VarDesc, var: VarDesc):
        self._require_building()
        for m in self._mems:
            if m[1] == mem.name:
                m[2] = var.name
                return
        raise ValueError("update_memory: %r is not a StaticRNN memory"
                         % mem.name)

    def step_output(self, o: VarDesc):
        self._require_building()
        # stacked shape = (T,) + per-step shape; T from the first
        # step_input's outer leading dim
        t = None
        if self._step_ins:
            outer_in = self.program.blocks[
                self._sub.parent_idx].var(self._step_ins[0][0])
            if outer_in.shape:
                t = outer_in.shape[0]
        shape = ((t,) + tuple(o.shape)) if o.shape is not None else None
        outer = self.program.blocks[self._sub.parent_idx].create_var(
            self.helper.unique_name("rnn_out"),
            shape=shape, dtype=o.dtype, stop_gradient=False)
        self._outs.append((o.name, outer.name))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _finalize(self):
        if not self._step_ins:
            raise ValueError("StaticRNN: at least one step_input "
                             "is required")
        for m in self._mems:
            if m[2] is None:
                raise ValueError("StaticRNN: memory %r never updated "
                                 "(update_memory missing)" % m[1])
        parent = self.program.blocks[self._sub.parent_idx]
        parent.append_op(
            "static_rnn",
            inputs={"X": [o for o, _ in self._step_ins],
                    "Init": [m[0] for m in self._mems]},
            outputs={"Out": [outer for _, outer in self._outs]},
            attrs={"sub_block": self._sub.idx,
                   "step_in_names": [i for _, i in self._step_ins],
                   "mem_pre_names": [m[1] for m in self._mems],
                   "mem_post_names": [m[2] for m in self._mems],
                   "step_out_names": [i for i, _ in self._outs]})
        self._built = True

    def __call__(self):
        if not self._built:
            raise RuntimeError("StaticRNN: build the step block first")
        block = self.program.current_block()
        outs = [block.var(outer) for _, outer in self._outs]
        return outs[0] if len(outs) == 1 else outs
