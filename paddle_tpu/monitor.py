"""Runtime stats registry — the platform monitor analog, grown into a
typed-instrument registry.

Analog of /root/reference/paddle/fluid/platform/monitor.{h,cc} (the
STAT_ADD/STAT_RESET int64 registry) exposed to python as
get_float_stats/get_int_stats (pybind.cc:1664 get_float_stats), extended
with the instrument kinds a runtime that wants to explain its own time
needs (docs/observability.md):

- **counters** — monotonically accumulated floats (`stat_add`). The
  original STAT registry; every legacy call keeps working unchanged.
- **gauges** — last-written values (`gauge_set`): queue depths,
  in-flight windows, cache sizes.
- **timers** — latency histograms (`timer_observe`, microseconds by
  convention, TIMER_* names): count/sum/min/max plus p50/p95 computed
  over a bounded ring of the most recent samples.

`snapshot()` returns all three as one plain dict; `dump()` serializes it
to JSON and `to_prometheus()` to Prometheus text exposition format, so a
bench artifact and a scrape endpoint read the same registry.
Everything is thread-safe and process-global.

    from paddle_tpu.monitor import stat_add, timer_observe, snapshot
    stat_add("STAT_executor_compile", 1)
    timer_observe("TIMER_executor_dispatch_us", 412.0)
    snapshot()  # {"counters": {...}, "gauges": {...}, "timers": {...}}

Well-known counters include STAT_executor_compile (in-memory cache
miss -> trace), STAT_executor_cache_evict (LRU bound hit), and the
persistent AOT program cache set (core/program_cache.py):
STAT_program_cache_trace_hit / _trace_miss / _corrupt / _unexportable
and _bytes_read / _bytes_written.

The async dispatch pipeline (docs/async_pipeline.md) exposes:
- STAT_executor_dispatch: jitted steps dispatched by Executor.run
  (bumped at dispatch, before any fetch is read), and
- STAT_executor_sync: blocking device->host materialization events
  (Executor.run's return_numpy=True conversion, a FetchHandle's first
  read, the fast_check_nan_inf scalar check).
The dispatch/sync ratio is the pipeline's health signal: a loop that
should be dispatch-ahead but shows sync == dispatch has a forced sync
on its hot path, and tests pin the ratio so regressions are visible.

Timer latencies land here when FLAGS_telemetry is on (telemetry.py):
TIMER_executor_compile_us / _dispatch_us / _sync_us,
TIMER_program_cache_load_us / _store_us, TIMER_fetch_sync_us,
TIMER_pipeline_drain_us / _feed_stage_us, TIMER_trainstep_dispatch_us,
TIMER_hapi_epoch_drain_us / _callback_us.

The serving path (docs/serving.md) exposes:
- bucketing: STAT_predictor_bucket_hit / _cold (warm-signature vs
  newly-compiled bucketed calls), _skip / _overflow (calls that
  bypassed bucketing), STAT_predictor_pad_rows / _pad_elements
  (padding waste), STAT_program_cache_warm (warmup_buckets compiles);
- the PredictorPool batcher: STAT_serving_requests / _batches /
  _batched_rows (rows/batches = the amortization factor), _rejected
  (ServingQueueFull backpressure), _batch_errors,
  GAUGE_serving_queue_depth / _last_batch_rows, and the always-on
  TIMER_serving_queue_wait_us / _batch_us histograms (queue wait and
  batch execution are the serving SLO — recorded without
  FLAGS_telemetry, like the program-cache timers).

The generation engine (docs/generation.md) exposes:
- STAT_generation_requests / _prefills / _tokens (throughput),
  _compile (engine-level compilations — the zero-steady-state-
  recompile pin counts THIS standing still), _evictions (pool-pressure
  preemptions), _errors, _rejected (ServingQueueFull backpressure),
  STAT_generation_blocks_allocated / _blocks_freed (KV ledger churn);
- GAUGE_generation_blocks_free / _blocks_used (pool occupancy),
  _active_seqs, _queue_depth;
- always-on TIMER_generation_prefill_us / _decode_step_us /
  _inter_token_us histograms (tokens/s and p95 inter-token latency are
  the generation SLO; bench.py's generation block gates on the
  decode-step p95 via tools/stat_diff.py).

The mesh-native SPMD runtime (paddle_tpu/mesh/, docs/spmd.md)
exposes (always-on, like the serving timers):
- STAT_mesh_placements / STAT_mesh_reshard_bytes: device_put work a
  ShardingPlan actually performed (values already resident with the
  right sharding are skipped) — a steady-state training loop must show
  these standing still, or state is ping-ponging between layouts;
- STAT_mesh_collective_<axis>: host-level collective launches per mesh
  axis (parallel/collective.py — all_reduce/all_gather/broadcast/
  all_to_all outside shard_map), the per-axis traffic census
  MULTICHIP_r06.json records;
- GAUGE_mesh_devices: device count of the most recently built plan;
- TIMER_mesh_compile_us: walltime of plan.compile()'s first
  (trace+compile) call with explicit in/out shardings.

XLA program accounting (core/program_accounting.py, scraped live via
introspect.py /programz):
- GAUGE_program_flops_<tag> / _bytes_accessed_<tag> / _temp_bytes_<tag>
  / _hbm_bytes_<tag>: per compiled program, captured at compile time
  from compiled.cost_analysis() / memory_analysis();
- GAUGE_programs_count / _hbm_bytes (process-wide compiled HBM
  footprint) / _flops_compiled / _achieved_flops_per_s (FLOPs
  dispatched per wall-second over the process lifetime);
- STAT_program_account_fallback: accounted executions that fell back
  to the plain jitted path (input mismatch — costs one recompile).

Request-lifecycle tracing (tracing.py, /tracez — always-on like the
serving timers, gated by FLAGS_request_tracing):
- stage-decomposition timers observed at trace finish:
  TIMER_serving_admit_us / _batch_join_us / _dispatch_us / _execute_us
  / _fetch_us / _total_us and TIMER_generation_queue_wait_us /
  _decode_us / _total_us — plus TIMER_generation_ttft_us (first token,
  observed once per request) and TIMER_generation_tpot_us (per-decode-
  token deltas), observed inline as tokens arrive;
- STAT_trace_completed / _errored / _nonmonotonic (ordering audit),
  STAT_<kind>_deadline_missed and STAT_<kind>_budget_<stage>_us for
  deadline-armed submits (where deadlined traffic burns its budget);
- GAUGE_tracing_exemplars + GAUGE_trace_exemplar_us_<id> per kept
  slow/errored exemplar (retracted on ring eviction,
  STAT_tracing_exemplar_evict).

The robustness layer (failpoints.py, docs/robustness.md):
- self-healing pools: STAT_serving_restarts / _restart_exhausted and
  STAT_generation_restarts / _restart_exhausted (supervised worker
  restarts and terminal budget exhaustion — tools/stat_diff.py treats
  the whole _shed_/_restart families as cost counters);
- deadline shedding: STAT_serving_shed_at_admit /
  STAT_generation_shed_at_admit (requests whose deadline burned while
  queuing — rejected before any device work);
- crash-safe checkpoints (incubate/checkpoint/atomic.py):
  STAT_checkpoint_saves / _loads / _resumes / _corrupt_fallback and
  TIMER_checkpoint_save_us.
"""
from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

_LOCK = threading.Lock()
_STATS: Dict[str, float] = {}
_GAUGES: Dict[str, float] = {}
_TIMERS: Dict[str, "_Timer"] = {}

# quantiles are computed over a bounded ring of recent samples: exact
# for short runs, a sliding-window estimate for long ones — never
# unbounded memory
_TIMER_RING = 1024


class _Timer:
    """One latency histogram. All mutation happens under _LOCK."""

    __slots__ = ("count", "sum", "min", "max", "ring", "idx")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.ring: List[float] = []
        self.idx = 0

    def observe(self, v: float) -> None:
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if len(self.ring) < _TIMER_RING:
            self.ring.append(v)
        else:
            self.ring[self.idx] = v
            self.idx = (self.idx + 1) % _TIMER_RING

    def stats(self) -> Dict[str, float]:
        if not self.count:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "p50": 0.0, "p95": 0.0}
        s = sorted(self.ring)
        n = len(s)

        def q(p: float) -> float:
            return s[min(n - 1, int(p * (n - 1) + 0.5))]

        return {"count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "p50": q(0.50), "p95": q(0.95)}


# ---------------------------------------------------------------------------
# counters — the original STAT registry (API unchanged)
# ---------------------------------------------------------------------------

def stat_add(name: str, value: float = 1.0) -> None:
    with _LOCK:
        _STATS[name] = _STATS.get(name, 0.0) + float(value)


def stat_reset(name: str, value: float = 0.0) -> None:
    with _LOCK:
        _STATS[name] = float(value)


def stat_get(name: str) -> float:
    with _LOCK:
        return _STATS.get(name, 0.0)


def get_float_stats() -> Dict[str, float]:
    """pybind.cc:1664 get_float_stats: snapshot of every registered
    stat."""
    with _LOCK:
        return dict(_STATS)


def get_int_stats() -> Dict[str, int]:
    with _LOCK:
        return {k: int(v) for k, v in _STATS.items()}


# ---------------------------------------------------------------------------
# gauges
# ---------------------------------------------------------------------------

def gauge_set(name: str, value: float) -> None:
    with _LOCK:
        _GAUGES[name] = float(value)


def gauge_get(name: str, default: float = 0.0) -> float:
    with _LOCK:
        return _GAUGES.get(name, default)


# ---------------------------------------------------------------------------
# timers (latency histograms)
# ---------------------------------------------------------------------------

def timer_observe(name: str, value: float) -> None:
    """Record one latency sample (microseconds by convention)."""
    with _LOCK:
        t = _TIMERS.get(name)
        if t is None:
            t = _TIMERS[name] = _Timer()
        t.observe(float(value))


def timer_get(name: str) -> Dict[str, float]:
    """count/sum/min/max/p50/p95 for one timer (zeros when absent)."""
    with _LOCK:
        t = _TIMERS.get(name)
        return t.stats() if t is not None else _Timer().stats()


def observe_many(timers=(), stats=()) -> None:
    """Record several timer samples and counter increments under ONE
    lock acquisition — for hot paths that emit a burst of instruments
    per event (tracing.RequestTrace.finish observes a whole latency
    decomposition at once)."""
    with _LOCK:
        for name, v in timers:
            t = _TIMERS.get(name)
            if t is None:
                t = _TIMERS[name] = _Timer()
            t.observe(float(v))
        for name, v in stats:
            _STATS[name] = _STATS.get(name, 0.0) + float(v)


# ---------------------------------------------------------------------------
# whole-registry export
# ---------------------------------------------------------------------------

def snapshot() -> Dict[str, Dict]:
    """One consistent view of every instrument: a single lock
    acquisition covers all three registries, so a snapshot taken under
    concurrent writers never shows a counter ahead of the timer that
    timed it being updated mid-read."""
    with _LOCK:
        return {
            "counters": dict(_STATS),
            "gauges": dict(_GAUGES),
            "timers": {k: t.stats() for k, t in _TIMERS.items()},
        }


def dump(path: Optional[str] = None) -> str:
    """Serialize snapshot() to JSON; optionally also write it to
    `path` (the format tools/stat_diff.py consumes)."""
    text = json.dumps(snapshot(), sort_keys=True, indent=1)
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    if not out or not (out[0].isalpha() or out[0] == "_"):
        out = "_" + out
    return out


def to_prometheus(prefix: str = "paddle_tpu") -> str:
    """Prometheus text exposition format: counters as `<name>_total`,
    gauges as-is, timers as summaries (`_count`/`_sum` + quantile
    samples). One scrape-able string, same registry as dump()."""
    snap = snapshot()
    lines: List[str] = []
    for name, v in sorted(snap["counters"].items()):
        m = "%s_%s_total" % (prefix, _prom_name(name))
        lines.append("# TYPE %s counter" % m)
        lines.append("%s %.17g" % (m, v))
    for name, v in sorted(snap["gauges"].items()):
        m = "%s_%s" % (prefix, _prom_name(name))
        lines.append("# TYPE %s gauge" % m)
        lines.append("%s %.17g" % (m, v))
    for name, st in sorted(snap["timers"].items()):
        m = "%s_%s" % (prefix, _prom_name(name))
        lines.append("# TYPE %s summary" % m)
        lines.append('%s{quantile="0.5"} %.17g' % (m, st["p50"]))
        lines.append('%s{quantile="0.95"} %.17g' % (m, st["p95"]))
        lines.append("%s_sum %.17g" % (m, st["sum"]))
        lines.append("%s_count %d" % (m, st["count"]))
        # a summary family may only contain {quantile}/_sum/_count
        # samples — strict scrapers reject anything else inside it, so
        # min/max go out as their own gauge families
        lines.append("# TYPE %s_min gauge" % m)
        lines.append("%s_min %.17g" % (m, st["min"] if st["count"] else 0))
        lines.append("# TYPE %s_max gauge" % m)
        lines.append("%s_max %.17g" % (m, st["max"] if st["count"] else 0))
    return "\n".join(lines) + "\n"


def reset_all() -> None:
    """Clear every instrument (bench/test isolation)."""
    with _LOCK:
        _STATS.clear()
        _GAUGES.clear()
        _TIMERS.clear()
