"""Runtime stats registry — the platform monitor analog, grown into a
typed-instrument registry.

Analog of /root/reference/paddle/fluid/platform/monitor.{h,cc} (the
STAT_ADD/STAT_RESET int64 registry) exposed to python as
get_float_stats/get_int_stats (pybind.cc:1664 get_float_stats), extended
with the instrument kinds a runtime that wants to explain its own time
needs (docs/observability.md):

- **counters** — monotonically accumulated floats (`stat_add`). The
  original STAT registry; every legacy call keeps working unchanged.
- **gauges** — last-written values (`gauge_set`): queue depths,
  in-flight windows, cache sizes.
- **timers** — latency histograms (`timer_observe`, microseconds by
  convention, TIMER_* names): count/sum/min/max plus p50/p95 computed
  over a bounded ring of the most recent samples.

`snapshot()` returns all three as one plain dict; `dump()` serializes it
to JSON and `to_prometheus()` to Prometheus text exposition format, so a
bench artifact and a scrape endpoint read the same registry.
Everything is thread-safe and process-global.

    from paddle_tpu.monitor import stat_add, timer_observe, snapshot
    stat_add("STAT_executor_compile", 1)
    timer_observe("TIMER_executor_dispatch_us", 412.0)
    snapshot()  # {"counters": {...}, "gauges": {...}, "timers": {...}}

Well-known counters include STAT_executor_compile (in-memory cache
miss -> trace), STAT_executor_cache_evict (LRU bound hit), and the
persistent AOT program cache set (core/program_cache.py):
STAT_program_cache_trace_hit / _trace_miss / _corrupt / _unexportable
and _bytes_read / _bytes_written.

The async dispatch pipeline (docs/async_pipeline.md) exposes:
- STAT_executor_dispatch: jitted steps dispatched by Executor.run
  (bumped at dispatch, before any fetch is read), and
- STAT_executor_sync: blocking device->host materialization events
  (Executor.run's return_numpy=True conversion, a FetchHandle's first
  read, the fast_check_nan_inf scalar check).
The dispatch/sync ratio is the pipeline's health signal: a loop that
should be dispatch-ahead but shows sync == dispatch has a forced sync
on its hot path, and tests pin the ratio so regressions are visible.

Timer latencies land here when FLAGS_telemetry is on (telemetry.py):
TIMER_executor_compile_us / _dispatch_us / _sync_us,
TIMER_program_cache_load_us / _store_us, TIMER_fetch_sync_us,
TIMER_pipeline_drain_us / _feed_stage_us, TIMER_trainstep_dispatch_us,
TIMER_hapi_epoch_drain_us / _callback_us.

The serving path (docs/serving.md) exposes:
- bucketing: STAT_predictor_bucket_hit / _cold (warm-signature vs
  newly-compiled bucketed calls), _skip / _overflow (calls that
  bypassed bucketing), STAT_predictor_pad_rows / _pad_elements
  (padding waste), STAT_program_cache_warm (warmup_buckets compiles);
- the PredictorPool batcher: STAT_serving_requests / _batches /
  _batched_rows (rows/batches = the amortization factor), _rejected
  (ServingQueueFull backpressure), _batch_errors,
  GAUGE_serving_queue_depth / _last_batch_rows, and the always-on
  TIMER_serving_queue_wait_us / _batch_us histograms (queue wait and
  batch execution are the serving SLO — recorded without
  FLAGS_telemetry, like the program-cache timers).

The generation engine (docs/generation.md) exposes:
- STAT_generation_requests / _prefills / _tokens (throughput),
  _compile (engine-level compilations — the zero-steady-state-
  recompile pin counts THIS standing still), _evictions (pool-pressure
  preemptions), _errors, _rejected (ServingQueueFull backpressure),
  STAT_generation_blocks_allocated / _blocks_freed (KV ledger churn);
- GAUGE_generation_blocks_free / _blocks_used (pool occupancy),
  _active_seqs, _queue_depth;
- always-on TIMER_generation_prefill_us / _decode_step_us /
  _inter_token_us histograms (tokens/s and p95 inter-token latency are
  the generation SLO; bench.py's generation block gates on the
  decode-step p95 via tools/stat_diff.py).

The mesh-native SPMD runtime (paddle_tpu/mesh/, docs/spmd.md)
exposes (always-on, like the serving timers):
- STAT_mesh_placements / STAT_mesh_reshard_bytes: device_put work a
  ShardingPlan actually performed (values already resident with the
  right sharding are skipped) — a steady-state training loop must show
  these standing still, or state is ping-ponging between layouts;
- STAT_mesh_collective_<axis>: collective launches per mesh axis —
  host-level calls (parallel/collective.py: all_reduce/all_gather/
  broadcast/all_to_all outside shard_map) plus TrainStep's explicit
  gradient exchange (counted from its build-time wire manifest), the
  per-axis traffic census the MULTICHIP round artifact records;
- STAT_mesh_collective_bytes{axis,dtype}: payload bytes those
  launches put on the wire, by dtype, under a ring model: each of the
  p ranks forwards (p-1)/p of the payload per ring pass, AllReduce-
  family ops (psum/pmean/pmax) cost two passes, all_gather /
  psum_scatter / all_to_all one. This is the census that proves the
  int8 collective path (mesh/collectives.py) shrank gradient-sync
  bytes ≥3x vs fp32;
- STAT_collective_quant_buckets / _fallbacks and
  GAUGE_collective_quant_buckets / _small / _wire_bytes: quantized-
  collective health — bucket exchanges dispatched, buckets demoted to
  fp32 by the dist.collective_quant failpoint, and the live step's
  bucket geometry (gauges retracted when the step rebuilds with the
  flag off, like every PR-14+ gauge family);
- GAUGE_mesh_devices: device count of the most recently built plan;
- TIMER_mesh_compile_us: walltime of plan.compile()'s first
  (trace+compile) call with explicit in/out shardings.

XLA program accounting (core/program_accounting.py, scraped live via
introspect.py /programz):
- GAUGE_program_flops_<tag> / _bytes_accessed_<tag> / _temp_bytes_<tag>
  / _hbm_bytes_<tag>: per compiled program, captured at compile time
  from compiled.cost_analysis() / memory_analysis();
- GAUGE_programs_count / _hbm_bytes (process-wide compiled HBM
  footprint) / _flops_compiled / _achieved_flops_per_s (FLOPs
  dispatched per wall-second over the process lifetime);
- STAT_program_account_fallback: accounted executions that fell back
  to the plain jitted path (input mismatch — costs one recompile).

Request-lifecycle tracing (tracing.py, /tracez — always-on like the
serving timers, gated by FLAGS_request_tracing):
- stage-decomposition timers observed at trace finish:
  TIMER_serving_admit_us / _batch_join_us / _dispatch_us / _execute_us
  / _fetch_us / _total_us and TIMER_generation_queue_wait_us /
  _decode_us / _total_us — plus TIMER_generation_ttft_us (first token,
  observed once per request) and TIMER_generation_tpot_us (per-decode-
  token deltas), observed inline as tokens arrive;
- STAT_trace_completed / _errored / _nonmonotonic (ordering audit),
  STAT_<kind>_deadline_missed and STAT_<kind>_budget_<stage>_us for
  deadline-armed submits (where deadlined traffic burns its budget);
- GAUGE_tracing_exemplars + GAUGE_trace_exemplar_us_<id> per kept
  slow/errored exemplar (retracted on ring eviction,
  STAT_tracing_exemplar_evict).

The robustness layer (failpoints.py, docs/robustness.md):
- self-healing pools: STAT_serving_restarts / _restart_exhausted and
  STAT_generation_restarts / _restart_exhausted (supervised worker
  restarts and terminal budget exhaustion — tools/stat_diff.py treats
  the whole _shed_/_restart families as cost counters);
- deadline shedding: STAT_serving_shed_at_admit /
  STAT_generation_shed_at_admit (requests whose deadline burned while
  queuing — rejected before any device work);
- crash-safe checkpoints (incubate/checkpoint/atomic.py):
  STAT_checkpoint_saves / _loads / _resumes / _corrupt_fallback and
  TIMER_checkpoint_save_us.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

_LOCK = threading.Lock()
_STATS: Dict[str, float] = {}
_GAUGES: Dict[str, float] = {}
_TIMERS: Dict[str, "_Timer"] = {}

# quantiles are computed over a bounded ring of recent samples: exact
# for short runs, a sliding-window estimate for long ones — never
# unbounded memory
_TIMER_RING = 1024


class _Timer:
    """One latency histogram. All mutation happens under _LOCK."""

    __slots__ = ("count", "sum", "min", "max", "ring", "idx")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.ring: List[float] = []
        self.idx = 0

    def observe(self, v: float) -> None:
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if len(self.ring) < _TIMER_RING:
            self.ring.append(v)
        else:
            self.ring[self.idx] = v
            self.idx = (self.idx + 1) % _TIMER_RING

    def stats(self) -> Dict[str, float]:
        if not self.count:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "ring_min": 0.0, "ring_max": 0.0,
                    "p50": 0.0, "p95": 0.0}
        s = sorted(self.ring)
        n = len(s)

        def q(p: float) -> float:
            return s[min(n - 1, int(p * (n - 1) + 0.5))]

        # min/max are ALL-TIME extremes; p50/p95 come from the ring of
        # the last _TIMER_RING samples. ring_min/ring_max share the
        # ring's time base so one scrape line can be read consistently
        # against the quantiles (pinned by test_telemetry).
        return {"count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "ring_min": s[0], "ring_max": s[-1],
                "p50": q(0.50), "p95": q(0.95)}


# ---------------------------------------------------------------------------
# time-windowed aggregation (docs/observability.md, slo.py)
# ---------------------------------------------------------------------------
#
# All-time counters can't rate and the _Timer ring can't answer "p95
# over the last 5 minutes", so SLO evaluation needs a second, windowed
# view. Multi-resolution on the cheap: every write lands in a
# fixed-duration sub-bucket (default 10s); any window (1m/5m/1h) is
# composed from sub-buckets at READ time, so one write feeds every
# window. Buckets live in sparse bounded deques — an idle instrument
# costs nothing, a busy one is capped at n_buckets entries.
#
# Disabled by default: when _WINDOWS is None the only cost on the hot
# write paths is one attribute load + `is not None` test under the
# already-held _LOCK. slo.py enables this from FLAGS_slo; monitor stays
# flag-free.

# per-bucket sample reservoir for windowed quantiles: deterministic
# overwrite (newest wins) keeps memory bounded without randomness
_WINDOW_RESERVOIR = 64


class _Windows:
    """Sub-bucketed rolling state for every instrument kind.

    Bucket entries (mutated in place while current):
      counters: [bucket_id, sum]
      timers:   [bucket_id, count, sum, min, max, samples]
      gauges:   [bucket_id, last_value]
    All access happens under the registry _LOCK.
    """

    __slots__ = ("bucket_s", "n_buckets", "clock",
                 "counters", "timers", "gauges")

    def __init__(self, bucket_s: float = 10.0, n_buckets: int = 360,
                 clock=None):
        self.bucket_s = float(bucket_s)
        self.n_buckets = int(n_buckets)
        self.clock = clock if clock is not None else time.monotonic
        self.counters: Dict[str, deque] = {}
        self.timers: Dict[str, deque] = {}
        self.gauges: Dict[str, deque] = {}

    def _bid(self) -> int:
        return int(self.clock() / self.bucket_s)

    def record_counter(self, name: str, v: float) -> None:
        bid = self._bid()
        dq = self.counters.get(name)
        if dq is None:
            dq = self.counters[name] = deque(maxlen=self.n_buckets)
        if dq and dq[-1][0] == bid:
            dq[-1][1] += v
        else:
            dq.append([bid, v])

    def record_timer(self, name: str, v: float) -> None:
        bid = self._bid()
        dq = self.timers.get(name)
        if dq is None:
            dq = self.timers[name] = deque(maxlen=self.n_buckets)
        if dq and dq[-1][0] == bid:
            e = dq[-1]
            e[1] += 1
            e[2] += v
            if v < e[3]:
                e[3] = v
            if v > e[4]:
                e[4] = v
            if len(e[5]) < _WINDOW_RESERVOIR:
                e[5].append(v)
            else:
                e[5][e[1] % _WINDOW_RESERVOIR] = v
        else:
            dq.append([bid, 1, v, v, v, [v]])

    def record_gauge(self, name: str, v: float) -> None:
        bid = self._bid()
        dq = self.gauges.get(name)
        if dq is None:
            dq = self.gauges[name] = deque(maxlen=self.n_buckets)
        if dq and dq[-1][0] == bid:
            dq[-1][1] = v
        else:
            dq.append([bid, v])

    def _min_bid(self, window_s: float, now: float) -> int:
        # include buckets whose start lies within (now - window_s, now]
        return int((now - window_s) / self.bucket_s) + 1


_WINDOWS: Optional[_Windows] = None


def enable_windows(bucket_s: float = 10.0, n_buckets: int = 360,
                   clock=None) -> None:
    """Turn on windowed aggregation (idempotent for same config;
    reconfiguring discards accumulated window state)."""
    global _WINDOWS
    with _LOCK:
        w = _WINDOWS
        if w is not None and w.bucket_s == float(bucket_s) \
                and w.n_buckets == int(n_buckets) and clock is None:
            return
        _WINDOWS = _Windows(bucket_s, n_buckets, clock)


def disable_windows() -> None:
    global _WINDOWS
    with _LOCK:
        _WINDOWS = None


def windows_enabled() -> bool:
    return _WINDOWS is not None


def window_config() -> Optional[Dict[str, float]]:
    with _LOCK:
        w = _WINDOWS
        if w is None:
            return None
        return {"bucket_s": w.bucket_s, "n_buckets": w.n_buckets,
                "span_s": w.bucket_s * w.n_buckets}


def counter_window_sum(name: str, window_s: float,
                       now: Optional[float] = None) -> float:
    """Sum of a counter's increments over the trailing window (0.0 when
    windows are disabled or the counter never fired in-window)."""
    with _LOCK:
        w = _WINDOWS
        if w is None:
            return 0.0
        dq = w.counters.get(name)
        if not dq:
            return 0.0
        t = w.clock() if now is None else now
        lo = w._min_bid(window_s, t)
        return float(sum(e[1] for e in dq if e[0] >= lo))


def counter_rate(name: str, window_s: float,
                 now: Optional[float] = None) -> float:
    """Per-second rate of a counter over the trailing window — QPS,
    error rate, shed rate. 0.0 when windows are disabled."""
    with _LOCK:
        w = _WINDOWS
        if w is None:
            return 0.0
        dq = w.counters.get(name)
        if not dq:
            return 0.0
        t = w.clock() if now is None else now
        lo = w._min_bid(window_s, t)
        total = sum(e[1] for e in dq if e[0] >= lo)
        elapsed = max(t - lo * w.bucket_s, w.bucket_s)
        return float(total) / elapsed


def timer_window(name: str, window_s: float,
                 now: Optional[float] = None) -> Dict[str, float]:
    """count/sum/min/max/p50/p95 merged over the trailing window's
    sub-buckets (quantiles estimated from the per-bucket reservoirs).
    All-zero when windows are disabled or no samples landed."""
    zero = {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
            "p50": 0.0, "p95": 0.0}
    with _LOCK:
        w = _WINDOWS
        if w is None:
            return zero
        dq = w.timers.get(name)
        if not dq:
            return zero
        t = w.clock() if now is None else now
        lo = w._min_bid(window_s, t)
        count, total = 0, 0.0
        mn, mx = float("inf"), float("-inf")
        samples: List[float] = []
        for e in dq:
            if e[0] < lo:
                continue
            count += e[1]
            total += e[2]
            if e[3] < mn:
                mn = e[3]
            if e[4] > mx:
                mx = e[4]
            samples.extend(e[5])
        if not count:
            return zero
        samples.sort()
        n = len(samples)

        def q(p: float) -> float:
            return samples[min(n - 1, int(p * (n - 1) + 0.5))]

        return {"count": count, "sum": total, "min": mn, "max": mx,
                "p50": q(0.50), "p95": q(0.95)}


def timer_window_frac_le(name: str, threshold: float, window_s: float,
                         now: Optional[float] = None) -> Optional[float]:
    """Estimated fraction of in-window samples <= threshold — the
    good-ratio a latency SLO reads. Per-bucket reservoir fractions are
    weighted by true bucket counts. None when windows are disabled or
    no samples landed in-window."""
    with _LOCK:
        w = _WINDOWS
        if w is None:
            return None
        dq = w.timers.get(name)
        if not dq:
            return None
        t = w.clock() if now is None else now
        lo = w._min_bid(window_s, t)
        total, good = 0, 0.0
        for e in dq:
            if e[0] < lo or not e[5]:
                continue
            total += e[1]
            frac = sum(1 for s in e[5] if s <= threshold) / len(e[5])
            good += frac * e[1]
        if not total:
            return None
        return good / total


def gauge_trend(name: str, window_s: float,
                now: Optional[float] = None) -> float:
    """Per-second slope of a gauge over the trailing window — (last −
    first)/dt across in-window buckets. 0.0 when windows are disabled
    or fewer than two in-window buckets exist (no trend computable)."""
    with _LOCK:
        w = _WINDOWS
        if w is None:
            return 0.0
        dq = w.gauges.get(name)
        if not dq:
            return 0.0
        t = w.clock() if now is None else now
        lo = w._min_bid(window_s, t)
        ent = [e for e in dq if e[0] >= lo]
        if len(ent) < 2:
            return 0.0
        dt = (ent[-1][0] - ent[0][0]) * w.bucket_s
        return (ent[-1][1] - ent[0][1]) / dt if dt else 0.0


# ---------------------------------------------------------------------------
# labels — per-tenant / per-model series in one family
# ---------------------------------------------------------------------------

def labeled(name: str, labels: Dict[str, str]) -> str:
    """Compose a labeled series name, Prometheus-style:
    labeled("STAT_serving_requests", {"tenant": "acme"}) ->
    'STAT_serving_requests{tenant="acme"}'. The composed string is an
    ordinary registry key — stat_add/timer_observe/observe_many take it
    unchanged — and to_prometheus() groups all series of one family
    under a single # TYPE line. Label keys sort so the same label set
    always composes the same key; values are escaped per the
    exposition format."""
    if not labels:
        return name
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", "\\\\") \
            .replace('"', '\\"').replace("\n", "\\n")
        parts.append('%s="%s"' % (k, v))
    return "%s{%s}" % (name, ",".join(parts))


def _split_series(name: str) -> Tuple[str, str]:
    """Split a (possibly labeled) registry key into (family,
    label_block) — label_block keeps its braces, '' when unlabeled."""
    i = name.find("{")
    if i < 0:
        return name, ""
    return name[:i], name[i:]


# ---------------------------------------------------------------------------
# counters — the original STAT registry (API unchanged)
# ---------------------------------------------------------------------------

def stat_add(name: str, value: float = 1.0) -> None:
    with _LOCK:
        _STATS[name] = _STATS.get(name, 0.0) + float(value)
        w = _WINDOWS
        if w is not None:
            w.record_counter(name, float(value))


def stat_reset(name: str, value: float = 0.0) -> None:
    with _LOCK:
        _STATS[name] = float(value)


def stat_get(name: str) -> float:
    with _LOCK:
        return _STATS.get(name, 0.0)


def get_float_stats() -> Dict[str, float]:
    """pybind.cc:1664 get_float_stats: snapshot of every registered
    stat."""
    with _LOCK:
        return dict(_STATS)


def get_int_stats() -> Dict[str, int]:
    with _LOCK:
        return {k: int(v) for k, v in _STATS.items()}


# ---------------------------------------------------------------------------
# gauges
# ---------------------------------------------------------------------------

def gauge_set(name: str, value: float) -> None:
    with _LOCK:
        _GAUGES[name] = float(value)
        w = _WINDOWS
        if w is not None:
            w.record_gauge(name, float(value))


def gauge_get(name: str, default: float = 0.0) -> float:
    with _LOCK:
        return _GAUGES.get(name, default)


def gauge_retract(*names: str) -> int:
    """Remove gauges from the registry (and /metrics) by exact name.

    Gauges normally only accrete; retraction is for lifecycle events
    where a series must STOP being exported rather than freeze at its
    last value — e.g. slo.py retiring a front-door endpoint's objective
    gauges, or pools resetting per-request KV gauges. Returns how many
    of the given names were present and removed.
    """
    removed = 0
    with _LOCK:
        for n in names:
            if _GAUGES.pop(n, None) is not None:
                removed += 1
    return removed


# ---------------------------------------------------------------------------
# timers (latency histograms)
# ---------------------------------------------------------------------------

def timer_observe(name: str, value: float) -> None:
    """Record one latency sample (microseconds by convention)."""
    with _LOCK:
        t = _TIMERS.get(name)
        if t is None:
            t = _TIMERS[name] = _Timer()
        t.observe(float(value))
        w = _WINDOWS
        if w is not None:
            w.record_timer(name, float(value))


def timer_get(name: str) -> Dict[str, float]:
    """count/sum/min/max/p50/p95 for one timer (zeros when absent)."""
    with _LOCK:
        t = _TIMERS.get(name)
        return t.stats() if t is not None else _Timer().stats()


def observe_many(timers=(), stats=()) -> None:
    """Record several timer samples and counter increments under ONE
    lock acquisition — for hot paths that emit a burst of instruments
    per event (tracing.RequestTrace.finish observes a whole latency
    decomposition at once)."""
    with _LOCK:
        w = _WINDOWS
        for name, v in timers:
            t = _TIMERS.get(name)
            if t is None:
                t = _TIMERS[name] = _Timer()
            t.observe(float(v))
            if w is not None:
                w.record_timer(name, float(v))
        for name, v in stats:
            _STATS[name] = _STATS.get(name, 0.0) + float(v)
            if w is not None:
                w.record_counter(name, float(v))


# ---------------------------------------------------------------------------
# whole-registry export
# ---------------------------------------------------------------------------

def snapshot() -> Dict[str, Dict]:
    """One consistent view of every instrument: a single lock
    acquisition covers all three registries, so a snapshot taken under
    concurrent writers never shows a counter ahead of the timer that
    timed it being updated mid-read."""
    with _LOCK:
        return {
            "counters": dict(_STATS),
            "gauges": dict(_GAUGES),
            "timers": {k: t.stats() for k, t in _TIMERS.items()},
        }


def dump(path: Optional[str] = None) -> str:
    """Serialize snapshot() to JSON; optionally also write it to
    `path` (the format tools/stat_diff.py consumes)."""
    text = json.dumps(snapshot(), sort_keys=True, indent=1)
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    if not out or not (out[0].isalpha() or out[0] == "_"):
        out = "_" + out
    return out


def _group_families(series: Dict) -> List[Tuple[str, List[Tuple[str, object]]]]:
    """Group (possibly labeled) registry keys by family: returns
    [(family, [(label_block, value), ...])] with families sorted and
    each family's label blocks sorted — labeled series don't sort
    adjacent to their base name, so explicit grouping keeps every
    family's samples contiguous under one # TYPE line."""
    fams: Dict[str, List[Tuple[str, object]]] = {}
    for name, v in series.items():
        fam, lbl = _split_series(name)
        fams.setdefault(fam, []).append((lbl, v))
    return [(f, sorted(fams[f])) for f in sorted(fams)]


def _merge_label(lbl: str, extra: str) -> str:
    """Merge one extra label into an existing label block:
    '{tenant="a"}' + 'quantile="0.5"' -> '{tenant="a",quantile="0.5"}'."""
    if not lbl:
        return "{%s}" % extra
    return lbl[:-1] + "," + extra + "}"


def to_prometheus(prefix: str = "paddle_tpu") -> str:
    """Prometheus text exposition format: counters as `<name>_total`,
    gauges as-is, timers as summaries (`_count`/`_sum` + quantile
    samples). Labeled series (see labeled()) render as label blocks on
    their family's samples, one # TYPE per family. One scrape-able
    string, same registry as dump()."""
    snap = snapshot()
    lines: List[str] = []
    for fam, entries in _group_families(snap["counters"]):
        m = "%s_%s_total" % (prefix, _prom_name(fam))
        lines.append("# TYPE %s counter" % m)
        for lbl, v in entries:
            lines.append("%s%s %.17g" % (m, lbl, v))
    for fam, entries in _group_families(snap["gauges"]):
        m = "%s_%s" % (prefix, _prom_name(fam))
        lines.append("# TYPE %s gauge" % m)
        for lbl, v in entries:
            lines.append("%s%s %.17g" % (m, lbl, v))
    timer_fams = _group_families(snap["timers"])
    for fam, entries in timer_fams:
        m = "%s_%s" % (prefix, _prom_name(fam))
        lines.append("# TYPE %s summary" % m)
        for lbl, st in entries:
            lines.append("%s%s %.17g"
                         % (m, _merge_label(lbl, 'quantile="0.5"'),
                            st["p50"]))
            lines.append("%s%s %.17g"
                         % (m, _merge_label(lbl, 'quantile="0.95"'),
                            st["p95"]))
            lines.append("%s_sum%s %.17g" % (m, lbl, st["sum"]))
            lines.append("%s_count%s %d" % (m, lbl, st["count"]))
    # a summary family may only contain {quantile}/_sum/_count
    # samples — strict scrapers reject anything else inside it, so
    # min/max (all-time) and ring_min/ring_max (quantile window) go
    # out as their own gauge families
    for suffix, key in (("min", "min"), ("max", "max"),
                        ("ring_min", "ring_min"), ("ring_max", "ring_max")):
        for fam, entries in timer_fams:
            m = "%s_%s_%s" % (prefix, _prom_name(fam), suffix)
            lines.append("# TYPE %s gauge" % m)
            for lbl, st in entries:
                lines.append("%s%s %.17g"
                             % (m, lbl, st[key] if st["count"] else 0))
    return "\n".join(lines) + "\n"


def reset_all() -> None:
    """Clear every instrument (bench/test isolation). Window state is
    cleared too but the window configuration survives."""
    with _LOCK:
        _STATS.clear()
        _GAUGES.clear()
        _TIMERS.clear()
        w = _WINDOWS
        if w is not None:
            w.counters.clear()
            w.timers.clear()
            w.gauges.clear()
