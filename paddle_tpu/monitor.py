"""Runtime stats registry — the platform monitor analog.

Analog of /root/reference/paddle/fluid/platform/monitor.{h,cc} (the
STAT_ADD/STAT_RESET int64 registry) exposed to python as
get_float_stats/get_int_stats (pybind.cc:1664 get_float_stats). Stats
are named counters any subsystem bumps (executor compiles, host-op
dispatches, bytes fed); thread-safe, process-global.

    from paddle_tpu.monitor import stat_add, get_float_stats
    stat_add("STAT_executor_compile", 1)
    get_float_stats()  # {"STAT_executor_compile": 1.0, ...}

Well-known counters include STAT_executor_compile (in-memory cache
miss -> trace), STAT_executor_cache_evict (LRU bound hit), and the
persistent AOT program cache set (core/program_cache.py):
STAT_program_cache_trace_hit / _trace_miss / _corrupt / _unexportable
and _bytes_read / _bytes_written.

The async dispatch pipeline (docs/async_pipeline.md) exposes:
- STAT_executor_dispatch: jitted steps dispatched by Executor.run
  (bumped at dispatch, before any fetch is read), and
- STAT_executor_sync: blocking device->host materialization events
  (Executor.run's return_numpy=True conversion, a FetchHandle's first
  read, the fast_check_nan_inf scalar check).
The dispatch/sync ratio is the pipeline's health signal: a loop that
should be dispatch-ahead but shows sync == dispatch has a forced sync
on its hot path, and tests pin the ratio so regressions are visible.
"""
from __future__ import annotations

import threading
from typing import Dict

_LOCK = threading.Lock()
_STATS: Dict[str, float] = {}


def stat_add(name: str, value: float = 1.0) -> None:
    with _LOCK:
        _STATS[name] = _STATS.get(name, 0.0) + float(value)


def stat_reset(name: str, value: float = 0.0) -> None:
    with _LOCK:
        _STATS[name] = float(value)


def stat_get(name: str) -> float:
    with _LOCK:
        return _STATS.get(name, 0.0)


def get_float_stats() -> Dict[str, float]:
    """pybind.cc:1664 get_float_stats: snapshot of every registered
    stat."""
    with _LOCK:
        return dict(_STATS)


def get_int_stats() -> Dict[str, int]:
    with _LOCK:
        return {k: int(v) for k, v in _STATS.items()}
