"""Request-lifecycle tracing: where did THIS request's time go?

PR 3/7 built the aggregate half of observability — every counter,
timer, and chrome-trace lane describes the process. This module is the
per-request half, the tracing subsystem the TensorFlow lineage treats
as first-class (arXiv:1605.08695 §5) and the metric set TPU serving
deployments are actually judged on (TTFT / TPOT / queue-wait
decomposition — the Gemma-on-TPU serving comparison in PAPERS.md):

- :class:`RequestTrace` — a process-unique trace id plus monotonic
  stage timestamps (serving: submit → admit → batch_join → dispatch →
  execute → fetch → done; generation: submit → admit → prefill_start →
  first_token → done, with per-decode-token deltas and
  preemption/replay events). Created by ``begin(kind)`` at the pool
  front door (serving.PredictorPool.submit / GenerationPool.submit /
  GenerationEngine.submit) and carried on the request through every
  layer; telemetry spans executed under :func:`telemetry.trace_scope`
  carry the id into the chrome trace, and errored requests land in the
  flight recorder keyed ``req:<trace_id>``.
- latency-decomposition timers — ``finish()`` observes one monitor
  histogram per stage interval (TIMER_serving_admit_us /
  _batch_join_us / _dispatch_us / _execute_us / _fetch_us / _total_us,
  TIMER_generation_queue_wait_us / _ttft_us / _tpot_us / _decode_us /
  _total_us), so /metrics exports the same decomposition /tracez shows
  per exemplar.
- deadlines — ``begin(kind, deadline=seconds)`` arms a latency budget:
  ``finish()`` bumps STAT_<kind>_deadline_missed when the budget is
  blown and accumulates per-stage budget burn into
  STAT_<kind>_budget_<stage>_us counters (where deadlined traffic
  spends its budget is the signal SLO-aware scheduling needs).
- exemplar ring — a bounded registry keeping the N slowest plus every
  errored/deadline-missed request with full timeline, events, and a
  flight-recorder slice. Per-exemplar gauges
  (GAUGE_trace_exemplar_us_<id>) are retracted on eviction, like
  core/program_accounting.py's registry bound.
- ``/tracez`` (introspect.py) — recent completions + exemplars +
  rolling TTFT/TPOT, text or ``?format=json``.

Gate: ``FLAGS_request_tracing`` (default ON — tracing is how serving
explains itself; bench.py measures the enabled overhead under 1% on
the serving workload). The disabled path is ONE flag lookup:
``begin()`` returns the shared :data:`NOOP_TRACE`, whose methods are
no-ops, so threaded code never branches and never re-reads the flag.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

import itertools

from .flags import get_flag
from .monitor import (gauge_set, labeled, observe_many, stat_add,
                      timer_get, timer_observe)

__all__ = ["RequestTrace", "NOOP_TRACE", "begin", "recent", "exemplars",
           "tracez", "tracez_text", "reset"]

_LOCK = threading.Lock()
# trace ids without a lock: next() on itertools.count is atomic in
# CPython, and begin() sits on the request hot path
_NEXT_ID = itertools.count(1)

# recently completed traces (summaries), newest last — the /tracez
# "recent" table. Bounded; the exemplar ring below is what keeps the
# interesting ones beyond this horizon.
_RECENT_CAP = 128
_RECENT: deque = deque(maxlen=_RECENT_CAP)

# exemplar ring: trace_id -> full record. Bounded by
# FLAGS_tracing_exemplars; eviction retracts the exemplar's gauge.
_EXEMPLARS: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()

# cached admission floor: the smallest total_us among kept CLEAN
# exemplars, or None when it must be rescanned. Keeps the steady-state
# finish() path (ring full, request faster than every kept exemplar) at
# one float comparison instead of an O(cap) scan per request.
_CLEAN_FLOOR: List[Optional[float]] = [None]

# stage-interval decomposition per kind: (label, from_stage, to_stage).
# finish() observes TIMER_<kind>_<label>_us for every interval whose
# stages both happened (retries/replays use the LAST occurrence), and
# mirrors the same intervals into STAT_<kind>_budget_<label>_us
# counters for deadline-armed traces. TTFT/TPOT are observed inline by
# token() — sampling them at finish would misdate a long decode.
_DECOMP: Dict[str, Tuple[Tuple[str, str, str], ...]] = {
    "serving": (
        ("admit", "submit", "admit"),
        ("batch_join", "admit", "batch_join"),
        ("dispatch", "batch_join", "dispatch"),
        ("execute", "dispatch", "execute"),
        ("fetch", "execute", "fetch"),
        ("total", "submit", "done"),
    ),
    "generation": (
        ("queue_wait", "submit", "prefill_start"),
        ("decode", "first_token", "done"),
        ("total", "submit", "done"),
    ),
}

# instrument names are precomputed per kind — finish() runs once per
# request and should not pay %-formatting for every interval
_DECOMP_NAMES: Dict[str, Tuple[Tuple[str, str, str, str], ...]] = {
    kind: tuple(("TIMER_%s_%s_us" % (kind, label),
                 "STAT_%s_budget_%s_us" % (kind, label), frm, to)
                for label, frm, to in rows)
    for kind, rows in _DECOMP.items()
}
_TTFT_TIMER = {k: "TIMER_%s_ttft_us" % k for k in _DECOMP}
_TPOT_TIMER = {k: "TIMER_%s_tpot_us" % k for k in _DECOMP}

# per-tenant attribution (docs/observability.md, slo.py): labeled
# instrument names are precomputed per (kind, tenant) — label
# composition costs string work finish() should pay once per tenant,
# not once per request. Distinct tenants are capped: past the cap, new
# tenants collapse into "__other__" so a tenant-id typo can't grow the
# registry without bound (the standard label-cardinality defense).
_TENANT_CAP = 64
_TENANT_OVERFLOW = "__other__"
_TENANT_NAMES: Dict[Tuple[str, str],
                    Tuple[str, str, str, str, str]] = {}
_TENANT_SEEN: set = set()


def _tenant_names(kind: str,
                  tenant: str) -> Tuple[str, str, str, str, str]:
    orig_key = (kind, tenant)
    got = _TENANT_NAMES.get(orig_key)
    if got is not None:
        return got
    with _LOCK:
        got = _TENANT_NAMES.get(orig_key)
        if got is not None:
            return got
        if tenant != _TENANT_OVERFLOW and tenant not in _TENANT_SEEN \
                and len(_TENANT_SEEN) >= _TENANT_CAP:
            stat_add("STAT_tracing_tenant_overflow")
            tenant = _TENANT_OVERFLOW
        _TENANT_SEEN.add(tenant)
        key = (kind, tenant)
        got = _TENANT_NAMES.get(key)
        if got is None:
            lbl = {"tenant": tenant}
            got = (labeled("TIMER_%s_total_us" % kind, lbl),
                   labeled("TIMER_%s_ttft_us" % kind, lbl),
                   labeled("STAT_%s_requests" % kind, lbl),
                   labeled("STAT_%s_errors" % kind, lbl),
                   labeled("STAT_%s_deadline_missed" % kind, lbl))
            _TENANT_NAMES[key] = got
        # overflowed tenants cache under their ORIGINAL key too, so the
        # next request from the same tenant is one dict hit again
        _TENANT_NAMES[orig_key] = got
        return got


# per-model attribution (frontdoor.py, docs/frontdoor.md): when the
# front door routes a request into a pool it stamps model/version on
# the trace, and finish() flushes {model,version[,tenant]}-labeled
# series alongside the decomposition — same cached-name + capped
# cardinality scheme as _tenant_names. The cap bounds distinct
# (model, version, tenant) label sets; past it new combinations
# collapse into model="__other__".
_MODEL_CAP = 64
_MODEL_NAMES: Dict[Tuple[str, str, str, str],
                   Tuple[str, str, str, str]] = {}


def _model_names(kind: str, model: str, version: str,
                 tenant: str) -> Tuple[str, str, str, str]:
    orig_key = (kind, model, version, tenant)
    got = _MODEL_NAMES.get(orig_key)
    if got is not None:
        return got
    with _LOCK:
        got = _MODEL_NAMES.get(orig_key)
        if got is not None:
            return got
        if model != _TENANT_OVERFLOW and len(_MODEL_NAMES) >= _MODEL_CAP:
            stat_add("STAT_tracing_model_overflow")
            model, version, tenant = _TENANT_OVERFLOW, "", ""
        key = (kind, model, version, tenant)
        got = _MODEL_NAMES.get(key)
        if got is None:
            lbl = {"model": model, "version": version}
            if tenant:
                lbl["tenant"] = tenant
            got = (labeled("TIMER_%s_total_us" % kind, lbl),
                   labeled("STAT_%s_requests" % kind, lbl),
                   labeled("STAT_%s_errors" % kind, lbl),
                   labeled("STAT_%s_deadline_missed" % kind, lbl))
            _MODEL_NAMES[key] = got
        _MODEL_NAMES[orig_key] = got
        return got


class _NoopTrace:
    """Shared do-nothing trace: what ``begin()`` returns with
    FLAGS_request_tracing off. Callers thread it exactly like a real
    trace — no None-guards, no second flag lookup anywhere."""

    __slots__ = ()
    trace_id = None
    deadline_s = None
    tenant = None
    model = None
    version = None

    def stage(self, name: str) -> None:
        pass

    def event(self, name: str, **fields: Any) -> None:
        pass

    def token(self) -> None:
        pass

    def note(self, **fields: Any) -> None:
        pass

    def finish(self, error: Optional[BaseException] = None,
               **fields: Any) -> None:
        pass

    def last_stage(self) -> Optional[str]:
        return None


NOOP_TRACE = _NoopTrace()


class RequestTrace:
    """One request's lifecycle: monotonic stage timestamps, token
    timing, events, and an optional latency budget. NOT thread-safe by
    itself — the pools hand a request (and its trace) between threads
    through locked queues, so every touch is ordered by a
    happens-before edge already."""

    __slots__ = ("trace_id", "kind", "t0", "deadline_s", "tenant",
                 "model", "version", "stages", "events", "tokens",
                 "t_first_token", "t_last_token", "fields", "error",
                 "_done", "_total_us", "_missed")

    def __init__(self, trace_id: str, kind: str,
                 deadline: Optional[float] = None,
                 tenant: Optional[str] = None,
                 model: Optional[str] = None,
                 version: Optional[str] = None):
        now = time.monotonic()
        self.trace_id = trace_id
        self.kind = kind
        self.t0 = now
        self.deadline_s = None if deadline is None else float(deadline)
        self.tenant = tenant
        self.model = model
        self.version = version
        self.stages: List[Tuple[str, float]] = [("submit", now)]
        self.events: List[Dict[str, Any]] = []
        self.tokens = 0
        self.t_first_token: Optional[float] = None
        self.t_last_token: Optional[float] = None
        self.fields: Dict[str, Any] = {}
        self.error: Optional[str] = None
        self._done = False
        self._total_us = 0.0
        self._missed = False

    # --- recording ----------------------------------------------------

    def stage(self, name: str) -> None:
        """Timestamp one lifecycle stage (monotonic clock — the same
        clock every deadline computation uses)."""
        self.stages.append((name, time.monotonic()))

    def event(self, name: str, **fields: Any) -> None:
        """Record a non-stage occurrence (preempt, replay, retry)."""
        e = {"name": name, "t_us": (time.monotonic() - self.t0) * 1e6}
        e.update(fields)
        self.events.append(e)

    def token(self) -> None:
        """One generated token: the first records the ``first_token``
        stage and TTFT; every later one records a TPOT delta."""
        now = time.monotonic()
        self.tokens += 1
        if self.t_first_token is None:
            self.t_first_token = now
            self.stages.append(("first_token", now))
            timer_observe(_TTFT_TIMER.get(self.kind)
                          or "TIMER_%s_ttft_us" % self.kind,
                          (now - self.t0) * 1e6)
        else:
            timer_observe(_TPOT_TIMER.get(self.kind)
                          or "TIMER_%s_tpot_us" % self.kind,
                          (now - self.t_last_token) * 1e6)
        self.t_last_token = now

    def note(self, **fields: Any) -> None:
        """Attach free-form metadata (rows, finish_reason, ...)."""
        self.fields.update(fields)

    def last_stage(self) -> Optional[str]:
        return self.stages[-1][0] if self.stages else None

    # --- completion ---------------------------------------------------

    def finish(self, error: Optional[BaseException] = None,
               **fields: Any) -> None:
        """Close the trace (idempotent): records ``done``, observes the
        per-stage decomposition timers, burns the deadline budget, and
        files the trace into the recent + exemplar rings."""
        if self._done:
            return
        self._done = True
        if fields:
            self.fields.update(fields)
        if error is not None:
            self.error = repr(error)
        now = time.monotonic()
        if self.stages[-1][0] != "done":
            self.stages.append(("done", now))
        total_us = (self.stages[-1][1] - self.t0) * 1e6
        # one batched monitor flush below: the whole decomposition plus
        # the completion counters go in under a single registry lock
        timers: List[Tuple[str, float]] = []
        stats: List[Tuple[str, float]] = [("STAT_trace_completed", 1.0)]
        # monotonic-ordering audit: stage appends are ordered by the
        # pool locks, so a violation means a real threading bug
        ts = [t for _, t in self.stages]
        if any(b < a for a, b in zip(ts, ts[1:])):
            stats.append(("STAT_trace_nonmonotonic", 1.0))
        # last-occurrence stage index (retries/replays re-stamp)
        at = {name: t for name, t in self.stages}
        deadlined = self.deadline_s is not None
        for timer, budget, frm, to in _DECOMP_NAMES.get(self.kind, ()):
            if frm in at and to in at and at[to] >= at[frm]:
                dur_us = (at[to] - at[frm]) * 1e6
                timers.append((timer, dur_us))
                if deadlined:
                    stats.append((budget, dur_us))
        self._total_us = total_us
        self._missed = deadlined and (now - self.t0) > self.deadline_s
        if self._missed:
            stats.append(("STAT_%s_deadline_missed" % self.kind, 1.0))
        if self.error is not None:
            stats.append(("STAT_trace_errored", 1.0))
        if self.tenant:
            # per-tenant attribution: the labeled series join the SAME
            # single observe_many flush as the decomposition
            tn = _tenant_names(self.kind, self.tenant)
            timers.append((tn[0], total_us))
            if self.t_first_token is not None:
                timers.append((tn[1],
                               (self.t_first_token - self.t0) * 1e6))
            stats.append((tn[2], 1.0))
            if self.error is not None:
                stats.append((tn[3], 1.0))
            if self._missed:
                stats.append((tn[4], 1.0))
        if self.model:
            # per-model/version attribution (front-door routing): same
            # flush, cached labeled names (see _model_names)
            mn = _model_names(self.kind, self.model,
                              self.version or "", self.tenant or "")
            timers.append((mn[0], total_us))
            stats.append((mn[1], 1.0))
            if self.error is not None:
                stats.append((mn[2], 1.0))
            if self._missed:
                stats.append((mn[3], 1.0))
        observe_many(timers, stats)
        if self.error is not None:
            # errored requests join the flight recorder keyed by trace
            # id, so /flightz and exception notes can correlate them
            from . import telemetry
            telemetry.flight_begin("req:%s" % self.trace_id,
                                   kind=self.kind, error=self.error,
                                   total_us=round(total_us, 1))
        _file(self)

    def _record(self) -> Dict[str, Any]:
        """Build the display/JSON record. Deliberately NOT called on
        the finish() hot path — the rings keep the trace object and
        format lazily when /tracez or recent() actually reads it."""
        rec = {
            "trace_id": self.trace_id,
            "kind": self.kind,
            "total_us": round(self._total_us, 1),
            "stages": [(name, round((t - self.t0) * 1e6, 1))
                       for name, t in self.stages],
            "error": self.error,
        }
        if self.tenant:
            rec["tenant"] = self.tenant
        if self.model:
            rec["model"] = self.model
            if self.version:
                rec["version"] = self.version
        if self.events:
            rec["events"] = list(self.events)
        if self.tokens:
            rec["tokens"] = self.tokens
            if self.t_first_token is not None:
                rec["ttft_us"] = round(
                    (self.t_first_token - self.t0) * 1e6, 1)
        if self.deadline_s is not None:
            rec["deadline_us"] = round(self.deadline_s * 1e6, 1)
            rec["deadline_missed"] = self._missed
        if self.fields:
            rec["fields"] = dict(self.fields)
        return rec


def begin(kind: str, deadline: Optional[float] = None,
          tenant: Optional[str] = None, model: Optional[str] = None,
          version: Optional[str] = None):
    """Open a trace for one request. THE disabled fast path: exactly
    one flag lookup, returning the shared no-op trace. ``deadline`` is
    a latency budget in seconds from now (monotonic); ``tenant`` routes
    the request's counters/timers into labeled per-tenant series at
    finish (capped cardinality, see _tenant_names); ``model``/
    ``version`` do the same for {model,version}-labeled series when the
    request arrived through the serving front door (frontdoor.py)."""
    if not get_flag("FLAGS_request_tracing"):
        return NOOP_TRACE
    return RequestTrace("t%06d" % next(_NEXT_ID), kind,
                        deadline=deadline, tenant=tenant,
                        model=model, version=version)


# ---------------------------------------------------------------------------
# rings: recent completions + slow/errored exemplars
# ---------------------------------------------------------------------------

def _exemplar_cap() -> int:
    try:
        return max(1, int(get_flag("FLAGS_tracing_exemplars", 32) or 32))
    except (TypeError, ValueError):
        return 32


def _file(tr: RequestTrace) -> None:
    """File one finished trace: always into the recent ring; into the
    exemplar ring when errored/deadline-missed or while it ranks among
    the slowest. Eviction drops the fastest clean exemplar first
    (errored ones persist until only errored remain, then oldest-first)
    and retracts its gauge — totals stay honest, like
    program_accounting."""
    cap = _exemplar_cap()
    with _LOCK:
        _RECENT.append(tr)
        interesting = tr.error is not None or tr._missed
        if not interesting and len(_EXEMPLARS) >= cap:
            if _CLEAN_FLOOR[0] is None:
                clean = [r["total_us"] for r in _EXEMPLARS.values()
                         if r["error"] is None
                         and not r.get("deadline_missed")]
                _CLEAN_FLOOR[0] = min(clean) if clean else -1.0
            if 0.0 <= _CLEAN_FLOOR[0] and \
                    _CLEAN_FLOOR[0] >= tr._total_us:
                return  # faster than every kept clean exemplar
        rec = tr._record()  # admitted: now pay for the full record
        if interesting:
            # a flight-recorder slice makes the exemplar
            # self-contained even after the deque scrolls
            from . import telemetry
            rec["flight"] = telemetry.flight_records()[-6:]
        _EXEMPLARS[rec["trace_id"]] = rec
        _CLEAN_FLOOR[0] = None  # membership changed: rescan lazily
        gauge_set("GAUGE_trace_exemplar_us_%s" % rec["trace_id"],
                  rec["total_us"])
        while len(_EXEMPLARS) > cap:
            _evict_locked()
        gauge_set("GAUGE_tracing_exemplars", float(len(_EXEMPLARS)))


def _evict_locked() -> None:
    victim = None
    for tid, r in _EXEMPLARS.items():
        if r["error"] is None and not r.get("deadline_missed"):
            if victim is None or r["total_us"] \
                    < _EXEMPLARS[victim]["total_us"]:
                victim = tid
    if victim is None:  # all errored: oldest goes
        victim = next(iter(_EXEMPLARS))
    _EXEMPLARS.pop(victim)
    _CLEAN_FLOOR[0] = None
    from .monitor import _GAUGES, _LOCK as _MLOCK
    with _MLOCK:
        _GAUGES.pop("GAUGE_trace_exemplar_us_%s" % victim, None)
    stat_add("STAT_tracing_exemplar_evict")


def recent() -> List[Dict[str, Any]]:
    """Recently completed traces, newest last (records are built here,
    lazily — the ring stores the trace objects)."""
    with _LOCK:
        return [t._record() for t in _RECENT]


def exemplars() -> List[Dict[str, Any]]:
    """The kept slow/errored exemplars, oldest first."""
    with _LOCK:
        return [dict(r) for r in _EXEMPLARS.values()]


def reset() -> None:
    """Clear both rings and retract exemplar gauges (test/bench
    isolation). Monitor counters/timers are left alone — use
    monitor.reset_all for those."""
    with _LOCK:
        _RECENT.clear()
        from .monitor import _GAUGES, _LOCK as _MLOCK
        with _MLOCK:
            for tid in _EXEMPLARS:
                _GAUGES.pop("GAUGE_trace_exemplar_us_%s" % tid, None)
            _GAUGES.pop("GAUGE_tracing_exemplars", None)
        _EXEMPLARS.clear()
        _CLEAN_FLOOR[0] = None
        _TENANT_NAMES.clear()
        _TENANT_SEEN.clear()
        _MODEL_NAMES.clear()


# ---------------------------------------------------------------------------
# /tracez payloads (introspect.py serves these)
# ---------------------------------------------------------------------------

_ROLLING = (
    ("serving_queue_wait", "TIMER_serving_queue_wait_us"),
    ("serving_execute", "TIMER_serving_execute_us"),
    ("serving_total", "TIMER_serving_total_us"),
    ("generation_ttft", "TIMER_generation_ttft_us"),
    ("generation_tpot", "TIMER_generation_tpot_us"),
    ("generation_total", "TIMER_generation_total_us"),
)


def rolling() -> Dict[str, Dict[str, float]]:
    """Rolling latency summary (us) from the decomposition timers —
    only families that have samples appear."""
    out = {}
    for label, timer in _ROLLING:
        st = timer_get(timer)
        if st["count"]:
            out[label] = {"count": st["count"], "p50": st["p50"],
                          "p95": st["p95"], "max": st["max"]}
    return out


def tracez(tenant: Optional[str] = None) -> Dict[str, Any]:
    """The ``/tracez?format=json`` payload. ``tenant`` filters recent
    and exemplars to one tenant's traces (``/tracez?tenant=acme``)."""
    rec, ex = recent(), exemplars()
    if tenant is not None:
        rec = [r for r in rec if r.get("tenant") == tenant]
        ex = [r for r in ex if r.get("tenant") == tenant]
    out = {
        "enabled": bool(get_flag("FLAGS_request_tracing")),
        "rolling_us": rolling(),
        "recent": rec,
        "exemplars": ex,
    }
    if tenant is not None:
        out["tenant"] = tenant
    return out


def _fmt_trace(rec: Dict[str, Any], verbose: bool) -> List[str]:
    head = "%s %-10s total=%.0fus" % (rec["trace_id"], rec["kind"],
                                      rec["total_us"])
    if rec.get("tenant"):
        head += " tenant=%s" % rec["tenant"]
    if rec.get("tokens"):
        head += " tokens=%d" % rec["tokens"]
        if "ttft_us" in rec:
            head += " ttft=%.0fus" % rec["ttft_us"]
    if rec.get("deadline_missed"):
        head += " DEADLINE_MISSED(budget=%.0fus)" % rec["deadline_us"]
    if rec["error"] is not None:
        head += " ERROR %s" % rec["error"]
    if not verbose:
        return [head + "  stages: " + " ".join(
            "%s+%.0f" % (n, t) for n, t in rec["stages"])]
    lines = [head]
    lines.extend("    %-14s +%.0fus" % (n, t) for n, t in rec["stages"])
    for e in rec.get("events", ()):
        extra = " ".join("%s=%s" % (k, v) for k, v in sorted(e.items())
                         if k not in ("name", "t_us"))
        lines.append("    event %-8s +%.0fus %s"
                     % (e["name"], e["t_us"], extra))
    return lines


def tracez_text(tenant: Optional[str] = None) -> str:
    """The human ``/tracez`` page: rolling decomposition, the recent
    tail, and every exemplar with its full timeline. ``tenant``
    restricts recent/exemplars to one tenant."""
    snap = tracez(tenant=tenant)
    head = "request traces (FLAGS_request_tracing=%s)" \
           % ("on" if snap["enabled"] else "off")
    if tenant is not None:
        head += "  [tenant=%s]" % tenant
    lines = [head, ""]
    lines.append("rolling latency (us):")
    if snap["rolling_us"]:
        for label, st in sorted(snap["rolling_us"].items()):
            lines.append("  %-22s n=%-6d p50=%-10.0f p95=%-10.0f "
                         "max=%.0f" % (label, st["count"], st["p50"],
                                       st["p95"], st["max"]))
    else:
        lines.append("  (no samples yet)")
    lines.append("")
    lines.append("recent (last %d of cap %d, newest last):"
                 % (len(snap["recent"]), _RECENT_CAP))
    for rec in snap["recent"][-32:]:
        lines.extend("  " + ln for ln in _fmt_trace(rec, verbose=False))
    lines.append("")
    lines.append("exemplars (slowest + errored, cap %d):"
                 % _exemplar_cap())
    for rec in snap["exemplars"]:
        lines.extend("  " + ln for ln in _fmt_trace(rec, verbose=True))
    return "\n".join(lines)
