"""User custom-op loading — the load_op_library mechanism.

Analog of the reference's custom-op path
(/root/reference/paddle/fluid/framework/load_op_lib.h +
pybind.cc:1654 load_op_library: users compile ops into a .so against
the framework headers; loading joins them to the global op registry).

TPU-native twins, both joining the same REGISTRY the built-in ops use:

1. **Python custom ops** (`load_op_module`): a module that uses
   @register_op — the idiomatic path, since a jnp/pallas lowering IS a
   TPU kernel. This mirrors the reference's intent (user code extends
   the op set) with the compile step collapsed into XLA.

2. **Native C/C++ custom ops** (`load_op_library`): a .so exporting the
   `ptcop_*` C ABI below. These register as host ops (executor runs
   them between jit segments on host buffers) — the analog of the
   reference's CPU-kernel custom ops. Contract (all functions return 0
   on success):

       int  ptcop_num_ops(void);
       const char* ptcop_op_name(int i);
       int  ptcop_num_inputs(const char* op);
       int  ptcop_num_outputs(const char* op);
       // fill out_dims (rank<=8 each) from input shapes
       int  ptcop_infer_shape(const char* op, int n_in,
                              const long long* in_dims, const int* in_ranks,
                              long long* out_dims, int* out_ranks,
                              const char* attrs_json);
       // float32 buffers, caller-allocated outputs
       int  ptcop_compute(const char* op, int n_in, const float** ins,
                          const long long* in_dims, const int* in_ranks,
                          int n_out, float** outs, const char* attrs_json);

   in_dims/out_dims use a FIXED stride of 8 slots per tensor: tensor
   i's dims occupy [i*8, i*8 + rank_i); unused slots are zero. Max
   rank is 8.
"""
from __future__ import annotations

import ctypes
import importlib
import importlib.util
import json
import os
from typing import List

import numpy as np

from .core.registry import REGISTRY, register_op

_LOADED_LIBS = {}

_MAX_RANK = 8


def load_op_module(module_or_path: str) -> List[str]:
    """Import a python module of @register_op lowerings; returns the op
    names it added."""
    before = set(REGISTRY.names())
    if os.path.exists(module_or_path):
        name = "paddle_tpu_custom_%s" % (
            os.path.basename(module_or_path).rsplit(".", 1)[0])
        spec = importlib.util.spec_from_file_location(name, module_or_path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    else:
        importlib.import_module(module_or_path)
    return sorted(set(REGISTRY.names()) - before)


def load_op_library(so_path: str) -> List[str]:
    """Load a ptcop_* .so and register each exported op as a host op;
    returns the op names added. Idempotent per path."""
    so_path = os.path.abspath(so_path)
    if so_path in _LOADED_LIBS:
        return _LOADED_LIBS[so_path]
    lib = ctypes.CDLL(so_path)
    lib.ptcop_num_ops.restype = ctypes.c_int
    lib.ptcop_op_name.restype = ctypes.c_char_p
    lib.ptcop_op_name.argtypes = [ctypes.c_int]
    for f in ("ptcop_num_inputs", "ptcop_num_outputs"):
        getattr(lib, f).restype = ctypes.c_int
        getattr(lib, f).argtypes = [ctypes.c_char_p]
    lib.ptcop_infer_shape.restype = ctypes.c_int
    lib.ptcop_infer_shape.argtypes = [
        ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int),
        ctypes.c_char_p]
    lib.ptcop_compute.restype = ctypes.c_int
    lib.ptcop_compute.argtypes = [
        ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int),
        ctypes.c_int, ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_char_p]

    names = [lib.ptcop_op_name(i).decode()
             for i in range(lib.ptcop_num_ops())]
    # validate the whole set BEFORE registering any — a duplicate must
    # not leave partial registrations behind (load_op_lib.h refuses
    # duplicate custom ops the same way)
    dups = [n for n in names if REGISTRY.has(n)]
    if dups:
        raise ValueError(
            "load_op_library: ops already registered: %s" % dups)
    for op_name in names:
        _register_native_op(lib, op_name)
    _LOADED_LIBS[so_path] = names
    return names


def _register_native_op(lib, op_name: str):
    n_in = lib.ptcop_num_inputs(op_name.encode())
    n_out = lib.ptcop_num_outputs(op_name.encode())

    @register_op(op_name, inputs=("X",), outputs=("Out",), no_grad=True,
                 host=True)
    def _custom(ctx, ins, attrs, lib=lib, op_name=op_name, n_in=n_in,
                n_out=n_out):
        xs = [np.ascontiguousarray(np.asarray(x), np.float32)
              for x in ins.get("X", [])]
        if len(xs) != n_in:
            raise ValueError("%s expects %d inputs, got %d"
                             % (op_name, n_in, len(xs)))
        for x in xs:
            if x.ndim > _MAX_RANK:
                raise ValueError(
                    "%s: input rank %d exceeds the ptcop ABI limit of %d"
                    % (op_name, x.ndim, _MAX_RANK))
        attrs_json = json.dumps(
            {k: v for k, v in attrs.items()
             if isinstance(v, (int, float, str, bool, list))}).encode()
        in_dims = (ctypes.c_longlong * (n_in * _MAX_RANK))(
            *[d for x in xs
              for d in (list(x.shape) + [0] * (_MAX_RANK - x.ndim))])
        in_ranks = (ctypes.c_int * n_in)(*[x.ndim for x in xs])
        out_dims = (ctypes.c_longlong * (n_out * _MAX_RANK))()
        out_ranks = (ctypes.c_int * n_out)()
        rc = lib.ptcop_infer_shape(op_name.encode(), n_in, in_dims,
                                   in_ranks, out_dims, out_ranks,
                                   attrs_json)
        if rc != 0:
            raise RuntimeError("%s: infer_shape failed rc=%d"
                               % (op_name, rc))
        outs = [np.empty([out_dims[j * _MAX_RANK + k]
                          for k in range(out_ranks[j])], np.float32)
                for j in range(n_out)]
        in_ptrs = (ctypes.POINTER(ctypes.c_float) * n_in)(
            *[x.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for x in xs])
        out_ptrs = (ctypes.POINTER(ctypes.c_float) * n_out)(
            *[o.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for o in outs])
        rc = lib.ptcop_compute(op_name.encode(), n_in, in_ptrs, in_dims,
                               in_ranks, n_out, out_ptrs, attrs_json)
        if rc != 0:
            raise RuntimeError("%s: compute failed rc=%d" % (op_name, rc))
        return {"Out": outs}
