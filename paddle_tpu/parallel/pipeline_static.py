"""Static-graph pipeline parallelism: device_guard sections -> one SPMD
GPipe schedule.

Reference mechanics being replaced
(/root/reference/python/paddle/fluid/optimizer.py:3666 PipelineOptimizer
-> _split_program at optimizer.py:3790;
/root/reference/paddle/fluid/framework/trainer.h:207 PipelineTrainer;
/root/reference/paddle/fluid/framework/section_worker.cc:82-132): the
program (forward+backward+optimize) is split into per-device section
programs, each driven by a SectionWorker thread, with blocking queues
carrying tensors between consecutive sections and microbatches pumped
through to overlap the stages.

TPU-native design — no threads, no queues, one XLA program:

- `PipelineOptimizer.minimize` REWRITES the program: the stamped forward
  ops move into one sub-block per device_guard section and are replaced
  by a single `pipeline_train` meta-op that outputs the loss and a
  `@GRAD` var per parameter. The inner optimizer then appends its normal
  update ops against those grads, so the optimizer stage of the
  reference's pipeline collapses into the tail of the same jitted step.
- The meta-op's lowering plays the GPipe clock exactly like the dygraph
  `gpipe()` (pipeline.py): stage s = mesh position s on the `pp` axis,
  one lax.scan tick per (microbatch, stage) diagonal, lax.ppermute
  handing activations to the next stage over ICI. Sections are
  *heterogeneous* programs, so each tick `lax.switch`es into this
  device's section; inter-stage activations ride two fixed-shape packed
  buffers (f32 + i32) because an SPMD carry needs one static type while
  section boundaries have many (conv->fc pipelines change activation
  shape at every cut). The reference's queues are dynamically typed;
  packing is the static-shape price, paid once at trace time.
- The backward sections of the reference (section_worker backward
  microbatch passes) are jax.value_and_grad through the whole schedule:
  differentiating the scan+ppermute runs the communication in reverse
  automatically.

Semantics notes:
- the loss var must be a batch MEAN (the standard book-config convention):
  the schedule averages the per-microbatch losses, which equals the
  full-batch mean only for mean-reduced losses.
- persistable vars WRITTEN inside a section (BatchNorm running stats)
  are not written back to the scope — the rewrite warns. Use LayerNorm
  (or keep BN out of the pipelined middle), the same constraint the
  SPMD formulation puts on the dygraph gpipe path.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..mesh.compat import pcast as _pcast, shard_map as _shard_map, \
    typeof as _typeof
from .env import PP_AXIS

GRAD_SUFFIX = "@GRAD"
PIPELINE_OP = "pipeline_train"


# ---------------------------------------------------------------------------
# minimize-side program rewrite
# ---------------------------------------------------------------------------

def rewrite_pipeline_program(program, loss, num_microbatches: int,
                             axis: str = PP_AXIS, parameter_list=None):
    """Move device_guard sections into sub-blocks behind one
    `pipeline_train` meta-op; return params_grads for apply_gradients.

    Mirrors _split_program (reference optimizer.py:3790) + the
    role of PipelineTrainer section wiring, as a Program->Program
    rewrite."""
    from .pipeline import split_program_by_device
    block = program.global_block
    sections = split_program_by_device(program)
    # ops before the first device_guard (feed/data plumbing) belong to
    # stage 0 (the reference's _add_op_device_attr does the same
    # inheritance forward)
    if len(sections) > 1 and sections[0][0] is None:
        dev1, ops1 = sections[1]
        sections = [(dev1, sections[0][1] + ops1)] + list(sections[2:])
    if len(sections) < 2:
        raise ValueError(
            "pipeline requires >=2 device_guard sections; got %d "
            "(stamp the forward with fluid.device_guard)" % len(sections))
    devs = [d for d, _ in sections]
    if len(set(devs)) != len(devs):
        raise ValueError(
            "pipeline sections must be contiguous per device; got %s "
            "(interleaved device_guard blocks)" % devs)

    all_ops = [o for _, ops in sections for o in ops]
    written: set = set()
    ext: set = set()
    for o in all_ops:
        for ns in o.inputs.values():
            ext.update(n for n in ns if n not in written)
        for ns in o.outputs.values():
            written.update(ns)
    param_set = {v.name for v in program.all_parameters()}
    persist = {v.name for v in program.persistable_vars()}
    params = sorted(n for n in ext if n in param_set)
    if parameter_list is not None:
        # restrict trainable params exactly like append_backward's
        # parameter_list contract — everything else stays frozen
        keep = {p if isinstance(p, str) else p.name for p in parameter_list}
        frozen = [p for p in params if p not in keep]
        params = [p for p in params if p in keep]
    else:
        frozen = []
    feeds = sorted(n for n in ext if n not in persist)
    # frozen params still feed the sections — as non-differentiated extras
    extras = sorted([n for n in ext if n in persist and n not in param_set]
                    + frozen)
    bad_writes = sorted(n for n in written
                        if n in persist and n not in param_set)
    if bad_writes:
        logging.getLogger("paddle_tpu").warning(
            "pipeline: persistable vars written inside sections are NOT "
            "written back to the scope (per-microbatch state has no "
            "single post-step value): %s", bad_writes)

    sub_idxs = []
    for _dev, ops in sections:
        blk = program.create_block(parent_idx=block.idx)
        blk.ops.extend(ops)
        sub_idxs.append(blk.idx)
    moved = {id(o) for o in all_ops}
    block.ops = [o for o in block.ops if id(o) not in moved]

    grad_names = []
    for p in params:
        pv = block.var(p)
        if not block.has_var(p + GRAD_SUFFIX):
            block.create_var(p + GRAD_SUFFIX, shape=list(pv.shape),
                             dtype=pv.dtype, stop_gradient=True)
        grad_names.append(p + GRAD_SUFFIX)
    block.append_op(
        PIPELINE_OP,
        inputs={"Feeds": feeds, "Params": params, "Extras": extras},
        outputs={"Loss": [loss.name], "ParamGrads": grad_names},
        attrs={"sub_blocks": sub_idxs, "num_microbatches":
               int(num_microbatches), "loss": loss.name, "axis": axis,
               "devices": devs})
    return [(block.var(p), block.var(g))
            for p, g in zip(params, grad_names)]


# ---------------------------------------------------------------------------
# run-side lowering (registered in core.control_flow.LOWERINGS)
# ---------------------------------------------------------------------------

def _pick_mesh(ctx_mesh, axis: str, n_stages: int):
    from .env import get_mesh
    for mesh in (ctx_mesh, get_mesh()):
        if mesh is not None and axis in mesh.shape and \
                mesh.shape[axis] == n_stages:
            return mesh
    devs = jax.devices()
    if len(devs) < n_stages:
        raise RuntimeError(
            "pipeline_train needs %d devices on axis %r but only %d are "
            "visible and no matching global mesh exists "
            "(init_parallel_env({'%s': %d}))"
            % (n_stages, axis, len(devs), axis, n_stages))
    from jax.sharding import Mesh
    return Mesh(np.array(devs[:n_stages]), (axis,))


def _is_float(dt) -> bool:
    return jnp.issubdtype(dt, jnp.floating)


class _Layout:
    """Static packing plan for one stage boundary: which vars, at which
    flat offsets, in the f32 buffer (floats; bf16 rides losslessly as
    f32) vs the i32 buffer (ints/bools)."""

    def __init__(self, names: List[str], shapes: Dict[str, Any]):
        self.f_entries, self.i_entries = [], []
        f_off = i_off = 0
        for n in names:
            aval = shapes[n]
            size = int(np.prod(aval.shape)) if aval.shape else 1
            if _is_float(aval.dtype):
                self.f_entries.append((n, aval.shape, aval.dtype,
                                       f_off, size))
                f_off += size
            else:
                self.i_entries.append((n, aval.shape, aval.dtype,
                                       i_off, size))
                i_off += size
        self.f_size, self.i_size = f_off, i_off

    def pack(self, env: Dict[str, Any], f_total: int, i_total: int):
        fbuf = jnp.zeros((f_total,), jnp.float32)
        ibuf = jnp.zeros((i_total,), jnp.int32)
        for n, shape, dt, off, size in self.f_entries:
            fbuf = fbuf.at[off:off + size].set(
                jnp.reshape(env[n], (size,)).astype(jnp.float32))
        for n, shape, dt, off, size in self.i_entries:
            ibuf = ibuf.at[off:off + size].set(
                jnp.reshape(env[n], (size,)).astype(jnp.int32))
        return fbuf, ibuf

    def unpack(self, fbuf, ibuf) -> Dict[str, Any]:
        out = {}
        for n, shape, dt, off, size in self.f_entries:
            out[n] = jnp.reshape(fbuf[off:off + size], shape).astype(dt)
        for n, shape, dt, off, size in self.i_entries:
            out[n] = jnp.reshape(ibuf[off:off + size], shape).astype(dt)
        return out


def lower_pipeline_train(lowerer, op, env: Dict[str, Any]) -> None:
    from ..core.executor import _BlockLowerer
    from ..core.registry import LowerCtx

    program = lowerer.program
    sub_idxs = [int(i) for i in op.attr("sub_blocks")]
    n_stages = len(sub_idxs)
    n_mb = int(op.attr("num_microbatches"))
    loss_name = op.attr("loss")
    axis = op.attr("axis", PP_AXIS)
    param_names = list(op.input("Params"))
    feed_names = list(op.input("Feeds"))
    extra_names = list(op.input("Extras"))
    sections = [program.blocks[i].ops for i in sub_idxs]
    mesh = _pick_mesh(lowerer.ctx.mesh, axis, n_stages)

    # --- dataflow across stage cuts -----------------------------------
    produced_at: Dict[str, int] = {}
    last_use: Dict[str, int] = {}
    for s, ops in enumerate(sections):
        for o in ops:
            for n in o.input_names():
                if n in produced_at:
                    last_use[n] = max(last_use.get(n, -1), s)
            for n in o.output_names():
                produced_at.setdefault(n, s)
    boundaries = [sorted(n for n, ps in produced_at.items()
                         if ps <= s and last_use.get(n, -1) > s)
                  for s in range(n_stages - 1)]

    # --- microbatch feeds ---------------------------------------------
    feeds_mb_abs: Dict[str, jax.ShapeDtypeStruct] = {}
    feeds_stacked: Dict[str, Any] = {}
    mb = None
    for k in feed_names:
        v = jnp.asarray(env[k])
        if v.ndim < 1 or v.shape[0] % n_mb != 0:
            raise ValueError(
                "pipeline feed %r batch %s is not divisible by "
                "num_microbatches=%d" % (k, v.shape, n_mb))
        mb = v.shape[0] // n_mb
        feeds_stacked[k] = v.reshape((n_mb, mb) + v.shape[1:])
        feeds_mb_abs[k] = jax.ShapeDtypeStruct((mb,) + v.shape[1:], v.dtype)
    params_env = {n: jnp.asarray(env[n]) for n in param_names}
    extras_env = {n: jnp.asarray(env[n]) for n in extra_names}

    def run_section(s, env_sec, key):
        ctx2 = LowerCtx(key, is_test=lowerer.ctx.is_test, mesh=mesh)
        sub = _BlockLowerer(program, ctx2)
        env2 = dict(env_sec)
        sub.run_ops(sections[s], env2)
        return env2

    # --- boundary shapes via abstract eval of the sequential chain ----
    bnames = sorted({n for b in boundaries for n in b})

    def seq_chain(params, extras, feeds_mb, key):
        e: Dict[str, Any] = {}
        e.update(params); e.update(extras); e.update(feeds_mb)
        for s in range(n_stages):
            e = run_section(s, e, key)
        return {n: e[n] for n in bnames}

    shapes = jax.eval_shape(seq_chain, params_env, extras_env, feeds_mb_abs,
                            jax.ShapeDtypeStruct((2,), jnp.uint32))
    layouts = [_Layout(b, shapes) for b in boundaries]
    f_total = max([1] + [lo.f_size for lo in layouts])
    i_total = max([1] + [lo.i_size for lo in layouts])

    # --- per-stage branch functions for lax.switch --------------------
    def make_branch(s):
        def branch(fbuf, ibuf, feeds_mb, params, extras, key):
            e: Dict[str, Any] = {}
            e.update(params); e.update(extras); e.update(feeds_mb)
            if s > 0:
                e.update(layouts[s - 1].unpack(fbuf, ibuf))
            e2 = run_section(s, e, key)
            if s < n_stages - 1:
                fb, ib = layouts[s].pack(e2, f_total, i_total)
            else:
                fb = jnp.zeros((f_total,), jnp.float32)
                ib = jnp.zeros((i_total,), jnp.int32)
            if s == n_stages - 1:
                loss = jnp.asarray(e2[loss_name], jnp.float32)
                loss = loss if loss.ndim == 0 else jnp.mean(loss)
            else:
                loss = jnp.zeros((), jnp.float32)
            # every branch's outputs must agree on the varying-manual-axes
            # type for lax.switch: a stage whose outputs are fresh zeros
            # (unvarying) must match one whose outputs came through the
            # device-varying buffers
            def vary(x):
                if axis in getattr(_typeof(x), "vma", ()):
                    return x  # already device-varying on this axis
                return _pcast(x, (axis,), to="varying")
            return vary(fb), vary(ib), vary(loss)
        return branch

    branches = [make_branch(s) for s in range(n_stages)]
    T = n_mb + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    key0 = lowerer.ctx.rng()

    def shard_body(feeds_all, params, extras, key):
        stage = jax.lax.axis_index(axis)
        # params arrive stage-tiled (leading pp dim of 1 per shard, see
        # pipe_loss below); drop the tile dim
        params = jax.tree.map(lambda p: p[0], params)
        to_vary = lambda x: _pcast(x, (axis,), to="varying")
        # cast ALL inputs to device-varying before the scan: a branch
        # closing over a replicated (unvarying) value would get a psum
        # inserted inside the switch when transposed for the backward
        # pass, and per-device-divergent collectives deadlock — casting
        # here moves that psum to this uniform point instead
        feeds_all, params, extras, key = jax.tree.map(
            to_vary, (feeds_all, params, extras, key))
        fbuf = to_vary(jnp.zeros((f_total,), jnp.float32))
        ibuf = to_vary(jnp.zeros((i_total,), jnp.int32))
        loss0 = to_vary(jnp.zeros((), jnp.float32))

        def tick(carry, t):
            fb, ib, loss_acc = carry
            # stage s works on microbatch t - s at tick t (the GPipe
            # diagonal): feeds consumed mid-pipeline (labels at the loss
            # stage) must be sliced by THIS stage's microbatch, not the
            # entry stage's
            src = jnp.clip(t - stage, 0, n_mb - 1)
            feeds_mb = {k: v[src] for k, v in feeds_all.items()}
            key_t = jax.random.fold_in(key, t)
            # warmup/drain ticks (stage idle on the GPipe diagonal) must
            # not RUN the section at all: zero-filled boundary buffers
            # drive ops with unbounded backward at 0 (log, sqrt, div) to
            # inf, and 0-cotangent * inf = NaN would poison the psum'd
            # parameter grads (ADVICE r4). lax.cond skips the compute —
            # also saving the warmup/drain FLOPs — and passes the
            # buffers through unchanged, which downstream stages only
            # ever read on their own live ticks.
            live = jnp.logical_and(t >= stage, t - stage < n_mb)

            def run_tick(fb, ib, feeds_mb, params, extras, key_t):
                return jax.lax.switch(stage, branches, fb, ib, feeds_mb,
                                      params, extras, key_t)

            def skip_tick(fb, ib, feeds_mb, params, extras, key_t):
                # fb[0]*0: a device-varying zero (fresh constants are
                # unvarying and would mismatch the live branch's vma)
                return fb, ib, fb[0] * 0.0

            fb2, ib2, loss_mb = jax.lax.cond(
                live, run_tick, skip_tick, fb, ib, feeds_mb, params,
                extras, key_t)
            valid = jnp.logical_and(stage == n_stages - 1,
                                    t >= n_stages - 1)
            loss_acc = loss_acc + jnp.where(valid, loss_mb, 0.0)
            fb3 = jax.lax.ppermute(fb2, axis, perm)
            ib3 = jax.lax.ppermute(ib2, axis, perm)
            return (fb3, ib3, loss_acc), None

        (_, _, loss_acc), _ = jax.lax.scan(
            tick, (fbuf, ibuf, loss0), jnp.arange(T))
        return jax.lax.psum(loss_acc, axis) / n_mb

    from jax.sharding import PartitionSpec as P
    # Differentiated params enter TILED over the pp axis (one identical
    # slice per stage — per-device memory is unchanged vs replicated)
    # so their in_spec mentions the axis: with the rep-checker off
    # (which old jax's lax.switch typing forces, and new jax's vma
    # pcasts make redundant) an unmentioned differentiated input has no
    # transpose rule to psum its cotangent, while the tile's own
    # transpose sums the per-stage partial grads for free.
    sharded = _shard_map(
        shard_body, mesh=mesh,
        in_specs=(P(), P(axis), P(), P()), out_specs=P(),
        check_vma=False)

    # remat the whole sharded region: under partial eval (the executor
    # traces this inside jit) old jax names dim 0 of every shard_map
    # residual, so a RANK-0 residual (the scalar loss carry) cannot
    # cross the forward/backward split — recomputing from the (all
    # rank>=1) inputs sidesteps it, and a pipeline recomputes its
    # stages under remat anyway
    sharded = jax.checkpoint(
        sharded, policy=jax.checkpoint_policies.nothing_saveable)

    def pipe_loss(params):
        tiled = jax.tree.map(
            lambda p: jnp.tile(p[None], (n_stages,) + (1,) * p.ndim),
            params)
        return sharded(feeds_stacked, tiled, extras_env, key0)

    loss_val, grads = jax.value_and_grad(pipe_loss)(params_env)
    env[loss_name] = loss_val
    for p in param_names:
        env[p + GRAD_SUFFIX] = grads[p]
