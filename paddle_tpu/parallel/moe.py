"""Mixture-of-Experts with expert parallelism over an `ep` mesh axis.

The reference (v1.8) has no MoE; this implements the standard
GShard/Switch dispatch the TPU way so the framework's parallelism
axes (dp/mp/pp/sp) extend to ep: tokens are routed top-k with a
capacity cap, dispatched to experts with one-hot combine tensors
(einsum — MXU-friendly, no gathers), and under a mesh the experts
shard over `ep` with `jax.lax.all_to_all` exchanging token slices
inside shard_map (ICI traffic, no host round-trip).

Public surface:
  - router_topk(logits, k, capacity): gates + dispatch/combine tensors
    (+ the Switch load-balance auxiliary loss).
  - moe_ffn(x, params, k, capacity_factor): single-device MoE FFN.
  - moe_ffn_sharded(x, params, mesh, axis="ep", ...): expert-parallel
    twin — identical math, experts split over the axis.
  - init_moe_params(key, n_experts, d_model, d_ff): parameter pytree.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..mesh.compat import shard_map as _shard_map

__all__ = ["router_topk", "moe_ffn", "moe_ffn_sharded",
           "init_moe_params"]


def init_moe_params(key, n_experts: int, d_model: int, d_ff: int,
                    dtype=jnp.float32):
    """Experts' FFN weights [E, ...] plus the router projection."""
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = 1.0 / math.sqrt(d_model)
    s2 = 1.0 / math.sqrt(d_ff)
    return {
        "router": jax.random.normal(k1, (d_model, n_experts),
                                    dtype) * s1,
        "w_in": jax.random.normal(k2, (n_experts, d_model, d_ff),
                                  dtype) * s1,
        "w_out": jax.random.normal(k3, (n_experts, d_ff, d_model),
                                   dtype) * s2,
    }


def router_topk(logits, k: int, capacity: int):
    """Top-k routing with capacity: returns (dispatch [T,E,C],
    combine [T,E,C], aux_loss).

    dispatch is a 0/1 mask sending token t to slot (e, c); combine is
    dispatch scaled by the token's normalized gate for that expert.
    Tokens over an expert's capacity are DROPPED (standard Switch
    behavior — the residual connection carries them).  aux_loss is the
    Switch load-balance loss: E * sum_e mean_t(gates_e) *
    mean_t(routed_e).
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)        # [T, k]
    # normalize the kept gates so the combine weights sum to 1
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)

    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    # cumulative position of each token within its expert's queue,
    # processed per routing priority (0th choice first, GShard order)
    fill = jnp.zeros((E,), jnp.int32)
    for j in range(k):
        e_j = gate_idx[:, j]                              # [T]
        onehot = jax.nn.one_hot(e_j, E, dtype=jnp.int32)  # [T, E]
        # position of token t in expert e_j's queue
        pos_in_e = (jnp.cumsum(onehot, axis=0) - 1) * onehot  # [T, E]
        pos = pos_in_e.sum(-1) + fill[e_j]                # [T]
        keep = pos < capacity
        slot = jax.nn.one_hot(pos, capacity,
                              dtype=jnp.float32) * keep[:, None]
        d_j = onehot.astype(jnp.float32)[:, :, None] * slot[:, None, :]
        dispatch = dispatch + d_j
        combine = combine + d_j * gate_vals[:, j][:, None, None]
        fill = fill + onehot.sum(0)

    # Switch aux-loss statistics over the FIRST choice: return the two
    # [E] means separately so a sharded caller can pmean them BEFORE
    # the product (sum_e mean(prob_e)*mean(routed_e) is a product of
    # global means — per-shard products would not average to it)
    me = probs.mean(axis=0)                               # [E]
    ce = jax.nn.one_hot(gate_idx[:, 0], E,
                        dtype=jnp.float32).mean(axis=0)   # [E]
    return dispatch, combine, (me, ce)


def _expert_ffn(xe, w_in, w_out):
    """xe: [E, C, M] through per-expert FFN -> [E, C, M]."""
    h = jax.nn.gelu(jnp.einsum("ecm,emf->ecf", xe, w_in))
    return jnp.einsum("ecf,efm->ecm", h, w_out)


def moe_ffn(x, params, k: int = 2,
            capacity_factor: float = 1.25,
            capacity: Optional[int] = None):
    """Single-device MoE FFN. x: [T, M] (flatten batch x seq first).
    Returns (y [T, M], aux_loss)."""
    T, M = x.shape
    E = params["router"].shape[1]
    C = capacity if capacity is not None else max(
        1, int(capacity_factor * k * T / E))
    logits = x.astype(jnp.float32) @ params["router"]
    dispatch, combine, (me, ce) = router_topk(logits, k, C)
    xe = jnp.einsum("tm,tec->ecm", x.astype(jnp.float32), dispatch)
    ye = _expert_ffn(xe, params["w_in"].astype(jnp.float32),
                     params["w_out"].astype(jnp.float32))
    y = jnp.einsum("ecm,tec->tm", ye, combine)
    return y.astype(x.dtype), (me * ce).sum() * E


def moe_ffn_sharded(x, params, mesh, axis: str = "ep", k: int = 2,
                    capacity_factor: float = 1.25,
                    capacity: Optional[int] = None):
    """Expert-parallel MoE FFN: tokens sharded over `axis`, experts
    sharded over `axis` (E % n == 0). Same math as moe_ffn.

    Per shard: route the LOCAL tokens against all E experts, then
    all_to_all swaps the expert axis for the token-shard axis so each
    device applies only its E/n experts to every shard's slice, and the
    reverse all_to_all brings expert outputs home for the combine —
    the GShard dispatch pattern on ICI.
    """
    from jax.sharding import PartitionSpec as P
    T, M = x.shape
    E = params["router"].shape[1]
    n = mesh.shape[axis]
    assert E % n == 0, (E, n)
    assert T % n == 0, (T, n)
    t_local = T // n
    C = capacity if capacity is not None else max(
        1, int(capacity_factor * k * t_local / E))

    def body(xs, router, w_in, w_out):
        # xs: [T/n, M] local tokens; w_in/w_out: [E/n, ...] local experts
        logits = xs.astype(jnp.float32) @ router
        dispatch, combine, (me, ce) = router_topk(logits, k, C)
        xe = jnp.einsum("tm,tec->ecm", xs.astype(jnp.float32), dispatch)
        # [E, C, M] -> exchange: concat_axis splits E over devices and
        # gathers the device axis into a leading shard dim
        xe = xe.reshape(n, E // n, C, M)
        xe = jax.lax.all_to_all(xe, axis, split_axis=0, concat_axis=0,
                                tiled=True)        # [n*(E/n) rows home]
        xe = xe.reshape(n, E // n, C, M)           # [src_shard, e_loc, C, M]
        ye = jax.vmap(_expert_ffn, in_axes=(0, None, None))(
            xe.astype(jnp.float32), w_in.astype(jnp.float32),
            w_out.astype(jnp.float32))             # [n, E/n, C, M]
        ye = ye.reshape(n * (E // n), C, M)
        ye = jax.lax.all_to_all(ye, axis, split_axis=0, concat_axis=0,
                                tiled=True)
        ye = ye.reshape(E, C, M)                   # this shard's tokens
        y = jnp.einsum("ecm,tec->tm", ye, combine)
        # global aux: average the statistics across shards FIRST
        aux = (jax.lax.pmean(me, axis)
               * jax.lax.pmean(ce, axis)).sum() * E
        return y.astype(xs.dtype), aux

    return _shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), P(), P(axis), P(axis)),
        out_specs=(P(axis), P()),
        # old-jax rep-rewrite chokes on the symbolic-zero cotangent of
        # a discarded aux output ('Zero' has no reshape); with the
        # checker off, the router (the one unmentioned input) gets its
        # cotangent psum from the explicit transpose path instead
        check_vma=False,
    )(x, params["router"], params["w_in"], params["w_out"])
