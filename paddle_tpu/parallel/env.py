"""Distributed environment: device mesh + rank/world info.

TPU-native replacement for the reference's communicator bootstrap stack
(/root/reference/paddle/fluid/platform/collective_helper.h:50 NCCLComm /
NCCLCommContext keyed by ring_id; imperative/nccl_context.cc TCP ncclUniqueId
exchange; python/paddle/distributed/parallel.py:32 init_parallel_env).
On TPU the NCCL-ring machinery collapses into a jax.sharding.Mesh over the
ICI topology: `ring_id` becomes a named mesh axis, comm bootstrap becomes
mesh construction, and XLA inserts/schedules the collectives.

Axes (left open for every parallelism family the framework supports):
  dp — data parallel          mp — tensor/model parallel
  pp — pipeline stages        sp — sequence/context parallel
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DP_AXIS = "dp"
MP_AXIS = "mp"
PP_AXIS = "pp"
SP_AXIS = "sp"
ALL_AXES = (DP_AXIS, MP_AXIS, PP_AXIS, SP_AXIS)


class DistEnv:
    """Global parallel environment (ParallelEnv analog,
    dygraph/parallel.py:96)."""

    def __init__(self, mesh: Optional[Mesh] = None):
        self.mesh = mesh

    @property
    def nranks(self) -> int:
        return self.mesh.size if self.mesh is not None else 1

    @property
    def world_size(self) -> int:
        return self.nranks

    @property
    def rank(self) -> int:
        # single-controller SPMD: the host drives all devices; per-device
        # rank only exists inside shard_map'ped code (lax.axis_index)
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))

    @property
    def local_rank(self) -> int:
        return self.rank

    def axis_size(self, axis: str) -> int:
        if self.mesh is None or axis not in self.mesh.axis_names:
            return 1
        return self.mesh.shape[axis]


_env = DistEnv()


def init_parallel_env(mesh_shape: Optional[Dict[str, int]] = None,
                      devices: Optional[Sequence] = None) -> DistEnv:
    """Create the global mesh (paddle.distributed.init_parallel_env analog,
    parallel.py:32 — there: gen ncclUniqueId + init comm rings; here: build
    a Mesh over the PjRt device list; XLA owns the rings).

    mesh_shape maps axis name -> extent; unspecified axes get extent 1.
    Default: all devices on the data axis.
    """
    global _env
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if mesh_shape is None:
        mesh_shape = {DP_AXIS: n}
    axes = [a for a in ALL_AXES if mesh_shape.get(a, 1) > 1] or [DP_AXIS]
    extents = [mesh_shape.get(a, 1) for a in axes]
    total = int(np.prod(extents))
    if total != n:
        # grow the data axis to cover all devices, but only when the user
        # did not pin it explicitly — a pinned dp that doesn't fit is an
        # error, never silently resized
        dp_pinned = mesh_shape.get(DP_AXIS) is not None
        others = total // (mesh_shape.get(DP_AXIS) or 1)
        if DP_AXIS in axes and not dp_pinned and n % others == 0:
            extents[axes.index(DP_AXIS)] = n // others
        elif not dp_pinned and DP_AXIS not in axes and n % total == 0:
            axes.insert(0, DP_AXIS)
            extents.insert(0, n // total)
        else:
            raise ValueError(
                f"mesh shape {mesh_shape} does not cover {n} devices "
                f"(product {total})")
    dev_array = np.array(devices).reshape(extents)
    _env.mesh = Mesh(dev_array, tuple(axes))
    return _env


def get_env() -> DistEnv:
    return _env


def get_mesh() -> Optional[Mesh]:
    return _env.mesh


def get_world_size() -> int:
    return _env.nranks


def get_rank() -> int:
    return _env.rank


def sharding(*spec) -> NamedSharding:
    """NamedSharding over the global mesh with the given PartitionSpec
    entries, e.g. sharding('dp', None) for batch-sharded 2-D data."""
    if _env.mesh is None:
        raise RuntimeError("init_parallel_env() first")
    return NamedSharding(_env.mesh, PartitionSpec(*spec))


def shard_batch(batch, axis: str = DP_AXIS):
    """Device-put a host batch sharded along its leading dim — the analog of
    the reference feeding per-device scopes
    (framework/parallel_executor.cc BCast/feed split)."""
    if _env.mesh is None or _env.axis_size(axis) == 1:
        return jax.device_put(batch)
    sh = sharding(axis)

    def put(x):
        ndim = np.ndim(x)
        spec = PartitionSpec(*([axis] + [None] * (ndim - 1)))
        return jax.device_put(x, NamedSharding(_env.mesh, spec))

    return jax.tree.map(put, batch)
