"""Distributed environment: device mesh + rank/world info.

TPU-native replacement for the reference's communicator bootstrap stack
(/root/reference/paddle/fluid/platform/collective_helper.h:50 NCCLComm /
NCCLCommContext keyed by ring_id; imperative/nccl_context.cc TCP ncclUniqueId
exchange; python/paddle/distributed/parallel.py:32 init_parallel_env).
On TPU the NCCL-ring machinery collapses into a jax.sharding.Mesh over the
ICI topology: `ring_id` becomes a named mesh axis, comm bootstrap becomes
mesh construction, and XLA inserts/schedules the collectives.

Axes (left open for every parallelism family the framework supports):
  dp — data parallel          mp — tensor/model parallel
  pp — pipeline stages        sp — sequence/context parallel
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


class RendezvousTimeout(RuntimeError):
    """jax.distributed rendezvous could not form inside its bounded
    budget (timeout x retries). Typed so the gang supervisor and tests
    can tell "the cluster never assembled" from a training error —
    and so a missing peer is a raised error, never a silent hang."""

    def __init__(self, coordinator: str, attempts: int, elapsed_s: float,
                 cause: Optional[BaseException] = None):
        super().__init__(
            "rendezvous with %s failed after %d attempt(s) in %.1fs%s"
            % (coordinator, attempts, elapsed_s,
               ": %r" % (cause,) if cause is not None else ""))
        self.coordinator = coordinator
        self.attempts = attempts
        self.elapsed_s = elapsed_s
        self.cause = cause

DP_AXIS = "dp"
MP_AXIS = "mp"
PP_AXIS = "pp"
SP_AXIS = "sp"
ALL_AXES = (DP_AXIS, MP_AXIS, PP_AXIS, SP_AXIS)


def _plan_mesh() -> Optional[Mesh]:
    """Mesh of the active ShardingPlan (paddle_tpu.mesh), if one is
    installed — lazy import to keep env importable standalone."""
    try:
        from ..mesh.plan import current_plan
    except ImportError:  # pragma: no cover - partial install
        return None
    plan = current_plan()
    return plan.mesh if plan is not None else None


class DistEnv:
    """Global parallel environment (ParallelEnv analog,
    dygraph/parallel.py:96).

    Topology resolution order: the explicit mesh from
    init_parallel_env(), else the active ShardingPlan's mesh
    (mesh.install_plan / use_plan), else single-rank — so collective
    helpers and the plan always agree on world size."""

    def __init__(self, mesh: Optional[Mesh] = None):
        self.mesh = mesh

    def _mesh(self) -> Optional[Mesh]:
        return self.mesh if self.mesh is not None else _plan_mesh()

    @property
    def nranks(self) -> int:
        mesh = self._mesh()
        return mesh.size if mesh is not None else 1

    @property
    def world_size(self) -> int:
        return self.nranks

    @property
    def rank(self) -> int:
        # single-controller SPMD: the host drives all devices; per-device
        # rank only exists inside shard_map'ped code (lax.axis_index).
        # Under the cluster contract PADDLE_TRAINER_ID wins; with only a
        # plan installed the process index is the rank.
        tid = os.environ.get("PADDLE_TRAINER_ID")
        if tid is not None:
            return int(tid)
        if self._mesh() is not None:
            return int(jax.process_index())
        return 0

    @property
    def local_rank(self) -> int:
        return self.rank

    def axis_size(self, axis: str) -> int:
        mesh = self._mesh()
        if mesh is None or axis not in mesh.axis_names:
            return 1
        return mesh.shape[axis]


_env = DistEnv()
_dist_initialized = False


def _rendezvous_budget() -> Tuple[float, int, float]:
    """(per-attempt timeout_s, retries, backoff_s). Env vars win (the
    launcher exports them to workers); flags are the in-process
    default. All deadline math downstream is time.monotonic()."""
    from ..flags import get_flag

    def _f(env: str, flag: str, cast):
        v = os.environ.get(env)
        if v is not None:
            return cast(v)
        return cast(get_flag(flag))

    timeout_s = _f("PADDLE_RENDEZVOUS_TIMEOUT_S",
                   "FLAGS_rendezvous_timeout_s", float)
    retries = _f("PADDLE_RENDEZVOUS_RETRIES",
                 "FLAGS_rendezvous_retries", int)
    backoff_s = _f("PADDLE_RENDEZVOUS_BACKOFF_MS",
                   "FLAGS_rendezvous_backoff_ms", float) / 1e3
    return timeout_s, retries, backoff_s


def init_distributed_runtime(coordinator_address: Optional[str] = None,
                             num_processes: Optional[int] = None,
                             process_id: Optional[int] = None,
                             timeout_s: Optional[float] = None) -> bool:
    """Multi-process/multi-host bootstrap — the TPU analog of the
    reference's c_gen_nccl_id -> c_comm_init op pair
    (/root/reference/python/paddle/fluid/transpiler/collective.py:113-123)
    and the raw-TCP ncclUniqueId exchange
    (/root/reference/paddle/fluid/imperative/nccl_context.cc:21-77).

    Consumes the cluster env contract materialized by fleet/launch.py and
    spawn() (role_maker.py:421-492): PADDLE_TRAINER_ID,
    PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ENDPOINTS. Rank 0's endpoint hosts
    the coordination service; jax.distributed wires every process into ONE
    global PjRt topology, after which jax.devices() spans all hosts and a
    Mesh over it rides ICI within a slice / DCN across hosts.

    Rendezvous is BOUNDED: each jax.distributed.initialize attempt gets
    `timeout_s` (default FLAGS_rendezvous_timeout_s, env-overridable as
    PADDLE_RENDEZVOUS_TIMEOUT_S), failed attempts retry with backoff up
    to FLAGS_rendezvous_retries, and exhaustion raises a typed
    :class:`RendezvousTimeout` — a gang missing one peer fails loudly
    instead of hanging until an operator notices (launch.py turns that
    raise into a supervised gang restart).

    Must run before the local backend initializes. Returns True when a
    multi-process runtime was (already) formed.
    """
    global _dist_initialized
    if _dist_initialized:
        return True
    n = num_processes if num_processes is not None else \
        int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if n <= 1:
        return False
    rank = process_id if process_id is not None else \
        int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coordinator_address is None:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        coordinator_address = os.environ.get("PADDLE_COORDINATOR_ENDPOINT") \
            or (eps.split(",")[0] if eps else None)
    if not coordinator_address:
        raise RuntimeError(
            "multi-process init needs PADDLE_TRAINER_ENDPOINTS or "
            "PADDLE_COORDINATOR_ENDPOINT (launch/spawn set these)")
    # under a supervisor (launch.py), start beating BEFORE rendezvous:
    # a worker wedged in rendezvous is alive-but-stuck, and its own
    # RendezvousTimeout (below) is what turns that into a restart
    from ..launch import maybe_start_worker_heartbeat
    maybe_start_worker_heartbeat(state="rendezvous")
    # CPU backends need an explicit cross-process collectives impl:
    # without it XLA:CPU refuses multi-process computations outright
    # ("Multiprocess computations aren't implemented on the CPU
    # backend"). Gloo ships in jaxlib; select it before the backend
    # initializes. TPU/GPU use their native fabrics and ignore this.
    platforms = jax.config.jax_platforms \
        or os.environ.get("JAX_PLATFORMS", "")
    if platforms == "cpu":
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # pragma: no cover - jaxlib without gloo
            pass
    from ..failpoints import failpoint
    from ..monitor import stat_add
    per_try, retries, backoff_s = _rendezvous_budget()
    if timeout_s is not None:
        per_try = float(timeout_s)
    t0 = time.monotonic()  # monotonic: wall-clock jumps must not
    attempts = 0           # shrink or stretch the rendezvous budget
    last_err: Optional[BaseException] = None
    while attempts <= retries:
        attempts += 1
        try:
            # failpoint sits INSIDE the attempt loop so raise@once
            # models a transient coordinator blip (retry succeeds)
            # and plain raise models a peer that never shows up
            failpoint("dist.rendezvous")
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=n, process_id=rank,
                initialization_timeout=max(1, int(per_try)))
            _dist_initialized = True
            from ..launch import set_worker_state
            set_worker_state("running")
            return True
        except Exception as e:
            last_err = e
            try:  # release any half-formed client before retrying
                jax.distributed.shutdown()
            except Exception:
                pass
            if attempts <= retries:
                stat_add("STAT_worker_rendezvous_retries")
                time.sleep(backoff_s * (2 ** (attempts - 1)))
    raise RendezvousTimeout(coordinator_address, attempts,
                            time.monotonic() - t0, last_err)


def init_parallel_env(mesh_shape: Optional[Dict[str, int]] = None,
                      devices: Optional[Sequence] = None) -> DistEnv:
    """Create the global mesh (paddle.distributed.init_parallel_env analog,
    parallel.py:32 — there: gen ncclUniqueId + init comm rings; here: build
    a Mesh over the PjRt device list; XLA owns the rings).

    mesh_shape maps axis name -> extent; unspecified axes get extent 1.
    Default: all devices on the data axis.
    """
    global _env
    if devices is None and not _dist_initialized and \
            int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1 and \
            os.environ.get("PADDLE_TRAINER_ENDPOINTS"):
        # launched under the cluster contract: form the global runtime
        # first so jax.devices() below spans every process
        init_distributed_runtime()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if mesh_shape is None:
        mesh_shape = {DP_AXIS: n}
    axes = [a for a in ALL_AXES if mesh_shape.get(a, 1) > 1] or [DP_AXIS]
    extents = [mesh_shape.get(a, 1) for a in axes]
    total = int(np.prod(extents))
    if total != n:
        # grow the data axis to cover all devices, but only when the user
        # did not pin it explicitly — a pinned shape that fits in fewer
        # devices becomes a sub-mesh over the first `total` devices (the
        # reference likewise forms comm rings over a subset of places)
        dp_pinned = mesh_shape.get(DP_AXIS) is not None
        others = total // (mesh_shape.get(DP_AXIS) or 1)
        if DP_AXIS in axes and not dp_pinned and n % others == 0:
            extents[axes.index(DP_AXIS)] = n // others
        elif not dp_pinned and DP_AXIS not in axes and n % total == 0:
            axes.insert(0, DP_AXIS)
            extents.insert(0, n // total)
        elif total < n:
            # explicit sub-mesh: legitimate (rings over a subset of
            # places) but loud — idle chips are a silent throughput cliff
            import logging
            logging.getLogger("paddle_tpu").warning(
                "mesh %s uses %d of %d devices; %d devices stay idle",
                mesh_shape, total, n, n - total)
            devices = devices[:total]
            if jax.process_count() > 1 and not any(
                    d.process_index == jax.process_index()
                    for d in devices):
                raise ValueError(
                    f"sub-mesh over {total} devices excludes every device "
                    f"addressable by process {jax.process_index()}; shrink "
                    "PADDLE_TRAINERS_NUM or grow the mesh")
        else:
            raise ValueError(
                f"mesh shape {mesh_shape} needs {total} devices but only "
                f"{n} are available")
    dev_array = np.array(devices).reshape(extents)
    _env.mesh = Mesh(dev_array, tuple(axes))
    return _env


def get_env() -> DistEnv:
    return _env


def get_mesh() -> Optional[Mesh]:
    """The ambient mesh: init_parallel_env's, else the active
    ShardingPlan's (docs/spmd.md)."""
    return _env._mesh()


def get_world_size() -> int:
    return _env.nranks


def get_rank() -> int:
    return _env.rank


def sharding(*spec) -> NamedSharding:
    """NamedSharding over the global mesh with the given PartitionSpec
    entries, e.g. sharding('dp', None) for batch-sharded 2-D data."""
    mesh = _env._mesh()
    if mesh is None:
        raise RuntimeError("init_parallel_env() or install a ShardingPlan "
                           "first")
    return NamedSharding(mesh, PartitionSpec(*spec))


def shard_batch(batch, axis: str = DP_AXIS, mesh=None):
    """Device-put a host batch sharded along its leading dim — the analog of
    the reference feeding per-device scopes
    (framework/parallel_executor.cc BCast/feed split).

    Single-process: `batch` is the GLOBAL batch, split across the axis.
    Multi-process (after init_distributed_runtime): `batch` is this
    process's LOCAL shard (standard SPMD data loading — each trainer reads
    its own files, as the reference's DataFeed does) and is assembled into
    a global array spanning all hosts."""
    use_mesh = mesh if mesh is not None else _env._mesh()
    axis_n = (use_mesh.shape.get(axis, 1) if use_mesh is not None
              else 1)
    multiproc = jax.process_count() > 1
    if use_mesh is None or axis_n == 1:
        if multiproc and use_mesh is not None:
            # no dp axis (pure mp/pp): the batch is REPLICATED, but in
            # multi-process SPMD every jit input must still be a global
            # array over the mesh — assemble it from the (identical)
            # per-process copies
            rep = NamedSharding(use_mesh, PartitionSpec())

            def put_rep(x):
                return jax.make_array_from_process_local_data(
                    rep, np.asarray(x))
            return jax.tree.map(put_rep, batch)
        return jax.device_put(batch)

    def put(x):
        ndim = np.ndim(x)
        spec = PartitionSpec(*([axis] + [None] * (ndim - 1)))
        sh = NamedSharding(use_mesh, spec)
        if multiproc:
            return jax.make_array_from_process_local_data(sh, np.asarray(x))
        return jax.device_put(x, sh)

    return jax.tree.map(put, batch)
