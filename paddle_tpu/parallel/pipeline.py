"""Pipeline parallelism: GPipe microbatching over a mesh 'pp' axis.

TPU-native replacement for the reference's pipeline stack
(/root/reference/python/paddle/fluid/optimizer.py:3666 PipelineOptimizer
splitting programs by device_guard; framework/trainer.h:207
PipelineTrainer + section_worker.cc:82 running microbatches through
per-stage threads with queue vars between sections). Instead of threads
and queues, the schedule is a single SPMD computation: stage parameters
are stacked on a leading axis and shard_map'ed over 'pp', and one
lax.scan plays the GPipe clock — each tick every device runs its stage
on its current microbatch and lax.ppermute hands the activation to the
next stage over ICI. Bubbles are the scan steps where a stage's input is
not yet (or no longer) valid; their results are masked out. The whole
schedule — forward, backward (jax.grad through ppermute reverses the
ring), and optimizer — compiles to one XLA program.

Constraints (inherent to the SPMD formulation): every stage consumes and
produces activations of the same shape [mb, ...]; heterogeneous head /
embedding layers run replicated outside the pipelined middle.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..mesh.compat import pcast as _pcast, shard_map as _shard_map
from .env import PP_AXIS


def stack_stage_params(per_stage_params: Sequence[Any]):
    """[pytree per stage] -> single pytree with leading stage dim, ready
    to shard over 'pp'."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def gpipe(stage_fn: Callable[[Any, jax.Array], jax.Array],
          stacked_params: Any,
          x: jax.Array,
          num_microbatches: int,
          mesh: Mesh,
          axis: str = PP_AXIS,
          remat: bool = True) -> jax.Array:
    """Run x through num_stages pipeline stages with GPipe microbatching.

    stage_fn(params_of_one_stage, act[mb, ...]) -> act[mb, ...]
    stacked_params: leading dim == mesh.shape[axis] (see
    stack_stage_params)
    x: [B, ...] with B % num_microbatches == 0.

    Differentiable end-to-end; with remat=True each stage's forward is
    rematerialized in the backward (the reference reaches the same
    memory trade via recompute checkpointing, backward.py:145).
    """
    n_stages = mesh.shape[axis]
    B = x.shape[0]
    assert B % num_microbatches == 0, (B, num_microbatches)
    mb = B // num_microbatches
    x_mb = x.reshape((num_microbatches, mb) + x.shape[1:])
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    T = num_microbatches + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def shard_body(params, x_all):
        # params leaves carry a leading local-stage dim of 1
        params = jax.tree.map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis)
        # mark the carries as device-varying along the pp axis (jax>=0.9
        # shard_map vma tracking; the loop body makes them varying)
        zero = _pcast(jnp.zeros(x_all.shape[1:], x_all.dtype),
                             (axis,), to="varying")
        outs0 = _pcast(jnp.zeros_like(x_all), (axis,), to="varying")

        def tick(carry, t):
            recv, outs = carry
            src = jnp.clip(t, 0, num_microbatches - 1)
            inp = jnp.where(stage == 0, x_all[src], recv)
            y = fn(params, inp)
            # collect on the last stage once the first microbatch arrives
            out_idx = jnp.clip(t - (n_stages - 1), 0, num_microbatches - 1)
            take = jnp.logical_and(stage == n_stages - 1,
                                   t >= n_stages - 1)
            outs = jnp.where(
                take, jax.lax.dynamic_update_index_in_dim(
                    outs, y, out_idx, 0), outs)
            recv_new = jax.lax.ppermute(y, axis, perm)
            return (recv_new, outs), None

        (_, outs), _ = jax.lax.scan(tick, (zero, outs0), jnp.arange(T))
        # replicate the last stage's outputs to every device
        return jax.lax.psum(
            jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs)),
            axis)

    pspec_params = jax.tree.map(lambda _: P(axis), stacked_params)
    out = _shard_map(
        shard_body, mesh=mesh,
        in_specs=(pspec_params, P()),
        out_specs=P(),
    )(stacked_params, x_mb)
    return out.reshape((B,) + out.shape[2:])


class PipelineLayer:
    """Convenience wrapper: uniform dygraph blocks -> pipelined callable.

    blocks: list of nn.Layer with matching in/out activation shapes (one
    or more per stage; len(blocks) % n_stages == 0 — consecutive blocks
    group onto a stage, the reference's section assignment).
    """

    def __init__(self, blocks, mesh: Mesh, num_microbatches: int,
                 axis: str = PP_AXIS, remat: bool = True):
        from ..jit import state_of, functional_call
        from ..dygraph.tape import Tensor
        n_stages = mesh.shape[axis]
        assert len(blocks) % n_stages == 0, \
            "blocks must divide evenly over stages"
        self.blocks = list(blocks)
        self.mesh = mesh
        self.axis = axis
        self.num_microbatches = num_microbatches
        self.remat = remat
        self.per_stage = len(blocks) // n_stages
        self._functional_call = functional_call
        self._Tensor = Tensor
        states = [state_of(b) for b in blocks]
        # One scanned stage_fn serves every stage, so the block at
        # within-stage position i must be structurally identical across
        # stages: same class (same forward code) and same param pytree.
        # A heterogeneous list would silently run stage 0's code with the
        # other stages' params — refuse it up front.
        def spec(st):
            # shape/dtype only — jnp.result_type reads the dtype without
            # materializing numpy-backed leaves on device
            return (jax.tree.structure(st),
                    jax.tree.map(lambda x: (jnp.shape(x),
                                            jnp.result_type(x)), st))

        def config(b):
            # scalar constructor config (dropout p, eps, axis flags...):
            # two same-type blocks with different config would otherwise
            # pass the param check and silently run stage 0's settings
            return {k: v for k, v in vars(b).items()
                    if isinstance(v, (int, float, str, bool, type(None)))}

        for i, b in enumerate(blocks):
            cfg = config(b)
            p = cfg.get("p", cfg.get("dropout_prob", 0)) or 0
            if isinstance(p, (int, float)) and p > 0:
                import warnings
                warnings.warn(
                    "PipelineLayer block %d (%s) has dropout prob %g but "
                    "the pipelined stage_fn runs blocks in eval mode (no "
                    "rng is threaded through the scan) — dropout will NOT "
                    "apply inside the pipeline" % (i, type(b).__name__, p))
                break

        specs0 = [spec(states[i]) for i in range(self.per_stage)]
        for s in range(1, n_stages):
            for i in range(self.per_stage):
                a, b = blocks[i], blocks[s * self.per_stage + i]
                if type(a) is not type(b):
                    raise TypeError(
                        "PipelineLayer requires structurally identical "
                        "stages: block %d of stage %d is %s but stage 0's "
                        "is %s. Run heterogeneous layers (embeddings, "
                        "heads) replicated outside the pipelined middle."
                        % (i, s, type(b).__name__, type(a).__name__))
                if spec(states[s * self.per_stage + i]) != specs0[i]:
                    raise ValueError(
                        "PipelineLayer stage %d block %d param structure "
                        "differs from stage 0's — stages must be "
                        "structurally identical" % (s, i))
                if config(a) != config(b):
                    raise ValueError(
                        "PipelineLayer stage %d block %d config %r differs "
                        "from stage 0's %r — one scanned stage_fn runs "
                        "stage 0's configuration for every stage"
                        % (s, i, config(b), config(a)))
        # group block states per stage, then stack across stages
        self._keys = sorted(states[0])
        grouped = []
        for s in range(n_stages):
            stage_blocks = states[s * self.per_stage:(s + 1) * self.per_stage]
            grouped.append({"b%d_%s" % (i, k): v
                            for i, st in enumerate(stage_blocks)
                            for k, v in st.items()})
        self.stacked = stack_stage_params(grouped)

    def _stage_fn(self, params, act):
        out = act
        for i in range(self.per_stage):
            st = {k.split("_", 1)[1]: v for k, v in params.items()
                  if k.startswith("b%d_" % i)}
            # blocks[i] is stage 0's block at within-stage position i —
            # by the construction-time check it is structurally
            # representative of every stage's block i
            r, _ = self._functional_call(self.blocks[i], st,
                                         self._Tensor(out), training=False)
            out = r.value if hasattr(r, "value") else r
        return out

    def __call__(self, x):
        return gpipe(self._stage_fn, self.stacked, x,
                     self.num_microbatches, self.mesh, self.axis,
                     self.remat)


# ---------------------------------------------------------------------------
# static-graph side: device_guard sections (optimizer.py:3790
# PipelineOptimizer._split_program)
# ---------------------------------------------------------------------------

def split_program_by_device(program):
    """Group the global block's ops into sections by their op_device
    stamp (set under core.program.device_guard). Unstamped ops inherit
    the previous op's device, like the reference's
    _add_op_device_attr_for_op. Returns [(device, [OpDesc, ...]), ...] in
    program order."""
    sections = []
    cur_dev, cur_ops = None, []
    for op in program.global_block.ops:
        dev = op.attrs.get("op_device", cur_dev)
        if dev != cur_dev and cur_ops:
            sections.append((cur_dev, cur_ops))
            cur_ops = []
        cur_dev = dev
        cur_ops.append(op)
    if cur_ops:
        sections.append((cur_dev, cur_ops))
    return sections


class PipelineOptimizer:
    """optimizer.py:3666 PipelineOptimizer for the static path: rewrites
    the device_guard-stamped forward into one `pipeline_train` meta-op
    (per-section sub-blocks driven by the SPMD GPipe schedule of
    pipeline_static.py — the reference's SectionWorker threads + queues
    become one shard_map'ed scan over the `pp` mesh axis), then appends
    the inner optimizer's update ops against the grads the schedule
    produces. num_microbatches=1 keeps the rewrite but degenerates to
    sequential stages (still correct, no overlap)."""

    def __init__(self, optimizer, num_microbatches: int = 1):
        self._inner = optimizer
        self.num_microbatches = num_microbatches
        self.sections = None

    def minimize(self, loss, startup_program=None, program=None,
                 parameter_list=None, no_grad_set=None):
        from ..core.program import (default_main_program,
                                    default_startup_program)
        from .pipeline_static import rewrite_pipeline_program
        if no_grad_set:
            # append_backward's no_grad_set contract, via its own name
            # normalizer + trainable/stop_gradient filter
            from ..core.backward import _var_name
            names = {_var_name(p) for p in no_grad_set}
            if parameter_list is None:
                parameter_list = [
                    v.name for v in (
                        program or default_main_program())
                    .all_parameters()
                    if v.name not in names and v.trainable
                    and not v.stop_gradient]
            else:
                parameter_list = [p for p in parameter_list
                                  if _var_name(p) not in names]
        # hasattr is NOT enough: MetaOptimizerBase.__getattr__ delegates
        # to the innermost optimizer, which would silently bypass the
        # wrapper's semantics (gradient merge, DGC...) — require
        # apply_gradients defined on the class itself
        if not any("apply_gradients" in vars(k)
                   for k in type(self._inner).__mro__):
            raise TypeError(
                "PipelineOptimizer needs a base optimizer DEFINING "
                "apply_gradients (got %s — a wrapper whose rewrite the "
                "pipeline schedule would silently drop); wrap the base "
                "optimizer directly, as the reference requires "
                "(optimizer.py:3666)" % type(self._inner).__name__)
        prog = program if program is not None else default_main_program()
        startup = startup_program if startup_program is not None \
            else default_startup_program()
        params_grads = rewrite_pipeline_program(
            prog, loss, self.num_microbatches,
            parameter_list=parameter_list)
        self._inner.apply_gradients(params_grads, prog, startup)
        return None, params_grads
