"""Dygraph DataParallel + process spawn.

Analog of /root/reference/python/paddle/fluid/dygraph/parallel.py
(DataParallel:236 — scale_loss:337 divides by nranks,
apply_collective_grads:449 coalesces + allreduces gradients over NCCL)
and python/paddle/distributed/spawn.py:231.

On a single-controller TPU mesh, DataParallel shards every batch input
over the dp axis (a taped reshard, so input grads flow) and lets the
sharding propagate: each device computes its shard, XLA inserts the
cross-device reductions — the wrapper IS the execution path, not API
dressing. scale_loss/apply_collective_grads keep the reference's shape
for shard_map-per-device flows. spawn() forks per-rank host processes
with the reference's env contract — the multi-host (one controller per
host) deployment path.
"""
from __future__ import annotations

import multiprocessing as mp
import os
from typing import List, Optional

import numpy as np

from . import collective
from .env import DP_AXIS, get_env, get_mesh


class DataParallel:
    """Wraps a dygraph Layer for data-parallel training.

    This is a REAL execution path, not API dressing: forward() stages
    every batch input sharded over the mesh's dp axis before calling
    the wrapped layer. In eager jax, computation follows sharding —
    each device computes its batch shard of every op, batch-axis
    reductions become cross-device psums XLA inserts, and the tape's
    backward inherits the same layout, which is exactly the reference's
    replicated-module + grad-allreduce semantics
    (dygraph/parallel.py:236) without a wrapper-side collective."""

    def __init__(self, layers, strategy=None, comm_buffer_size_MB: int = 25,
                 last_comm_buffer_size_MB: int = 1):
        self._layers = layers
        self._nranks = max(1, get_env().nranks)

    def __getattr__(self, name):
        return getattr(self._layers, name)

    def _shard_input(self, x):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..dygraph.tape import Tensor
        mesh = get_mesh()
        if mesh is None or DP_AXIS not in mesh.axis_names or \
                mesh.shape[DP_AXIS] <= 1:
            return x
        t = x if isinstance(x, Tensor) else None
        v = t.value if t is not None else x
        if not hasattr(v, "ndim"):
            # only array-likes shard; containers/None/scalars pass
            # through untouched (a list of states must STAY a list)
            return x
        n = mesh.shape[DP_AXIS]
        if v.ndim < 1 or v.shape[0] % n != 0:
            return x
        spec = P(DP_AXIS, *([None] * (v.ndim - 1)))
        sh = NamedSharding(mesh, spec)
        if t is not None and not t.stop_gradient:
            # TAPED reshard: grads must flow back to the caller's
            # tensor (input-saliency/GAN flows read input grads), so
            # the device_put goes through apply_fn which records a
            # proper GradNode — grad of a reshard is identity
            from ..dygraph.tape import apply_fn
            return apply_fn(lambda a: [jax.device_put(a, sh)], t)[0]
        return Tensor(jax.device_put(v, sh), stop_gradient=True)

    def __call__(self, *args, **kw):
        return self.forward(*args, **kw)

    def forward(self, *args, **kw):
        args = [self._shard_input(a) for a in args]
        kw = {k: self._shard_input(v) for k, v in kw.items()}
        return self._layers(*args, **kw)

    def scale_loss(self, loss):
        """parallel.py:337 — divide by trainer count so the summed
        allreduce averages."""
        if self._nranks <= 1:
            return loss
        return loss * (1.0 / self._nranks)

    def apply_collective_grads(self):
        """parallel.py:449 — allreduce every parameter gradient over the
        dp axis (coalescing is XLA's job)."""
        if self._nranks <= 1 or get_mesh() is None:
            return
        for p in self._layers.parameters():
            if p.grad is None:
                continue
            g = p.grad
            if hasattr(g, "values"):  # SelectedRows: reduce values
                g.values = collective.all_reduce(g.values, "sum",
                                                 axis=DP_AXIS)
            else:
                p.grad = collective.all_reduce(g, "sum", axis=DP_AXIS)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)


def _spawn_target(fn, rank, nprocs, env, args):
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    fn(*args) if args else fn(rank)


def spawn(func, args=(), nprocs: int = 1, join: bool = True,
          daemon: bool = False, **options) -> List[mp.Process]:
    """paddle.distributed.spawn (spawn.py:231): one process per rank
    with the cluster env contract; join waits and raises on failure."""
    ctx = mp.get_context("spawn")
    eps = ",".join("127.0.0.1:%d" % (61000 + i) for i in range(nprocs))
    procs = []
    for rank in range(nprocs):
        env = {"PADDLE_TRAINER_ENDPOINTS": eps,
               "TRAINING_ROLE": "TRAINER"}
        p = ctx.Process(target=_spawn_target,
                        args=(func, rank, nprocs, env, args),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError("spawned rank failed with exit code %s"
                                   % p.exitcode)
    return procs
