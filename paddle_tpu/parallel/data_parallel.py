"""Dygraph DataParallel + process spawn.

Analog of /root/reference/python/paddle/fluid/dygraph/parallel.py
(DataParallel:236 — scale_loss:337 divides by nranks,
apply_collective_grads:449 coalesces + allreduces gradients over NCCL)
and python/paddle/distributed/spawn.py:231.

On a single-controller TPU mesh the replicated-dygraph formulation is
degenerate (every "rank" computes the same grads), so allreduce is a
mathematical no-op there; the class exists for API parity and for
shard_map-per-device flows where grads really do differ. spawn() forks
per-rank host processes with the reference's env contract — the
multi-host (one controller per host) deployment path.
"""
from __future__ import annotations

import multiprocessing as mp
import os
from typing import List, Optional

import numpy as np

from . import collective
from .env import DP_AXIS, get_env, get_mesh


class DataParallel:
    """Wraps a dygraph Layer for data-parallel training."""

    def __init__(self, layers, strategy=None, comm_buffer_size_MB: int = 25,
                 last_comm_buffer_size_MB: int = 1):
        self._layers = layers
        self._nranks = max(1, get_env().nranks)

    def __getattr__(self, name):
        return getattr(self._layers, name)

    def __call__(self, *args, **kw):
        return self._layers(*args, **kw)

    def forward(self, *args, **kw):
        return self._layers(*args, **kw)

    def scale_loss(self, loss):
        """parallel.py:337 — divide by trainer count so the summed
        allreduce averages."""
        if self._nranks <= 1:
            return loss
        return loss * (1.0 / self._nranks)

    def apply_collective_grads(self):
        """parallel.py:449 — allreduce every parameter gradient over the
        dp axis (coalescing is XLA's job)."""
        if self._nranks <= 1 or get_mesh() is None:
            return
        for p in self._layers.parameters():
            if p.grad is None:
                continue
            g = p.grad
            if hasattr(g, "values"):  # SelectedRows: reduce values
                g.values = collective.all_reduce(g.values, "sum",
                                                 axis=DP_AXIS)
            else:
                p.grad = collective.all_reduce(g, "sum", axis=DP_AXIS)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)


def _spawn_target(fn, rank, nprocs, env, args):
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    fn(*args) if args else fn(rank)


def spawn(func, args=(), nprocs: int = 1, join: bool = True,
          daemon: bool = False, **options) -> List[mp.Process]:
    """paddle.distributed.spawn (spawn.py:231): one process per rank
    with the cluster env contract; join waits and raises on failure."""
    ctx = mp.get_context("spawn")
    eps = ",".join("127.0.0.1:%d" % (61000 + i) for i in range(nprocs))
    procs = []
    for rank in range(nprocs):
        env = {"PADDLE_TRAINER_ENDPOINTS": eps,
               "TRAINING_ROLE": "TRAINER"}
        p = ctx.Process(target=_spawn_target,
                        args=(func, rank, nprocs, env, args),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError("spawned rank failed with exit code %s"
                                   % p.exitcode)
    return procs
