"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

First-class long-context support (absent in the reference — SURVEY.md §5
notes v1.8 predates it; its longest-sequence tools were LoD ragged
batching and recompute). Two standard schemes over the mesh 'sp' axis:

- ring_attention: Q stays put, K/V blocks rotate around the ring via
  lax.ppermute while an online-softmax accumulator (the same
  recurrence as kernels/flash_attention.py, at the shard level) folds
  in one block per step. Memory per device is O(S/n) and the KV
  transfer overlaps compute on ICI.
- ulysses_attention: all-to-all re-partitions [B, H/n, S, D] <->
  [B, H, S/n, D] so each device computes full-sequence attention for a
  head subset (DeepSpeed-Ulysses scheme); cheaper at moderate S, needs
  H % n == 0.

Both are differentiable (grad of ppermute is the reverse permute; grad
of all_to_all is all_to_all back) and compose with the dp/mp axes.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..mesh.compat import pcast as _pcast, shard_map as _shard_map
from .env import SP_AXIS


def _online_block(q, k_blk, v_blk, acc, m, l, sm_scale, mask=None):
    """Fold one K/V block into the running (acc, m, l) softmax state.
    q: [B,H,Sq,D]; k_blk/v_blk: [B,H,Sk,D]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk).astype(jnp.float32) * sm_scale
    if mask is not None:
        s = jnp.where(mask, s, -1e30)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + p.sum(axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
    return acc_new, m_new, l_new


def ring_attention(q, k, v, mesh: Mesh, axis: str = SP_AXIS,
                   causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Attention over a sequence sharded on `axis`.

    q, k, v: [B, H, S, D] *global* arrays (sharded or shardable on S).
    Returns [B, H, S, D] with the same sharding. Inside, each device
    holds S/n query rows and rotates K/V shards n times.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = mesh.shape[axis]
    S = q.shape[2]
    assert S % n == 0, (S, n)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(q_l, k_l, v_l):
        # local shapes [B, H, S/n, D]
        my = jax.lax.axis_index(axis)
        s_loc = q_l.shape[2]
        # device-varying initial accumulators (jax>=0.9 shard_map vma)
        acc = _pcast(jnp.zeros(q_l.shape, jnp.float32), (axis,),
                            to="varying")
        m = _pcast(jnp.full(q_l.shape[:3], -1e30, jnp.float32),
                          (axis,), to="varying")
        l = _pcast(jnp.zeros(q_l.shape[:3], jnp.float32), (axis,),
                          to="varying")

        def step(carry, i):
            acc, m, l, k_cur, v_cur = carry
            # k_cur currently holds the shard that started on device
            # (my - i) mod n
            src = (my - i) % n
            if causal:
                def compute(args):
                    acc, m, l = args
                    q_pos = my * s_loc + jnp.arange(s_loc)[:, None]
                    k_pos = src * s_loc + jnp.arange(s_loc)[None, :]
                    mask = (q_pos >= k_pos)[None, None]
                    return _online_block(q_l, k_cur, v_cur, acc, m, l,
                                         sm_scale, mask)

                # a K/V shard strictly in this device's future (src > my)
                # is FULLY masked: skip the whole score/PV block. The
                # predicate is per-device (divergent branches are fine —
                # no collective inside; the ppermutes below run
                # unconditionally on every device). Saves ~(n-1)/2n of
                # the causal schedule's FLOPs, the shard-level analog of
                # the flash kernel's nk_live loop bound.
                acc, m, l = jax.lax.cond(src <= my, compute,
                                         lambda args: args, (acc, m, l))
            else:
                acc, m, l = _online_block(q_l, k_cur, v_cur, acc, m, l,
                                          sm_scale, None)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return (acc, m, l, k_nxt, v_nxt), None

        (acc, m, l, _, _), _ = jax.lax.scan(
            step, (acc, m, l, k_l, v_l), jnp.arange(n))
        l = jnp.maximum(l, 1e-30)
        return (acc / l[..., None]).astype(q_l.dtype)

    return _shard_map(
        body, mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None),
        # old-jax rep-checker can't type the cond/scan ring (jax says:
        # workaround check_rep=False); every in_spec mentions the axis,
        # so the transpose needs no replication rewrite either
        check_vma=False,
    )(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = SP_AXIS,
                      causal: bool = False,
                      sm_scale: Optional[float] = None):
    """All-to-all sequence parallelism: trade the S shard for an H shard,
    run full-sequence attention per head subset, trade back."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = mesh.shape[axis]
    B, H, S, D = q.shape
    assert H % n == 0 and S % n == 0, (H, S, n)

    def body(q_l, k_l, v_l):
        # local [B, H, S/n, D] -> [B, H/n, S, D]
        def seq2head(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        def head2seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        qh, kh, vh = seq2head(q_l), seq2head(k_l), seq2head(v_l)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh).astype(jnp.float32) \
            * sm_scale
        if causal:
            mask = jnp.tril(jnp.ones((S, S), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p,
                       vh.astype(jnp.float32)).astype(q_l.dtype)
        return head2seq(o)

    return _shard_map(
        body, mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None),
        # old-jax rep-checker can't type the cond/scan ring (jax says:
        # workaround check_rep=False); every in_spec mentions the axis,
        # so the transpose needs no replication rewrite either
        check_vma=False,
    )(q, k, v)
