"""Collective communication API + collective ops.

Parity surface: /root/reference/paddle/fluid/operators/collective/
(c_allreduce_{sum,max,min,prod}_op.cc, c_broadcast_op.cc, c_allgather_op.cc,
c_reducescatter_op.cc, c_scatter_op.cc, barrier_op.cc) and the python API
/root/reference/python/paddle/distributed/collective.py:59-419.

Design: the reference keys comms by ring_id and issues NCCL calls on comm
streams (c_allreduce_op.h:108-133); here `ring_id` maps to a *named mesh
axis* and each collective lowers to the XLA collective primitive
(psum/all_gather/psum_scatter/ppermute) which rides ICI. Outside shard_map
(no device axis bound), SPMD semantics make the host-level call the
identity over a replicated value — matching single-rank behavior of the
reference. The functional API below works in BOTH positions:

- inside shard_map/pjit-manual code: real lax collectives,
- at host level on sharded jax.Arrays: jit-wrapped collectives via
  shard_map over the global mesh.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.registry import register_op
from ..mesh import compat as _compat
from . import env as _envmod

# ring_id -> axis name registry: the analog of NCCLCommContext's ring table
# (collective_helper.h:62). Transpilers create rings; here ring 0 is the
# data axis by default.
_ring_axes = {0: _envmod.DP_AXIS}


def set_ring_axis(ring_id: int, axis: str):
    _ring_axes[ring_id] = axis


def ring_axis(ring_id: int) -> str:
    return _ring_axes.get(ring_id, _envmod.DP_AXIS)


def _in_shard_map(axis: str) -> bool:
    return _compat.in_named_axis(axis)


def _count_launch(axis: str, val=None, passes: int = 2) -> None:
    """Per-axis host-level collective census — op counts
    (STAT_mesh_collective_dp etc.) plus payload bytes by dtype
    (STAT_mesh_collective_bytes{axis,dtype}), the mesh instrument
    family of docs/spmd.md. Bytes follow the ring model documented in
    monitor.py: each rank forwards (p-1)/p of the payload per ring
    pass; AllReduce-family ops cost two passes, gather/scatter/
    all_to_all one."""
    from ..monitor import labeled, stat_add
    stat_add("STAT_mesh_collective_%s" % axis)
    if val is None:
        return
    mesh = _envmod.get_mesh()
    p = int(mesh.shape[axis]) if (
        mesh is not None and axis in mesh.axis_names) else 1
    nbytes = int(getattr(val, "nbytes", 0) or 0)
    if p > 1 and nbytes:
        stat_add(labeled("STAT_mesh_collective_bytes",
                         {"axis": axis, "dtype": str(val.dtype)}),
                 int(passes * nbytes * (p - 1) / p))


def _host_collective(fn, x, axis, passes: int = 2):
    """Apply a per-rank collective to a host-level value via shard_map.

    Rank semantics follow the input's sharding. An array actually sharded
    over `axis` (e.g. via shard_batch) enters shard-per-rank — each shard
    is that rank's value. Anything else (numpy, single-device,
    replicated) is the SAME logical value on every rank — the reference's
    replicated-per-process dygraph grads — so each rank runs the
    collective on its copy: allreduce-sum multiplies by nranks, exactly
    the NCCL semantics DataParallel.scale_loss pre-divides for
    (dygraph/parallel.py:337)."""
    from jax.sharding import NamedSharding
    mesh = _envmod.get_mesh()
    if mesh is None or axis not in mesh.axis_names or \
            mesh.shape[axis] == 1:
        return x  # single rank: identity (matches reference nranks==1)
    spec = P()
    sh = getattr(x, "sharding", None)
    if isinstance(sh, NamedSharding):
        in_axes = [a for entry in sh.spec if entry is not None
                   for a in (entry if isinstance(entry, tuple) else (entry,))]
        if axis in in_axes:
            spec = sh.spec
    _count_launch(axis, x, passes)
    return jax.jit(_compat.shard_map(fn, mesh=mesh, in_specs=spec,
                                     out_specs=spec, check_vma=False))(x)


_REDUCERS = {
    "sum": jax.lax.psum,
    "max": jax.lax.pmax,
    "min": jax.lax.pmin,
    "prod": lambda x, axis_name: jnp.exp(
        jax.lax.psum(jnp.log(x), axis_name)),
}


def all_reduce(x, op: str = "sum", axis: Optional[str] = None,
               ring_id: int = 0):
    """c_allreduce_{sum,max,min,prod} analog."""
    axis = axis or ring_axis(ring_id)
    red = _REDUCERS[op]
    if _in_shard_map(axis):
        return red(x, axis)

    def f(shard):
        r = red(shard, axis)
        # host-level semantic: every shard becomes the reduction → out
        # sharding stays the same but all shards equal; express as
        # reduce + broadcast by returning replicated-value shards
        return r
    val = x.value if hasattr(x, "value") else x
    out = _host_collective(f, val, axis)
    return _rewrap(x, out)


def all_gather(x, axis: Optional[str] = None, ring_id: int = 0,
               tensor_axis: int = 0):
    """c_allgather analog: concat shards along tensor_axis."""
    axis = axis or ring_axis(ring_id)
    if _in_shard_map(axis):
        return jax.lax.all_gather(x, axis, axis=tensor_axis, tiled=True)
    mesh = _envmod.get_mesh()
    val = x.value if hasattr(x, "value") else x
    if mesh is None or axis not in mesh.axis_names:
        return x
    spec_in = P(*([axis] + [None] * (jnp.ndim(val) - 1)))
    spec_out = P(*([None] * jnp.ndim(val)))

    def f(shard):
        return jax.lax.all_gather(shard, axis, axis=tensor_axis, tiled=True)
    _count_launch(axis, val, passes=1)
    out = jax.jit(_compat.shard_map(f, mesh=mesh, in_specs=spec_in,
                                    out_specs=spec_out, check_vma=False))(val)
    return _rewrap(x, out)


def reduce_scatter(x, axis: Optional[str] = None, ring_id: int = 0,
                   tensor_axis: int = 0):
    """c_reducescatter analog."""
    axis = axis or ring_axis(ring_id)
    if _in_shard_map(axis):
        return jax.lax.psum_scatter(x, axis, scatter_dimension=tensor_axis,
                                    tiled=True)
    raise NotImplementedError(
        "host-level reduce_scatter: shard the array and use pjit shardings")


def broadcast(x, src: int = 0, axis: Optional[str] = None, ring_id: int = 0):
    """c_broadcast analog: everyone takes rank `src`'s shard."""
    axis = axis or ring_axis(ring_id)
    if _in_shard_map(axis):
        n = _compat.axis_size(axis)
        return jax.lax.ppermute(x, axis, [(src, i) for i in range(n)])
    val = x.value if hasattr(x, "value") else x

    def f(shard):
        n = _compat.axis_size(axis)
        return jax.lax.ppermute(shard, axis, [(src, i) for i in range(n)])
    out = _host_collective(f, val, axis, passes=1)
    return _rewrap(x, out)


def all_to_all(x, axis: Optional[str] = None, ring_id: int = 0,
               split_axis: int = 0, concat_axis: int = 0):
    """alltoall analog (distributed/collective.py:376) — the primitive for
    sequence-parallel attention (DeepSpeed-Ulysses style) and sharded
    embedding exchange."""
    axis = axis or ring_axis(ring_id)
    if _in_shard_map(axis):
        return jax.lax.all_to_all(x, axis, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)
    # host level: the value's dim 0 is the stacked per-rank axis (the
    # reference alltoall's in_tensor_list flattened) — same contract as
    # all_gather above. Shard it over `axis` if it isn't already, run
    # the tiled lax.all_to_all per shard, keep the same layout out
    # (per-shard shapes are uniform, so in/out specs agree).
    from jax.sharding import NamedSharding
    mesh = _envmod.get_mesh()
    val = x.value if hasattr(x, "value") else x
    if mesh is None or axis not in mesh.axis_names or \
            mesh.shape[axis] == 1:
        return x  # single rank: identity, matches reference nranks==1
    n = mesh.shape[axis]
    if jnp.shape(val)[0] % n != 0:
        raise ValueError(
            "all_to_all: leading dim %d not divisible by axis %r size %d"
            % (jnp.shape(val)[0], axis, n))
    spec = P(*([axis] + [None] * (jnp.ndim(val) - 1)))
    sh = getattr(val, "sharding", None)
    if not (isinstance(sh, NamedSharding) and sh.spec == spec):
        val = jax.device_put(val, NamedSharding(mesh, spec))

    def f(shard):
        return jax.lax.all_to_all(shard, axis, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)
    _count_launch(axis, val, passes=1)
    out = jax.jit(_compat.shard_map(f, mesh=mesh, in_specs=spec,
                                    out_specs=spec, check_vma=False))(val)
    return _rewrap(x, out)


def ppermute(x, perm, axis: Optional[str] = None, ring_id: int = 0):
    """send/recv pair analog for pipeline stage boundaries."""
    axis = axis or ring_axis(ring_id)
    return jax.lax.ppermute(x, axis, perm)


def barrier(ring_id: int = 0):
    """barrier op analog — a no-op at host level: the single-controller
    dispatch plus XLA program order already serialize; kept for API
    parity."""
    return None


def _rewrap(x, out):
    if hasattr(x, "value"):
        from ..dygraph.tape import Tensor
        return Tensor(out, stop_gradient=getattr(x, "stop_gradient", True))
    return out


# ---------------------------------------------------------------------------
# collective *ops* for static programs: the transpiler inserts these; the
# executor lowers them. Inside a sharded executor (CompiledProgram with a
# mesh) they become real collectives; single-device they are identity,
# mirroring the reference's nranks==1 fast path.
# ---------------------------------------------------------------------------
def _c_allreduce(kind):
    def lower(ctx, ins, attrs):
        x = ins["X"][0]
        axis = attrs.get("axis") or ring_axis(attrs.get("ring_id", 0))
        if _in_shard_map(axis):
            return {"Out": [_REDUCERS[kind](x, axis)]}
        return {"Out": [x]}
    return lower


for _k in ("sum", "max", "min", "prod"):
    register_op(f"c_allreduce_{_k}", inputs=("X",), no_grad=True)(
        _c_allreduce(_k))


@register_op("c_broadcast", inputs=("X",), no_grad=True)
def _c_broadcast(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis") or ring_axis(attrs.get("ring_id", 0))
    root = attrs.get("root", 0)
    if _in_shard_map(axis):
        n = _compat.axis_size(axis)
        return {"Out": [jax.lax.ppermute(
            x, axis, [(root, i) for i in range(n)])]}
    return {"Out": [x]}


@register_op("c_allgather", inputs=("X",), no_grad=True)
def _c_allgather(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis") or ring_axis(attrs.get("ring_id", 0))
    if _in_shard_map(axis):
        return {"Out": [jax.lax.all_gather(x, axis, axis=0, tiled=True)]}
    return {"Out": [x]}


@register_op("c_reducescatter", inputs=("X",), no_grad=True)
def _c_reducescatter(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis") or ring_axis(attrs.get("ring_id", 0))
    if _in_shard_map(axis):
        return {"Out": [jax.lax.psum_scatter(x, axis, scatter_dimension=0,
                                             tiled=True)]}
    return {"Out": [x]}


@register_op("c_sync_calc_stream", inputs=("X",), no_grad=True)
def _c_sync_calc(ctx, ins, attrs):
    # stream sync is moot under XLA scheduling (SURVEY.md §5 mapping)
    return {"Out": [ins["X"][0]]}


@register_op("c_sync_comm_stream", inputs=("X",), no_grad=True)
def _c_sync_comm(ctx, ins, attrs):
    return {"Out": [ins["X"][0]]}


@register_op("barrier", inputs=("X",), no_grad=True)
def _barrier_op(ctx, ins, attrs):
    return {"Out": [ins["X"][0]]}


@register_op("c_comm_init_all", inputs=(), outputs=(), no_grad=True)
def _c_comm_init_all(ctx, ins, attrs):
    # comm bootstrap collapses into mesh construction (SURVEY.md §2.7);
    # the op is accepted for program compatibility and does nothing.
    return {}


@register_op("c_gen_nccl_id", inputs=(), outputs=(), no_grad=True)
def _c_gen_nccl_id(ctx, ins, attrs):
    return {}


@register_op("c_comm_init", inputs=(), outputs=(), no_grad=True)
def _c_comm_init(ctx, ins, attrs):
    return {}


def _c_reduce(kind):
    def lower(ctx, ins, attrs):
        """c_reduce_{sum,max,min,prod} (c_reduce_op.h): reduce to the
        root rank. Under GSPMD the all-reduce result IS the per-root
        value (every replica holds it); root selection is a rank-side
        concern the single controller doesn't have — semantics match
        the reference's root output."""
        x = ins["X"][0]
        axis = ring_axis(attrs.get("ring_id", 0))
        if _in_shard_map(axis):
            red = {"sum": jax.lax.psum, "max": jax.lax.pmax,
                   "min": jax.lax.pmin}.get(kind)
            if red is None:  # prod: log-space psum is lossy; use
                return {"Out": [jax.lax.all_gather(x, axis).prod(0)]}
            return {"Out": [red(x, axis)]}
        return {"Out": [x]}
    return lower


for _k in ("sum", "max", "min", "prod"):
    register_op("c_reduce_%s" % _k, inputs=("X",), no_grad=True)(
        _c_reduce(_k))


@register_op("c_scatter", inputs=("X",), no_grad=True)
def _c_scatter(ctx, ins, attrs):
    """c_scatter_op.cc: root's tensor splits across the ring; rank i
    takes slice i. Inside shard_map: slice by axis_index."""
    x = ins["X"][0]
    axis = ring_axis(attrs.get("ring_id", 0))
    nranks = int(attrs.get("nranks", 1))
    if _in_shard_map(axis):
        i = jax.lax.axis_index(axis)
        per = x.shape[0] // _compat.axis_size(axis)
        return {"Out": [jax.lax.dynamic_slice_in_dim(x, i * per, per, 0)]}
    # single-controller: emit the full split stack; GSPMD shards it
    return {"Out": [x.reshape((nranks, x.shape[0] // nranks)
                              + x.shape[1:])]}


@register_op("allreduce", inputs=("X",), no_grad=True)
def _allreduce_legacy(ctx, ins, attrs):
    """Legacy allreduce op (operators/collective/allreduce_op.h):
    reduce_type attr selects the reduction; rides the same mesh axis as
    c_allreduce_*."""
    import jax
    x = ins["X"][0]
    axis = attrs.get("axis") or ring_axis(attrs.get("ring_id", 0))
    red = {0: jax.lax.psum, 1: jax.lax.pmax, 2: jax.lax.pmin}.get(
        int(attrs.get("reduce_type", 0)), jax.lax.psum)
    if _in_shard_map(axis):
        return {"Out": [red(x, axis)]}
    return {"Out": [x]}


@register_op("broadcast", inputs=("X",), no_grad=True)
def _broadcast_legacy(ctx, ins, attrs):
    """Legacy broadcast op (operators/collective/broadcast_op.cc) —
    c_broadcast semantics with the root attr."""
    from ..core.registry import REGISTRY as _R
    return _R.get("c_broadcast").lower(ctx, ins, attrs)


@register_op("gen_nccl_id", inputs=(), outputs=("NCCLID",), no_grad=True,
             host=True)
def _gen_nccl_id(ctx, ins, attrs):
    """gen_nccl_id_op.cc bootstraps NCCL communicators over RPC; on TPU
    the rendezvous is jax.distributed's coordinator, so the op returns
    an opaque token for program-level parity."""
    import numpy as np
    return {"NCCLID": [np.zeros((1,), np.int64)]}
