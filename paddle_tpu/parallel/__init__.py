from .env import (DistEnv, get_env, get_mesh, get_rank,  # noqa: F401
                  get_world_size, init_distributed_runtime,
                  init_parallel_env, shard_batch, sharding,
                  DP_AXIS, MP_AXIS, PP_AXIS, SP_AXIS)
from .collective import (all_gather, all_reduce, all_to_all, barrier,  # noqa: F401
                         broadcast, ppermute, reduce_scatter, ring_axis,
                         set_ring_axis)
from .pipeline import (gpipe, stack_stage_params, PipelineLayer,  # noqa: F401
                       PipelineOptimizer, split_program_by_device)
from .ring_attention import ring_attention, ulysses_attention  # noqa: F401
from .moe import (init_moe_params, moe_ffn,  # noqa: F401
                  moe_ffn_sharded)
from .data_parallel import DataParallel, spawn  # noqa: F401
