"""Program visualization — the debugger/graphviz analog.

Analog of /root/reference/python/paddle/fluid/debugger.py (draw_block_graphviz)
+ tools' graphviz.py and the ir/graph_viz_pass: renders a Program block
as DOT text (ops as boxes, vars as ellipses, parameters shaded) for
chrome/graphviz inspection. Pure text — no graphviz binary needed to
generate; `dot -Tpng` renders it wherever available.
"""
from __future__ import annotations

from typing import Optional

from .core.program import Program


def _esc(s: str) -> str:
    return s.replace('"', '\\"')


def program_to_dot(program: Program, block_idx: int = 0,
                   title: Optional[str] = None,
                   max_vars_per_op: int = 8) -> str:
    """DOT source for one block (debugger.py draw_block_graphviz)."""
    block = program.blocks[block_idx]
    lines = ["digraph Program {",
             '  rankdir=TB; node [fontsize=10];']
    if title:
        lines.append('  label="%s"; labelloc=t;' % _esc(title))
    emitted_vars = set()

    def var_node(name: str) -> str:
        nid = "var_" + name.replace(".", "_").replace("@", "_AT_")
        if name not in emitted_vars:
            emitted_vars.add(name)
            v = block.vars.get(name)
            if v is not None and v.persistable:
                style = 'shape=ellipse style=filled fillcolor=lightblue'
            else:
                style = 'shape=ellipse'
            shape = "" if v is None or v.shape is None else \
                "\\n%s" % (tuple(v.shape),)
            lines.append('  %s [%s label="%s%s"];'
                         % (nid, style, _esc(name), shape))
        return nid

    for i, op in enumerate(block.ops):
        op_id = "op_%d" % i
        lines.append('  %s [shape=box style=filled fillcolor=gold '
                     'label="%s"];' % (op_id, _esc(op.type)))
        for names in op.inputs.values():
            for n in names[:max_vars_per_op]:
                lines.append("  %s -> %s;" % (var_node(n), op_id))
        for names in op.outputs.values():
            for n in names[:max_vars_per_op]:
                lines.append("  %s -> %s;" % (op_id, var_node(n)))
    lines.append("}")
    return "\n".join(lines)


def save_program_dot(program: Program, path: str, **kw) -> str:
    dot = program_to_dot(program, **kw)
    with open(path, "w") as f:
        f.write(dot)
    return path
