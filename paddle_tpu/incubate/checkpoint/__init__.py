from .atomic import AtomicCheckpointer, CheckpointCorrupt  # noqa: F401
from .auto_checkpoint import train_epoch_range  # noqa: F401
from .checkpoint_saver import CheckpointSaver  # noqa: F401
from .sharded import (ShardedCheckpointer,  # noqa: F401
                      restore_train_step, save_train_step)
