"""Sharded (multi-host) checkpointing via orbax.

The reference checkpoints distributed runs through its fleet FS layer
(/root/reference/python/paddle/fluid/incubate/checkpoint/checkpoint_saver.py:59
SerializableBase/PaddleModel over HDFS), gathering tensors host-side.
The TPU-native answer keeps arrays SHARDED end to end: orbax writes
each host's shards of a NamedSharding'ed pytree in parallel (OCDBT),
and restore re-materializes them with the SAME shardings — no host
gather, no single-writer bottleneck, works under jax.distributed
multi-host exactly like a one-process virtual mesh.

`ShardedCheckpointer` handles any pytree of jax arrays;
`save_train_step` / `restore_train_step` wrap a `paddle_tpu.jit.
TrainStep`'s full training state (params+buffers, optimizer slots,
lr step).
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

__all__ = ["ShardedCheckpointer", "save_train_step", "restore_train_step"]


class ShardedCheckpointer:
    """Step-indexed checkpoint directory of sharded pytrees.

    >>> ck = ShardedCheckpointer(root, max_to_keep=3)
    >>> ck.save(step, {"params": params, "opt": opt_state})
    >>> tree = ck.restore(template={"params": params0, "opt": opt0})
    """

    def __init__(self, root: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(root),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))

    def save(self, step: int, pytree: Any, force: bool = False) -> bool:
        """Async: returns once the save is COMMITTED to background
        write (training overlaps the OCDBT write); reads
        (restore/latest_step/all_steps) and close() flush first."""
        ocp = self._ocp
        return bool(self._mgr.save(int(step),
                                   args=ocp.args.StandardSave(pytree),
                                   force=force))

    def latest_step(self) -> Optional[int]:
        self._mgr.wait_until_finished()
        return self._mgr.latest_step()

    def all_steps(self):
        self._mgr.wait_until_finished()
        return list(self._mgr.all_steps())

    def restore(self, step: Optional[int] = None,
                template: Any = None) -> Any:
        """Restore `step` (default: latest). `template` — a pytree of
        arrays or jax.ShapeDtypeStruct(..., sharding=...) — pins the
        restored shardings; without it arrays come back host-resident
        and the caller re-device_puts."""
        ocp = self._ocp
        self._mgr.wait_until_finished()
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoint steps saved yet")
        if template is not None:
            abstract = jax.tree.map(
                lambda a: a if isinstance(a, jax.ShapeDtypeStruct)
                else jax.ShapeDtypeStruct(
                    np.shape(a), a.dtype,
                    sharding=getattr(a, "sharding", None)),
                template)
            return self._mgr.restore(
                int(step), args=ocp.args.StandardRestore(abstract))
        return self._mgr.restore(int(step))

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()


def _train_step_tree(ts) -> Dict[str, Any]:
    if ts._step_fn is None:
        # force lazy state init (TrainStep builds state on first call)
        raise RuntimeError(
            "TrainStep has not run yet — checkpoint after at least one "
            "step (its state materializes lazily)")
    return {"state": ts._state, "opt_state": ts._opt_state,
            "lr_step": ts._lr_step}


def save_train_step(ck: ShardedCheckpointer, step: int, ts) -> bool:
    """Checkpoint a TrainStep's full training state, shardings and all."""
    return ck.save(step, _train_step_tree(ts))


def restore_train_step(ck: ShardedCheckpointer, ts,
                       step: Optional[int] = None) -> int:
    """Restore into a TrainStep that has run >=1 step (so its state
    exists as the sharding template). Returns the restored step."""
    tmpl = _train_step_tree(ts)
    if step is None:
        step = ck.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoint steps saved yet")
    tree = ck.restore(step, template=tmpl)
    ts._state = tree["state"]
    ts._opt_state = tree["opt_state"]
    ts._lr_step = tree["lr_step"]
    return int(step)
