"""Auto-checkpoint: transparent epoch-level resume.

Analog of /root/reference/python/paddle/fluid/incubate/checkpoint/
auto_checkpoint.py:71 — the reference wraps the user's epoch loop in
``train_epoch_range``, hashes the program + range to name a checkpoint
stream, saves persistables to HDFS every interval, and on restart skips
already-completed epochs. Same contract here over LocalFS: the hash keys
on the serialized main/startup programs + the range; state is the scope's
persistables (saved via io.save_persistables) + a status JSON.

Enabled when PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT (reference
_get_running_key env contract) or when ``always=True`` is passed.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Optional

from ... import io as _io
from ...core.executor import Executor
from ...core.program import default_main_program, default_startup_program
from .checkpoint_saver import CheckpointSaver, LocalFS

_checker = None


class AutoCheckpointChecker:
    """Env contract (reference auto_checkpoint.py:113 AutoCheckpointChecker:
    run env, job id, hdfs dir, save interval)."""

    def __init__(self):
        self.run_env = os.environ.get("PADDLE_RUNNING_ENV", "")
        self.job_id = os.environ.get("PADDLE_JOB_ID", "default_job")
        self.ckpt_dir = os.environ.get(
            "PADDLE_EDL_HDFS_CHECKPOINT_PATH",
            os.environ.get("PADDLE_CHECKPOINT_DIR", "./auto_checkpoint"))
        self.save_interval = int(
            os.environ.get("PADDLE_EDL_SAVE_CHECKPOINT_INTER", "900"))

    def get_range_checkpoint_path(self, name: str) -> str:
        return os.path.join(self.ckpt_dir, self.job_id, "range", name)

    @property
    def valid(self) -> bool:
        return self.run_env == "PADDLE_EDL_AUTO_CHECKPOINT"


def _get_checker() -> AutoCheckpointChecker:
    global _checker
    if _checker is None:
        _checker = AutoCheckpointChecker()
    return _checker


class ExeTrainStatus:
    """auto_checkpoint.py:193 — serializable per-range status."""

    def __init__(self):
        self.epoch_no = -1
        self.hash_key = None
        self.checkpoint_no = None

    def to_dict(self):
        return {"epoch_no": self.epoch_no, "hash_key": self.hash_key,
                "checkpoint_no": self.checkpoint_no}

    @classmethod
    def from_dict(cls, d):
        st = cls()
        st.epoch_no = d.get("epoch_no", -1)
        st.hash_key = d.get("hash_key")
        st.checkpoint_no = d.get("checkpoint_no")
        return st


class TrainEpochRange:
    def __init__(self, max_epoch_num: int, name: str,
                 save_checkpoint_inter: Optional[int] = None,
                 checker: Optional[AutoCheckpointChecker] = None):
        self.name = name
        self.max_epoch_num = max_epoch_num
        self._checker = checker or _get_checker()
        self._saver = CheckpointSaver(LocalFS())
        self._save_inter = (save_checkpoint_inter
                            if save_checkpoint_inter is not None
                            else self._checker.save_interval)
        self._last_save = time.time()
        self._status = ExeTrainStatus()
        self._status.hash_key = self._hash()
        self._root = self._checker.get_range_checkpoint_path(name)
        self._restore()

    def _hash(self) -> str:
        h = hashlib.md5()
        h.update(default_main_program().to_json().encode())
        h.update(default_startup_program().to_json().encode())
        h.update(str(self.max_epoch_num).encode())
        return h.hexdigest()

    # --- persistence ----------------------------------------------------
    def _save_fn(self, path):
        exe = Executor()
        _io.save_persistables(exe, path, default_main_program())
        with open(os.path.join(path, "status.json"), "w") as f:
            json.dump(self._status.to_dict(), f)

    def _load_fn(self, path):
        status_file = os.path.join(path, "status.json")
        with open(status_file) as f:
            st = ExeTrainStatus.from_dict(json.load(f))
        if st.hash_key != self._status.hash_key:
            return  # different program/range: don't resume
        exe = Executor()
        _io.load_persistables(exe, path, default_main_program())
        self._status = st

    def _restore(self):
        if self._saver.get_checkpoint_no(self._root):
            self._saver.load_checkpoint(self._root, self._load_fn)

    def save_checkpoint(self):
        self._status.checkpoint_no = self._saver.save_checkpoint(
            self._root, self._save_fn)
        self._last_save = time.time()

    # --- the epoch generator (auto_checkpoint.py train_epoch_range) -----
    def get(self):
        start = self._status.epoch_no + 1
        for epoch in range(start, self.max_epoch_num):
            yield epoch
            self._status.epoch_no = epoch
            if time.time() - self._last_save >= self._save_inter or \
                    epoch == self.max_epoch_num - 1:
                self.save_checkpoint()


def train_epoch_range(max_epoch_num: int, name: str = "default",
                      save_checkpoint_inter: Optional[int] = None):
    """for epoch in train_epoch_range(N): ... — transparently resumes at
    the first un-finished epoch after a crash/restart when auto-checkpoint
    is enabled; plain range otherwise."""
    checker = _get_checker()
    if not checker.valid and save_checkpoint_inter is None:
        for epoch in range(max_epoch_num):
            yield epoch
        return
    tr = TrainEpochRange(max_epoch_num, name,
                         save_checkpoint_inter=save_checkpoint_inter,
                         checker=checker)
    for epoch in tr.get():
        yield epoch
