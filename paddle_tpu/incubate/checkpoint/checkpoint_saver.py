"""Generic checkpoint saver over a filesystem abstraction.

Analog of /root/reference/python/paddle/fluid/incubate/checkpoint/
checkpoint_saver.py (SerializableBase/CheckpointSaver over fleet.utils.fs
LocalFS/HDFSClient). Checkpoints are numbered directories
``<dir>/__paddle_checkpoint__.<no>``; save trims older ones, load picks
the newest."""
from __future__ import annotations

import os
import shutil
from typing import List, Optional

CKPT_PREFIX = "__paddle_checkpoint__"


class LocalFS:
    """fleet/utils/fs.py:119 LocalFS surface (the subset checkpointing
    needs). An HDFS twin would shell out like the reference's
    HDFSClient:258; out of scope without a cluster."""

    def ls_dir(self, path):
        if not os.path.isdir(path):
            return [], []
        dirs, files = [], []
        for e in sorted(os.listdir(path)):
            full = os.path.join(path, e)
            (dirs if os.path.isdir(full) else files).append(e)
        return dirs, files

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src, dst):
        self.delete(dst)
        shutil.move(src, dst)


class CheckpointSaver:
    def __init__(self, fs=None):
        self.fs = fs or LocalFS()

    def _numbered(self, root) -> List[int]:
        dirs, _ = self.fs.ls_dir(root)
        nos = []
        for d in dirs:
            if d.startswith(CKPT_PREFIX + "."):
                try:
                    nos.append(int(d.split(".")[-1]))
                except ValueError:
                    pass
        return sorted(nos)

    def save_checkpoint(self, root: str, save_fn, max_num: int = 3) -> int:
        """save_fn(path) writes the payload into a tmp dir; commit is an
        atomic rename (the reference's tmp + mv dance)."""
        self.fs.mkdirs(root)
        nos = self._numbered(root)
        no = (nos[-1] + 1) if nos else 0
        final = os.path.join(root, "%s.%d" % (CKPT_PREFIX, no))
        tmp = final + ".tmp"
        self.fs.delete(tmp)
        self.fs.mkdirs(tmp)
        save_fn(tmp)
        self.fs.mv(tmp, final)
        for old in nos[:-max(0, max_num - 1)] if max_num > 0 else []:
            self.fs.delete(os.path.join(root, "%s.%d" % (CKPT_PREFIX, old)))
        return no

    def get_checkpoint_no(self, root: str) -> List[int]:
        return self._numbered(root)

    def load_checkpoint(self, root: str, load_fn,
                        checkpoint_no: Optional[int] = None):
        nos = self._numbered(root)
        if not nos:
            return None
        no = checkpoint_no if checkpoint_no is not None else nos[-1]
        path = os.path.join(root, "%s.%d" % (CKPT_PREFIX, no))
        load_fn(path)
        return no

    def clean_redundant_checkpoints(self, root: str, reserved: int = 1):
        nos = self._numbered(root)
        for old in nos[:-reserved] if reserved > 0 else nos:
            self.fs.delete(os.path.join(root, "%s.%d" % (CKPT_PREFIX, old)))
