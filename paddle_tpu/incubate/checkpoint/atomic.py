"""Atomic single-host checkpoints with manifest-committed writes.

The crash-safety layer under FLAGS_auto_checkpoint_steps
(docs/robustness.md). The sharded orbax path (sharded.py) covers
multi-host; this module is the dependency-free analog with an explicit
commit protocol a kill test can reason about:

1. the payload (one .npz of flat name->array entries) is serialized to
   bytes, fingerprinted (sha256), written to a temp file in the
   checkpoint directory, fsync'd, and os.replace'd into place — a
   reader sees the old file or the new file, never a torn one;
2. the MANIFEST (json: step, payload fingerprint + byte size, mesh
   topology, array names) is written the same way, strictly AFTER the
   payload. The manifest is the commit record: a payload without a
   valid matching manifest does not exist.

Load walks manifests newest-first and verifies the payload fingerprint
before trusting it, so a checkpoint truncated or corrupted mid-write
(process killed between steps 1 and 2, disk damage, an armed
``checkpoint.save=corrupt`` failpoint) falls back to the previous one
(STAT_checkpoint_corrupt_fallback) instead of wedging the resume.

Failpoint sites (failpoints.py): ``checkpoint.save`` transforms the
payload bytes before the write (corrupt/truncate model torn writes),
``checkpoint.load`` transforms them after the read.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import re
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...failpoints import failpoint
from ...monitor import stat_add, timer_observe

__all__ = ["AtomicCheckpointer", "CheckpointCorrupt"]

_MANIFEST_RE = re.compile(r"^ckpt_(\d{8})\.json$")
FORMAT_VERSION = 1


class CheckpointCorrupt(RuntimeError):
    """No loadable checkpoint: every manifest present failed
    validation (missing/truncated/fingerprint-mismatched payload)."""


def _mesh_topology() -> Optional[list]:
    try:
        from ...mesh.plan import current_plan
        plan = current_plan()
        if plan is None:
            return None
        return [list(t) if isinstance(t, tuple) else t
                for t in plan.topology()]
    except Exception:
        return None


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + write + fsync + os.replace: the publish is all-or-nothing
    (same idiom as program_cache.store_trace, plus a directory fsync so
    the rename itself is durable)."""
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_ckpt_")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # platform without directory fsync: rename is still atomic


class AtomicCheckpointer:
    """Step-indexed atomic checkpoints of flat name->ndarray dicts.

    >>> ck = AtomicCheckpointer(root, keep=3)
    >>> ck.save(120, {"w": w, "rng": key})
    >>> step, arrays, manifest = ck.load_latest()
    """

    def __init__(self, root: str, keep: int = 3):
        if not root:
            raise ValueError("checkpoint root must be a path")
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.root = str(root)
        self.keep = int(keep)

    # --- paths ---------------------------------------------------------

    def _payload_path(self, step: int) -> str:
        return os.path.join(self.root, "ckpt_%08d.npz" % step)

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.root, "ckpt_%08d.json" % step)

    def steps(self) -> List[int]:
        """Committed steps (manifest present), ascending. Payload
        validity is checked at load, not here."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        out = []
        for n in names:
            m = _MANIFEST_RE.match(n)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # --- save ----------------------------------------------------------

    def save(self, step: int, arrays: Dict[str, Any],
             extra_meta: Optional[Dict[str, Any]] = None) -> str:
        """Write one committed checkpoint; returns the manifest path.
        `arrays` is a flat name->array dict (callers flatten nested
        training state with '//'-joined keys, io.save_dygraph style)."""
        import time as _time
        t0 = _time.perf_counter()
        step = int(step)
        buf = io.BytesIO()
        np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
        payload = buf.getvalue()
        payload = failpoint("checkpoint.save", payload)
        _atomic_write(self._payload_path(step), payload)
        manifest = {
            "format": FORMAT_VERSION,
            "step": step,
            "fingerprint": hashlib.sha256(payload).hexdigest(),
            "payload_bytes": len(payload),
            "mesh_topology": _mesh_topology(),
            "arrays": sorted(arrays),
        }
        if extra_meta:
            manifest["meta"] = extra_meta
        _atomic_write(self._manifest_path(step),
                      json.dumps(manifest, indent=1,
                                 sort_keys=True).encode() + b"\n")
        stat_add("STAT_checkpoint_saves")
        timer_observe("TIMER_checkpoint_save_us",
                      (_time.perf_counter() - t0) * 1e6)
        self._retain()
        return self._manifest_path(step)

    def _retain(self) -> None:
        for step in self.steps()[:-self.keep]:
            for p in (self._payload_path(step),
                      self._manifest_path(step)):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    # --- load ----------------------------------------------------------

    def _load_step(self, step: int) -> Tuple[Dict[str, np.ndarray],
                                             Dict[str, Any]]:
        with open(self._manifest_path(step), "rb") as f:
            manifest = json.loads(f.read())
        with open(self._payload_path(step), "rb") as f:
            payload = f.read()
        payload = failpoint("checkpoint.load", payload)
        if manifest.get("format") != FORMAT_VERSION:
            raise CheckpointCorrupt("manifest format %r != %d"
                                    % (manifest.get("format"),
                                       FORMAT_VERSION))
        fp = hashlib.sha256(payload).hexdigest()
        if fp != manifest.get("fingerprint") \
                or len(payload) != manifest.get("payload_bytes"):
            raise CheckpointCorrupt(
                "payload fingerprint mismatch at step %d "
                "(%d bytes on disk, manifest says %s)"
                % (step, len(payload), manifest.get("payload_bytes")))
        try:
            with np.load(io.BytesIO(payload)) as z:
                arrays = {k: z[k] for k in z.files}
        except Exception as e:
            # a torn/corrupt payload the fingerprint could not catch
            # (e.g. the checkpoint.save failpoint truncates BEFORE
            # fingerprinting, so the manifest matches unreadable bytes;
            # np.load then raises zipfile.BadZipFile, outside the OSError
            # family) — normalize to CheckpointCorrupt so load_latest
            # falls back
            raise CheckpointCorrupt(
                "unreadable payload at step %d: %s: %s"
                % (step, type(e).__name__, e))
        if sorted(arrays) != manifest.get("arrays"):
            raise CheckpointCorrupt(
                "array set mismatch at step %d" % step)
        return arrays, manifest

    def load_latest(self) -> Optional[Tuple[int, Dict[str, np.ndarray],
                                            Dict[str, Any]]]:
        """(step, arrays, manifest) for the newest VALID checkpoint —
        a corrupt/truncated latest falls back to the previous one
        (STAT_checkpoint_corrupt_fallback per skip). None when the
        directory holds no committed checkpoint at all; raises
        CheckpointCorrupt when manifests exist but none validates."""
        steps = self.steps()
        if not steps:
            return None
        last_err: Optional[Exception] = None
        for step in reversed(steps):
            try:
                arrays, manifest = self._load_step(step)
                stat_add("STAT_checkpoint_loads")
                return step, arrays, manifest
            except (OSError, ValueError, KeyError, json.JSONDecodeError,
                    CheckpointCorrupt) as e:
                stat_add("STAT_checkpoint_corrupt_fallback")
                last_err = e
        raise CheckpointCorrupt(
            "no valid checkpoint under %s (%d manifests, newest "
            "failure: %s)" % (self.root, len(steps), last_err))
