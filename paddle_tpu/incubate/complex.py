"""paddle.incubate.complex (exported as paddle.complex) — complex-tensor
math over ComplexVariable pairs.

Analog of /root/reference/python/paddle/incubate/complex/tensor/
(elementwise add/sub/mul/div, kron, trace, sum, matmul, reshape,
transpose on (real, imag) pairs). TPUs have no native complex MXU path,
so every op composes the real-number ops on the two parts — which is
exactly what the reference does (its ComplexVariable kernels are
real-pair compositions too, helper.py), and lets jax autodiff flow
through both parts.
"""
from __future__ import annotations

from ..framework_api import ComplexVariable
from .. import tensor as _t

__all__ = ["elementwise_add", "elementwise_sub", "elementwise_mul",
           "elementwise_div", "kron", "trace", "sum", "matmul",
           "reshape", "transpose"]


def _cv(x):
    if isinstance(x, ComplexVariable):
        return x
    return ComplexVariable(x, _t.zeros_like(x))


def elementwise_add(x, y, name=None):
    x, y = _cv(x), _cv(y)
    return ComplexVariable(x.real + y.real, x.imag + y.imag)


def elementwise_sub(x, y, name=None):
    x, y = _cv(x), _cv(y)
    return ComplexVariable(x.real - y.real, x.imag - y.imag)


def elementwise_mul(x, y, name=None):
    x, y = _cv(x), _cv(y)
    return ComplexVariable(x.real * y.real - x.imag * y.imag,
                           x.real * y.imag + x.imag * y.real)


def elementwise_div(x, y, name=None):
    x, y = _cv(x), _cv(y)
    den = y.real * y.real + y.imag * y.imag
    return ComplexVariable(
        (x.real * y.real + x.imag * y.imag) / den,
        (x.imag * y.real - x.real * y.imag) / den)


def kron(x, y, name=None):
    x, y = _cv(x), _cv(y)
    return ComplexVariable(
        _t.kron(x.real, y.real) - _t.kron(x.imag, y.imag),
        _t.kron(x.real, y.imag) + _t.kron(x.imag, y.real))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    x = _cv(x)
    return ComplexVariable(_t.trace(x.real, offset, axis1, axis2),
                           _t.trace(x.imag, offset, axis1, axis2))


def sum(x, axis=None, keepdim=False, name=None):  # noqa: A001
    x = _cv(x)
    return ComplexVariable(_t.sum(x.real, axis, keepdim),
                           _t.sum(x.imag, axis, keepdim))


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0,
           name=None):
    x, y = _cv(x), _cv(y)

    def mm(a, b):
        return _t.matmul(a, b, transpose_x, transpose_y)

    real = mm(x.real, y.real) - mm(x.imag, y.imag)
    imag = mm(x.real, y.imag) + mm(x.imag, y.real)
    if alpha != 1.0:
        real = real * alpha
        imag = imag * alpha
    return ComplexVariable(real, imag)


def reshape(x, shape, name=None):
    x = _cv(x)
    return ComplexVariable(_t.reshape(x.real, shape),
                           _t.reshape(x.imag, shape))


def transpose(x, perm, name=None):
    x = _cv(x)
    return ComplexVariable(_t.transpose(x.real, perm),
                           _t.transpose(x.imag, perm))
