"""paddle.incubate.reader (reference fluid/contrib/reader/): the
distributed reader shard decorator."""

__all__ = ["distributed_batch_reader"]


def distributed_batch_reader(batch_reader):
    """Shard a batch reader across trainers (reference
    distributed_reader.py:21): each rank keeps every nranks-th batch,
    rank/world size from the cluster-contract env."""
    from ..parallel import get_rank, get_world_size

    def decorated():
        rank = get_rank()
        nranks = max(get_world_size(), 1)
        for i, batch in enumerate(batch_reader()):
            if i % nranks == rank:
                yield batch
    return decorated
