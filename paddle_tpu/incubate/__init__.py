from . import checkpoint  # noqa: F401
from . import reader  # noqa: F401  (paddle.incubate.reader)
