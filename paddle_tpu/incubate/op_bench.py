"""Per-op micro-benchmark harness.

Analog of the reference's op_tester
(/root/reference/paddle/fluid/operators/benchmark/op_tester.cc — build
one op from a config, run it N times on the target device, report
latency). Here the op's registry lowering is jit-compiled standalone
and timed on the default backend; use it to rank kernel variants or
catch lowering regressions:

    from paddle_tpu.incubate.op_bench import bench_op
    r = bench_op("softmax", {"X": (128, 1024)}, repeat=100)
    print(r["mean_us"], r["p50_us"])
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np


def bench_op(op_type: str, input_shapes: Dict[str, Any],
             attrs: Optional[Dict[str, Any]] = None, repeat: int = 50,
             warmup: int = 5, dtype="float32", seed: int = 0,
             grad: bool = False) -> Dict[str, Any]:
    """Time one op lowering under jit.

    input_shapes: slot -> shape tuple (or list of shapes, or a concrete
    ndarray to control dtype/values). With grad=True the timed function
    is value+grad w.r.t. the first input slot instead of forward only.
    """
    import jax
    import jax.numpy as jnp

    from ..core.registry import LowerCtx, REGISTRY

    opdef = REGISTRY.get(op_type)
    rng = np.random.RandomState(seed)

    def make(v):
        if isinstance(v, np.ndarray):
            return jnp.asarray(v)
        return jnp.asarray(rng.randn(*v).astype(dtype))

    ins = {}
    for slot, v in input_shapes.items():
        vals = v if isinstance(v, list) else [v]
        ins[slot] = [make(x) for x in vals]
    attrs = dict(attrs or {})
    key = jax.random.PRNGKey(seed)

    def fwd(ins_vals):
        outs = opdef.lower(LowerCtx(key), ins_vals, attrs)
        return [v for vals in outs.values() for v in vals if v is not None]

    if grad:
        first_slot = next(iter(ins))

        def loss(x0):
            iv = dict(ins)
            iv[first_slot] = [x0] + ins[first_slot][1:]
            return sum(jnp.sum(o.astype(jnp.float32)) for o in fwd(iv))
        fn = jax.jit(jax.value_and_grad(loss))
        arg = ins[first_slot][0]
    else:
        fn = jax.jit(lambda iv: fwd(iv))
        arg = ins

    out = fn(arg)
    jax.block_until_ready(out)
    for _ in range(warmup):
        out = fn(arg)
    jax.block_until_ready(out)
    times = []
    for _ in range(repeat):
        t0 = time.perf_counter()
        out = fn(arg)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) * 1e6)
    times = np.asarray(times)
    return {"op": op_type, "repeat": repeat,
            "mean_us": float(times.mean()),
            "p50_us": float(np.percentile(times, 50)),
            "p99_us": float(np.percentile(times, 99)),
            "min_us": float(times.min())}
