"""Legacy AsyncExecutor facade.

Analog of /root/reference/paddle/fluid/framework/async_executor.h:63
(AsyncExecutor::RunFromFile: spin up per-thread DataFeeds + ExecutorThreadWorkers
over a filelist and drain it through the program). The reference itself
superseded this class with the Trainer/Dataset path
(Executor.train_from_dataset); this facade keeps the legacy call shape
alive by building a QueueDataset from the DataFeedDesc + filelist and
delegating to exactly that successor — the same consolidation the
reference performed.
"""
from __future__ import annotations

import warnings
from typing import Optional, Sequence

from .core.executor import Executor
from .dataset import DataFeedDesc, DatasetFactory

__all__ = ["AsyncExecutor"]


class AsyncExecutor:
    def __init__(self, place=None, run_mode: str = ""):
        warnings.warn(
            "AsyncExecutor is the legacy surface; prefer "
            "Executor.train_from_dataset (the reference deprecated it "
            "the same way)", DeprecationWarning)
        self._exe = Executor(place)

    def run(self, program, data_feed: DataFeedDesc,
            filelist: Sequence[str], thread_num: int,
            fetch_names: Optional[Sequence] = None,
            mode: str = "", debug: bool = False):
        """async_executor.h RunFromFile: filelist + DataFeedDesc ->
        thread_num workers draining batches through `program`."""
        if thread_num <= 0:
            raise ValueError("thread_num must be positive")
        ds = DatasetFactory().create_dataset("QueueDataset")
        ds.set_filelist(list(filelist))
        ds.set_batch_size(data_feed.batch_size)
        if data_feed.pipe_command:
            ds.set_pipe_command(data_feed.pipe_command)
        ds.set_thread(thread_num)

        class _V:  # slot name/dtype carriers for set_use_var
            def __init__(self, name, dtype):
                self.name, self.dtype = name, dtype

        type_map = {"uint64": "int64", "float": "float32"}
        ds.set_use_var([
            _V(s["name"], type_map.get(s["type"], s["type"]))
            for s in data_feed.slots if s["is_used"]])
        results = self._exe.train_from_dataset(
            program=program, dataset=ds, thread=thread_num,
            debug=debug, fetch_list=list(fetch_names or []))
        # legacy call shape: one value per batch (train_from_dataset
        # itself now returns the full fetch_list per batch)
        return [r[0] if isinstance(r, list) and len(r) == 1 else r
                for r in results]
