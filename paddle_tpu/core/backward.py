"""Static-graph autodiff: append_backward / gradients.

API analog of /root/reference/python/paddle/fluid/backward.py
(append_backward:1215, gradients:1742). The reference walks the op list and
appends one grad OpDesc per forward op via C++-registered GradOpMakers; the
TPU-native design instead appends a single `backward` meta-op whose lowering
(core/executor.py:_lower_backward) differentiates the traced forward section
with one jax.value_and_grad pass whose primal values supersede the outer
forward (dead-code-eliminated by XLA) — one fused forward+backward
computation, simpler and faster than per-op grad kernels.

Recompute segments (reference backward.py:37 ProgramStats,
:145 modify_forward_desc_for_recompute) are carried as op-index ranges in the
backward op's `remat_segments` attr and lowered with jax.checkpoint.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from . import dtypes
from .executor import BACKWARD_OP, GRAD_SUFFIX
from .program import Program, VarDesc, default_main_program


def _var_name(v) -> str:
    return v.name if isinstance(v, VarDesc) else str(v)


def append_backward(loss, parameter_list: Optional[Sequence] = None,
                    no_grad_set: Optional[set] = None,
                    checkpoints: Optional[Sequence] = None,
                    program: Optional[Program] = None,
                    loss_scale: float = 1.0,
                    loss_scale_var: Optional[str] = None,
                    ) -> List[Tuple[VarDesc, VarDesc]]:
    """Append the backward meta-op computing d(loss)/d(param) for every
    trainable parameter; returns [(param, grad)] like the reference
    (backward.py:1215).
    """
    program = program or default_main_program()
    block = program.global_block
    loss_name = _var_name(loss)
    no_grad = {_var_name(v) for v in (no_grad_set or set())}

    if parameter_list is not None:
        params = [_var_name(p) for p in parameter_list]
    else:
        params = [v.name for v in program.all_parameters()
                  if v.trainable and not v.stop_gradient]
    params = [p for p in params if p not in no_grad
              and dtypes.is_float(block.var(p).dtype)]

    remat_segments = []
    if checkpoints:
        remat_segments = _segments_from_checkpoints(block, checkpoints)

    grad_names = []
    for p in params:
        pv = block.var(p)
        g = block.create_var(p + GRAD_SUFFIX, shape=pv.shape, dtype=pv.dtype,
                             stop_gradient=True)
        grad_names.append(g.name)

    ins = {"Loss": [loss_name]}
    if loss_scale_var is not None:
        # dynamic loss scaling (AMP): scale read from a variable each step
        ins["LossScale"] = [loss_scale_var]
    block.append_op(
        BACKWARD_OP,
        inputs=ins,
        outputs={"Grads": grad_names},
        attrs={"parameter_list": params,
               "loss_scale": loss_scale,
               "remat_segments": remat_segments})

    return [(block.var(p), block.var(p + GRAD_SUFFIX)) for p in params]


def gradients(targets, inputs, target_gradients=None,
              no_grad_set: Optional[set] = None,
              program: Optional[Program] = None) -> List[VarDesc]:
    """d(sum(targets))/d(inputs) for arbitrary vars (reference
    backward.py:1742 gradients). Supports both leaf vars (feeds/params) and
    intermediate activations."""
    program = program or default_main_program()
    block = program.global_block
    target_names = [_var_name(t) for t in (targets if isinstance(
        targets, (list, tuple)) else [targets])]
    input_names = [_var_name(t) for t in (inputs if isinstance(
        inputs, (list, tuple)) else [inputs])]
    no_grad = {_var_name(v) for v in (no_grad_set or set())}
    input_names = [n for n in input_names if n not in no_grad]

    if len(target_names) == 1:
        loss_name = target_names[0]
    else:
        loss_name = program._unique_name("grad_target_sum")
        block.create_var(loss_name, dtype=block.var(target_names[0]).dtype,
                         shape=(), stop_gradient=False)
        block.append_op("sum_of_sums", inputs={"X": target_names},
                        outputs={"Out": [loss_name]})

    grads = []
    for n in input_names:
        v = block.var(n)
        g = block.create_var(n + GRAD_SUFFIX, shape=v.shape, dtype=v.dtype,
                             stop_gradient=True)
        grads.append(g)

    block.append_op(
        BACKWARD_OP,
        inputs={"Loss": [loss_name]},
        outputs={"Grads": [g.name for g in grads]},
        attrs={"parameter_list": input_names, "loss_scale": 1.0,
               "remat_segments": []})
    return grads


def _segments_from_checkpoints(block, checkpoints) -> List[List[int]]:
    """Convert checkpoint var names into [start, end) op-index segments:
    each segment ends right after the op producing a checkpoint var —
    mirrors the reference's segment search (backward.py:37 ProgramStats)."""
    names = [_var_name(c) for c in checkpoints]
    boundaries = []
    for i, op in enumerate(block.ops):
        if any(n in op.output_names() for n in names):
            boundaries.append(i + 1)
    segments = []
    start = 0
    for b in sorted(set(boundaries)):
        if b - start > 1:
            segments.append([start, b])
        start = b
    return segments
