"""Program pass framework: named, composable Program→Program rewrites.

Analog of the reference's IR pass registry
(/root/reference/paddle/fluid/framework/ir/pass.h:160 Pass::Apply +
pass_registry; build_strategy.cc wiring passes into compilation). The
reference runs passes over its SSA graph; here passes rewrite the
OpDesc list directly (the JSON IR is the graph — XLA does the
instruction-level optimization, so framework passes are the
*semantic* rewrites: AMP casts, recompute segmentation, eval pruning).

    from paddle_tpu.core.passes import apply_pass, register_pass
    prog2 = apply_pass(prog, "amp_rewrite")
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

from .program import Program

PassFn = Callable[[Program, dict], Program]

_PASSES: Dict[str, PassFn] = {}


def register_pass(name: str):
    def deco(fn: PassFn):
        if name in _PASSES:
            raise ValueError("pass %r registered twice" % name)
        _PASSES[name] = fn
        return fn
    return deco


def apply_pass(program: Program, name: str, **attrs) -> Program:
    """Apply one registered pass; returns the rewritten Program (passes
    may rewrite in place AND return, like the reference's graph
    passes)."""
    if name not in _PASSES:
        raise KeyError("unknown pass %r (have: %s)"
                       % (name, sorted(_PASSES)))
    out = _PASSES[name](program, attrs)
    return out if out is not None else program


def list_passes():
    return sorted(_PASSES)


# --------------------------------------------------------------------------
# built-in passes
# --------------------------------------------------------------------------

@register_pass("amp_rewrite")
def _amp_pass(program: Program, attrs: dict) -> Program:
    """Static AMP: insert bf16 casts around whitelisted ops
    (contrib/mixed_precision.py rewrite_program; reference
    fluid/contrib/mixed_precision/fp16_utils.py:rewrite_program)."""
    from ..contrib.mixed_precision import (AutoMixedPrecisionLists,
                                           rewrite_program)
    lists = attrs.get("amp_lists") or AutoMixedPrecisionLists()
    rewrite_program(program, lists,
                    dest_dtype=attrs.get("dtype", "bfloat16"))
    return program


@register_pass("test_prune")
def _test_prune(program: Program, attrs: dict) -> Program:
    """Forward-only clone (backward + optimizer ops dropped, is_test
    flipped) — the clone(for_test) rewrite exposed as a pass."""
    return program.clone(for_test=True)


@register_pass("drop_dropout_eval")
def _drop_dropout(program: Program, attrs: dict) -> Program:
    """Inference cleanup (the reference's inference-optimize pass):
    test-mode dropout is identity under upscale_in_train — delete the
    op and rewire consumers; under the default downgrade_in_infer it
    multiplies by (1 - p) at test time — substitute a scale op."""
    from .program import OpDesc
    for blk in program.blocks:
        rename: Dict[str, str] = {}
        kept = []
        for op in blk.ops:
            if op.type == "dropout":
                impl = op.attr("dropout_implementation",
                               "downgrade_in_infer")
                src = op.input("X")[0]
                dst = op.output("Out")[0]
                src = rename.get(src, src)
                if impl == "upscale_in_train":
                    rename[dst] = src
                    continue
                p = float(op.attr("dropout_prob", 0.5))
                kept.append(OpDesc("scale", {"X": [src]},
                                   {"Out": [dst]},
                                   {"scale": 1.0 - p, "bias": 0.0}))
                continue
            # rewire inputs through accumulated renames
            for slot, names in op.inputs.items():
                op.inputs[slot] = [rename.get(n, n) for n in names]
            kept.append(op)
        blk.ops = kept
    return program


@register_pass("fuse_elewise_add_act")
def _fuse_add_act(program: Program, attrs: dict) -> Program:
    """Marker pass for build_strategy.fuse_elewise_add_act_ops: on TPU
    the add+activation fusion is XLA's (elementwise fusion into the
    preceding GEMM); the pass validates the pattern exists and is a
    no-op rewrite — kept so strategy plumbing round-trips."""
    return program
