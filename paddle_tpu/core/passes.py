"""Program pass framework: named, composable Program→Program rewrites.

Analog of the reference's IR pass registry
(/root/reference/paddle/fluid/framework/ir/pass.h:160 Pass::Apply +
pass_registry; build_strategy.cc wiring passes into compilation). The
reference runs passes over its SSA graph; here passes rewrite the
OpDesc list directly (the JSON IR is the graph — XLA does the
instruction-level optimization, so framework passes are the
*semantic* rewrites: AMP casts, recompute segmentation, eval pruning).

    from paddle_tpu.core.passes import apply_pass, register_pass
    prog2 = apply_pass(prog, "amp_rewrite")
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

from .program import Program

PassFn = Callable[[Program, dict], Program]

_PASSES: Dict[str, PassFn] = {}


def register_pass(name: str):
    def deco(fn: PassFn):
        if name in _PASSES:
            raise ValueError("pass %r registered twice" % name)
        _PASSES[name] = fn
        return fn
    return deco


def apply_pass(program: Program, name: str, **attrs) -> Program:
    """Apply one registered pass; returns the rewritten Program (passes
    may rewrite in place AND return, like the reference's graph
    passes)."""
    if name not in _PASSES:
        raise KeyError("unknown pass %r (have: %s)"
                       % (name, sorted(_PASSES)))
    out = _PASSES[name](program, attrs)
    return out if out is not None else program


def list_passes():
    return sorted(_PASSES)


# --------------------------------------------------------------------------
# built-in passes
# --------------------------------------------------------------------------

@register_pass("amp_rewrite")
def _amp_pass(program: Program, attrs: dict) -> Program:
    """Static AMP: insert bf16 casts around whitelisted ops
    (contrib/mixed_precision.py rewrite_program; reference
    fluid/contrib/mixed_precision/fp16_utils.py:rewrite_program)."""
    from ..contrib.mixed_precision import (AutoMixedPrecisionLists,
                                           rewrite_program)
    lists = attrs.get("amp_lists") or AutoMixedPrecisionLists()
    rewrite_program(program, lists,
                    dest_dtype=attrs.get("dtype", "bfloat16"))
    return program


@register_pass("test_prune")
def _test_prune(program: Program, attrs: dict) -> Program:
    """Forward-only clone (backward + optimizer ops dropped, is_test
    flipped) — the clone(for_test) rewrite exposed as a pass."""
    return program.clone(for_test=True)


@register_pass("drop_dropout_eval")
def _drop_dropout(program: Program, attrs: dict) -> Program:
    """Inference cleanup (the reference's inference-optimize pass):
    test-mode dropout is identity under upscale_in_train — delete the
    op and rewire consumers; under the default downgrade_in_infer it
    multiplies by (1 - p) at test time — substitute a scale op."""
    from .program import OpDesc
    for blk in program.blocks:
        rename: Dict[str, str] = {}
        kept = []
        for op in blk.ops:
            if op.type == "dropout":
                impl = op.attr("dropout_implementation",
                               "downgrade_in_infer")
                src = op.input("X")[0]
                dst = op.output("Out")[0]
                src = rename.get(src, src)
                if impl == "upscale_in_train":
                    rename[dst] = src
                    continue
                p = float(op.attr("dropout_prob", 0.5))
                kept.append(OpDesc("scale", {"X": [src]},
                                   {"Out": [dst]},
                                   {"scale": 1.0 - p, "bias": 0.0}))
                continue
            # rewire inputs through accumulated renames
            for slot, names in op.inputs.items():
                op.inputs[slot] = [rename.get(n, n) for n in names]
            kept.append(op)
        blk.ops = kept
    return program


@register_pass("fuse_elewise_add_act")
def _fuse_add_act(program: Program, attrs: dict) -> Program:
    """Marker pass for build_strategy.fuse_elewise_add_act_ops: on TPU
    the add+activation fusion is XLA's (elementwise fusion into the
    preceding GEMM); the pass validates the pattern exists and is a
    no-op rewrite — kept so strategy plumbing round-trips."""
    return program


# --------------------------------------------------------------------------
# inference fusion passes (paddle_pass_builder.cc:104 GPU list — the
# SEMANTIC members XLA cannot recover from the op graph: they reroute
# subgraphs onto this repo's fused/Pallas kernels)
# --------------------------------------------------------------------------

def _producer_map(ops):
    prod = {}
    for op in ops:
        for names in op.outputs.values():
            for n in names:
                prod[n] = op
    return prod


def _consumer_counts(ops):
    cnt: Dict[str, int] = {}
    for op in ops:
        for names in op.inputs.values():
            for n in names:
                cnt[n] = cnt.get(n, 0) + 1
    return cnt


@register_pass("embedding_eltwise_layernorm_fuse")
def _emb_ln_fuse(program: Program, attrs: dict) -> Program:
    """embedding_eltwise_layernorm_fuse_pass.cc: N lookup_table lookups
    summed by elementwise_add and layer-normalized -> one
    fused_embedding_eltwise_layernorm op (the BERT embedding block).

    attrs["protected"]: var names that must keep their producers
    (fetch targets — the Predictor passes its fetch list)."""
    from .program import OpDesc
    blk = program.global_block
    ops = blk.ops
    prod = _producer_map(ops)
    cnt = _consumer_counts(ops)
    protected = set(attrs.get("protected", ()))

    def fusible(name):
        # an intermediate may be deleted only if the fused op fully
        # replaces it: one op-to-op consumer, not fetched
        return cnt.get(name, 0) == 1 and name not in protected

    def lookup_leaves(name, acc):
        """Walk the elementwise_add tree feeding `name`; collect
        (ids, emb) per lookup leaf, or return None if any leaf is not a
        single-consumer lookup."""
        op = prod.get(name)
        if op is None:
            return None
        if op.type in ("lookup_table", "lookup_table_v2"):
            if not fusible(name):
                return None
            # the fused lowering has no padding_idx handling: a lookup
            # that zeroes padding rows must stay unfused or outputs
            # silently change
            if op.attr("padding_idx", -1) not in (-1, None):
                return None
            acc.append((op.input("Ids")[0], op.input("W")[0], op))
            return acc
        if op.type == "elementwise_add" and fusible(name):
            for side in (op.input("X")[0], op.input("Y")[0]):
                if lookup_leaves(side, acc) is None:
                    return None
            acc.append((None, None, op))
            return acc
        return None

    changed = True
    while changed:
        changed = False
        for ln in ops:
            if ln.type != "layer_norm":
                continue
            # the fused lowering normalizes the TRAILING dim of the
            # rank-3 [B,S,H] embedding sum with an affine: anything
            # else (default begin_norm_axis=1, scale/shift off) must
            # stay unfused
            if ln.attr("begin_norm_axis", 1) != 2 or \
                    not ln.input("Scale") or not ln.input("Bias"):
                continue
            # the fused op has no Mean/Variance outputs: a consumed or
            # fetched saved-stat keeps the pattern unfused
            stats = ln.output("Mean") + ln.output("Variance")
            if any(cnt.get(nm, 0) > 0 or nm in protected for nm in stats):
                continue
            acc = lookup_leaves(ln.input("X")[0], [])
            leaves = [(i, w) for i, w, _ in (acc or []) if i is not None]
            if acc is None or len(leaves) < 2:
                continue
            dead = {id(op) for _, _, op in acc} | {id(ln)}
            fused = OpDesc(
                "fused_embedding_eltwise_layernorm",
                {"Ids": [i for i, _ in leaves],
                 "Embs": [w for _, w in leaves],
                 "Scale": ln.input("Scale"), "Bias": ln.input("Bias")},
                {"Out": ln.output("Y")},
                {"epsilon": ln.attr("epsilon", 1e-5)})
            idx = next(i for i, op in enumerate(ops) if id(op) == id(ln))
            blk.ops = [op for op in ops[:idx] if id(op) not in dead] + \
                [fused] + [op for op in ops[idx + 1:]
                           if id(op) not in dead]
            ops = blk.ops
            prod = _producer_map(ops)
            cnt = _consumer_counts(ops)
            changed = True
            break
    return program


def _match_proj(prod, t_op, input_name=None):
    """transpose2([0,2,1,3]) <- reshape2([0,0,nh,d]) <-
    elementwise_add(bias) <- mul(x, W). Returns (x, W, b, nh, d) or
    None."""
    if t_op is None or t_op.type != "transpose2" or \
            list(t_op.attr("axis", [])) != [0, 2, 1, 3]:
        return None
    r_op = prod.get(t_op.input("X")[0])
    if r_op is None or r_op.type != "reshape2":
        return None
    shape = list(r_op.attr("shape", []))
    if len(shape) != 4:
        return None
    nh, d = shape[2], shape[3]
    a_op = prod.get(r_op.input("X")[0])
    if a_op is None or a_op.type != "elementwise_add":
        return None
    m_op = prod.get(a_op.input("X")[0])
    if m_op is None or m_op.type != "mul" or \
            m_op.attr("x_num_col_dims", 1) != 2:
        return None
    x = m_op.input("X")[0]
    if input_name is not None and x != input_name:
        return None
    return (x, m_op.input("Y")[0], a_op.input("Y")[0], nh, d,
            [t_op, r_op, a_op, m_op])


@register_pass("multihead_matmul_fuse")
def _multihead_fuse(program: Program, attrs: dict) -> Program:
    """multihead_matmul_fuse_pass.cc: the canonical q/k/v mul+add ->
    reshape2/transpose2 -> scaled matmul (+mask) -> softmax -> matmul
    -> transpose2/reshape2 subgraph becomes ONE multihead_matmul op,
    whose lowering runs the Pallas flash-attention kernel. The packed
    [H,3,H] weight / [3H] bias the reference pass materializes on the
    CPU are built here as in-graph reshape+concat ops — XLA constant-
    folds them at compile time, so no scope access is needed."""
    from .program import OpDesc
    blk = program.global_block
    protected = set(attrs.get("protected", ()))

    def try_fuse():
        ops = blk.ops
        prod = _producer_map(ops)
        cons: Dict[str, list] = {}
        for op in ops:
            for names in op.inputs.values():
                for n in names:
                    cons.setdefault(n, []).append(op)

        def sole(name):
            # deletable intermediate: exactly one op-to-op consumer and
            # not a fetch target — a probs/activation tap anywhere in
            # the subgraph keeps the whole pattern unfused
            return len(cons.get(name, ())) == 1 and name not in protected

        for sm in ops:
            if sm.type != "softmax":
                continue
            pre = prod.get(sm.input("X")[0])
            mask = None
            dead_mask = []
            if pre is not None and pre.type == "elementwise_add":
                if not sole(pre.output("Out")[0]):
                    continue
                mask = pre.input("Y")[0]
                dead_mask = [pre]
                pre = prod.get(pre.input("X")[0])
            if pre is None or pre.type != "matmul" or \
                    not pre.attr("transpose_Y", False) or \
                    pre.attr("transpose_X", False):
                continue
            alpha = pre.attr("alpha", 1.0)
            q = _match_proj(prod, prod.get(pre.input("X")[0]))
            k = _match_proj(prod, prod.get(pre.input("Y")[0]),
                            input_name=q[0] if q else None)
            if q is None or k is None:
                continue
            ctx_list = cons.get(sm.output("Out")[0], [])
            if len(ctx_list) != 1 or ctx_list[0].type != "matmul":
                continue
            ctx = ctx_list[0]
            # probs @ V must be a plain matmul: a non-default alpha or
            # transpose has no slot in the fused op — skip, don't corrupt
            if ctx.attr("alpha", 1.0) != 1.0 or \
                    ctx.attr("transpose_X", False) or \
                    ctx.attr("transpose_Y", False):
                continue
            v = _match_proj(prod, prod.get(ctx.input("Y")[0]),
                            input_name=q[0])
            if v is None:
                continue
            t2_list = cons.get(ctx.output("Out")[0], [])
            if len(t2_list) != 1 or t2_list[0].type != "transpose2" or \
                    list(t2_list[0].attr("axis", [])) != [0, 2, 1, 3]:
                continue
            t2 = t2_list[0]
            r2_list = cons.get(t2.output("Out")[0], [])
            if len(r2_list) != 1 or r2_list[0].type != "reshape2":
                continue
            r2 = r2_list[0]
            x_name, nh, d = q[0], q[3], q[4]
            if (k[3], k[4]) != (nh, d) or (v[3], v[4]) != (nh, d):
                continue
            # every matched op's output must be a deletable
            # intermediate (q[5] etc = [transpose2, reshape2, add, mul])
            matched = [sm, pre, ctx, t2] + q[5] + k[5] + v[5]
            if not all(sole(o) for op in matched
                       for o in op.output("Out")):
                continue
            H = nh * d

            def tmp(suffix, shape):
                name = program._unique_name("mha_fuse_" + suffix)
                blk.create_var(name, shape=list(shape), dtype="float32",
                               stop_gradient=True)
                return name

            new_ops = []
            packed_w = []
            for tag, (_, w, _b, *_rest) in (("q", q), ("k", k), ("v", v)):
                rw = tmp(tag + "_w3", (H, 1, H))
                xs = tmp(tag + "_w3_xs", (0,))
                new_ops.append(OpDesc("reshape2", {"X": [w]},
                                      {"Out": [rw], "XShape": [xs]},
                                      {"shape": [H, 1, H]}))
                packed_w.append(rw)
            w_all = tmp("w", (H, 3, H))
            new_ops.append(OpDesc("concat", {"X": packed_w},
                                  {"Out": [w_all]}, {"axis": 1}))
            b_all = tmp("b", (3 * H,))
            new_ops.append(OpDesc("concat", {"X": [q[2], k[2], v[2]]},
                                  {"Out": [b_all]}, {"axis": 0}))
            fused_inputs = {"Input": [x_name], "W": [w_all],
                            "Bias": [b_all]}
            if mask is not None:
                fused_inputs["BiasQK"] = [mask]
            new_ops.append(OpDesc(
                "multihead_matmul", fused_inputs,
                {"Out": r2.output("Out")},
                {"head_number": nh, "alpha": alpha}))

            dead = {id(o) for o in ([sm, pre, ctx, t2, r2] + dead_mask +
                                    q[5] + k[5] + v[5])}
            idx = next(i for i, op in enumerate(ops)
                       if id(op) == id(r2))
            blk.ops = [op for op in ops[:idx]
                       if id(op) not in dead] + new_ops + \
                [op for op in ops[idx + 1:] if id(op) not in dead]
            return True  # rewrote one head; caller rescans
        return False

    while try_fuse():
        pass
    return program
