"""XLA program cost/memory accounting (docs/observability.md).

Every compiled program the runtime serves — Executor steps, Predictor
bucket executables, the generation engine's prefill/decode steps — is
captured HERE at compile time: ``jitted.lower(*args).compile()`` yields
the XLA executable plus its own accounting
(``compiled.cost_analysis()`` — flops, transcendentals, bytes accessed
— and ``compiled.memory_analysis()`` — argument/output/temp/
generated-code bytes). The record lands in a bounded process-global
registry and as ``GAUGE_program_*`` monitor instruments, so
``monitor.snapshot()``, ``/metrics``, and ``/programz``
(introspect.py) all see what every program on this process actually
costs — the numbers a TPU deployment plans capacity around (HBM
footprint per executable, achieved FLOP/s), not the analytic
hand-counts bench.py used to carry alone.

The capture is free in steady state: ``lower()`` is the trace the
first call would have paid anyway, ``compile()`` is the one XLA
compile, and the returned :class:`AccountedProgram` *is* the compiled
executable — the jitted fallback only runs (and recompiles, counted
``STAT_program_account_fallback``) if a later call's inputs don't
match the compiled signature, which the runtime's shape-pinned cache
keys make rare. Any failure inside the capture (cost analysis missing
on a backend, unlowerable args) degrades to the plain jitted callable:
accounting is an observation, never a dependency.

Process-wide aggregates:
- ``GAUGE_programs_count`` — live accounting records;
- ``GAUGE_programs_hbm_bytes`` — the compiled HBM footprint: sum over
  programs of argument+output+temp+generated-code bytes (what the
  executables pin, not what the allocator happens to hold);
- ``GAUGE_programs_flops_compiled`` — sum of per-program flops;
- ``GAUGE_programs_achieved_flops_per_s`` — sum(flops × calls) /
  process wall-time: FLOPs *dispatched* per second, refreshed on
  capture and on every scrape (``refresh_throughput``).
"""
from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

_LOCK = threading.Lock()
_PROGRAMS: "OrderedDict[str, ProgramRecord]" = OrderedDict()
_EPOCH = time.time()

# registry bound: programs outliving 512 distinct compiles (shape
# churn) age out oldest-first — the gauges of evicted entries are
# retracted so totals stay honest
_MAX_RECORDS = 512

_tls = threading.local()


def _stat_add(name: str, value: float = 1.0) -> None:
    from ..monitor import stat_add
    stat_add(name, value)


def _gauge_set(name: str, value: float) -> None:
    from ..monitor import gauge_set
    gauge_set(name, value)


class ProgramRecord:
    """Accounting for one compiled program."""

    __slots__ = ("tag", "key", "meta", "flops", "transcendentals",
                 "bytes_accessed", "argument_bytes", "output_bytes",
                 "temp_bytes", "generated_code_bytes", "alias_bytes",
                 "compile_seconds", "created_s", "calls")

    def __init__(self, tag: str, key: str, meta: Optional[dict]):
        self.tag = tag
        self.key = key
        self.meta = dict(meta or {})
        self.flops = 0.0
        self.transcendentals = 0.0
        self.bytes_accessed = 0.0
        self.argument_bytes = 0
        self.output_bytes = 0
        self.temp_bytes = 0
        self.generated_code_bytes = 0
        self.alias_bytes = 0
        self.compile_seconds = 0.0
        self.created_s = time.time() - _EPOCH
        self.calls = 0

    @property
    def hbm_bytes(self) -> int:
        """What this executable pins: arguments + outputs + scratch +
        the program text itself (aliased/donated bytes excluded — they
        reuse argument buffers)."""
        return int(self.argument_bytes + self.output_bytes +
                   self.temp_bytes + self.generated_code_bytes -
                   self.alias_bytes)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "tag": self.tag,
            "key": self.key,
            "meta": self.meta,
            # quant mode the program was traced under (engine meta
            # carries qm=; fp32/legacy programs report "off") — the
            # /programz answer to "which checkpoint flavor compiled
            # this" without digging through meta
            "quant": str(self.meta.get("qm", "off")),
            # autotune policy label the program was traced under
            # (engine meta carries policy=; untuned / legacy programs
            # report "") — the /programz answer to "which tuned
            # geometry compiled this" (docs/autotune.md)
            "policy": str(self.meta.get("policy", "")),
            "flops": self.flops,
            "transcendentals": self.transcendentals,
            "bytes_accessed": self.bytes_accessed,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "alias_bytes": self.alias_bytes,
            "hbm_bytes": self.hbm_bytes,
            "compile_seconds": round(self.compile_seconds, 4),
            "age_s": round(time.time() - _EPOCH - self.created_s, 1),
            "calls": self.calls,
        }


def _cost_analysis(compiled) -> Dict[str, float]:
    """Defensive pull of compiled.cost_analysis(): jax returns a dict
    on some versions, a per-partition list of dicts on others, and
    some backends omit keys entirely."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    return ca


def _memory_analysis(compiled):
    try:
        return compiled.memory_analysis()
    except Exception:
        return None


def _fill_record(rec: ProgramRecord, compiled) -> None:
    ca = _cost_analysis(compiled)
    rec.flops = float(ca.get("flops", 0.0) or 0.0)
    rec.transcendentals = float(ca.get("transcendentals", 0.0) or 0.0)
    rec.bytes_accessed = float(ca.get("bytes accessed", 0.0) or 0.0)
    ma = _memory_analysis(compiled)
    if ma is not None:
        for attr, field in (("argument_size_in_bytes", "argument_bytes"),
                            ("output_size_in_bytes", "output_bytes"),
                            ("temp_size_in_bytes", "temp_bytes"),
                            ("generated_code_size_in_bytes",
                             "generated_code_bytes"),
                            ("alias_size_in_bytes", "alias_bytes")):
            try:
                setattr(rec, field, int(getattr(ma, attr, 0) or 0))
            except Exception:
                pass


def _publish_locked(rec: ProgramRecord) -> None:
    base = "GAUGE_program_%%s_%s" % rec.tag
    _gauge_set(base % "flops", rec.flops)
    _gauge_set(base % "bytes_accessed", rec.bytes_accessed)
    _gauge_set(base % "temp_bytes", float(rec.temp_bytes))
    _gauge_set(base % "hbm_bytes", float(rec.hbm_bytes))


def _retract_locked(rec: ProgramRecord) -> None:
    from ..monitor import _GAUGES, _LOCK as _MLOCK
    base = "GAUGE_program_%%s_%s" % rec.tag
    with _MLOCK:
        for k in ("flops", "bytes_accessed", "temp_bytes", "hbm_bytes"):
            _GAUGES.pop(base % k, None)


def _publish_totals_locked() -> None:
    _gauge_set("GAUGE_programs_count", float(len(_PROGRAMS)))
    _gauge_set("GAUGE_programs_hbm_bytes",
               float(sum(r.hbm_bytes for r in _PROGRAMS.values())))
    _gauge_set("GAUGE_programs_flops_compiled",
               float(sum(r.flops for r in _PROGRAMS.values())))


def refresh_throughput() -> float:
    """Recompute GAUGE_programs_achieved_flops_per_s: FLOPs dispatched
    (sum of flops × calls) per wall-second of process lifetime. Called
    at capture time and by every introspect scrape, so the gauge is
    fresh wherever it is read."""
    with _LOCK:
        dispatched = sum(r.flops * r.calls for r in _PROGRAMS.values())
    dt = max(time.time() - _EPOCH, 1e-9)
    rate = dispatched / dt
    _gauge_set("GAUGE_programs_achieved_flops_per_s", rate)
    return rate


def record(compiled, *, tag: str, key: str = "",
           meta: Optional[dict] = None,
           compile_seconds: float = 0.0) -> ProgramRecord:
    """Register accounting for `compiled` under `tag` (re-recording a
    tag overwrites — a recompile of the same program replaces its
    numbers). Publishes the per-program gauges and the process totals."""
    rec = ProgramRecord(tag, key, meta)
    rec.compile_seconds = compile_seconds
    _fill_record(rec, compiled)
    with _LOCK:
        old = _PROGRAMS.pop(tag, None)
        if old is not None:
            rec.calls = old.calls
        _PROGRAMS[tag] = rec
        while len(_PROGRAMS) > _MAX_RECORDS:
            _, evicted = _PROGRAMS.popitem(last=False)
            _retract_locked(evicted)
            _stat_add("STAT_program_account_evict")
        _publish_locked(rec)
        _publish_totals_locked()
    refresh_throughput()
    return rec


class AccountedProgram:
    """The compiled executable, callable in place of the jitted fn it
    was lowered from. Falls back to the jitted path permanently on the
    first call whose inputs the compiled signature rejects (counted
    STAT_program_account_fallback; costs one recompile, never wrong
    results). Calls are tallied for the achieved-FLOP/s gauge."""

    __slots__ = ("_compiled", "_fallback", "record")

    def __init__(self, compiled, fallback, rec: ProgramRecord):
        self._compiled = compiled
        self._fallback = fallback
        self.record = rec

    def __call__(self, *args, **kwargs):
        compiled = self._compiled
        if compiled is not None:
            try:
                out = compiled(*args, **kwargs)
                self.record.calls += 1
                return out
            except (TypeError, ValueError):
                # signature mismatch is raised before execution (no
                # buffer was donated) — safe to retry via jit
                self._compiled = None
                _stat_add("STAT_program_account_fallback")
        out = self._fallback(*args, **kwargs)
        self.record.calls += 1
        return out


def accounted(jitted, example_args, *, tag: str, key: str = "",
              meta: Optional[dict] = None):
    """AOT-compile `jitted` against `example_args` (concrete values or
    ShapeDtypeStructs), record its XLA accounting, and return an
    :class:`AccountedProgram` serving the compiled executable. On any
    failure returns `jitted` unchanged — the caller's behavior without
    accounting."""
    try:
        t0 = time.perf_counter()
        compiled = jitted.lower(*example_args).compile()
        dt = time.perf_counter() - t0
    except Exception:
        _stat_add("STAT_program_account_errors")
        return jitted
    try:
        rec = record(compiled, tag=tag, key=key, meta=meta,
                     compile_seconds=dt)
    except Exception:
        _stat_add("STAT_program_account_errors")
        return jitted
    return AccountedProgram(compiled, jitted, rec)


# ---------------------------------------------------------------------------
# ambient tag labels — lets a layer above the Executor (the Predictor's
# bucket runner) name the entries its executions compile
# ---------------------------------------------------------------------------

class _TagScope:
    __slots__ = ("tag",)

    def __init__(self, tag: str):
        self.tag = tag

    def __enter__(self):
        stack = getattr(_tls, "tags", None)
        if stack is None:
            stack = _tls.tags = []
        stack.append(self.tag)
        return self

    def __exit__(self, *exc):
        _tls.tags.pop()
        return False


def tag_scope(tag: str) -> _TagScope:
    """Thread-locally label programs compiled inside the scope."""
    return _TagScope(tag)


def current_tag() -> Optional[str]:
    stack = getattr(_tls, "tags", None)
    return stack[-1] if stack else None


def safe_tag(text: str) -> str:
    """Collapse arbitrary text into a monitor/Prometheus-safe tag."""
    return "".join(c if c.isalnum() or c == "_" else "_" for c in text)


def key_token(obj: Any) -> str:
    """Short stable-within-process token for an unhashable/clunky cache
    key (repr-hash; used to make executor tags unique per entry)."""
    return hashlib.sha1(repr(obj).encode()).hexdigest()[:10]


# ---------------------------------------------------------------------------
# registry views
# ---------------------------------------------------------------------------

def programs() -> List[Dict[str, Any]]:
    """Accounting records, oldest first (the /programz payload)."""
    with _LOCK:
        return [r.as_dict() for r in _PROGRAMS.values()]


def totals() -> Dict[str, float]:
    with _LOCK:
        return {
            "count": len(_PROGRAMS),
            "hbm_bytes": float(sum(r.hbm_bytes
                                   for r in _PROGRAMS.values())),
            "flops_compiled": float(sum(r.flops
                                        for r in _PROGRAMS.values())),
            "calls": float(sum(r.calls for r in _PROGRAMS.values())),
        }


def reset() -> None:
    """Clear the registry and retract its gauges (test isolation)."""
    with _LOCK:
        for rec in _PROGRAMS.values():
            _retract_locked(rec)
        _PROGRAMS.clear()
        _publish_totals_locked()
