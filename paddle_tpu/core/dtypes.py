"""Dtype registry for paddle_tpu.

Mirrors the VarType.Type dtype enum of the reference
(/root/reference/paddle/fluid/framework/framework.proto:104-136) but maps
directly onto jax.numpy dtypes; TPU-native default compute dtype is float32
with bfloat16 as the AMP dtype (reference uses float16 on CUDA).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# canonical name -> jnp dtype
_DTYPES = {
    "bool": jnp.bool_,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
}

_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bf16": "bfloat16",
    "fp16": "float16",
    "fp32": "float32",
    "fp64": "float64",
}


# process-wide default float dtype (reference framework.py
# set_default_dtype / get_default_dtype): consulted wherever a float
# dtype is omitted (tensor creation, layer parameter init)
_DEFAULT_DTYPE = "float32"


def set_default_dtype(d) -> None:
    global _DEFAULT_DTYPE
    name = convert_dtype(d)
    if name not in ("float16", "bfloat16", "float32", "float64"):
        raise TypeError(
            "set_default_dtype only accepts float dtypes, got %r" % (d,))
    # jax truncates 64-bit dtypes unless x64 mode is on; a float64
    # default is an explicit user request, so turn x64 ON for it (TPU
    # emulates f64 — slow but correct, matching the reference's CPU f64
    # contract). Never force it OFF: the user may have enabled x64
    # independently, and the reference's set_default_dtype('float32')
    # is side-effect-free.
    if name == "float64":
        import jax
        jax.config.update("jax_enable_x64", True)
    _DEFAULT_DTYPE = name


def get_default_dtype() -> str:
    return _DEFAULT_DTYPE


def convert_dtype(dtype) -> str:
    """Normalise any dtype spec (str, np dtype, jnp dtype) to a canonical name."""
    if dtype is None:
        return _DEFAULT_DTYPE
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name not in _DTYPES:
            raise ValueError(f"unsupported dtype {dtype!r}")
        return name
    name = np.dtype(dtype).name if not hasattr(dtype, "name") else dtype.name
    name = _ALIASES.get(name, name)
    if name not in _DTYPES:
        raise ValueError(f"unsupported dtype {dtype!r}")
    return name


def to_jax_dtype(dtype):
    return _DTYPES[convert_dtype(dtype)]


def is_float(dtype) -> bool:
    return convert_dtype(dtype) in ("float16", "bfloat16", "float32", "float64")


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in ("int8", "uint8", "int16", "int32", "int64")
