"""FetchHandle: a lazy fetch result for the async dispatch pipeline.

jax dispatches asynchronously — the jitted step returns futures and the
host is free to stage the next batch while the device computes.  The
reference's Executor instead blocks on a device→host FetchOp copy every
step (/root/reference/paddle/fluid/framework/executor.cc:474 hot loop +
fetch_op.cc), and our `Executor.run(return_numpy=True)` inherited that
bubble through `np.asarray(fetch)`.  `FetchHandle` is the non-blocking
alternative (`return_numpy="lazy"`): it wraps the on-device value and
only pays the device→host transfer when the caller actually reads it —
the same deferred-sync contract as TF's async executor fetches
(PAPERS.md, arXiv:1605.08695 §4.1) and jax's own DeviceArray.

Reading is any of: `numpy()`, `np.asarray(handle)`, `float()`/`int()`,
indexing, or comparison.  Metadata (`shape`/`dtype`/`ndim`/`size`) and
`block_until_ready()` never copy to host.  Every first materialization
of a device value bumps `STAT_executor_sync` (monitor.py), so forced
syncs on the hot path are visible in tests and benchmarks.
"""
from __future__ import annotations

from typing import Any

import numpy as np

__all__ = ["FetchHandle"]


class FetchHandle:
    """Holds one fetched value on device; converts to numpy on read.

    The host copy is computed once and cached — repeated reads are
    free.  Handles are safe to keep after the Executor dispatched more
    steps: fetches are never donated, so the underlying buffer stays
    valid for the handle's lifetime.
    """

    __slots__ = ("_device", "_host", "_step", "_trace")

    def __init__(self, value: Any):
        # step-correlated telemetry: remember which pipeline step (and
        # which request traces, when created under a trace_scope)
        # produced this fetch, so the first-read sync span lands on the
        # right step id / trace ids even though the read happens window
        # steps later (docs/observability.md)
        from .. import telemetry as _tm
        if _tm.enabled():
            self._step = _tm.current_step()
            self._trace = _tm.current_trace()
        else:
            self._step = None
            self._trace = None
        if isinstance(value, FetchHandle):  # idempotent wrap
            self._device = value._device
            self._host = value._host
            self._step = value._step if value._step is not None \
                else self._step
            self._trace = value._trace if value._trace is not None \
                else self._trace
            return
        if isinstance(value, (np.ndarray, np.generic)):
            self._device = None
            self._host = np.asarray(value)
        else:
            self._device = value
            self._host = None

    # -- metadata: never syncs -------------------------------------------
    @property
    def value(self):
        """The wrapped value as-is (on-device when not yet read)."""
        return self._host if self._device is None else self._device

    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def ndim(self):
        return len(self.value.shape)

    @property
    def size(self):
        return int(np.prod(self.value.shape)) if self.value.shape else 1

    def is_materialized(self) -> bool:
        """True once the host copy exists (no sync to ask)."""
        return self._host is not None

    def block_until_ready(self) -> "FetchHandle":
        """Wait for the device computation WITHOUT copying to host —
        the in-flight-window drain uses this so bounding the pipeline
        costs no transfer."""
        v = self._device
        if v is not None and hasattr(v, "block_until_ready"):
            v.block_until_ready()
        return self

    # -- reads: first one pays the device->host transfer -----------------
    def numpy(self) -> np.ndarray:
        if self._host is None:
            from ..monitor import stat_add
            stat_add("STAT_executor_sync")
            from ..failpoints import failpoint
            failpoint("executor.fetch")
            from .. import telemetry as _tm
            with _tm.trace_scope(self._trace), \
                    _tm.span("fetch/sync", step=self._step,
                             track="sync",
                             timer="TIMER_fetch_sync_us"):
                self._host = np.asarray(self._device)
            _tm.flight_note(self._step, "sync_count", add=1)
            if self._trace is not None:
                _tm.flight_note(self._step, "trace", self._trace)
        return self._host

    def __array__(self, dtype=None, copy=None):
        a = self.numpy()
        if dtype is not None and a.dtype != np.dtype(dtype):
            return a.astype(dtype)
        if copy:
            return a.copy()
        return a

    def __float__(self):
        return float(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __bool__(self):
        return bool(self.numpy())

    def __len__(self):
        shape = self.shape
        if not shape:
            raise TypeError("len() of a 0-d fetch")
        return shape[0]

    def __getitem__(self, idx):
        return self.numpy()[idx]

    def __iter__(self):
        return iter(self.numpy())

    def __eq__(self, other):
        return self.numpy() == other

    def __lt__(self, other):
        return self.numpy() < other

    def __le__(self, other):
        return self.numpy() <= other

    def __gt__(self, other):
        return self.numpy() > other

    def __ge__(self, other):
        return self.numpy() >= other

    __hash__ = None  # mutable-ish container semantics, like ndarray

    def __repr__(self):
        state = "host" if self._host is not None else "device"
        return "FetchHandle(shape=%s, dtype=%s, %s)" % (
            self.shape, self.dtype, state)
