"""Operator registry: op name -> lowering to jax.

TPU-native analog of the reference's kernel registry
(/root/reference/paddle/fluid/framework/op_registry.h:55 REGISTER_OPERATOR and
op_info.h OpInfoMap). Where the reference registers per-device C++/CUDA
kernels dispatched at runtime by OpKernelType (operator.cc:1068 ChooseKernel),
here each op registers a single *lowering function* that emits jax/lax ops;
XLA then compiles and fuses for the target device — there is no per-device
kernel dispatch to reimplement.

Gradients: most ops need no hand-written grad because the executor
differentiates the composed forward with jax.vjp (core/backward.py). Ops that
are non-differentiable or need custom treatment mark themselves accordingly.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax

Arrays = Dict[str, List[Any]]  # slot -> list of jax arrays
LowerFn = Callable[["LowerCtx", Arrays, Dict[str, Any]], Arrays]


class LowerCtx:
    """Context passed to op lowering functions.

    Carries the PRNG key chain (reference analog: framework/generator.h
    per-device Generator) and mode flags. Splitting the key per random op
    keeps lowering deterministic and jit-friendly.
    """

    def __init__(self, rng_key=None, is_test: bool = False, mesh=None):
        self._key = rng_key
        self.is_test = is_test
        self.mesh = mesh

    def rng(self):
        if self._key is None:
            raise RuntimeError(
                "op requires randomness but no RNG key was provided "
                "(executor seeds one automatically; in eager mode "
                "paddle_tpu.seed() sets the global key)")
        self._key, sub = jax.random.split(self._key)
        return sub

    @property
    def key_out(self):
        return self._key


@dataclass
class OpDef:
    name: str
    lower: LowerFn
    # slots, for introspection / OpTest harness
    input_slots: tuple = ()
    output_slots: tuple = ()
    # ops with no gradient (REGISTER_OP_WITHOUT_GRADIENT analog)
    no_grad: bool = False
    # uses ctx.rng()
    is_random: bool = False
    # which input slots are non-differentiable (int indices etc.)
    non_diff_inputs: tuple = ()
    # ops that mutate persistable state (optimizer ops): output slot ->
    # input slot whose variable it updates in place (e.g. ParamOut -> Param)
    inplace_map: Dict[str, str] = field(default_factory=dict)
    # host-side ops (PS send/recv RPC, py_func, save/load IO): NOT
    # jax-traceable. The executor splits the block into jit segments at
    # host-op boundaries and runs these eagerly on numpy between them —
    # the analog of the reference running RPC ops on the CPU compute
    # stream while CUDA kernels run async (distributed_ops/send_op.cc).
    host: bool = False


class OpRegistry:
    def __init__(self):
        self._ops: Dict[str, OpDef] = {}

    def register(self, opdef: OpDef):
        if opdef.name in self._ops:
            raise ValueError(f"op {opdef.name!r} registered twice")
        self._ops[opdef.name] = opdef

    def get(self, name: str) -> OpDef:
        if name not in self._ops:
            raise KeyError(
                f"op {name!r} is not registered (have {len(self._ops)} ops)")
        return self._ops[name]

    def has(self, name: str) -> bool:
        return name in self._ops

    def names(self) -> List[str]:
        return sorted(self._ops)


REGISTRY = OpRegistry()


def register_op(name: str, *, inputs=(), outputs=("Out",), no_grad=False,
                is_random=False, non_diff_inputs=(), inplace_map=None,
                host=False):
    """Decorator registering a lowering function for op `name`.

    The lowering fn signature is fn(ctx, ins, attrs) -> outs where ins/outs
    map slot name -> list of jax arrays (numpy arrays for host=True ops).
    """
    def deco(fn: LowerFn):
        REGISTRY.register(OpDef(
            name=name, lower=fn, input_slots=tuple(inputs),
            output_slots=tuple(outputs), no_grad=no_grad,
            is_random=is_random, non_diff_inputs=tuple(non_diff_inputs),
            inplace_map=dict(inplace_map or {}), host=host))
        return fn
    return deco
