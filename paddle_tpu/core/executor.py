"""Executor: compiles a Program block into one jitted XLA computation.

TPU-native replacement for the reference's op-by-op C++ interpreter
(/root/reference/paddle/fluid/framework/executor.cc:180 Executor::Run, hot
loop :474-480) and its Python driver
(/root/reference/python/paddle/fluid/executor.py:474 Executor,
:1238 _run_program). Where the reference creates variables in a Scope and
runs each op's device kernel in desc order, here the whole block is *traced*
through the op lowerings once (LowerCtx + registry) into a single jax
function

    step(state, feeds, rng) -> (fetches, new_state, rng')

which XLA compiles, fuses, and schedules. Persistable variables (parameters,
optimizer accumulators) form the donated `state` pytree, so in-place-style
optimizer ops (sgd/adam ParamOut) become functional state updates with buffer
donation — the TPU analog of the reference's in-place kernel writes.

The backward op appended by core/backward.py:append_backward is lowered here
with one jax.value_and_grad pass over the replayed forward section; the
replay's primal values overwrite the eagerly-lowered forward's env entries,
leaving the outer copy dead for XLA DCE (see _lower_backward — CSE was
measured NOT to dedupe the two copies on transformer blocks). This replaces
the reference's per-op GradOpMaker machinery
(/root/reference/python/paddle/fluid/backward.py:1215).
"""
from __future__ import annotations

from collections import OrderedDict
from contextlib import nullcontext as _nullcontext
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.export
import jax.numpy as jnp
import numpy as np

from . import dtypes, program_cache
from .program import (Block, OpDesc, Program, VarDesc, default_main_program)
from .registry import REGISTRY, LowerCtx
from .scope import Scope, global_scope

BACKWARD_OP = "backward"
GRAD_SUFFIX = "@GRAD"
RNG_VAR = "@rng_state@"


class _BlockLowerer:
    """Lowers the ops of one block into a traced environment."""

    def __init__(self, program: Program, ctx: LowerCtx):
        self.program = program
        self.ctx = ctx
        ctx.program = program
        ctx.lowerer = self

    def run_ops(self, ops: Sequence[OpDesc], env: Dict[str, Any],
                initial_env: Optional[Dict[str, Any]] = None,
                initial_key=None) -> None:
        """Execute op lowerings in order, mutating env."""
        for i, op in enumerate(ops):
            if op.type == BACKWARD_OP:
                self._lower_backward(ops, i, env, initial_env, initial_key)
                continue
            self._lower_any(op, env)

    def _lower_any(self, op: OpDesc, env: Dict[str, Any]) -> None:
        from .control_flow import LOWERINGS as _CF
        if op.type in _CF:
            # structural ops get name-level env access (the reference
            # hands them the Scope: while_op.cc:42)
            _CF[op.type](self, op, env)
        else:
            self._lower_one(op, env)

    def _lower_one(self, op: OpDesc, env: Dict[str, Any]) -> None:
        opdef = REGISTRY.get(op.type)
        ins = {slot: [env[n] for n in names]
               for slot, names in op.inputs.items() if names}
        try:
            from ..profiler import RecordEvent
            with RecordEvent(op.type, "op_lower"):
                outs = opdef.lower(self.ctx, ins, op.attrs)
        except Exception as e:  # annotate with op context, PADDLE_ENFORCE-style
            e.add_note(f"while lowering op {op.type!r} "
                       f"(in={op.inputs}, out={op.outputs})")
            raise
        from ..flags import get_flag
        if get_flag("check_nan_inf"):
            # FLAGS_check_nan_inf (operator.cc:1056): traced finite-check
            # on every float output, reporting at runtime
            from .enforce import check_numerics
            for slot, vals in outs.items():
                names = op.outputs.get(slot, [])
                for n, v in zip(names, vals or []):
                    check_numerics(v, op.type, n)
        block = self.program.global_block
        for slot, names in op.outputs.items():
            vals = outs.get(slot)
            if vals is None:
                continue
            if len(vals) < len(names):
                raise RuntimeError(
                    f"op {op.type} produced {len(vals)} values for slot "
                    f"{slot} but {len(names)} outputs declared")
            for n, v in zip(names, vals):
                # honor stop_gradient on produced vars (reference Variable
                # stop_gradient, framework.py:1107) — leaves (feeds/params)
                # are handled by grad-target selection instead.
                if n in block.vars:
                    vd = block.vars[n]
                    if vd.stop_gradient and not vd.is_parameter and \
                            hasattr(v, "dtype") and \
                            jnp.issubdtype(v.dtype, jnp.floating):
                        v = jax.lax.stop_gradient(v)
                env[n] = v

    def _lower_backward(self, ops: Sequence[OpDesc], idx: int,
                        env: Dict[str, Any],
                        initial_env: Optional[Dict[str, Any]],
                        initial_key) -> None:
        """Lower the `backward` meta-op: grads of loss wrt parameter_list.

        Replays ops[0:idx] as a pure function of the parameters with the
        *same* rng key chain inside ONE jax.value_and_grad pass, then
        overwrites every forward output in env with the replay's primal
        values.  The overwrite makes the eagerly-lowered outer forward
        dead code — nothing downstream (fetches, optimizer ops) refers
        to it — so XLA DCE removes it.  Relying on XLA CSE to merge the
        two forwards instead was measured to FAIL on transformer blocks
        (tools/check_backward_replay.py: 12-layer bert-shaped step held
        ~80 duplicate forward dots); DCE of dead values is structural
        and cannot fail that way.
        """
        op = ops[idx]
        loss_name = op.input("Loss")[0]
        param_names = list(op.attr("parameter_list", []))
        if initial_env is None:
            raise RuntimeError("backward op requires block-level replay env")
        scale = op.attr("loss_scale", 1.0)
        if op.input("LossScale"):
            # dynamic loss scaling: scale value read from the env var
            scale = env[op.input("LossScale")[0]]
        remat_segments = op.attr("remat_segments", [])  # list of [start, end)
        fwd_ops = list(ops[:idx])
        # grads wrt leaves (params/feeds in the initial env) are taken by
        # re-binding them as function arguments; grads wrt intermediates
        # (gradients() API) by overriding the produced value with the
        # argument during replay.
        leaf = [p for p in param_names if p in initial_env]
        mid = [p for p in param_names if p not in initial_env]

        def fwd(injected: Dict[str, Any]):
            ctx2 = LowerCtx(initial_key, is_test=self.ctx.is_test,
                            mesh=self.ctx.mesh)
            sub = _BlockLowerer(self.program, ctx2)
            env2 = dict(initial_env)
            for n in leaf:
                env2[n] = injected[n]
            if remat_segments and not mid:
                _run_with_remat(sub, fwd_ops, env2, remat_segments)
            else:
                for fop in fwd_ops:
                    sub._lower_any(fop, env2)
                    for n in fop.output_names():
                        if n in mid:
                            env2[n] = injected[n]
            loss = env2[loss_name]
            if loss.ndim != 0:
                loss = jnp.sum(loss)
            return loss * jnp.asarray(scale, loss.dtype), env2

        primal = {}
        for p in param_names:
            if p in initial_env:
                primal[p] = initial_env[p]
            elif p in env:
                primal[p] = env[p]
            else:
                raise KeyError(f"gradient target {p!r} has no primal value")
        (_, fwd_env), grads = jax.value_and_grad(fwd, has_aux=True)(primal)
        for p in param_names:
            env[p + GRAD_SUFFIX] = grads[p]
        # replace the outer forward's outputs with the replay's primal
        # values so the outer copy is dead and XLA DCEs it (see
        # docstring).  Walk fwd_env rather than declared output names:
        # structural ops (while/conditional_block) publish carry vars
        # beyond their declared Out slots, and an overwrite that misses
        # one keeps the outer forward live through that name.
        for n, v in fwd_env.items():
            if n not in initial_env or v is not initial_env[n]:
                env[n] = v


def _run_with_remat(lowerer: _BlockLowerer, ops, env, segments):
    """Apply jax.checkpoint to op index ranges — the recompute /
    activation-checkpointing analog of the reference's forward-desc rewrite
    (/root/reference/python/paddle/fluid/backward.py:145
    modify_forward_desc_for_recompute)."""
    seg_starts = {s: e for s, e in segments}
    i = 0
    while i < len(ops):
        if i in seg_starts:
            end = seg_starts[i]
            seg_ops = ops[i:end]
            in_names = sorted({n for op in seg_ops for n in op.input_names()
                               if n in env})
            out_names = sorted({n for op in seg_ops
                                for n in op.output_names()})

            def seg_fn(vals, _ops=seg_ops, _in=in_names, _out=out_names):
                env2 = dict(zip(_in, vals))
                # segment may read anything already computed; close over env
                for k, v in env.items():
                    env2.setdefault(k, v)
                lowerer.run_ops(_ops, env2)
                return [env2[n] for n in _out]

            outs = jax.checkpoint(seg_fn)([env[n] for n in in_names])
            env.update(dict(zip(out_names, outs)))
            i = end
        else:
            lowerer._lower_any(ops[i], env)
            i += 1


def _feed_sig(feed: Dict[str, np.ndarray]) -> tuple:
    return tuple(sorted((k, tuple(v.shape), str(v.dtype)
                         if hasattr(v, "dtype")
                         else str(np.asarray(v).dtype))
                        for k, v in feed.items()))


def _as_feed(v):
    """Normalize one feed value. Host values become numpy; a value the
    caller already staged with jax.device_put (the pipelined dataset
    loop, reader.py's _DevicePrefetcher) stays ON DEVICE — np.asarray
    here would block on a device→host copy and re-serialize the very
    loop the async pipeline overlaps."""
    if isinstance(v, (np.ndarray, np.generic)):
        return v
    if isinstance(v, jax.Array):
        return v
    return np.asarray(v)


def _donate_state() -> bool:
    """Resolve FLAGS_executor_donate_state. Donation aliases each state
    input to its output buffer (in-place updates), but XLA:CPU executes
    donated computations SYNCHRONOUSLY — dispatch blocks until the step
    finishes, re-serializing the async pipeline (docs/async_pipeline.md).
    "auto" donates on every backend except cpu."""
    from ..flags import get_flag
    v = get_flag("FLAGS_executor_donate_state", "auto")
    if isinstance(v, str) and v.lower() == "auto":
        return jax.default_backend() != "cpu"
    return bool(v)


def _sds(v) -> jax.ShapeDtypeStruct:
    if not (hasattr(v, "shape") and hasattr(v, "dtype")):
        v = np.asarray(v)
    try:
        # jit canonicalizes feeds (int64->int32 under disabled x64), so
        # exported in_avals hold the canonical dtype; compare apples to
        # apples or every int64-fed program re-exports on warm start
        dt = jax.dtypes.canonicalize_dtype(v.dtype)
    except TypeError:  # extended dtypes (typed PRNG keys) pass through
        dt = v.dtype
    return jax.ShapeDtypeStruct(tuple(v.shape), dt)


def _plan_jit_kwargs(plan, step, example) -> Dict[str, Any]:
    """Explicit jit shardings for a plan-staged step: inputs pinned to
    their staged placements, persistable state OUTPUTS pinned to their
    input shardings (so steady-state steps hand the next step buffers
    that need no re-placement), the rng threaded replicated, and
    fetches left unconstrained (None prefix — GSPMD decides).

    The new_state pytree can gain keys the input state lacks (optimizer
    accumulators materialized by the first step), and an op is allowed
    to skip a declared output slot — so the output STRUCTURE is taken
    from an abstract trace (jax.eval_shape: trace-only, no XLA
    compile), not predicted from op descs. Keys without an input-state
    sharding get a None leaf (unconstrained)."""
    state, feed, rng = example
    rep = plan.replicated()

    def _sh(v):
        s = getattr(v, "sharding", None)
        return s if s is not None else rep

    state_sh = {n: _sh(v) for n, v in state.items()}
    feed_sh = {n: _sh(v) for n, v in feed.items()}
    avals = jax.tree.map(_sds, (state, dict(feed), rng))
    _, new_state_struct, _ = jax.eval_shape(step, *avals)
    out_state_sh = {n: state_sh.get(n) for n in new_state_struct}
    return dict(in_shardings=(state_sh, feed_sh, _sh(rng)),
                out_shardings=(None, out_state_sh, rep))


def _single_device(v) -> bool:
    """Exported modules are single-logical-device; a value already
    sharded across a mesh must take the plain jit path."""
    s = getattr(v, "sharding", None)
    if s is None:
        return True
    try:
        return len(s.device_set) <= 1
    except Exception:
        return False


def _avals_match(exported, example_args) -> bool:
    """A disk entry is only used when its recorded input avals agree
    exactly with what this process would pass — the last line of
    defense (after the fingerprint) against serving a stale or
    colliding entry with wrong shapes."""
    ours = [_sds(x) for x in jax.tree.leaves(example_args)]
    theirs = list(exported.in_avals)
    if len(ours) != len(theirs):
        return False
    return all(tuple(a.shape) == tuple(b.shape)
               and np.dtype(a.dtype) == np.dtype(b.dtype)
               for a, b in zip(ours, theirs))


class Executor:
    """Runs Programs. API mirrors fluid.Executor
    (/root/reference/python/paddle/fluid/executor.py:474): run(program, feed,
    fetch_list) plus train-loop conveniences.

    `place` is accepted for API parity; device placement on TPU is decided
    by jax/XLA (and by CompiledProgram shardings for multi-chip).
    """

    def __init__(self, place=None, program_cache_dir: Optional[str] = None):
        self.place = place
        # in-memory compiled-step cache: LRU bounded by
        # FLAGS_executor_cache_capacity; whole entries are evicted so
        # no partially-dropped donated-buffer bookkeeping survives
        self._cache: "OrderedDict[tuple, Any]" = OrderedDict()
        # per-Executor disk-cache override: None follows
        # FLAGS_program_cache_dir, "" disables for this Executor only
        self._program_cache_dir = program_cache_dir
        # fingerprints whose lowering cannot round-trip jax.export
        # (host callbacks etc.) — remembered so the failed export's
        # extra trace is paid once, not per run
        self._unexportable: set = set()
        self._seed_counter = 0
        # live introspection server (introspect.py): one flag lookup
        # when FLAGS_introspect_port is unset, a running /metrics +
        # /statusz endpoint when it names a port
        from ..introspect import maybe_start
        maybe_start()
        self._unused_checked: set = set()
        # telemetry step ids: monotonically counts run() calls; the
        # dataset loops install their own batch-number step scope and
        # run() then inherits it instead (telemetry.py step_scope)
        self._step_id = 0

    def _cache_get(self, key):
        entry = self._cache.get(key)
        if entry is not None:
            self._cache.move_to_end(key)
        return entry

    def _cache_put(self, key, entry) -> None:
        from ..flags import get_flag
        self._cache[key] = entry
        self._cache.move_to_end(key)
        cap = int(get_flag("FLAGS_executor_cache_capacity", 64) or 0)
        if cap > 0:
            while len(self._cache) > cap:
                self._cache.popitem(last=False)
                from ..monitor import stat_add
                stat_add("STAT_executor_cache_evict")

    # ------------------------------------------------------------------
    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None,
            scope: Optional[Scope] = None,
            return_numpy=True,
            use_program_cache: bool = True):
        """Run one step. `return_numpy` selects the fetch mode:

        - True (default): block and return numpy arrays — the
          reference's synchronous FetchOp contract.
        - False: return the raw on-device jax arrays.
        - "lazy": NON-BLOCKING — return FetchHandle objects that pay
          the device→host transfer only when read (core/fetch.py). The
          jitted step is dispatched and control returns immediately;
          donation keeps state on-device between steps, so a caller
          looping over run() gets a dispatch-ahead pipeline for free.
        """
        from .. import telemetry as _tm
        if not _tm.enabled():
            return self._run_impl(program, feed, fetch_list, scope,
                                  return_numpy, use_program_cache)
        # telemetry wrapper: step scope (inherited by inner spans and
        # FetchHandles), a flight record, and the exception-note dump
        step = _tm.current_step()
        if step is None:
            self._step_id += 1
            step = self._step_id
        prog = program
        from ..compiler import CompiledProgram as _CP0
        if isinstance(prog, _CP0):
            prog = prog._program
        if prog is None:
            prog = default_main_program()
        _tm.flight_begin(step, program="%x:%d" % (id(prog) & 0xffffffff,
                                                  prog._version))
        with _tm.step_scope(step):
            try:
                with _tm.span("executor/run", step=step, track="dispatch",
                              timer="TIMER_executor_run_us"):
                    out = self._run_impl(program, feed, fetch_list, scope,
                                         return_numpy, use_program_cache)
            except Exception as e:
                _tm.flight_note(step, "error", repr(e)[:200])
                _tm.attach_flight(e)
                raise
        _tm.counter_sample("STAT_executor_dispatch")
        _tm.counter_sample("STAT_executor_sync")
        return out

    def _run_impl(self, program, feed, fetch_list, scope, return_numpy,
                  use_program_cache):
        # Resolve the ShardingPlan (mesh/plan.py) this run stages
        # through. CompiledProgram.with_data_parallel builds a dp plan
        # over its mesh — GSPMD partitions the step and inserts the
        # grad all-reduces (the ParallelExecutor + AllReduceOpHandle
        # pipeline of the reference); everything else picks up the
        # globally active plan (mesh.install_plan / use_plan), so
        # mesh-native callers drive placement with no wrapper at all.
        from ..compiler import CompiledProgram as _CP
        from ..mesh.plan import current_plan, plan_topology
        if isinstance(program, _CP):
            cp = program
            program = cp._program
            plan = cp._get_plan()
        else:
            plan = current_plan()
        program = program if program is not None else default_main_program()
        scope = scope if scope is not None else global_scope()
        feed = dict(feed or {})
        fetch_names = [f.name if isinstance(f, VarDesc) else str(f)
                       for f in (fetch_list or [])]

        feed = {k: _as_feed(v) for k, v in feed.items()}
        if plan is not None:
            # batch feeds shard over the plan's data axis (uneven
            # batches replicate, loudly — plan.input_sharding owns the
            # one-time warning the old ad-hoc dp path used to emit)
            feed = plan.stage_feeds(feed)

        # run initializer-style programs (startup): ops writing persistables
        # with no feeds/fetches execute eagerly into the scope.
        block = program.global_block
        state_names = self._state_names(program, scope)
        state = {n: scope.find_var(n) for n in state_names}
        if plan is not None and state:
            # place persistable state per the plan's param rules and
            # write the placed buffers back, so every later step finds
            # them resident (plan.place skips equal shardings — steady
            # state does zero device_puts here)
            placed = plan.place_state(state)
            for n, v in placed.items():
                if v is not state[n]:
                    scope.set(n, v)
            state = placed
        rng = scope.find_var(RNG_VAR)
        if rng is None:
            seed = program.random_seed
            if seed is None:
                self._seed_counter += 1
                seed = self._seed_counter
            rng = jax.random.PRNGKey(seed)
        if plan is not None:
            rng = plan.place(rng, plan.replicated())

        # lowering-relevant flags are part of the compiled artifact: the
        # key snapshots them so flipping e.g. FLAGS_dropout_storage
        # mid-process recompiles instead of returning a stale executable
        from ..flags import get_flag, lowering_snapshot
        # plan_topology folds the mesh (axis names+sizes+device kind)
        # into the key: flipping the mesh recompiles instead of serving
        # an executable partitioned for the old topology; no plan keeps
        # the key byte-identical to the pre-mesh era.
        key = (id(program), program._version, _feed_sig(feed),
               tuple(fetch_names), tuple(state_names), lowering_snapshot(),
               plan_topology(plan))
        from .. import telemetry as _tm
        entry = self._cache_get(key) if use_program_cache else None
        if entry is None:
            from ..monitor import stat_add
            stat_add("STAT_executor_compile")
            example = None
            if use_program_cache:
                example = (state, feed, rng)
            with _tm.span("executor/compile", track="compile",
                          timer="TIMER_executor_compile_us"):
                entry = self._compile(program, block, sorted(feed),
                                      fetch_names, state_names,
                                      example=example, plan=plan,
                                      acct_key=key)
            if use_program_cache:
                self._cache_put(key, entry)
        fn = entry
        if get_flag("FLAGS_enable_unused_var_check"):
            self._warn_unused_vars(program, fetch_names)

        from ..failpoints import failpoint
        failpoint("executor.dispatch")
        with _tm.span("executor/dispatch", track="dispatch",
                      timer="TIMER_executor_dispatch_us"):
            fetches, new_state, new_rng = fn(state, feed, rng)
        from ..monitor import stat_add
        stat_add("STAT_executor_dispatch")
        _tm.flight_note(_tm.current_step(), "dispatched_us", _tm.now_us())
        for n, v in new_state.items():
            scope.set(n, v)
        scope.set(RNG_VAR, new_rng)

        if get_flag("FLAGS_fast_check_nan_inf") and \
                not get_flag("check_nan_inf"):
            # FLAGS_fast_check_nan_inf (operator.cc:1037): instead of
            # the per-op traced scan, only the fetched values are
            # checked. The reduction runs ON DEVICE (all fetches
            # all-reduced into one bool), so the check costs ONE scalar
            # transfer instead of host-copying every fetch — the
            # per-fetch host pass it replaced forced a full sync even
            # under return_numpy=False. The flag never changes the
            # caller's return type; the per-fetch copies happen only on
            # the failure path, to name the offending fetch.
            finite = None
            for v in fetches:
                if hasattr(v, "dtype") and \
                        jnp.issubdtype(v.dtype, jnp.floating):
                    f = jnp.all(jnp.isfinite(v))
                    finite = f if finite is None else \
                        jnp.logical_and(finite, f)
            if finite is not None:
                stat_add("STAT_executor_sync")
                _tm.flight_note(_tm.current_step(), "sync_count", add=1)
                with _tm.span("executor/nan_check_sync", track="sync",
                              timer="TIMER_executor_sync_us"):
                    finite_host = bool(finite)
                if not finite_host:
                    from .enforce import EnforceNotMet
                    for name, v in zip(fetch_names, fetches):
                        arr = np.asarray(v)
                        if arr.dtype.kind == "f" and \
                                not np.isfinite(arr).all():
                            raise EnforceNotMet(
                                "fast_check_nan_inf: fetch %r contains "
                                "nan/inf" % name)
                    raise EnforceNotMet(
                        "fast_check_nan_inf: a fetch contains nan/inf")

        if return_numpy == "lazy":
            # non-blocking: handles convert to numpy only when read
            from .fetch import FetchHandle
            return [FetchHandle(v) for v in fetches]
        if return_numpy:
            if any(isinstance(v, jax.Array) for v in fetches):
                stat_add("STAT_executor_sync")
                _tm.flight_note(_tm.current_step(), "sync_count", add=1)
            failpoint("executor.fetch")
            with _tm.span("executor/fetch_sync", track="sync",
                          timer="TIMER_executor_sync_us"):
                fetches = [np.asarray(v) for v in fetches]
        return fetches

    def _warn_unused_vars(self, program: Program, fetch_names):
        """FLAGS_enable_unused_var_check (unused_var_check.cc): warn once
        per program about vars an op produced that nothing consumes —
        usually a graph-construction bug."""
        pid = (id(program), program._version)
        if pid in self._unused_checked:
            return
        self._unused_checked.add(pid)
        consumed = set(fetch_names)
        produced = {}
        for blk in program.blocks:
            for op in blk.ops:
                for ns in op.inputs.values():
                    consumed.update(ns)
                for ns in op.outputs.values():
                    for n in ns:
                        produced.setdefault(n, op.type)
        block = program.global_block
        unused = sorted(
            n for n, op_type in produced.items()
            if n not in consumed
            and not (n in block.vars and block.vars[n].persistable))
        if unused:
            import logging
            logging.getLogger("paddle_tpu").warning(
                "unused_var_check: vars produced but never consumed: %s",
                ", ".join("%s (by %s)" % (n, produced[n])
                          for n in unused[:20]))

    # ------------------------------------------------------------------
    def _state_names(self, program: Program, scope: Scope) -> List[str]:
        """Persistable vars that already live in the scope are threaded
        through the jitted step as donated state."""
        names = []
        for v in program.persistable_vars():
            if scope.has(v.name):
                names.append(v.name)
        return sorted(names)

    def _compile(self, program: Program, block: Block,
                 feed_names: List[str], fetch_names: List[str],
                 state_names: List[str], example=None, plan=None,
                 acct_key=None):
        persistable = {v.name for v in program.persistable_vars()}
        has_host = any(REGISTRY.has(op.type) and REGISTRY.get(op.type).host
                       for op in block.ops)
        if has_host:
            return self._compile_segmented(program, block, feed_names,
                                           fetch_names, state_names,
                                           persistable)

        def step(state, feeds, rng):
            ctx = LowerCtx(rng)
            lowerer = _BlockLowerer(program, ctx)
            env: Dict[str, Any] = {}
            env.update(state)
            for n, v in feeds.items():
                env[n] = jnp.asarray(v)
            initial_env = dict(env)
            lowerer.run_ops(block.ops, env, initial_env=initial_env,
                            initial_key=rng)
            fetches = [env[n] for n in fetch_names]
            new_state = {}
            for n, v in env.items():
                if n in persistable:
                    new_state[n] = v
            # state vars never touched still flow through
            for n in state_names:
                new_state.setdefault(n, state[n])
            return fetches, new_state, ctx.key_out

        aot = self._aot_entry(program, step, example, fetch_names,
                              plan=plan)
        if aot is not None:
            entry = aot
        else:
            jit_kwargs = {}
            if plan is not None and example is not None:
                jit_kwargs = _plan_jit_kwargs(plan, step, example)
            entry = jax.jit(step,
                            donate_argnums=(0,) if _donate_state() else (),
                            **jit_kwargs)
        return self._account(entry, example, acct_key, feed_names,
                             fetch_names)

    def _account(self, entry, example, acct_key, feed_names,
                 fetch_names):
        """XLA program accounting (core/program_accounting.py): AOT-
        compile the entry against the example args, record
        cost_analysis()/memory_analysis() under a per-entry tag, and
        serve the compiled executable itself. The first call would have
        paid the identical trace+compile anyway, so steady-state cost
        is zero; any capture failure returns `entry` unchanged. Entries
        compiled under an ambient tag scope (the Predictor's bucket
        runner) are labeled by it, so /programz tells an executor step
        from a predictor bucket."""
        if example is None or acct_key is None:
            return entry
        from . import program_accounting as _acct
        base = _acct.current_tag() or "executor"
        tag = _acct.safe_tag("%s_%s" % (base, _acct.key_token(acct_key)))
        return _acct.accounted(
            entry, (example[0], dict(example[1]), example[2]),
            tag=tag, key=_acct.key_token(acct_key),
            meta={"feeds": list(feed_names),
                  "fetches": list(fetch_names)})

    # ------------------------------------------------------------------
    def _aot_entry(self, program: Program, step, example,
                   fetch_names: Sequence[str], plan=None):
        """Disk-backed AOT path (core/program_cache.py): serve the step
        from a StableHLO trace-cache entry, exporting and storing one on
        miss. Both hit and miss execute the DESERIALIZED module (the
        miss round-trips its own bytes) so the XLA persistent-cache key
        is identical across processes and the warm process skips the
        binary compile as well. Returns None whenever this program/run
        cannot be disk-cached — caller falls back to plain jit.

        Under a ShardingPlan the exported module is partitioned: the
        export carries the plan's explicit in/out shardings and the
        fingerprint carries the mesh topology, so an entry can only be
        served to a process with the IDENTICAL mesh (axis names, sizes,
        device kind) — a chip-count change is a fingerprint change,
        never a stale hit."""
        if example is None:
            return None
        cache_dir = program_cache.resolve_dir(self._program_cache_dir)
        if cache_dir is None:
            return None
        state, feed, rng = example
        if plan is None and not all(_single_device(v) for v in
                                    jax.tree.leaves((state, feed, rng))):
            # values sharded by some means OTHER than the plan (manual
            # device_put by the caller) are not reproducible from the
            # fingerprint — leave them to the JIT path
            return None
        feed_sig = _feed_sig(feed)
        state_sig = tuple((n, tuple(np.shape(v)), str(_sds(v).dtype))
                          for n, v in state.items())
        extra = (("mesh",) + tuple(plan.topology()),) if plan is not None \
            else ()
        fp = program.fingerprint(feed_sig, tuple(fetch_names), state_sig,
                                 extra=extra)
        if fp is None or fp in self._unexportable:
            return None
        # the deserialized module demands exactly this many devices in
        # the call context: the plan's mesh, or 1 when unplanned. A
        # program whose ops *internally* shard_map over a mesh (static
        # pipeline's pp axis) exports as a multi-device module that a
        # 1-device call can never run — it must stay on the jit path.
        want_devices = plan.spec.size if plan is not None else 1
        program_cache.ensure_xla_cache(cache_dir)
        avals = jax.tree.map(_sds, (state, dict(feed), rng))
        exported = None
        payload = program_cache.load_trace(cache_dir, fp)
        if payload is not None:
            try:
                cand = jax.export.deserialize(payload)
                if cand.nr_devices != want_devices:
                    # only the pre-guard buggy path wrote such entries
                    # (fingerprints already separate mesh topologies)
                    raise ValueError("device count mismatch")
                if _avals_match(cand, avals):
                    exported = cand
                else:
                    raise ValueError("aval mismatch")
            except Exception:
                from ..monitor import stat_add
                stat_add("STAT_program_cache_corrupt")
                program_cache.discard_trace(cache_dir, fp)
                exported = None
        if exported is None:
            try:
                jit_kwargs = {} if plan is None else \
                    _plan_jit_kwargs(plan, step, example)
                data = jax.export.export(
                    jax.jit(step, **jit_kwargs))(*avals).serialize()
                exported = jax.export.deserialize(data)
            except Exception:
                self._unexportable.add(fp)
                from ..monitor import stat_add
                stat_add("STAT_program_cache_unexportable")
                return None
            if exported.nr_devices != want_devices:
                self._unexportable.add(fp)
                from ..monitor import stat_add
                stat_add("STAT_program_cache_unexportable")
                return None
            program_cache.store_trace(cache_dir, fp, data)
        return jax.jit(exported.call,
                       donate_argnums=(0,) if _donate_state() else ())

    def _compile_segmented(self, program: Program, block: Block,
                           feed_names: List[str], fetch_names: List[str],
                           state_names: List[str], persistable):
        """Programs containing host ops (PS send/recv RPC, py_func, save
        IO): split the op list at host-op boundaries, jit each pure
        segment, run host ops eagerly on numpy in between — the analog of
        the reference interleaving RPC ops with device kernels on the
        compute stream (operators/distributed_ops/send_op.cc). The
        backward meta-op must live in the same segment as the forward ops
        it replays (PS trainer programs satisfy this: fwd+backward are
        contiguous, send/recv come after — distribute_transpiler.py:545
        appends send/recv at the tail)."""
        segments: List[Tuple[str, List[OpDesc]]] = []
        cur: List[OpDesc] = []
        for op in block.ops:
            if REGISTRY.has(op.type) and REGISTRY.get(op.type).host:
                if cur:
                    segments.append(("jit", cur))
                    cur = []
                segments.append(("host", [op]))
            else:
                cur.append(op)
        if cur:
            segments.append(("jit", cur))

        # static name-availability walk to fix each jit segment's
        # signature (the _compile cache key already pins the feed sig)
        available = set(state_names) | set(feed_names)
        seg_meta = []
        for kind, seg_ops in segments:
            in_names = sorted({n for op in seg_ops
                               for n in op.input_names()
                               if n in available})
            out_names = sorted({n for op in seg_ops
                                for n in op.output_names()})
            seg_meta.append([kind, seg_ops, in_names, out_names])
            available |= set(out_names)
        # liveness pruning: a jit segment must only export names a later
        # segment, a fetch, or the persistable state needs — exporting
        # every intermediate would force XLA to materialize all
        # activations as live outputs (blocking fusion/DCE)
        live = set(fetch_names) | (persistable & available)
        for meta in reversed(seg_meta):
            kind, seg_ops, in_names, out_names = meta
            meta[3] = sorted(set(out_names) & live)
            live = (live - set(out_names)) | set(in_names)

        jitted_segs = {}

        def make_seg(si, seg_ops, in_names, out_names):
            def seg(vals, key):
                ctx = LowerCtx(key)
                lowerer = _BlockLowerer(program, ctx)
                env2 = dict(zip(in_names, vals))
                initial_env = dict(env2)
                lowerer.run_ops(seg_ops, env2, initial_env=initial_env,
                                initial_key=key)
                outs = [env2[n] for n in out_names]
                key_out = ctx.key_out if ctx.key_out is not None else key
                return outs, key_out
            return jax.jit(seg)

        def step(state, feeds, rng):
            env: Dict[str, Any] = dict(state)
            env.update(feeds)
            key = rng
            for si, (kind, seg_ops, in_names, out_names) in \
                    enumerate(seg_meta):
                if kind == "host":
                    op = seg_ops[0]
                    opdef = REGISTRY.get(op.type)
                    ins = {slot: [np.asarray(env[n]) for n in names]
                           for slot, names in op.inputs.items() if names}
                    try:
                        outs = opdef.lower(LowerCtx(), ins, op.attrs)
                    except Exception as e:
                        e.add_note(f"while running host op {op.type!r}")
                        raise
                    for slot, names in op.outputs.items():
                        vals = (outs or {}).get(slot)
                        if vals is None:
                            continue
                        for n, v in zip(names, vals):
                            env[n] = v
                else:
                    fn = jitted_segs.get(si)
                    if fn is None:
                        fn = jitted_segs[si] = make_seg(
                            si, seg_ops, in_names, out_names)
                    outs, key = fn([env[n] for n in in_names], key)
                    env.update(dict(zip(out_names, outs)))
            fetches = [env[n] for n in fetch_names]
            new_state = {n: env[n] for n in env if n in persistable}
            for n in state_names:
                new_state.setdefault(n, state[n])
            return fetches, new_state, key

        return step

    # ------------------------------------------------------------------
    # dataset-driven training (reference executor.py:1597
    # train_from_dataset / :1520 infer_from_dataset — the Trainer-path
    # successor of the legacy AsyncExecutor)
    # ------------------------------------------------------------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None, keep_results=True):
        """Consume every batch of a fluid Dataset through this program.

        The batch loop is PIPELINED with a bounded in-flight window
        (FLAGS_executor_inflight_steps, default 2): batch N+1 is parsed
        and staged onto the device by a prefetch thread while step N
        executes, the step is dispatched without blocking (lazy
        fetches), and completed fetches drain to host off the critical
        path. Pipelining is donation-safe: step N+1 donates the state
        buffers step N *produced* (fresh futures), never the ones step
        N consumed — the chain holds with any window depth. Window 1
        restores the old dispatch→sync→dispatch loop.

        `thread` is accepted for API parity and recorded; host-side
        parse parallelism lives in the Dataset's own thread pool
        (dataset.py _parse_all) plus the prefetch stage here.

        `keep_results=False` drops per-batch fetches after the
        print_period / fetch_handler hooks have seen them (returns
        None) — an epoch over a large dataset otherwise accumulates
        every batch's fetches in host memory. FLAGS_dataset_results_window
        (> 0) instead keeps only the last N batches."""
        return self._run_from_dataset(program, dataset, scope, thread,
                                      debug, fetch_list, fetch_info,
                                      print_period, fetch_handler,
                                      is_infer=False,
                                      keep_results=keep_results)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None, keep_results=True):
        return self._run_from_dataset(program, dataset, scope, thread,
                                      debug, fetch_list, fetch_info,
                                      print_period, fetch_handler,
                                      is_infer=True,
                                      keep_results=keep_results)

    def _run_from_dataset(self, program, dataset, scope, thread, debug,
                          fetch_list, fetch_info, print_period,
                          fetch_handler, is_infer, keep_results=True):
        if dataset is None:
            raise ValueError("dataset is required")
        program = program if program is not None else \
            default_main_program()
        if is_infer:
            program = program.clone(for_test=True)
        fetch_names = [f.name if isinstance(f, VarDesc) else str(f)
                       for f in (fetch_list or [])]
        infos = list(fetch_info or fetch_names)

        from collections import deque
        from ..flags import get_flag
        window = max(1, int(get_flag("FLAGS_executor_inflight_steps", 2)
                            or 1))
        rwin = int(get_flag("FLAGS_dataset_results_window", 0) or 0)
        if not keep_results:
            results = None
        elif rwin > 0:
            # bounded result history: an epoch over a large dataset
            # must not accumulate every batch's fetches on the host
            results = deque(maxlen=rwin)
        else:
            results = []
        from ..compiler import CompiledProgram as _CP
        stage = window > 1 and not isinstance(program, _CP)
        batches = iter(dataset)
        if stage:
            # prefetch thread: parse/collate batch N+1 and start its
            # host→device transfer while step N executes (reader.py's
            # buffered_reader analog, shared with DataLoader)
            from ..reader import _DevicePrefetcher
            batches = _DevicePrefetcher(batches, depth=window)
        pending = deque()  # (batch_no, lazy fetch handles)

        from .. import telemetry as _tm

        def drain_one():
            n, outs = pending.popleft()
            # materialize off the critical path: by drain time the step
            # is `window` dispatches old and usually already complete
            with _tm.span("pipeline/drain", step=n, track="drain",
                          timer="TIMER_pipeline_drain_us"):
                host = [h.numpy() for h in outs]
            _tm.flight_note(n, "drained_us", _tm.now_us())
            if results is not None:
                # full fetch_list per batch (single-var callers index
                # [0]); ADVICE r4: keeping only outs[0] silently
                # dropped the rest
                results.append(host if host else None)
            if fetch_names and (debug or n % max(print_period, 1) == 0):
                # logging reads the already-drained host copies — the
                # print_period boundary forces no extra sync
                import logging
                logging.getLogger("paddle_tpu").info(
                    "batch %d: %s", n,
                    ", ".join("%s=%s" % (i, v.ravel()[:4])
                              for i, v in zip(infos, host)))
                if fetch_handler is not None:
                    # reference FetchHandler contract: user callback on
                    # the fetched vars (time-based there; per
                    # print_period here, the same observability hook)
                    fetch_handler.handler(dict(zip(fetch_names, host)))

        # If the loop raises mid-window (bad batch, nan check, dataset
        # error), the pending handles are simply dropped: fetches are
        # never donated and the scope already holds the LAST DISPATCHED
        # step's state futures, so `scope` stays consistent — exactly
        # the state after that many completed sequential steps.
        try:
            for n, batch in enumerate(batches, start=1):
                # the batch number is the pipeline's step id: dispatch
                # N, feed-stage N+1 (prefetch thread), and drain
                # N−window land on separate trace tracks correlated by
                # it (docs/observability.md)
                with _tm.step_scope(n) if _tm.enabled() else \
                        _nullcontext():
                    with _tm.span("pipeline/dispatch", step=n,
                                  track="dispatch"):
                        outs = self.run(program, feed=batch,
                                        fetch_list=fetch_names,
                                        scope=scope, return_numpy="lazy")
                pending.append((n, outs))
                if len(pending) >= window:
                    drain_one()
            while pending:
                drain_one()
        except Exception as e:
            # a failed step's window is dropped (see comment above) —
            # but its last-N timeline survives in the exception notes
            _tm.attach_flight(e)
            raise
        return list(results) if isinstance(results, deque) else results

    def close(self):
        self._cache.clear()
