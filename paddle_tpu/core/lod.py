"""LoDTensor — the feedable variable-length batch container.

API analog of the reference's LoD (level-of-detail) tensor
(/root/reference/paddle/fluid/framework/lod_tensor.h:104): a packed
[total_items, ...] buffer plus nested sequence offsets. The TPU-native
compute representation is padded+lengths (ops/sequence.py — XLA needs
static shapes), so this class is the BRIDGE: it stores the packed numpy
buffer + recursive sequence lengths the way user feed code expects, and
converts to/from the padded form the sequence ops consume.

Kept host-side on purpose: LoD bookkeeping is data-pipeline work; only
the padded dense result ships to the chip.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np


def _lengths_to_offsets(lengths: Sequence[int]) -> List[int]:
    out = [0]
    for n in lengths:
        out.append(out[-1] + int(n))
    return out


def _offsets_to_lengths(offsets: Sequence[int]) -> List[int]:
    return [int(b) - int(a) for a, b in zip(offsets[:-1], offsets[1:])]


class LoDTensor:
    """Packed data + nested sequence structure.

    `lod()` returns offset-style LoD (reference lod_tensor.h), while
    `recursive_sequence_lengths()` returns length-style — both setters
    accept the matching style, mirroring fluid.LoDTensor's pybind API.
    """

    def __init__(self, data=None, recursive_seq_lens=None):
        self._data = None if data is None else np.asarray(data)
        self._rsl: List[List[int]] = []
        if recursive_seq_lens is not None:
            self.set_recursive_sequence_lengths(recursive_seq_lens)

    # -- buffer -----------------------------------------------------------
    def set(self, data, place=None):
        """place is accepted for API parity; jax owns real placement."""
        self._data = np.asarray(data)

    def shape(self):
        return () if self._data is None else tuple(self._data.shape)

    def __array__(self, dtype=None):
        arr = self._data if self._data is not None else np.empty((0,))
        return arr.astype(dtype) if dtype is not None else arr

    # -- structure --------------------------------------------------------
    def set_lod(self, lod: Sequence[Sequence[int]]):
        self._rsl = [_offsets_to_lengths(level) for level in lod]

    def lod(self) -> List[List[int]]:
        return [_lengths_to_offsets(level) for level in self._rsl]

    def set_recursive_sequence_lengths(self, rsl: Sequence[Sequence[int]]):
        self._rsl = [[int(n) for n in level] for level in rsl]

    def recursive_sequence_lengths(self) -> List[List[int]]:
        return [list(level) for level in self._rsl]

    def has_valid_recursive_sequence_lengths(self) -> bool:
        if not self._rsl:
            return True
        # each level's sequences must tile the level below; the last
        # level must tile the leading data dim (lod_tensor.cc CheckLoD)
        expect = None
        for level in self._rsl:
            if expect is not None and len(level) != expect:
                return False
            expect = sum(level)
        return self._data is None or expect == self._data.shape[0]

    # -- bridge to the TPU-native padded representation -------------------
    def to_padded(self, pad_value=0.0):
        """(padded [B, T_max, ...], lengths int32 [B]) for the finest
        level — the layout ops/sequence.py consumes."""
        if self._data is None:
            raise ValueError("LoDTensor has no data")
        if not self._rsl:
            return self._data[None], np.asarray(
                [self._data.shape[0]], np.int32)
        lengths = self._rsl[-1]
        t_max = max(lengths) if lengths else 0
        trail = self._data.shape[1:]
        out = np.full((len(lengths), t_max) + trail, pad_value,
                      dtype=self._data.dtype)
        ofs = 0
        for i, n in enumerate(lengths):
            out[i, :n] = self._data[ofs:ofs + n]
            ofs += n
        return out, np.asarray(lengths, np.int32)

    @staticmethod
    def from_padded(padded, lengths) -> "LoDTensor":
        padded = np.asarray(padded)
        lengths = [int(n) for n in np.asarray(lengths)]
        packed = np.concatenate(
            [padded[i, :n] for i, n in enumerate(lengths)], axis=0) \
            if lengths else padded.reshape((0,) + padded.shape[2:])
        return LoDTensor(packed, [lengths])

    def __repr__(self):
        return "LoDTensor(shape=%s, recursive_sequence_lengths=%s)" % (
            self.shape(), self._rsl)


class LoDTensorArray(list):
    """fluid.LoDTensorArray — a list of LoDTensors (the reference's
    pybind type is a std::vector<LoDTensor> with list semantics)."""
