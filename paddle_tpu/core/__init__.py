from . import dtypes  # noqa: F401
from .backward import append_backward, gradients  # noqa: F401
from .executor import Executor  # noqa: F401
from .fetch import FetchHandle  # noqa: F401
from .program import (  # noqa: F401
    Block, OpDesc, Program, VarDesc, default_main_program,
    default_startup_program, device_guard, disable_static, enable_static,
    in_dygraph_mode, in_static_mode, program_guard)
from .registry import REGISTRY, register_op  # noqa: F401
from .scope import Scope, global_scope, scope_guard  # noqa: F401
