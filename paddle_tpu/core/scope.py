"""Scope: name -> value store for static-graph execution.

Analog of the reference's hierarchical Scope
(/root/reference/paddle/fluid/framework/scope.h:46 — Var/FindVar/NewScope).
Values are jax.Arrays (device-resident) or numpy arrays (host staging);
hierarchy is kept for parity with local/step scopes used by executors and
control flow.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, Any] = {}
        self._parent = parent
        self._kids: List["Scope"] = []

    def var(self, name: str, value=None):
        """Create (or get) a variable in this scope."""
        if name not in self._vars:
            self._vars[name] = value
        elif value is not None:
            self._vars[name] = value
        return self._vars[name]

    def set(self, name: str, value) -> None:
        self._vars[name] = value

    def find_var(self, name: str):
        scope: Optional[Scope] = self
        while scope is not None:
            if name in scope._vars:
                return scope._vars[name]
            scope = scope._parent
        return None

    def has(self, name: str) -> bool:
        return self.find_var(name) is not None

    def erase(self, name: str) -> None:
        self._vars.pop(name, None)

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self) -> None:
        self._kids.clear()

    def local_names(self) -> List[str]:
        return list(self._vars)

    def items(self):
        return self._vars.items()


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


class scope_guard:
    """Swap the global scope (fluid.scope_guard, executor.py:52)."""

    def __init__(self, scope: Scope):
        self._scope = scope
        self._old = None

    def __enter__(self):
        global _global_scope
        self._old = _global_scope
        _global_scope = self._scope
        return self

    def __exit__(self, *exc):
        global _global_scope
        _global_scope = self._old
        return False
