"""Graph-build-time shape/dtype inference.

The reference implements per-op InferShape in C++
(/root/reference/paddle/fluid/framework/operator.h:448 OperatorWithKernel::
InferShape, shape_inference.h) — ~520 hand-written shape functions. The
TPU-native design gets all of them for free: each op already *is* a jax
lowering, so `jax.eval_shape` abstractly evaluates it (no FLOPs, no memory)
and yields output shapes/dtypes. Dynamic (batch) dims are round-tripped
through a sentinel extent.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .dtypes import to_jax_dtype
from .registry import REGISTRY, LowerCtx

# placeholder extent standing in for -1 (dynamic/batch) dims during abstract
# evaluation; mapped back to -1 in results.
_DYN = 1247


def _var_struct(var) -> Optional[jax.ShapeDtypeStruct]:
    if var.shape is None:
        return None
    shape = tuple(_DYN if d in (-1, None) else int(d) for d in var.shape)
    return jax.ShapeDtypeStruct(shape, to_jax_dtype(var.dtype))


def infer_op_shapes(block, op) -> bool:
    """Fill in shapes/dtypes of op's output VarDescs. Returns True on
    success; failures (unregistered op, unknown input shape, lowering that
    needs concrete values) leave shapes as None — harmless, later layers
    simply can't rely on them."""
    if not REGISTRY.has(op.type):
        return False
    opdef = REGISTRY.get(op.type)
    ins_structs = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            try:
                v = block.var(n)
            except KeyError:
                return False
            s = _var_struct(v)
            if s is None:
                return False
            vals.append(s)
        ins_structs[slot] = vals

    def f(key, ins):
        ctx = LowerCtx(key, is_test=True)
        return opdef.lower(ctx, ins, dict(op.attrs))

    try:
        outs = jax.eval_shape(f, jax.ShapeDtypeStruct((2,), jnp.uint32),
                              ins_structs)
    except Exception:
        return False

    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        for n, s in zip(names, vals):
            try:
                v = block.var(n)
            except KeyError:
                continue
            if v.shape is None:
                v.shape = tuple(-1 if d == _DYN else int(d)
                                for d in s.shape)
                v.dtype = np.dtype(s.dtype).name if not hasattr(
                    s.dtype, "name") else s.dtype.name
                from .dtypes import convert_dtype
                v.dtype = convert_dtype(v.dtype)
    return True
