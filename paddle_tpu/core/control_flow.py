"""Structural (control-flow) op lowerings: while, conditional_block,
tensor arrays.

TPU-native analog of /root/reference/paddle/fluid/operators/controlflow/
(while_op.cc:42 — an OperatorBase running its sub-block through a nested
Executor with per-iteration step scopes; conditional_block_op.cc — same
for an if-branch) and of the LoDTensorArray ops
(operators/controlflow/{write,read}_to_array... lod_tensor_array ops).

Design: these ops need *name-level* access to the traced environment (the
reference gives them the Scope), so they are special-cased by the
executor's _BlockLowerer rather than registered as value-level lowerings:

- while        -> lax.while_loop with an explicit carry = the sub-block's
                  externally-read + exported vars (the reference's
                  step-scope saving maps to this carry). Forward only:
                  XLA cannot reverse-differentiate a dynamic trip count;
                  differentiable loops should build with lax.scan-style
                  static unrolling (StaticRNN) instead.
- conditional_block -> lax.cond; false branch forwards the pre-existing
                  values of the block's outputs (so they must be
                  assigned before the op, as the reference requires for
                  grads). Differentiable.
- write_to_array / read_from_array / array_length -> TensorArrays are
  trace-time python lists in the environment. Writes append (the
  canonical fluid pattern writes at index == length); reads with a
  traced index stack the list and dynamically index. Arrays cannot
  cross a `while` boundary (a growing list has no fixed XLA type) —
  use them with build-time python loops, as StaticRNN does.
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

STRUCTURAL_OPS = ("while", "conditional_block", "write_to_array",
                  "read_from_array", "array_length", "run_program",
                  "static_rnn")


def _block_io(block) -> Tuple[Set[str], Set[str]]:
    """(external_reads, writes) of a block: reads-before-writes vs all
    writes, in op order."""
    written: Set[str] = set()
    ext: Set[str] = set()
    for op in block.ops:
        for ns in op.inputs.values():
            for n in ns:
                if n not in written:
                    ext.add(n)
        for ns in op.outputs.values():
            written.update(ns)
    return ext, written


def _as_pred(x) -> jax.Array:
    return jnp.reshape(jnp.asarray(x), ()).astype(bool)


def lower_while(lowerer, op, env: Dict[str, Any]) -> None:
    from .executor import _BlockLowerer  # cycle-free at call time
    from .registry import LowerCtx

    program = lowerer.program
    sub = program.blocks[int(op.attr("sub_block"))]
    cond_name = op.input("Condition")[0]
    ext_reads, writes = _block_io(sub)
    exported = writes & (set(env) | set(op.output("Out")))
    carry_names = sorted(((ext_reads & set(env)) | exported | {cond_name}))
    missing = [n for n in carry_names if n not in env]
    if missing:
        raise RuntimeError(
            "while: loop vars %s must be assigned before the loop "
            "(while_op.cc requires them in the outer scope)" % missing)
    for n in carry_names:
        if isinstance(env[n], list):
            raise NotImplementedError(
                "while: LoDTensorArray cannot cross a while boundary on "
                "XLA (dynamic-length list has no fixed type); build the "
                "loop with a python-level loop / StaticRNN instead")

    key0 = lowerer.ctx.key_out

    def cond_fn(carry):
        vals, _ = carry
        return _as_pred(vals[carry_names.index(cond_name)])

    def body_fn(carry):
        vals, key = carry
        env2 = dict(env)  # loop-invariant outer vars stay visible
        env2.update(zip(carry_names, vals))
        ctx2 = LowerCtx(key, is_test=lowerer.ctx.is_test,
                        mesh=lowerer.ctx.mesh)
        sub_low = _BlockLowerer(program, ctx2)
        sub_low.run_ops(sub.ops, env2)
        new_vals = tuple(env2[n] for n in carry_names)
        for n, old, new in zip(carry_names,
                               vals, new_vals):
            if jnp.shape(old) != jnp.shape(new) or \
                    jnp.result_type(old) != jnp.result_type(new):
                raise RuntimeError(
                    "while: loop var %r changed shape/dtype across an "
                    "iteration (%s/%s -> %s/%s); XLA while requires "
                    "loop-invariant types" %
                    (n, jnp.shape(old), jnp.result_type(old),
                     jnp.shape(new), jnp.result_type(new)))
        return new_vals, ctx2.key_out

    init = (tuple(jnp.asarray(env[n]) for n in carry_names), key0)
    final_vals, final_key = jax.lax.while_loop(cond_fn, body_fn, init)
    lowerer.ctx._key = final_key
    env.update(zip(carry_names, final_vals))
    for n in op.output("Out"):
        if n not in env:
            raise RuntimeError("while: output %r never assigned" % n)


def lower_conditional_block(lowerer, op, env: Dict[str, Any]) -> None:
    from .executor import _BlockLowerer
    from .registry import LowerCtx

    program = lowerer.program
    sub = program.blocks[int(op.attr("sub_block"))]
    cond_name = op.input("Cond")[0]
    out_names = list(op.output("Out"))
    ext_reads, writes = _block_io(sub)
    reads = sorted(ext_reads & set(env))
    exports = sorted(set(out_names) or (writes & set(env)))
    missing = [n for n in exports if n not in env]
    if missing:
        raise RuntimeError(
            "conditional_block: outputs %s must be assigned before the op "
            "so the false branch has values (fluid requires the same for "
            "grad: conditional_block_op.cc)" % missing)

    key0 = lowerer.ctx.key_out
    read_vals = tuple(env[n] for n in reads)
    out_prev = tuple(jnp.asarray(env[n]) for n in exports)

    def true_fn(operands):
        read_vals, out_prev, key = operands
        env2 = dict(env)
        env2.update(zip(reads, read_vals))
        ctx2 = LowerCtx(key, is_test=lowerer.ctx.is_test,
                        mesh=lowerer.ctx.mesh)
        sub_low = _BlockLowerer(program, ctx2)
        sub_low.run_ops(sub.ops, env2)
        return tuple(jnp.asarray(env2[n]).astype(jnp.result_type(p))
                     .reshape(jnp.shape(p))
                     for n, p in zip(exports, out_prev))

    def false_fn(operands):
        _, out_prev, _ = operands
        return out_prev

    outs = jax.lax.cond(_as_pred(env[cond_name]), true_fn, false_fn,
                        (read_vals, out_prev, key0))
    # burn the key whether or not the branch ran, keeping the chain aligned
    lowerer.ctx._key = jax.random.split(key0)[0] if key0 is not None else None
    env.update(zip(exports, outs))


def lower_write_to_array(lowerer, op, env: Dict[str, Any]) -> None:
    x = env[op.input("X")[0]]
    out_name = op.output("Out")[0]
    arr = env.get(out_name)
    if arr is None:
        arr = []
    elif not isinstance(arr, list):
        raise RuntimeError("write_to_array: %r is not a tensor array"
                           % out_name)
    # canonical fluid pattern appends at index == length; a concrete
    # in-range index overwrites (lod_tensor_array semantics)
    idx = env.get(op.input("I")[0]) if op.input("I") else None
    pos = None
    if idx is not None:
        try:
            pos = int(np.asarray(jax.core.concrete_or_error(
                None, idx, "write_to_array index")))
        except Exception:
            pos = None  # traced index -> append
    new = list(arr)
    if pos is not None and 0 <= pos < len(new):
        new[pos] = x
    else:
        new.append(x)
    env[out_name] = new


def lower_read_from_array(lowerer, op, env: Dict[str, Any]) -> None:
    arr = env[op.input("X")[0]]
    if not isinstance(arr, list):
        raise RuntimeError("read_from_array: input is not a tensor array")
    if not arr:
        raise RuntimeError("read_from_array: empty tensor array")
    idx = env[op.input("I")[0]]
    out_name = op.output("Out")[0]
    try:
        pos = int(np.asarray(jax.core.concrete_or_error(
            None, idx, "read_from_array index")))
        env[out_name] = arr[pos]
    except Exception:
        stacked = jnp.stack([jnp.asarray(v) for v in arr])
        i = jnp.clip(jnp.reshape(jnp.asarray(idx), ()).astype(jnp.int32),
                     0, len(arr) - 1)
        env[out_name] = jax.lax.dynamic_index_in_dim(stacked, i,
                                                     keepdims=False)


def lower_array_length(lowerer, op, env: Dict[str, Any]) -> None:
    arr = env[op.input("X")[0]]
    env[op.output("Out")[0]] = jnp.asarray([len(arr)], jnp.int64)


def lower_cond_block_pair(lowerer, op, env: Dict[str, Any]) -> None:
    """layers.cond's lowering: both branch blocks under one lax.cond.
    (The reference emits two conditional_blocks + select_input per
    output; lax.cond is the native XLA merge and stays differentiable.)"""
    from .executor import _BlockLowerer
    from .registry import LowerCtx

    program = lowerer.program
    t_blk = program.blocks[int(op.attr("true_block"))]
    f_blk = program.blocks[int(op.attr("false_block"))]
    t_outs = list(op.attr("true_outs", []))
    f_outs = list(op.attr("false_outs", []))
    out_names = list(op.output("Out"))
    cond_name = op.input("Cond")[0]

    reads_t, _ = _block_io(t_blk)
    reads_f, _ = _block_io(f_blk)
    reads = sorted((reads_t | reads_f) & set(env))
    key0 = lowerer.ctx.key_out
    read_vals = tuple(env[n] for n in reads)

    def run_branch(blk, outs):
        def fn(operands):
            read_vals, key = operands
            env2 = dict(env)
            env2.update(zip(reads, read_vals))
            ctx2 = LowerCtx(key, is_test=lowerer.ctx.is_test,
                            mesh=lowerer.ctx.mesh)
            _BlockLowerer(program, ctx2).run_ops(blk.ops, env2)
            return tuple(jnp.asarray(env2[n]) for n in outs)
        return fn

    true_fn = run_branch(t_blk, t_outs)
    false_fn = run_branch(f_blk, f_outs)
    outs = jax.lax.cond(_as_pred(env[cond_name]), true_fn, false_fn,
                        (read_vals, key0))
    lowerer.ctx._key = jax.random.split(key0)[0] if key0 is not None else None
    env.update(zip(out_names, outs))


def lower_run_program(lowerer, op, env: Dict[str, Any]) -> None:
    """run_program op (operators/run_program_op.cc): execute a captured
    sub-block inline — the op @to_static emits so a traced Program runs
    inside dygraph. The sub-block's ops lower straight into the current
    trace (one fused XLA computation, no interpreter hop), reading
    outer vars from env and publishing the declared outputs."""
    from .executor import _BlockLowerer

    program = lowerer.program
    sub = program.blocks[int(op.attr("sub_block"))]
    # the captured sub-block reads outer vars by their own names (the
    # @to_static capture shares the var table), so the outer env is the
    # feed — no renaming layer exists in this IR
    env2 = dict(env)
    sub_lowerer = _BlockLowerer(program, lowerer.ctx)
    sub_lowerer.run_ops(sub.ops, env2, initial_env=dict(env2),
                        initial_key=lowerer.ctx.key_out)
    for n in list(op.output("Out")) + list(op.output("DOut")):
        if n in env2:
            env[n] = env2[n]


def lower_pipeline_train(lowerer, op, env: Dict[str, Any]) -> None:
    # deferred import: the schedule lives with the rest of the pipeline
    # machinery in parallel/, which imports core
    from ..parallel.pipeline_static import lower_pipeline_train as impl
    impl(lowerer, op, env)


LOWERINGS = {
    "while": lower_while,
    "conditional_block": lower_conditional_block,
    "cond_block_pair": lower_cond_block_pair,
    "write_to_array": lower_write_to_array,
    "read_from_array": lower_read_from_array,
    "array_length": lower_array_length,
    "run_program": lower_run_program,
    "pipeline_train": lower_pipeline_train,
}


def lower_static_rnn(lowerer, op, env: Dict[str, Any]) -> None:
    """static_rnn structural op (fluid StaticRNN, layers
    control_flow.py:449): scan the step sub-block over the time-major
    leading dim of the step inputs with lax.scan — memories are the
    carry, step outputs stack to [T, ...]."""
    from .executor import _BlockLowerer
    from .registry import LowerCtx

    program = lowerer.program
    sub = program.blocks[int(op.attr("sub_block"))]
    seq_names = list(op.input("X"))
    init_names = list(op.input("Init"))
    out_names = list(op.output("Out"))
    step_in = list(op.attr("step_in_names"))
    mem_pre = list(op.attr("mem_pre_names"))
    mem_post = list(op.attr("mem_post_names"))
    step_out = list(op.attr("step_out_names"))

    seqs = [jnp.asarray(env[n]) for n in seq_names]
    inits = [jnp.asarray(env[n]) for n in init_names]
    outer_env = dict(env)
    key0 = lowerer.ctx.key_out

    def body(carry, xs_t):
        mems, key = carry
        key, sub_key = (jax.random.split(key) if key is not None
                        else (None, None))
        ctx2 = LowerCtx(sub_key, is_test=lowerer.ctx.is_test,
                        mesh=lowerer.ctx.mesh)
        env2 = dict(outer_env)
        for n, v in zip(step_in, xs_t):
            env2[n] = v
        for n, v in zip(mem_pre, mems):
            env2[n] = v
        _BlockLowerer(program, ctx2).run_ops(sub.ops, env2)
        new_mems = tuple(env2[n] for n in mem_post)
        outs = tuple(env2[n] for n in step_out)
        return (new_mems, key), outs

    (final_mems, final_key), stacked = jax.lax.scan(
        body, (tuple(inits), key0), tuple(seqs))
    # thread the POST-loop carry key out (lower_while's discipline):
    # rewinding to split(key0) would hand later ops keys the steps
    # already consumed, duplicating dropout masks
    lowerer.ctx._key = final_key
    for n, v in zip(out_names, stacked):
        env[n] = v


LOWERINGS["static_rnn"] = lower_static_rnn
