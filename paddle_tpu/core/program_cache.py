"""Persistent AOT program cache: disk-backed trace + compile reuse.

Kills the retrace+recompile cold start the reference pays per process
(PERF_NOTES: ~3.3 s trace + ~21 s XLA compile for the 12-layer
BERT-shaped train step, again in EVERY interpreter). Two disk layers
share one directory (FLAGS_program_cache_dir, default
~/.cache/paddle_tpu/aot, env override PADDLE_TPU_PROGRAM_CACHE_DIR):

  <dir>/trace/<fingerprint>.stablehlo
      jax.export bytes of the fully-lowered Executor step, keyed by
      Program.fingerprint() (op descs/attrs + feed/state signatures +
      lowering-relevant FLAGS + jax/backend versions + a framework
      source token). A hit skips the Python retrace entirely.
  <dir>/policy/<fingerprint>.json
      autotune's winning dispatch forms (paddle_tpu/autotune.py,
      docs/autotune.md) — one JSON entry per (shape-bucket, backend,
      quant-mode) key, version-stamped and self-healing like the
      trace layer, so a tuned deployment restarts straight into its
      winning geometry with zero re-tuning and zero recompiles.
  <dir>/xla/
      jax's persistent compilation cache — XLA binaries keyed by HLO.
      Both the cold and the warm path execute the SAME deserialized
      StableHLO module (the cold path round-trips its own bytes), so
      the warm process's XLA key matches and compilation is skipped
      too: warm start pays neither trace nor compile.

Every entry is written via temp-file + atomic os.replace so concurrent
processes can share one directory; a truncated/corrupt/version-skewed
entry is deleted and falls back to a clean recompile (never a crash,
never wrong fetches — the caller re-exports and overwrites). Counters
land in monitor.py: STAT_program_cache_trace_hit / _trace_miss /
_corrupt / _unexportable / _bytes_read / _bytes_written.

The role model is the reference's serialized-engine flow
(analysis_predictor.cc SaveOptimModel:900 + TRT engine cache), promoted
from a one-off inference artifact into the framework-wide execution
path for both Executor.run and the inference Predictor.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from typing import Optional

MAGIC = b"PTAOT1\n"
FORMAT_VERSION = 1

# set once per process by ensure_xla_cache(); remembered so we re-point
# only a dir WE configured (a user's own jax_compilation_cache_dir
# setting is never overridden)
_xla_cache_dir_set: Optional[str] = None
_framework_token: Optional[str] = None


def _stat_add(name: str, value: float = 1.0) -> None:
    from ..monitor import stat_add
    stat_add(name, value)


class _timed:
    """Record wall time of the enclosed disk operation into a monitor
    latency histogram (always on: these are once-per-program cold
    paths, and their latency is exactly what the hit/miss counters
    can't show — docs/observability.md)."""

    __slots__ = ("name", "_t0")

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        from ..monitor import timer_observe
        timer_observe(self.name, (time.perf_counter() - self._t0) * 1e6)
        return False


def default_dir() -> str:
    """The auto cache location: env override, else the home cache."""
    env = os.environ.get("PADDLE_TPU_PROGRAM_CACHE_DIR")
    if env is not None:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "paddle_tpu", "aot")


def resolve_dir(override: Optional[str] = None) -> Optional[str]:
    """Effective cache dir or None when disabled. Precedence:
    per-Executor override > FLAGS_program_cache_dir > env > home
    default; "" at any level disables."""
    d = override
    if d is None:
        from ..flags import get_flag
        d = get_flag("FLAGS_program_cache_dir")
    if d is None:
        d = default_dir()
    return d or None


def framework_token() -> str:
    """Hash over the paddle_tpu source tree's (path, mtime, size) — the
    op-lowering code IS part of the traced computation, so a source
    change must invalidate disk entries (same pyc-style heuristic as
    CPython's import system). Memoized per process."""
    global _framework_token
    if _framework_token is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        h = hashlib.sha256()
        for dirpath, dirnames, filenames in sorted(os.walk(root)):
            dirnames.sort()
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                p = os.path.join(dirpath, fn)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                h.update(("%s:%d:%d;" % (os.path.relpath(p, root),
                                         st.st_mtime_ns,
                                         st.st_size)).encode())
        _framework_token = h.hexdigest()
    return _framework_token


def ensure_xla_cache(cache_dir: str) -> None:
    """Point jax's persistent compilation cache at <cache_dir>/xla with
    a zero min-compile-time threshold (small CPU test programs must
    cache too). Never overrides a dir the user configured themselves."""
    global _xla_cache_dir_set
    try:
        import jax
        current = jax.config.jax_compilation_cache_dir
        if current and current != _xla_cache_dir_set:
            return  # user-configured; leave it alone
        xla_dir = os.path.join(cache_dir, "xla")
        if current == xla_dir:
            return
        os.makedirs(xla_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", xla_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _xla_cache_dir_set = xla_dir
        # jax latches its cache state at the process's FIRST compile
        # (_initialize_cache runs "at most once"), and the Executor has
        # usually jitted something (PRNG fold-in, state prep) before we
        # get here — un-latch so the next compile picks up the new dir
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # config knob skew across jax versions: cache is
        pass           # an optimization, never a hard dependency


def _trace_path(cache_dir: str, fingerprint: str) -> str:
    return os.path.join(cache_dir, "trace", fingerprint + ".stablehlo")


def _header_bytes(fingerprint: str) -> bytes:
    import jax
    import jaxlib
    return json.dumps({
        "format": FORMAT_VERSION,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "fingerprint": fingerprint,
    }, sort_keys=True).encode() + b"\n"


def load_trace(cache_dir: str, fingerprint: str) -> Optional[bytes]:
    """Return the serialized jax.export payload for `fingerprint`, or
    None on miss. Any malformed/truncated/version-skewed entry is
    deleted (counted STAT_program_cache_corrupt) so the caller's fresh
    export overwrites it."""
    path = _trace_path(cache_dir, fingerprint)
    try:
        with _timed("TIMER_program_cache_load_us"), \
                open(path, "rb") as f:
            blob = f.read()
    except OSError:
        _stat_add("STAT_program_cache_trace_miss")
        return None
    from ..failpoints import failpoint
    # corrupt/truncate injection lands BEFORE validation: the header +
    # payload checks below must catch the damage and self-heal (discard
    # + fresh export), which is exactly what the chaos tests prove
    blob = failpoint("program_cache.load", blob)
    try:
        if not blob.startswith(MAGIC):
            raise ValueError("bad magic")
        rest = blob[len(MAGIC):]
        nl = rest.index(b"\n")
        hdr = json.loads(rest[:nl])
        payload = rest[nl + 1:]
        import jax
        import jaxlib
        if (hdr.get("format") != FORMAT_VERSION
                or hdr.get("jax") != jax.__version__
                or hdr.get("jaxlib") != jaxlib.__version__
                or hdr.get("fingerprint") != fingerprint
                or not payload):
            raise ValueError("header mismatch")
    except (ValueError, KeyError):
        _stat_add("STAT_program_cache_corrupt")
        _stat_add("STAT_program_cache_trace_miss")
        discard_trace(cache_dir, fingerprint)
        return None
    _stat_add("STAT_program_cache_trace_hit")
    _stat_add("STAT_program_cache_bytes_read", len(blob))
    return payload


def store_trace(cache_dir: str, fingerprint: str, payload: bytes) -> bool:
    """Atomically publish an entry (temp file + os.replace) so a
    concurrent reader sees either nothing or a complete file. IO
    failure disables nothing — it just means no cache this time."""
    path = _trace_path(cache_dir, fingerprint)
    blob = MAGIC + _header_bytes(fingerprint) + payload
    from ..failpoints import failpoint
    blob = failpoint("program_cache.store", blob)
    try:
        with _timed("TIMER_program_cache_store_us"):
            os.makedirs(os.path.dirname(path), exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                       prefix=".tmp_" + fingerprint[:16])
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
    except OSError:
        return False
    _stat_add("STAT_program_cache_bytes_written", len(blob))
    return True


def discard_trace(cache_dir: str, fingerprint: str) -> None:
    try:
        os.unlink(_trace_path(cache_dir, fingerprint))
    except OSError:
        pass


def has_trace(cache_dir: str, fingerprint: str) -> bool:
    """Cheap existence probe (no counters, no validation) — lets a
    warmup loop report disk-warm vs fresh-compile without paying a
    load. The entry is still fully validated on the real load path."""
    try:
        return os.path.getsize(_trace_path(cache_dir, fingerprint)) > \
            len(MAGIC)
    except OSError:
        return False


# ---------------------------------------------------------------------------
# autotune policy sidecar (paddle_tpu/autotune.py, docs/autotune.md):
# <dir>/policy/<fingerprint>.json holds the winning dispatch form for
# one (shape-bucket, backend, quant-mode) key — same MAGIC + JSON
# header + atomic-replace + corrupt-entry-self-heal recipe as the
# trace layer, so a damaged or version-skewed policy file is deleted
# and the key simply re-tunes (never a crash, never a stale form).
# ---------------------------------------------------------------------------

POLICY_MAGIC = b"PTPOL1\n"
POLICY_FORMAT_VERSION = 1

# The knobs autotune searches. They are EXCLUDED from the policy
# fingerprint's lowering snapshot: the policy's job is to choose them,
# so keying the policy on their current values would fragment the key
# space (every flag flip would look like a new deployment). A pinned
# tuned flag still isolates correctly — pins ride the key meta itself
# (autotune.py puts them there), not the flag snapshot.
TUNED_FLAGS = ("FLAGS_paged_attention_kernel",)


def _policy_path(cache_dir: str, fingerprint: str) -> str:
    return os.path.join(cache_dir, "policy", fingerprint + ".json")


def policy_fingerprint(meta: dict) -> str:
    """Disk key for one autotune policy entry: sha256 over the
    caller's key metadata (shape-bucket, backend, quant-mode, pins) +
    the NON-tuned lowering flags + jax/jaxlib/backend versions + the
    framework source token — the fn_fingerprint invalidation surface
    minus the knobs the policy itself chooses (TUNED_FLAGS)."""
    import jax
    import jaxlib
    from ..flags import lowering_snapshot
    flags = tuple(kv for kv in lowering_snapshot()
                  if kv[0] not in TUNED_FLAGS)
    h = hashlib.sha256()
    h.update(json.dumps({
        "tag": "autotune_policy",
        "meta": meta,
        "flags": flags,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "framework": framework_token(),
    }, sort_keys=True, default=str).encode())
    return h.hexdigest()


def _policy_header(fingerprint: str) -> bytes:
    import jax
    import jaxlib
    return json.dumps({
        "format": POLICY_FORMAT_VERSION,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "fingerprint": fingerprint,
    }, sort_keys=True).encode() + b"\n"


def load_policy(cache_dir: str, fingerprint: str) -> Optional[dict]:
    """Return the persisted policy entry dict for `fingerprint`, or
    None on miss. Malformed / truncated / version-skewed files are
    deleted (STAT_program_cache_corrupt) so the key re-tunes cleanly —
    the same self-heal contract as load_trace."""
    path = _policy_path(cache_dir, fingerprint)
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    try:
        if not blob.startswith(POLICY_MAGIC):
            raise ValueError("bad magic")
        rest = blob[len(POLICY_MAGIC):]
        nl = rest.index(b"\n")
        hdr = json.loads(rest[:nl])
        import jax
        import jaxlib
        if (hdr.get("format") != POLICY_FORMAT_VERSION
                or hdr.get("jax") != jax.__version__
                or hdr.get("jaxlib") != jaxlib.__version__
                or hdr.get("fingerprint") != fingerprint):
            raise ValueError("header mismatch")
        entry = json.loads(rest[nl + 1:])
        if not isinstance(entry, dict):
            raise ValueError("payload not a dict")
    except (ValueError, KeyError):
        _stat_add("STAT_program_cache_corrupt")
        discard_policy(cache_dir, fingerprint)
        return None
    return entry


def store_policy(cache_dir: str, fingerprint: str, entry: dict) -> bool:
    """Atomically publish a policy entry (temp file + os.replace).
    IO failure means no persistence this time — never an error."""
    path = _policy_path(cache_dir, fingerprint)
    blob = POLICY_MAGIC + _policy_header(fingerprint) \
        + json.dumps(entry, sort_keys=True, default=str).encode()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   prefix=".tmp_" + fingerprint[:16])
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except OSError:
        return False
    return True


def discard_policy(cache_dir: str, fingerprint: str) -> None:
    try:
        os.unlink(_policy_path(cache_dir, fingerprint))
    except OSError:
        pass


def fn_fingerprint(tag: str, meta: dict) -> str:
    """Disk-cache key for a non-Program jitted function (the generation
    engine's prefill/decode steps): sha256 over a caller-provided tag +
    JSON-able metadata (config, shapes, bucket) + lowering-relevant
    FLAGS + jax/backend versions + the framework source token — the
    same invalidation surface as Program.fingerprint, for computations
    that never had a Program."""
    import jax
    import jaxlib
    from ..flags import lowering_snapshot
    h = hashlib.sha256()
    h.update(json.dumps({
        "tag": tag,
        "meta": meta,
        "flags": lowering_snapshot(),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "framework": framework_token(),
    }, sort_keys=True, default=str).encode())
    return h.hexdigest()


def exported_entry(cache_dir: str, fingerprint: str, fn, avals,
                   tag: Optional[str] = None, meta: Optional[dict] = None):
    """Generic disk-backed AOT entry: the Executor._aot_entry recipe
    (load -> deserialize -> aval check -> jit(exported.call); on miss
    export, round-trip the bytes, store) for any jit-able `fn` called
    as `fn(*avals)`. Returns the callable, or None when this function
    cannot be disk-cached (unexportable lowering, IO trouble) — the
    caller falls back to plain jax.jit(fn).

    With `tag`, the entry is routed through the XLA program accounting
    registry (core/program_accounting.py): compiled at once from the
    avals, cost/memory analysis recorded under the tag, and the
    compiled executable served directly — this is how the generation
    engine's fn_fingerprint entries show up in /programz."""
    import jax
    import jax.export
    ensure_xla_cache(cache_dir)
    exported = None
    payload = load_trace(cache_dir, fingerprint)
    if payload is not None:
        try:
            cand = jax.export.deserialize(payload)
            ours = [(tuple(a.shape), str(a.dtype))
                    for a in jax.tree.leaves(
                        jax.eval_shape(lambda *xs: xs, *avals))]
            theirs = [(tuple(a.shape), str(a.dtype))
                      for a in cand.in_avals]
            if ours == theirs:
                exported = cand
            else:
                raise ValueError("aval mismatch")
        except Exception:
            _stat_add("STAT_program_cache_corrupt")
            discard_trace(cache_dir, fingerprint)
            exported = None
    if exported is None:
        try:
            data = jax.export.export(jax.jit(fn))(*avals).serialize()
            exported = jax.export.deserialize(data)
        except Exception:
            _stat_add("STAT_program_cache_unexportable")
            return None
        store_trace(cache_dir, fingerprint, data)
    entry = jax.jit(exported.call)
    if tag is not None:
        from . import program_accounting
        entry = program_accounting.accounted(
            entry, avals, tag=program_accounting.safe_tag(tag),
            key=fingerprint[:12], meta=meta)
    return entry


def warmup_ladder(buckets, compile_one) -> dict:
    """Compile-ahead of a shape-bucket ladder (docs/serving.md): run
    `compile_one(bucket)` for every bucket size, ascending, and report
    per-bucket wall time plus whether the trace came from disk —
    the serving analog of the reference pre-building one TRT engine
    per optimization profile. Counters: STAT_program_cache_warm per
    bucket compiled; failures are recorded, not raised (a bucket the
    program cannot trace at must not take the whole ladder down)."""
    from ..monitor import stat_get
    report = {}
    for b in sorted(set(int(x) for x in buckets)):
        h0 = stat_get("STAT_program_cache_trace_hit")
        t0 = time.perf_counter()
        try:
            compile_one(b)
        except Exception as e:
            report[b] = {"error": repr(e)[:200]}
            continue
        _stat_add("STAT_program_cache_warm")
        report[b] = {
            "seconds": round(time.perf_counter() - t0, 4),
            "disk_warm":
                stat_get("STAT_program_cache_trace_hit") > h0,
        }
    return report
