"""Serializable static-graph IR: Program > Block > OpDesc / VarDesc.

TPU-native analog of the reference's protobuf program IR
(/root/reference/paddle/fluid/framework/framework.proto:42-216 — OpDesc:42,
VarDesc:165, BlockDesc:174, ProgramDesc:212) and its Python graph builder
(/root/reference/python/paddle/fluid/framework.py — Program:3934, Block:2472,
Operator:1881, Variable:889).

Design departure from the reference: ops here are *named ops with attrs* that
lower to jax functions (see core/registry.py); the whole block is traced once
and compiled by XLA (core/executor.py) instead of being interpreted op-by-op
by a C++ Executor. Serialization is JSON (versioned), which keeps the
transpiler-style program rewrites (AMP, recompute, distributed) and
save/load_inference_model workflows of the reference possible.
"""
from __future__ import annotations

import copy
import json
from typing import Any, Dict, List, Optional, Sequence

from . import dtypes

IR_VERSION = 1

# Variable kinds — subset of the reference VarType enum that is meaningful on
# TPU (framework.proto:104: LOD_TENSOR, SELECTED_ROWS, LOD_TENSOR_ARRAY, ...).
DENSE = "dense"            # LOD_TENSOR
SELECTED_ROWS = "selected_rows"
TENSOR_ARRAY = "tensor_array"


class VarDesc:
    """Variable metadata in a Block (framework.proto:165 VarDesc)."""

    __slots__ = (
        "name", "shape", "dtype", "persistable", "is_parameter",
        "stop_gradient", "type", "initializer", "trainable", "lod_level",
    )

    def __init__(self, name: str, shape: Optional[Sequence[int]] = None,
                 dtype="float32", persistable: bool = False,
                 is_parameter: bool = False, stop_gradient: bool = True,
                 type: str = DENSE, initializer: Optional[dict] = None,
                 trainable: bool = True, lod_level: int = 0):
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtypes.convert_dtype(dtype)
        self.persistable = persistable
        self.is_parameter = is_parameter
        self.stop_gradient = stop_gradient
        self.type = type
        self.initializer = initializer  # {"type": op_type, "attrs": {...}}
        self.trainable = trainable
        self.lod_level = lod_level

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "persistable": self.persistable,
            "is_parameter": self.is_parameter,
            "stop_gradient": self.stop_gradient,
            "type": self.type,
            "initializer": self.initializer,
            "trainable": self.trainable,
            "lod_level": self.lod_level,
        }

    @staticmethod
    def from_dict(d: dict) -> "VarDesc":
        return VarDesc(
            d["name"], d.get("shape"), d.get("dtype", "float32"),
            d.get("persistable", False), d.get("is_parameter", False),
            d.get("stop_gradient", True), d.get("type", DENSE),
            d.get("initializer"), d.get("trainable", True),
            d.get("lod_level", 0))

    def __repr__(self):
        return (f"VarDesc({self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype!r}, persistable={self.persistable})")


class OpDesc:
    """One operator invocation (framework.proto:42 OpDesc).

    inputs/outputs map slot name -> list of variable names, exactly like the
    reference's OpDesc.Var (framework.proto:48).
    """

    __slots__ = ("type", "inputs", "outputs", "attrs")

    def __init__(self, type: str,
                 inputs: Optional[Dict[str, List[str]]] = None,
                 outputs: Optional[Dict[str, List[str]]] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    def input_names(self) -> List[str]:
        return [n for ns in self.inputs.values() for n in ns]

    def output_names(self) -> List[str]:
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def to_dict(self) -> dict:
        return {"type": self.type, "inputs": self.inputs,
                "outputs": self.outputs, "attrs": _jsonable_attrs(self.attrs)}

    @staticmethod
    def from_dict(d: dict) -> "OpDesc":
        return OpDesc(d["type"], d.get("inputs"), d.get("outputs"),
                      d.get("attrs"))

    def __repr__(self):
        return f"OpDesc({self.type!r}, in={self.inputs}, out={self.outputs})"


def _jsonable_attrs(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, (list, tuple)):
            v = [x.item() if hasattr(x, "item") else x for x in v]
        elif hasattr(v, "item") and getattr(v, "size", 1) == 1:
            v = v.item()
        out[k] = v
    return out


class Block:
    """Ordered op list + var table (framework.proto:174 BlockDesc)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, VarDesc] = {}
        self.ops: List[OpDesc] = []

    # --- var management -------------------------------------------------
    def create_var(self, name: Optional[str] = None, **kwargs) -> VarDesc:
        if name is None:
            name = self.program._unique_name("tmp")
        if name in self.vars:
            # get-or-create like the reference Block.create_var, but a
            # conflicting redefinition (e.g. a parameter name colliding with
            # an activation var) is an error, not a silent drop.
            existing = self.vars[name]
            for key in ("persistable", "is_parameter"):
                if key in kwargs and kwargs[key] != getattr(existing, key):
                    raise ValueError(
                        f"variable {name!r} already exists with "
                        f"{key}={getattr(existing, key)}; cannot recreate "
                        f"with {key}={kwargs[key]}")
            return existing
        var = VarDesc(name, **kwargs)
        self.vars[name] = var
        self.program._bump()
        return var

    def create_parameter(self, name: str, shape, dtype="float32",
                         initializer: Optional[dict] = None,
                         trainable: bool = True) -> VarDesc:
        return self.create_var(
            name, shape=shape, dtype=dtype, persistable=True,
            is_parameter=True, stop_gradient=not trainable,
            initializer=initializer, trainable=trainable)

    def var(self, name: str) -> VarDesc:
        blk: Optional[Block] = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = (self.program.blocks[blk.parent_idx]
                   if blk.parent_idx >= 0 else None)
        raise KeyError(f"variable {name!r} not found in block {self.idx}")

    def has_var(self, name: str) -> bool:
        try:
            self.var(name)
            return True
        except KeyError:
            return False

    # --- op management --------------------------------------------------
    def append_op(self, type: str, inputs=None, outputs=None, attrs=None) -> OpDesc:
        op = OpDesc(type, inputs, outputs, attrs)
        if _state.current_device is not None:
            # device_guard stamp (framework.py:5516: each op's op_device
            # attr drives PipelineOptimizer._split_program)
            op.attrs.setdefault("op_device", _state.current_device)
        self.ops.append(op)
        self.program._bump()
        return op

    def prepend_op(self, type: str, inputs=None, outputs=None, attrs=None) -> OpDesc:
        op = OpDesc(type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._bump()
        return op

    def insert_op(self, index: int, type: str, inputs=None, outputs=None,
                  attrs=None) -> OpDesc:
        op = OpDesc(type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self.program._bump()
        return op

    def to_dict(self) -> dict:
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [o.to_dict() for o in self.ops],
        }


class Program:
    """A whole computation: list of blocks, block 0 is global
    (framework.proto:212 ProgramDesc).
    """

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self._block_stack: List[int] = [0]
        self._name_counter = 0
        self.random_seed: Optional[int] = None
        # structural version, bumped on any mutation — used by the executor's
        # compilation cache (analog of the reference Executor's program cache
        # keyed by program id, executor.py:1103 _run_impl)
        self._version = 0

    def _bump(self):
        self._version += 1

    # --- naming ---------------------------------------------------------
    def _unique_name(self, prefix: str) -> str:
        self._name_counter += 1
        return f"{prefix}_{self._name_counter}"

    # --- blocks ---------------------------------------------------------
    @property
    def global_block(self) -> Block:
        return self.blocks[0]

    def create_block(self, parent_idx: Optional[int] = None) -> Block:
        if parent_idx is None:
            parent_idx = self.current_block().idx
        blk = Block(self, len(self.blocks), parent_idx)
        self.blocks.append(blk)
        return blk

    def current_block(self) -> Block:
        return self.blocks[self._block_stack[-1]]

    class _BlockGuard:
        def __init__(self, program: "Program", block: "Block"):
            self._program = program
            self._idx = block.idx

        def __enter__(self):
            self._program._block_stack.append(self._idx)
            return self._program.blocks[self._idx]

        def __exit__(self, *exc):
            self._program._block_stack.pop()
            return False

    def block_guard(self, block: Block) -> "_BlockGuard":
        """Build ops into a sub-block (framework.py Program._create_block /
        _rollback pairing used by control-flow layers)."""
        return Program._BlockGuard(self, block)

    # --- queries --------------------------------------------------------
    def all_parameters(self) -> List[VarDesc]:
        return [v for b in self.blocks for v in b.vars.values()
                if v.is_parameter]

    def persistable_vars(self) -> List[VarDesc]:
        return [v for b in self.blocks for v in b.vars.values()
                if v.persistable]

    def list_vars(self) -> List[VarDesc]:
        return [v for b in self.blocks for v in b.vars.values()]

    # --- serialization --------------------------------------------------
    def to_dict(self) -> dict:
        return {"ir_version": IR_VERSION,
                "random_seed": self.random_seed,
                "name_counter": self._name_counter,
                "blocks": [b.to_dict() for b in self.blocks]}

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @staticmethod
    def from_dict(d: dict) -> "Program":
        if d.get("ir_version", 0) > IR_VERSION:
            raise ValueError(f"program IR version {d['ir_version']} is newer "
                             f"than supported {IR_VERSION}")
        prog = Program()
        prog.random_seed = d.get("random_seed")
        prog._name_counter = d.get("name_counter", 0)
        prog.blocks = []
        for bd in d["blocks"]:
            blk = Block(prog, bd["idx"], bd.get("parent_idx", -1))
            for vd in bd["vars"]:
                blk.vars[vd["name"]] = VarDesc.from_dict(vd)
            blk.ops = [OpDesc.from_dict(od) for od in bd["ops"]]
            prog.blocks.append(blk)
        if not prog.blocks:
            prog.blocks = [Block(prog, 0)]
        return prog

    @staticmethod
    def from_json(s: str) -> "Program":
        return Program.from_dict(json.loads(s))

    def fingerprint(self, feed_sig: Sequence = (),
                    fetch_names: Sequence[str] = (),
                    state_sig: Sequence = (),
                    extra: Sequence = ()) -> Optional[str]:
        """Stable cross-process identity of the COMPILED computation:
        canonical JSON of every op desc/attr and var desc, the feed and
        state shapes+dtypes, the fetch list, every lowering-relevant
        FLAGS_* value (flags.lowering_snapshot), the jax/jaxlib/backend
        versions, and a framework source token (op-lowering code is
        part of the computation — see program_cache.framework_token).
        Keys the disk AOT cache (core/program_cache.py). Returns None
        when the program holds an attr that cannot be canonicalized —
        such programs are simply not disk-cached.
        """
        import hashlib

        def _default(o):
            # ndarray-valued attrs hash by content; truncated reprs
            # (numpy elides large arrays) must never collide entries
            if hasattr(o, "tobytes") and hasattr(o, "dtype"):
                return {"__nd__": [str(o.dtype), list(getattr(o, "shape", ())),
                                   hashlib.sha256(o.tobytes()).hexdigest()]}
            if isinstance(o, bytes):
                return {"__b__": hashlib.sha256(o).hexdigest()}
            raise TypeError(type(o).__name__)

        try:
            body = json.dumps(self.to_dict(), sort_keys=True,
                              default=_default)
        except (TypeError, ValueError):
            return None
        from ..flags import lowering_snapshot
        from . import program_cache
        import jax
        import jaxlib
        h = hashlib.sha256()
        for part in (
                "ptaot%d" % program_cache.FORMAT_VERSION, body,
                repr(tuple(sorted(feed_sig))), repr(tuple(fetch_names)),
                repr(tuple(sorted(state_sig))), repr(lowering_snapshot()),
                jax.__version__, jaxlib.__version__, jax.default_backend(),
                program_cache.framework_token(), repr(tuple(extra))):
            h.update(part.encode() if isinstance(part, str) else part)
            h.update(b"\x00")
        return h.hexdigest()

    def clone(self, for_test: bool = False) -> "Program":
        """Deep-copy; with for_test=True keep only the FORWARD section
        (everything before the backward meta-op, optimizer ops stripped)
        and flip is_test attrs — the reference's
        Program.clone(for_test=True) prunes the same way
        (framework.py:4179 "forward content of original one"). Without
        the prune, running an eval clone would apply an optimizer step
        and silently corrupt training state."""
        prog = Program.from_dict(copy.deepcopy(self.to_dict()))
        prog.random_seed = self.random_seed
        if for_test:
            # ops whose ParamOut writes a Param in place = optimizers
            from .registry import REGISTRY
            for blk in prog.blocks:
                cut = next((i for i, op in enumerate(blk.ops)
                            if op.type == "backward"), None)
                if cut is not None:
                    blk.ops = blk.ops[:cut]
                # strip OPTIMIZER ops precisely: ParamOut-in-place
                # writers. Other stateful forward ops (streaming 'auc'
                # stats etc.) must SURVIVE — the reference's test clone
                # keeps metric ops
                blk.ops = [op for op in blk.ops
                           if not (REGISTRY.has(op.type) and "ParamOut"
                                   in REGISTRY.get(op.type).inplace_map)]
                for op in blk.ops:
                    if "is_test" in op.attrs:
                        op.attrs["is_test"] = True
        return prog

    def __repr__(self):
        nops = sum(len(b.ops) for b in self.blocks)
        return f"Program(blocks={len(self.blocks)}, ops={nops})"


# ---------------------------------------------------------------------------
# global default program / eager-mode switch — mirrors
# fluid.framework.default_main_program / default_startup_program and
# in_dygraph_mode (framework.py).
# ---------------------------------------------------------------------------
class _GlobalState:
    def __init__(self):
        self.main_program = Program()
        self.startup_program = Program()
        self.static_mode = False  # eager by default, like paddle 2.x
        self.current_device = None  # set by device_guard


_state = _GlobalState()


def default_main_program() -> Program:
    return _state.main_program


def default_startup_program() -> Program:
    return _state.startup_program


def switch_main_program(prog: Program) -> Program:
    old = _state.main_program
    _state.main_program = prog
    return old


def switch_startup_program(prog: Program) -> Program:
    old = _state.startup_program
    _state.startup_program = prog
    return old


def enable_static():
    _state.static_mode = True


def disable_static():
    _state.static_mode = False


def in_static_mode() -> bool:
    return _state.static_mode


def in_dygraph_mode() -> bool:
    return not _state.static_mode


class program_guard:
    """Context manager swapping default main/startup programs
    (fluid.program_guard, framework.py:5570)."""

    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self._main = main_program
        self._startup = startup_program
        self._old_main = None
        self._old_startup = None

    def __enter__(self):
        self._old_main = switch_main_program(self._main)
        if self._startup is not None:
            self._old_startup = switch_startup_program(self._startup)
        return self

    def __exit__(self, *exc):
        switch_main_program(self._old_main)
        if self._old_startup is not None:
            switch_startup_program(self._old_startup)
        return False


class device_guard:
    """Stamp appended ops with an op_device attr (framework.py:5516
    fluid.device_guard). Device strings follow the reference's
    "gpu:<stage>" convention; here the stage index is what matters — the
    pipeline compiler groups ops by it."""

    def __init__(self, device: Optional[str] = None):
        self._device = device
        self._old = None

    def __enter__(self):
        self._old = _state.current_device
        _state.current_device = self._device
        return self

    def __exit__(self, *exc):
        _state.current_device = self._old
        return False
