"""SelectedRows: row-sparse tensor for embedding gradients.

TPU-native analog of the reference's SelectedRows
(/root/reference/paddle/fluid/framework/selected_rows.h:32 — a `rows`
index vector plus a `value` tensor whose i-th row is the data for logical
row rows[i], within a dense `height`). The reference emits these from
`lookup_table` grads when is_sparse=True (operators/lookup_table_op.cc:82)
and gives optimizers sparse overloads.

Here SelectedRows is a jax pytree (rows + values are traced arrays, height
is static), so it flows through jit. `merged()` combines duplicate rows
with a static output shape (jnp.unique(size=n) + segment_sum) — the XLA
answer to the reference's scatter-merge in merge_selected_rows
(operators/math/selected_rows_functor.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["SelectedRows"]


@jax.tree_util.register_pytree_node_class
class SelectedRows:
    __slots__ = ("rows", "values", "height", "_is_merged")

    def __init__(self, rows, values, height: int):
        self.rows = jnp.asarray(rows, jnp.int32)
        self.values = jnp.asarray(values)
        self.height = int(height)

    # --- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        return (self.rows, self.values), self.height

    @classmethod
    def tree_unflatten(cls, height, children):
        rows, values = children
        obj = object.__new__(cls)
        obj.rows = rows
        obj.values = values
        obj.height = height
        return obj

    # --- conversions ----------------------------------------------------
    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def to_dense(self) -> jnp.ndarray:
        """Scatter-add into the dense [height, ...] tensor. Out-of-range
        rows (used as drop markers) are dropped by XLA scatter mode."""
        dense = jnp.zeros(self.shape, self.values.dtype)
        return dense.at[self.rows].add(self.values, mode="drop")

    def merged(self) -> "SelectedRows":
        """Combine duplicate rows (summing values); same static length,
        vacated slots get row index = height (a drop marker). Idempotent:
        an already-merged result is returned as-is (the marker is a plain
        Python attribute, dropped by pytree transforms, so at worst the
        merge re-runs)."""
        if getattr(self, "_is_merged", False):
            return self
        n = self.rows.shape[0]
        uniq, inv = jnp.unique(self.rows, return_inverse=True, size=n,
                               fill_value=self.height)
        vals = jax.ops.segment_sum(self.values, inv.reshape(-1),
                                   num_segments=n)
        out = SelectedRows(uniq, vals, self.height)
        out._is_merged = True
        return out

    # --- arithmetic (for grad accumulation) -----------------------------
    def __add__(self, other):
        if isinstance(other, SelectedRows):
            assert other.height == self.height
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]), self.height)
        if other is None:
            return self
        # dense + sparse -> dense
        return self.to_dense() + other

    __radd__ = __add__

    def __mul__(self, scalar):
        return SelectedRows(self.rows, self.values * scalar, self.height)

    __rmul__ = __mul__

    def numpy(self) -> np.ndarray:
        return np.asarray(self.to_dense())

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nnz_rows={self.rows.shape[0]}, "
                f"value_shape={tuple(self.values.shape)})")
