"""Error machinery + nan/inf debugging.

Analog of /root/reference/paddle/fluid/platform/enforce.h
(PADDLE_ENFORCE* with typed errors and context notes) and of
details/nan_inf_utils_detail.cc (FLAGS_check_nan_inf scanning each op's
outputs, operator.cc:1056). Under XLA the per-op scan is traced into the
compiled step as a lax.cond + host debug callback, so it reports at
*runtime* with the op/var name that first produced a non-finite value.
"""
from __future__ import annotations

from typing import Any

__all__ = ["enforce", "EnforceNotMet", "check_numerics"]


class EnforceNotMet(RuntimeError):
    """PADDLE_ENFORCE failure (enforce.h ThrowOnError)."""


def enforce(cond: bool, msg: str = "", *fmt_args: Any) -> None:
    if not cond:
        raise EnforceNotMet(msg % fmt_args if fmt_args else msg)


def check_numerics(value, op_type: str, var_name: str):
    """Trace a finite-check on a float array; on a non-finite value the
    compiled step prints the culprit op/var (nan_inf_utils_detail.cc
    prints and aborts; XLA cannot abort, so this reports loudly)."""
    import jax
    import jax.numpy as jnp
    if not hasattr(value, "dtype") or \
            not jnp.issubdtype(value.dtype, jnp.floating):
        return value

    finite = jnp.all(jnp.isfinite(value))

    def _report(bad):
        if bad:
            print("!!! check_nan_inf: op %r output %r contains nan/inf"
                  % (op_type, var_name))

    def _bad(_):
        jax.debug.callback(_report, True)

    def _ok(_):
        pass

    jax.lax.cond(finite, _ok, _bad, None)
    return value
