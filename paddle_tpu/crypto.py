"""Encrypted model IO: AES-CTR + HMAC-SHA256 cipher objects.

Analog of the reference's crypto stack
(/root/reference/paddle/fluid/framework/io/crypto/cipher.h Cipher /
CipherFactory, aes_cipher.cc AESCipher — CryptoPP-backed, configured by
cipher_utils.cc config files with names like "AES_CTR_NoPadding(128)").

TPU-repo design: the AES block cipher + CTR keystream are native C++
(csrc/crypto.cc, FIPS-197 from scratch — no CryptoPP dependency),
bound via ctypes like the native DataFeed parser (dataset/native.py).
The reference's authenticated modes (AES_GCM) are provided as
encrypt-then-MAC: AES-CTR over the payload, HMAC-SHA256 (hashlib) over
iv||ciphertext — a standard AEAD composition with the MAC key derived
separately from the encryption key.

Wire format: IV(16) || ciphertext || tag(32).
"""
from __future__ import annotations

import ctypes
import hashlib
import hmac
import os
from typing import Optional

from .native_build import build_native_lib

_LIB = None
_LIB_FAILED = False
_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "csrc", "crypto.cc")

TAG_BYTES = 32
IV_BYTES = 16


def _build_lib() -> Optional[ctypes.CDLL]:
    global _LIB_FAILED
    lib = build_native_lib(_SRC, "crypto")
    if lib is None:
        _LIB_FAILED = True
        return None
    lib.aes_ctr_crypt.restype = ctypes.c_longlong
    lib.aes_ctr_crypt.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_longlong]
    lib.aes_encrypt_block.restype = ctypes.c_longlong
    lib.aes_encrypt_block.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p]
    return lib


def _get_lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None and not _LIB_FAILED:
        _LIB = _build_lib()
    if _LIB is None:
        raise RuntimeError(
            "AES cipher needs the native toolchain (g++) to build "
            "csrc/crypto.cc; no pure-python fallback is provided for "
            "crypto")
    return _LIB


def _aes_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    lib = _get_lib()
    buf = ctypes.create_string_buffer(data, len(data))
    rc = lib.aes_ctr_crypt(key, len(key), iv, buf, len(data))
    if rc != 0:
        raise ValueError("bad AES key length %d (want 16/24/32)" % len(key))
    return buf.raw


class Cipher:
    """cipher.h:24 Cipher interface."""

    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        raise NotImplementedError

    def decrypt(self, ciphertext: bytes, key: bytes) -> bytes:
        raise NotImplementedError

    def encrypt_to_file(self, plaintext: bytes, key: bytes,
                        filename: str) -> None:
        with open(filename, "wb") as f:
            f.write(self.encrypt(plaintext, key))

    def decrypt_from_file(self, key: bytes, filename: str) -> bytes:
        with open(filename, "rb") as f:
            return self.decrypt(f.read(), key)


class AESCipher(Cipher):
    """aes_cipher.cc AESCipher: AES-CTR + HMAC-SHA256 encrypt-then-MAC.

    keysize: 128/192/256 (bits). A key of exactly keysize/8 bytes is used
    directly; anything else is derived via SHA-256 (truncated), so string
    passphrases work like the reference's keyfile contents."""

    def __init__(self, keysize: int = 128):
        if keysize not in (128, 192, 256):
            raise ValueError("AES keysize must be 128/192/256")
        self._nbytes = keysize // 8

    def _keys(self, key: bytes):
        if isinstance(key, str):
            key = key.encode()
        enc = key if len(key) == self._nbytes else hashlib.sha256(
            b"paddle_tpu.aes.enc" + key).digest()[:self._nbytes]
        mac = hashlib.sha256(b"paddle_tpu.aes.mac" + key).digest()
        return enc, mac

    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        enc_key, mac_key = self._keys(key)
        iv = os.urandom(IV_BYTES)
        ct = _aes_ctr(enc_key, iv, bytes(plaintext))
        tag = hmac.new(mac_key, iv + ct, hashlib.sha256).digest()
        return iv + ct + tag

    def decrypt(self, ciphertext: bytes, key: bytes) -> bytes:
        if len(ciphertext) < IV_BYTES + TAG_BYTES:
            raise ValueError("ciphertext too short")
        enc_key, mac_key = self._keys(key)
        iv = ciphertext[:IV_BYTES]
        ct = ciphertext[IV_BYTES:-TAG_BYTES]
        tag = ciphertext[-TAG_BYTES:]
        want = hmac.new(mac_key, iv + ct, hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            # the reference's AuthenticatedDecryptionFilter throws on a
            # GCM tag mismatch; same contract here
            raise ValueError("cipher authentication failed "
                             "(wrong key or tampered data)")
        return _aes_ctr(enc_key, iv, ct)


class CipherFactory:
    """cipher.h:44 CipherFactory.CreateCipher(config_file).

    Config: lines of `cipher_name <NAME>` (space or ':' separated);
    recognised names mirror the reference's cipher_utils strings, e.g.
    AES_CTR_NoPadding(128) / AES_GCM_NoPadding(256). GCM maps onto the
    same CTR+HMAC AEAD (authenticated either way). No config (or no file)
    defaults to AES_CTR_NoPadding(128) like the reference."""

    @staticmethod
    def create_cipher(config_file: Optional[str] = None) -> Cipher:
        name = "AES_CTR_NoPadding(128)"
        if config_file and os.path.exists(config_file):
            with open(config_file) as f:
                for line in f:
                    parts = line.replace(":", " ").split()
                    if len(parts) >= 2 and parts[0] == "cipher_name":
                        name = parts[1]
        if not name.startswith(("AES_CTR", "AES_GCM")):
            raise ValueError("unsupported cipher %r" % name)
        keysize = 128
        if "(" in name:
            keysize = int(name[name.index("(") + 1:name.index(")")])
        return AESCipher(keysize)


def using_native() -> bool:
    try:
        _get_lib()
        return True
    except RuntimeError:
        return False
