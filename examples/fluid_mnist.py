"""Fluid-style static-graph training — the book's recognize_digits flow
ported verbatim (reference book/04): Program + Executor + DataFeeder.
Toy scale on CPU; raise EPOCHS/BATCH and feed real MNIST for the full
run (paddle_tpu.datasets.mnist serves cached-or-synthetic data)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu import datasets

BATCH, EPOCHS = 64, 2


def network(img, label):
    conv1 = fluid.nets.simple_img_conv_pool(
        img, num_filters=20, filter_size=5, pool_size=2, pool_stride=2,
        act="relu")
    conv2 = fluid.nets.simple_img_conv_pool(
        conv1, num_filters=50, filter_size=5, pool_size=2,
        pool_stride=2, act="relu")
    pred = fluid.layers.fc(conv2, 10, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    acc = fluid.layers.accuracy(pred, label)
    return loss, acc


def main():
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        img = fluid.layers.data("img", [1, 28, 28])
        label = fluid.layers.data("label", [1], dtype="int64")
        loss, acc = network(img, label)
        fluid.optimizer.Adam(1e-3).minimize(
            loss, startup_program=startup, program=main_prog)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feeder = fluid.DataFeeder(["img", "label"])
    train_reader = fluid.io.batch(datasets.mnist.train(), BATCH)
    for epoch in range(EPOCHS):
        for step, batch in enumerate(train_reader()):
            samples = [(np.asarray(x, np.float32).reshape(1, 28, 28),
                        np.asarray([y], np.int64)) for x, y in batch]
            lv, av = exe.run(main_prog, feed=feeder.feed(samples),
                             fetch_list=[loss, acc])
            if step % 20 == 0:
                print("epoch %d step %d loss %.4f acc %.3f"
                      % (epoch, step, float(np.asarray(lv)),
                         float(np.asarray(av))))
            if step >= 40:  # toy run
                break


if __name__ == "__main__":
    main()
