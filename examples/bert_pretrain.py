"""BERT pretraining on the TPU throughput path: ONE fused
forward+backward+update XLA computation (jit.TrainStep) with AMP bf16
and optional dp x mp mesh sharding — the configuration bench.py scores
(101k tok/s / 30.3% MFU on a single v5e chip at B=32 S=512).

CPU toy scale by default. On a TPU host: set TOY=False; for multi-chip
set MESH to e.g. {"dp": 4, "mp": 2} — parameters shard over mp, the
batch over dp, XLA inserts the collectives (GSPMD)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as pt
from paddle_tpu.jit import TrainStep
from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                    pretraining_loss)

TOY = True
MESH = None  # e.g. {"dp": 4, "mp": 2}


def main():
    pt.seed(0)
    if TOY:
        cfg = BertConfig(vocab_size=1000, hidden_size=128,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=256)
        B, S, M, steps, amp = 4, 128, 20, 5, None
    else:
        cfg = BertConfig()  # BERT-base
        B, S, M, steps, amp = 32, 512, 80, 100, "bfloat16"

    mesh = None
    rules = None
    if MESH:
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.parallel.env import init_parallel_env
        mesh = init_parallel_env(MESH).mesh
        H, I, V = (cfg.hidden_size, cfg.intermediate_size,
                   cfg.vocab_size)

        def rules(name, shape):
            # Megatron layout over the mp axis: FFN up column-sharded,
            # FFN down row-sharded (XLA inserts the activation
            # all-reduce), embedding table row-sharded. Everything else
            # replicates — without rules ALL params would replicate and
            # mp would just duplicate compute.
            if shape == (H, I):
                return P(None, "mp")
            if shape == (I, H):
                return P("mp", None)
            if shape == (V, H):
                return P("mp", None)
            return P()

    model = BertForPretraining(cfg)
    opt = pt.optimizer.Adam(1e-4, parameters=model.parameters())
    step = TrainStep(model, pretraining_loss, opt, amp_dtype=amp,
                     mesh=mesh, param_rules=rules)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    pos = np.stack([rng.choice(S, M, replace=False)
                    for _ in range(B)]).astype(np.int32)
    mlm = np.take_along_axis(ids, pos, 1).astype(np.int32)
    nsp = rng.randint(0, 2, (B, 1)).astype(np.int32)
    for i in range(steps):
        loss = step((ids, None, None, pos), (mlm, nsp))
        if i % max(steps // 5, 1) == 0:
            print("step %d loss %.4f" % (i, float(loss)))


if __name__ == "__main__":
    main()
