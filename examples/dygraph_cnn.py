"""2.0-style eager training: nn.Layer subclass + DataLoader +
optimizer.step/clear_grad — the paddle 2.x idiom on the dygraph tape."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


class SimpleCNN(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.conv1 = nn.Conv2D(1, 16, 3, padding=1)
        self.conv2 = nn.Conv2D(16, 32, 3, padding=1, stride=2)
        self.head = nn.Linear(32 * 14 * 14, num_classes)

    def forward(self, x):
        x = F.relu(self.conv1(x))
        x = F.relu(self.conv2(x))
        x = paddle.flatten(x, 1)
        return self.head(x)


class SyntheticDigits(paddle.io.Dataset):
    """Map-style dataset of separable synthetic digits."""

    def __init__(self, n=512, seed=0):
        rng = np.random.RandomState(seed)
        self.y = rng.randint(0, 10, (n,)).astype(np.int64)
        self.x = rng.randn(n, 1, 28, 28).astype(np.float32) * 0.1
        for i, yi in enumerate(self.y):
            self.x[i, 0, yi + 4, 4:24] += 2.0

    def __getitem__(self, i):
        return self.x[i], np.asarray([self.y[i]], np.int64)

    def __len__(self):
        return len(self.y)


def main():
    paddle.seed(0)
    model = SimpleCNN()
    opt = paddle.optimizer.Adam(1e-3, parameters=model.parameters())
    loader = paddle.io.DataLoader(SyntheticDigits(), batch_size=32,
                                  shuffle=True)
    for step, (x, y) in enumerate(loader):
        logits = model(paddle.to_tensor(x))
        loss = F.cross_entropy(logits, paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        if step % 5 == 0:
            print("step %d loss %.4f" % (step, float(loss)))


if __name__ == "__main__":
    main()
