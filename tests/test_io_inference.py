"""save/load + inference predictor + auto-checkpoint tests.

Mirrors the reference's book tests that save_inference_model then reload
and check identical outputs (tests/book/test_fit_a_line.py) and the
auto-checkpoint epoch-resume unit tests
(unittests/test_auto_checkpoint.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _build_regression():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        pred = layers.fc(x, 1, name="pred")
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = pt.optimizer.SGD(0.05)
        opt.minimize(loss, startup_program=startup, program=main)
    return main, startup, pred, loss


def _train(exe, main, loss, steps=10, seed=0):
    rng = np.random.RandomState(seed)
    out = None
    for _ in range(steps):
        xb = rng.randn(16, 4).astype(np.float32)
        yb = (xb @ np.array([[1.], [2.], [-1.], [0.5]], np.float32)
              + 0.1).astype(np.float32)
        out, = exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
    return out


def test_save_load_persistables(tmp_path):
    main, startup, pred, loss = _build_regression()
    exe = pt.Executor()
    exe.run(startup)
    _train(exe, main, loss)
    wname = main.all_parameters()[0].name
    w = np.asarray(pt.global_scope().find_var(wname))

    pt.save_persistables(exe, str(tmp_path), main)

    # clobber + reload
    pt.global_scope().set(wname, np.zeros_like(w))
    pt.load_persistables(exe, str(tmp_path), main)
    np.testing.assert_allclose(
        np.asarray(pt.global_scope().find_var(wname)), w)


def test_save_load_program_pickle_style(tmp_path):
    main, startup, pred, loss = _build_regression()
    exe = pt.Executor()
    exe.run(startup)
    _train(exe, main, loss)
    wname = main.all_parameters()[0].name
    w = np.asarray(pt.global_scope().find_var(wname))
    path = str(tmp_path / "model")
    pt.save(main, path)
    assert os.path.exists(path + ".pdparams")
    assert os.path.exists(path + ".pdmodel")
    pt.global_scope().set(wname, np.zeros_like(w))
    pt.load(main, path)
    np.testing.assert_allclose(
        np.asarray(pt.global_scope().find_var(wname)), w)


def test_inference_model_roundtrip(tmp_path):
    main, startup, pred, loss = _build_regression()
    exe = pt.Executor()
    exe.run(startup)
    _train(exe, main, loss)

    xb = np.random.RandomState(7).randn(5, 4).astype(np.float32)
    d = str(tmp_path / "infer")
    pt.save_inference_model(d, ["x"], [pred], exe, main)
    # expectation from the pruned graph in the ORIGINAL scope (running the
    # full main program would also apply its sgd op and move the weights)
    from paddle_tpu.io import prune_program
    infer_prog = prune_program(main.clone(for_test=True), ["x"], [pred.name])
    expect, = exe.run(infer_prog, feed={"x": xb}, fetch_list=[pred.name])

    # fresh scope reload
    exe2 = pt.Executor()
    scope = pt.Scope()
    with pt.scope_guard(scope):
        prog, feeds, fetches = pt.load_inference_model(d, exe2)
        assert feeds == ["x"]
        # pruned program must not contain the backward/optimizer ops
        types = {op.type for op in prog.global_block.ops}
        assert "sgd" not in types and not any("grad" in t for t in types)
        got, = exe2.run(prog, feed={"x": xb}, fetch_list=fetches)
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)


def test_predictor(tmp_path):
    main, startup, pred, loss = _build_regression()
    exe = pt.Executor()
    exe.run(startup)
    _train(exe, main, loss)
    xb = np.random.RandomState(3).randn(6, 4).astype(np.float32)
    d = str(tmp_path / "infer")
    pt.save_inference_model(d, ["x"], [pred], exe, main)
    from paddle_tpu.io import prune_program
    infer_prog = prune_program(main.clone(for_test=True), ["x"], [pred.name])
    expect, = exe.run(infer_prog, feed={"x": xb}, fetch_list=[pred.name])

    from paddle_tpu.inference import Config, create_predictor
    cfg = Config(model_dir=d)
    predictor = create_predictor(cfg)
    assert predictor.get_input_names() == ["x"]
    h = predictor.get_input_handle("x")
    h.copy_from_cpu(xb)
    predictor.run()
    out = predictor.get_output_handle(predictor.get_output_names()[0]) \
        .copy_to_cpu()
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)


def test_dygraph_state_dict_roundtrip(tmp_path):
    from paddle_tpu.nn.layers_lib import Linear
    was_dygraph = pt.in_dygraph_mode()
    pt.disable_static()
    try:
        lin = Linear(4, 3)
        sd = lin.state_dict()
        pt.save_dygraph(sd, str(tmp_path / "lin"))
        params, opt = pt.load_dygraph(str(tmp_path / "lin"))
        assert opt is None
        lin2 = Linear(4, 3)
        lin2.set_state_dict(params)
        for k in sd:
            np.testing.assert_allclose(np.asarray(sd[k]),
                                       np.asarray(lin2.state_dict()[k]))
    finally:
        if not was_dygraph:
            pt.enable_static()


def test_auto_checkpoint_resume(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_RUNNING_ENV", "PADDLE_EDL_AUTO_CHECKPOINT")
    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path / "ac"))
    monkeypatch.setenv("PADDLE_JOB_ID", "job0")
    import paddle_tpu.incubate.checkpoint.auto_checkpoint as ac
    monkeypatch.setattr(ac, "_checker", None)

    main, startup, pred, loss = _build_regression()
    exe = pt.Executor()

    with pt.program_guard(main, startup):
        exe.run(startup)
        seen = []
        for epoch in ac.train_epoch_range(3, name="r1",
                                          save_checkpoint_inter=0):
            seen.append(epoch)
            _train(exe, main, loss, steps=2, seed=epoch)
        assert seen == [0, 1, 2]
        wname = main.all_parameters()[0].name
        w_done = np.asarray(pt.global_scope().find_var(wname))

        # "restart": epochs should all be skipped, weights restored
        monkeypatch.setattr(ac, "_checker", None)
        pt.global_scope().set(wname, np.zeros_like(w_done))
        seen2 = [e for e in ac.train_epoch_range(3, name="r1",
                                                 save_checkpoint_inter=0)]
        assert seen2 == []
        np.testing.assert_allclose(
            np.asarray(pt.global_scope().find_var(wname)), w_done)


def test_predictor_pass_builder(tmp_path):
    """PassStrategy pipeline runs before trace (paddle_pass_builder
    analog): dropout ops must be rewritten out of the loaded program."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.inference import Config, create_predictor
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        h = layers.fc(x, size=8, act="relu")
        h = layers.dropout(h, dropout_prob=0.5)
        out = layers.fc(h, size=2)
    scope = pt.Scope()
    exe = pt.Executor()
    with pt.scope_guard(scope):
        exe.run(startup)
        pt.io.save_inference_model(str(tmp_path / "m"), ["x"], [out],
                                   exe, main_program=main, scope=scope)
    cfg = Config(str(tmp_path / "m"))
    assert "drop_dropout_eval" in cfg.pass_builder().passes()
    cfg.pass_builder().delete_pass("fuse_elewise_add_act")
    pred = create_predictor(cfg)
    assert not any(op.type == "dropout"
                   for op in pred.program.global_block.ops)
    ih = pred.get_input_handle("x")
    ih.copy_from_cpu(np.ones((3, 4), np.float32))
    pred.run()
    got = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    assert got.shape == (3, 2)


# ---------------------------------------------------------------------------
# inference fusion passes + AOT serving artifact (round-4 depth:
# paddle_pass_builder.cc semantic fusions + SaveOptimModel analog)
# ---------------------------------------------------------------------------

def _run_prog(prog, feed, fetch, scope):
    exe = pt.Executor()
    with pt.scope_guard(scope):
        return exe.run(prog, feed=feed, fetch_list=fetch)


def test_multihead_matmul_fuse_pass(tmp_path):
    from paddle_tpu.core.passes import apply_pass
    B, S, H, nh = 2, 8, 16, 4
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = pt.layers.data("x", [S, H])
        mask = pt.layers.data("mask", [1, S, S])
        out = pt.layers.multi_head_attention(x, nh, attn_mask=mask)
    scope = pt.Scope()
    exe = pt.Executor()
    with pt.scope_guard(scope):
        exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(B, S, H).astype(np.float32),
            "mask": np.zeros((B, 1, S, S), np.float32)}
    ref, = _run_prog(main, feed, [out.name], scope)

    fused = main.clone()
    apply_pass(fused, "multihead_matmul_fuse")
    types = [op.type for op in fused.global_block.ops]
    assert "multihead_matmul" in types, types
    assert "softmax" not in types and "mul" not in types, types
    got, = _run_prog(fused, feed, [out.name], scope)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_embedding_eltwise_layernorm_fuse_pass():
    from paddle_tpu.core.passes import apply_pass
    B, S, H, V = 2, 6, 8, 30
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        w_ids = pt.layers.data("w_ids", [S, 1], dtype="int64")
        p_ids = pt.layers.data("p_ids", [S, 1], dtype="int64")
        we = pt.layers.embedding(w_ids, size=[V, H])
        pe = pt.layers.embedding(p_ids, size=[V, H])
        summed = pt.layers.elementwise_add(we, pe)
        out = pt.layers.layer_norm(summed, begin_norm_axis=2)
    scope = pt.Scope()
    exe = pt.Executor()
    with pt.scope_guard(scope):
        exe.run(startup)
    rng = np.random.RandomState(1)
    feed = {"w_ids": rng.randint(0, V, (B, S, 1)).astype(np.int64),
            "p_ids": rng.randint(0, V, (B, S, 1)).astype(np.int64)}
    ref, = _run_prog(main, feed, [out.name], scope)

    fused = main.clone()
    apply_pass(fused, "embedding_eltwise_layernorm_fuse")
    types = [op.type for op in fused.global_block.ops]
    assert "fused_embedding_eltwise_layernorm" in types, types
    assert "lookup_table" not in types and \
        "lookup_table_v2" not in types, types
    got, = _run_prog(fused, feed, [out.name], scope)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_export_serialized_serves_in_fresh_process(tmp_path):
    """SaveOptimModel/engine-serialization analog: the exported artifact
    serves from a NEW python process with no Program IR / registry
    tracing involved."""
    import subprocess
    import sys
    import textwrap
    main, startup, pred, loss = _build_regression()
    exe = pt.Executor()
    exe.run(startup)
    _train(exe, main, loss)
    d = str(tmp_path / "m")
    pt.save_inference_model(d, ["x"], [pred], exe, main)
    from paddle_tpu.inference import Config, SerializedPredictor, \
        create_predictor
    predictor = create_predictor(Config(model_dir=d))
    xb = np.random.RandomState(5).randn(6, 4).astype(np.float32)
    expect, = predictor.run([xb])
    art = str(tmp_path / "art")
    predictor.export_serialized(art, [xb])

    # same-process load path
    sp = SerializedPredictor(art)
    got, = sp.run([xb])
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)

    # fresh-process serve (the real contract)
    script = textwrap.dedent("""
        import jax; jax.config.update("jax_platforms", "cpu")
        import numpy as np, sys
        from paddle_tpu.inference import SerializedPredictor
        sp = SerializedPredictor(sys.argv[1])
        xb = np.random.RandomState(5).randn(6, 4).astype(np.float32)
        out, = sp.run([xb])
        np.save(sys.argv[2], out)
    """)
    out_npy = str(tmp_path / "out.npy")
    proc = subprocess.run([sys.executable, "-c", script, art, out_npy],
                          capture_output=True, text=True, cwd="/root/repo",
                          timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    np.testing.assert_allclose(np.load(out_npy), expect,
                               rtol=1e-5, atol=1e-6)


def test_quant_frozen_graph_through_predictor(tmp_path):
    """QAT transform -> freeze -> save_inference_model -> Predictor:
    the quantized serving path of the reference's slim pipeline."""
    from paddle_tpu.contrib.slim.quantization import (
        QuantizationFreezePass, QuantizationTransformPass)
    main, startup = pt.Program(), pt.Program()
    rng = np.random.RandomState(0)
    with pt.program_guard(main, startup):
        x = pt.layers.data("x", [8])
        y = pt.layers.data("y", [1])
        h = pt.layers.fc(x, 16, act="relu")
        pred = pt.layers.fc(h, 1, name="qpred")
        loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    scope = pt.Scope()
    tp = QuantizationTransformPass(scope=scope, startup_program=startup)
    tp.apply(main)
    with pt.program_guard(main, startup):
        pt.optimizer.SGD(0.05).minimize(loss, startup_program=startup,
                                        program=main)
    exe = pt.Executor()
    true_w = rng.randn(8, 1).astype(np.float32)
    with pt.scope_guard(scope):
        exe.run(startup)
        for i in range(40):
            xb = rng.randn(32, 8).astype(np.float32)
            exe.run(main, feed={"x": xb, "y": xb @ true_w},
                    fetch_list=[loss])
        infer = main.clone(for_test=True)
        QuantizationFreezePass(scope=scope).apply(infer)
        xb = rng.randn(8, 8).astype(np.float32)
        expect, = exe.run(infer, feed={"x": xb,
                                       "y": np.zeros((8, 1), np.float32)},
                          fetch_list=[pred])
        d = str(tmp_path / "qmodel")
        pt.save_inference_model(d, ["x"], [pred], exe, infer)

    from paddle_tpu.inference import Config, create_predictor
    predictor = create_predictor(Config(model_dir=d))
    got, = predictor.run([xb])
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)


def test_fuse_passes_respect_taps_and_protected():
    """A tapped intermediate (second consumer or fetch target) must keep
    the subgraph unfused — the reference pass's no-external-consumer
    pattern constraint."""
    from paddle_tpu.core.passes import apply_pass
    B, S, H, nh = 2, 4, 8, 2
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = pt.layers.data("x", [S, H])
        out = pt.layers.multi_head_attention(x, nh)
    # find the softmax output and fetch it (a probs tap)
    sm_out = next(op.output("Out")[0] for op in main.global_block.ops
                  if op.type == "softmax")
    fused = main.clone()
    apply_pass(fused, "multihead_matmul_fuse", protected={sm_out})
    assert "multihead_matmul" not in \
        [op.type for op in fused.global_block.ops]
    # without protection it fuses
    fused2 = main.clone()
    apply_pass(fused2, "multihead_matmul_fuse")
    assert "multihead_matmul" in \
        [op.type for op in fused2.global_block.ops]


def test_fuse_passes_skip_unsupported_variants():
    """padding_idx lookups, non-default probs@V alpha, consumed
    layer_norm stats: all must skip fusion (silent-corruption guards)."""
    from paddle_tpu.core.passes import apply_pass
    # padding_idx embedding stays unfused
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        a = pt.layers.data("a", [4, 1], dtype="int64")
        b = pt.layers.data("b", [4, 1], dtype="int64")
        s = pt.layers.elementwise_add(
            pt.layers.embedding(a, size=[10, 8], padding_idx=0),
            pt.layers.embedding(b, size=[10, 8]))
        pt.layers.layer_norm(s, begin_norm_axis=2)
    apply_pass(main, "embedding_eltwise_layernorm_fuse")
    assert "fused_embedding_eltwise_layernorm" not in \
        [op.type for op in main.global_block.ops]

    # consumed layer_norm Mean keeps the pattern unfused
    main2, startup2 = pt.Program(), pt.Program()
    with pt.program_guard(main2, startup2):
        a = pt.layers.data("a", [4, 1], dtype="int64")
        b = pt.layers.data("b", [4, 1], dtype="int64")
        s = pt.layers.elementwise_add(
            pt.layers.embedding(a, size=[10, 8]),
            pt.layers.embedding(b, size=[10, 8]))
        pt.layers.layer_norm(s, begin_norm_axis=2)
        mean_name = next(op.output("Mean")[0]
                         for op in main2.global_block.ops
                         if op.type == "layer_norm")
    apply_pass(main2, "embedding_eltwise_layernorm_fuse",
               protected={mean_name})
    assert "fused_embedding_eltwise_layernorm" not in \
        [op.type for op in main2.global_block.ops]


def test_export_serialized_dynamic_batch(tmp_path):
    """dynamic_batch=True: one artifact serves ANY batch size (jax
    shape polymorphism) — the reference predictor's variable-batch
    contract."""
    main, startup, pred, loss = _build_regression()
    exe = pt.Executor()
    exe.run(startup)
    _train(exe, main, loss)
    d = str(tmp_path / "m")
    pt.save_inference_model(d, ["x"], [pred], exe, main)
    from paddle_tpu.inference import (Config, SerializedPredictor,
                                      create_predictor)
    predictor = create_predictor(Config(model_dir=d))
    xb = np.random.RandomState(5).randn(6, 4).astype(np.float32)
    art = str(tmp_path / "art_dyn")
    predictor.export_serialized(art, [xb], dynamic_batch=True)
    sp = SerializedPredictor(art)
    for b in (1, 6, 13):
        x = np.random.RandomState(b).randn(b, 4).astype(np.float32)
        got, = sp.run([x])
        expect, = predictor.run([x])
        assert got.shape[0] == b
        np.testing.assert_allclose(got, np.asarray(expect),
                                   rtol=1e-5, atol=1e-6)
