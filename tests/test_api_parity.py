"""Round-5 top-level parity surface: the names must not just resolve —
they must compute (reference analog: the per-API unit tests under
python/paddle/fluid/tests/unittests for the same ops)."""
import numpy as np
import pytest

import paddle_tpu as pt


def test_trig_and_unary_family():
    x = pt.to_tensor(np.asarray([0.1, 0.5, 0.9], np.float32))
    np.testing.assert_allclose(np.asarray(pt.sin(x).value),
                               np.sin([0.1, 0.5, 0.9]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pt.acos(x).value),
                               np.arccos([0.1, 0.5, 0.9]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pt.rsqrt(x).value),
                               1 / np.sqrt([0.1, 0.5, 0.9]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pt.log1p(x).value),
                               np.log1p([0.1, 0.5, 0.9]), rtol=1e-6)


def test_mm_addmm_addcmul_trace():
    a = pt.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    b = pt.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose(
        np.asarray(pt.mm(a, b).value),
        np.asarray(a.value) @ np.asarray(b.value))
    inp = pt.to_tensor(np.ones((2, 4), np.float32))
    out = pt.addmm(inp, a, b, beta=2.0, alpha=0.5)
    np.testing.assert_allclose(
        np.asarray(out.value),
        2.0 + 0.5 * (np.asarray(a.value) @ np.asarray(b.value)))
    t1 = pt.to_tensor(np.full((3,), 2.0, np.float32))
    t2 = pt.to_tensor(np.full((3,), 3.0, np.float32))
    res = pt.addcmul(pt.to_tensor(np.ones(3, np.float32)), t1, t2, 0.5)
    np.testing.assert_allclose(np.asarray(res.value), [4.0, 4.0, 4.0])
    sq = pt.to_tensor(np.arange(9, dtype=np.float32).reshape(3, 3))
    assert float(np.asarray(pt.trace(sq).value)) == 0 + 4 + 8


def test_logic_and_stats():
    x = pt.to_tensor(np.asarray([1.0, 2.0], np.float32))
    y = pt.to_tensor(np.asarray([1.0, 2.0], np.float32))
    z = pt.to_tensor(np.asarray([1.0, 3.0], np.float32))
    assert bool(np.asarray(pt.equal_all(x, y).value))
    assert not bool(np.asarray(pt.equal_all(x, z).value))
    inf = pt.to_tensor(np.asarray([1.0, np.inf, np.nan], np.float32))
    np.testing.assert_array_equal(np.asarray(pt.isinf(inf).value),
                                  [False, True, False])
    d = pt.dist(x, z, p=2.0)
    np.testing.assert_allclose(float(np.asarray(d.value)), 1.0)
    ls = pt.logsumexp(pt.to_tensor(np.zeros((4,), np.float32)))
    np.testing.assert_allclose(float(np.asarray(ls.value)),
                               np.log(4.0), rtol=1e-6)


def test_histogram_matches_numpy():
    vals = np.asarray([0.0, 0.1, 0.5, 0.9, 1.0, 2.0], np.float32)
    h = pt.histogram(pt.to_tensor(vals), bins=4, min=0.0, max=1.0)
    # numpy: values outside [0,1] dropped, right edge inclusive
    expect, _ = np.histogram(vals[vals <= 1.0], bins=4, range=(0, 1))
    np.testing.assert_array_equal(np.asarray(h.value), expect)
    # float32 rounding edge: width=0.3/3 rounds down; a value just
    # below max must land in the LAST bin, not the dropped overflow
    v2 = np.asarray([0.29999998], np.float32)
    h2 = pt.histogram(pt.to_tensor(v2), bins=3, min=0.0, max=0.3)
    assert np.asarray(h2.value).sum() == 1
    with pytest.raises(ValueError):
        pt.histogram(pt.to_tensor(vals), bins=3, min=2.0, max=1.0)


def test_meshgrid_broadcast_shuffle():
    a = pt.to_tensor(np.arange(3, dtype=np.float32))
    b = pt.to_tensor(np.arange(4, dtype=np.float32))
    ga, gb = pt.meshgrid(a, b)
    assert tuple(ga.shape) == (3, 4) and tuple(gb.shape) == (3, 4)
    t = pt.broadcast_to(pt.to_tensor(np.ones((1, 3), np.float32)),
                        [2, 3])
    assert tuple(t.shape) == (2, 3)
    pt.seed(7)
    s = pt.shuffle(pt.to_tensor(np.arange(8, dtype=np.float32)))
    assert sorted(np.asarray(s.value).tolist()) == list(range(8))


def test_lod_tensor_roundtrip():
    lt = pt.LoDTensor(np.arange(6, dtype=np.float32).reshape(6, 1),
                      recursive_seq_lens=[[2, 3, 1]])
    assert lt.has_valid_recursive_sequence_lengths()
    assert lt.lod() == [[0, 2, 5, 6]]
    padded, lengths = lt.to_padded()
    assert padded.shape == (3, 3, 1)
    np.testing.assert_array_equal(lengths, [2, 3, 1])
    back = pt.LoDTensor.from_padded(padded, lengths)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(lt))
    arr = pt.LoDTensorArray([lt])
    assert len(arr) == 1 and isinstance(arr, list)


def test_complex_variable_math():
    r = pt.to_tensor(np.asarray([1.0, 2.0], np.float32))
    i = pt.to_tensor(np.asarray([3.0, 4.0], np.float32))
    c = pt.ComplexVariable(r, i)
    prod = pt.complex.elementwise_mul(c, c)
    # (1+3j)^2 = -8+6j ; (2+4j)^2 = -12+16j
    np.testing.assert_allclose(np.asarray(prod.real.value), [-8, -12])
    np.testing.assert_allclose(np.asarray(prod.imag.value), [6, 16])
    m = pt.complex.matmul(
        pt.ComplexVariable(
            pt.to_tensor(np.eye(2, dtype=np.float32)),
            pt.to_tensor(np.zeros((2, 2), np.float32))),
        pt.ComplexVariable(
            pt.to_tensor(np.ones((2, 2), np.float32)),
            pt.to_tensor(np.ones((2, 2), np.float32))))
    np.testing.assert_allclose(np.asarray(m.real.value),
                               np.ones((2, 2)))


def test_compat_module():
    assert pt.compat.to_text(b"abc") == "abc"
    assert pt.compat.to_bytes("abc") == b"abc"
    assert pt.compat.to_text([b"a", b"b"]) == ["a", "b"]
    assert pt.compat.to_text((b"a", b"b")) == ("a", "b")
    assert pt.compat.to_bytes(("a",)) == (b"a",)
    assert pt.compat.round(2.5) == 3.0
    assert pt.compat.round(-2.5) == -3.0
    assert pt.compat.floor_division(7, 2) == 3
    assert pt.compat.get_exception_message(ValueError("boom")) == "boom"


def test_default_dtype_round_trip():
    import jax
    assert pt.get_default_dtype() == "float32"
    try:
        pt.set_default_dtype("float64")
        assert pt.get_default_dtype() == "float64"
        z = pt.zeros([2])
        assert str(np.asarray(z.value).dtype) == "float64"
        with pytest.raises(TypeError):
            pt.set_default_dtype("int32")
    finally:
        pt.set_default_dtype("float32")
        # set_default_dtype('float64') turns x64 ON but 'float32' does
        # NOT turn it off (a user may have enabled x64 independently) —
        # so this test owns restoring the canonical 32-bit world
        jax.config.update("jax_enable_x64", False)


def test_device_and_framework_modules():
    assert pt.device.is_compiled_with_cuda() is False
    assert pt.get_device() in ("cpu", "tpu:0")
    assert pt.get_cudnn_version() is None
    assert pt.sysconfig.get_include().endswith("csrc")
    st = pt.get_rng_state()
    pt.set_rng_state(st)
    cfg = pt.SaveLoadConfig()
    assert cfg.model_filename == "__model__"
    pt.monkey_patch_variable()       # no-op by design, must not raise
    pt.monkey_patch_math_varbase()
    assert pt.framework.get_default_dtype() == "float32"


def test_vision_and_text_namespaces():
    m = pt.vision.models.resnet18(num_classes=10)
    assert hasattr(m, "parameters")
    tr = pt.vision.transforms.Compose([])
    assert callable(tr)
    ds = pt.text.datasets.UCIHousing(mode="test")
    x, y = ds[0]
    assert x.shape == (13,)
    imdb = pt.text.Imdb(mode="test")
    tokens, label = imdb[0]
    assert tokens.dtype == np.int64 and label.shape == ()
    wmt = pt.text.WMT16(mode="test")
    src, trg, trg_next = wmt[0]
    assert len(trg) == len(trg_next)
    assert pt.text.BasicLSTMCell is not None


def test_elementwise_sum_and_aliases():
    xs = [pt.to_tensor(np.full((3,), float(i), np.float32))
          for i in range(3)]
    s = pt.elementwise_sum(xs)
    np.testing.assert_allclose(np.asarray(s.value), [3.0, 3.0, 3.0])
    a = pt.to_tensor(np.asarray([7.0], np.float32))
    b = pt.to_tensor(np.asarray([4.0], np.float32))
    np.testing.assert_allclose(np.asarray(pt.remainder(a, b).value),
                               [3.0])
    assert pt.floor_mod is pt.remainder
    assert pt.manual_seed is pt.seed
