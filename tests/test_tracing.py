"""Request-lifecycle tracing tests (ISSUE 8, docs/observability.md).

Covers the tentpole: RequestTrace stage ordering under concurrent
submitters through a real PredictorPool, TTFT/TPOT + decomposition
timers, the exemplar-ring bound with gauge-retracting eviction,
deadline-miss counters + budget burn, preemption/replay events on
generation pool-pressure replay, the /tracez endpoint (text + JSON),
and the disabled path (flag off: the shared no-op trace, no new
instruments, nothing recorded).
"""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, serving, tracing
from paddle_tpu.flags import get_flag, set_flags
from paddle_tpu.monitor import gauge_get, snapshot, stat_get, timer_get


@pytest.fixture(autouse=True)
def _fresh_rings():
    """Ring isolation (counters stay global — tests use deltas)."""
    tracing.reset()
    yield
    tracing.reset()
    set_flags({"FLAGS_request_tracing": True,
               "FLAGS_tracing_exemplars": 32})


@pytest.fixture
def model_dir(tmp_path):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [6])
        h = layers.fc(x, 16, act="relu")
        y = layers.fc(h, 3, name="out")
    exe = pt.Executor()
    exe.run(startup)
    d = str(tmp_path / "model")
    pt.io.save_inference_model(d, ["x"], [y], exe, main_program=main)
    return d


# ---------------------------------------------------------------------------
# RequestTrace core
# ---------------------------------------------------------------------------

def test_trace_ids_unique_and_stages_monotonic():
    seen = set()
    for _ in range(5):
        tr = tracing.begin("serving")
        assert tr.trace_id not in seen
        seen.add(tr.trace_id)
        for s in ("admit", "batch_join", "dispatch", "execute",
                  "fetch"):
            tr.stage(s)
        tr.finish()
    rec = tracing.recent()[-1]
    assert [s for s, _ in rec["stages"]] == [
        "submit", "admit", "batch_join", "dispatch", "execute",
        "fetch", "done"]
    offs = [t for _, t in rec["stages"]]
    assert offs == sorted(offs)
    assert rec["error"] is None


def test_finish_idempotent_and_decomposition_timers():
    c0 = stat_get("STAT_trace_completed")
    n0 = timer_get("TIMER_serving_total_us")["count"]
    tr = tracing.begin("serving")
    tr.stage("admit")
    tr.stage("batch_join")
    tr.stage("dispatch")
    tr.stage("execute")
    tr.stage("fetch")
    tr.finish()
    tr.finish()  # idempotent: no double counting
    assert stat_get("STAT_trace_completed") - c0 == 1
    assert timer_get("TIMER_serving_total_us")["count"] - n0 == 1
    # every serving interval got one sample
    for fam in ("admit", "batch_join", "dispatch", "execute",
                "fetch"):
        assert timer_get("TIMER_serving_%s_us" % fam)["count"] >= 1


def test_ttft_once_tpot_per_token():
    t0 = timer_get("TIMER_generation_ttft_us")["count"]
    p0 = timer_get("TIMER_generation_tpot_us")["count"]
    tr = tracing.begin("generation")
    tr.stage("admit")
    tr.stage("prefill_start")
    for _ in range(4):
        tr.token()
    tr.finish(finish_reason="length")
    assert timer_get("TIMER_generation_ttft_us")["count"] - t0 == 1
    assert timer_get("TIMER_generation_tpot_us")["count"] - p0 == 3
    rec = tracing.recent()[-1]
    assert rec["tokens"] == 4
    assert "first_token" in [s for s, _ in rec["stages"]]
    assert rec["ttft_us"] >= 0


def test_deadline_miss_counter_and_budget_burn():
    m0 = stat_get("STAT_serving_deadline_missed")
    b0 = stat_get("STAT_serving_budget_total_us")
    tr = tracing.begin("serving", deadline=1e-4)
    tr.stage("admit")
    time.sleep(0.005)
    tr.stage("execute")
    tr.finish()
    assert stat_get("STAT_serving_deadline_missed") - m0 == 1
    # budget burn attributed per decomposition interval
    assert stat_get("STAT_serving_budget_total_us") - b0 > 1e3
    rec = tracing.recent()[-1]
    assert rec["deadline_missed"] is True
    # a comfortable deadline does not flag
    tr2 = tracing.begin("serving", deadline=60.0)
    tr2.finish()
    assert stat_get("STAT_serving_deadline_missed") - m0 == 1
    assert tracing.recent()[-1]["deadline_missed"] is False


def test_errored_trace_counted_and_in_flight_recorder():
    from paddle_tpu import telemetry
    e0 = stat_get("STAT_trace_errored")
    tr = tracing.begin("serving")
    tr.stage("admit")
    tr.finish(error=RuntimeError("boom"))
    assert stat_get("STAT_trace_errored") - e0 == 1
    rec = tracing.recent()[-1]
    assert "boom" in rec["error"]
    # errored traces always make the exemplar ring, with a flight slice
    ex = {r["trace_id"]: r for r in tracing.exemplars()}
    assert tr.trace_id in ex
    assert "flight" in ex[tr.trace_id]
    # and land in the flight recorder keyed by trace id
    keys = [r.get("step") for r in telemetry.flight_records()]
    assert ("req:%s" % tr.trace_id) in keys


# ---------------------------------------------------------------------------
# exemplar ring: bound + gauge-retracting eviction
# ---------------------------------------------------------------------------

def test_exemplar_ring_bound_and_eviction():
    set_flags({"FLAGS_tracing_exemplars": 3})
    ids = []
    for i in range(6):
        tr = tracing.begin("serving")
        time.sleep(0.004 * (i + 1))  # strictly increasing totals,
        tr.finish()                  # spaced 4ms apart so scheduler
        ids.append(tr.trace_id)      # jitter cannot reorder them
    kept = [r["trace_id"] for r in tracing.exemplars()]
    assert len(kept) == 3
    # the fastest traces were evicted, the slowest kept
    assert set(kept) == set(ids[-3:])
    assert gauge_get("GAUGE_tracing_exemplars") == 3
    # eviction retracted the per-exemplar gauges
    from paddle_tpu.monitor import _GAUGES, _LOCK
    with _LOCK:
        for tid in ids[:3]:
            assert "GAUGE_trace_exemplar_us_%s" % tid not in _GAUGES
        for tid in ids[-3:]:
            assert "GAUGE_trace_exemplar_us_%s" % tid in _GAUGES
    assert stat_get("STAT_tracing_exemplar_evict") >= 3


def test_exemplar_ring_keeps_errored_over_fast_clean():
    set_flags({"FLAGS_tracing_exemplars": 2})
    bad = tracing.begin("serving")
    bad.finish(error=RuntimeError("keep me"))  # fast AND errored
    for i in range(4):
        tr = tracing.begin("serving")
        time.sleep(0.002)
        tr.finish()
    kept = tracing.exemplars()
    assert len(kept) == 2
    # the errored exemplar persists even though every clean trace is
    # slower; eviction prefers dropping clean ones
    assert any(r["trace_id"] == bad.trace_id for r in kept)


# ---------------------------------------------------------------------------
# disabled path
# ---------------------------------------------------------------------------

def test_flag_off_spawns_nothing_and_adds_no_instruments():
    set_flags({"FLAGS_request_tracing": False})
    names0 = {k for k in snapshot()["timers"]}
    c0 = stat_get("STAT_trace_completed")
    tr = tracing.begin("serving", deadline=0.001)
    assert tr is tracing.NOOP_TRACE
    assert tr.trace_id is None
    tr.stage("admit")
    tr.event("retry")
    tr.token()
    tr.note(rows=1)
    tr.finish(error=RuntimeError("ignored"))
    assert tr.last_stage() is None
    assert tracing.recent() == []
    assert tracing.exemplars() == []
    assert stat_get("STAT_trace_completed") == c0
    assert {k for k in snapshot()["timers"]} == names0
    payload = tracing.tracez()
    assert payload["enabled"] is False


# ---------------------------------------------------------------------------
# concurrent submitters through a real PredictorPool
# ---------------------------------------------------------------------------

def test_stage_ordering_under_concurrent_submitters(model_dir):
    from paddle_tpu.inference import Config
    T, N = 4, 10
    c0 = stat_get("STAT_trace_completed")
    n0 = stat_get("STAT_trace_nonmonotonic")
    with serving.PredictorPool(Config(model_dir), max_batch=8) as pool:
        rng = np.random.RandomState(0)
        feeds = [rng.randn(int(rng.randint(1, 5)), 6).astype(np.float32)
                 for _ in range(T * N)]

        def worker(tid):
            for i in range(tid, T * N, T):
                pool.run([feeds[i]], timeout=60.0)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(T)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    done = stat_get("STAT_trace_completed") - c0
    assert done == T * N
    assert stat_get("STAT_trace_nonmonotonic") - n0 == 0
    recs = [r for r in tracing.recent() if r["kind"] == "serving"]
    assert len(recs) >= T * N
    order = ["submit", "admit", "batch_join", "dispatch", "execute",
             "fetch", "done"]
    for rec in recs[-T * N:]:
        assert [s for s, _ in rec["stages"]] == order
        offs = [t for _, t in rec["stages"]]
        assert offs == sorted(offs)


# ---------------------------------------------------------------------------
# generation: preemption/replay events
# ---------------------------------------------------------------------------

def test_preempt_and_replay_events_on_generation_replay():
    from paddle_tpu.generation import (DecoderConfig, GenerationEngine,
                                       GenerationRequest, init_params)
    cfg = DecoderConfig(vocab_size=64, hidden=32, layers=2, heads=4,
                        max_seq_len=32)
    params = init_params(cfg, seed=0)
    # a pool too small for both sequences' full length: the youngest
    # gets preempted mid-decode and replayed (test_generation.py's
    # eviction scenario)
    eng = GenerationEngine(cfg, params, num_blocks=10, block_size=4,
                           decode_width=2, prefill_buckets="pow2:16")
    reqs = [GenerationRequest(prompt=[1 + i] * 12, max_new_tokens=12,
                              request_id=i) for i in range(2)]
    results = eng.generate(reqs)
    evicted = [r for r in results if r.evictions > 0]
    assert evicted, "workload did not trigger preemption"
    by_id = {}
    for rec in tracing.recent():
        if rec["kind"] == "generation":
            by_id[rec["fields"].get("request_id", rec["trace_id"])] = rec
    # match traces to results by token count + evictions fields
    preempts = [e for rec in by_id.values()
                for e in rec.get("events", ())
                if e["name"] == "preempt"]
    replays = [e for rec in by_id.values()
               for e in rec.get("events", ())
               if e["name"] == "replay"]
    assert len(preempts) >= 1
    assert len(replays) >= 1
    assert replays[0]["evictions"] >= 1
    # every trace is complete and ordered, replay or not
    for rec in by_id.values():
        names = [s for s, _ in rec["stages"]]
        assert names[0] == "submit" and names[-1] == "done"
        offs = [t for _, t in rec["stages"]]
        assert offs == sorted(offs)
        # TTFT observed exactly once even across replay
        assert names.count("first_token") == 1


def test_generation_trace_decomposition_timers():
    from paddle_tpu.generation import (DecoderConfig, GenerationEngine,
                                       GenerationRequest, init_params)
    cfg = DecoderConfig(vocab_size=64, hidden=32, layers=2, heads=4,
                        max_seq_len=32)
    params = init_params(cfg, seed=0)
    eng = GenerationEngine(cfg, params, num_blocks=64, block_size=4,
                           decode_width=4, prefill_buckets="pow2:16")
    t0 = timer_get("TIMER_generation_ttft_us")["count"]
    q0 = timer_get("TIMER_generation_queue_wait_us")["count"]
    eng.generate([GenerationRequest(prompt=[1, 2, 3],
                                    max_new_tokens=4)])
    assert timer_get("TIMER_generation_ttft_us")["count"] - t0 == 1
    assert timer_get("TIMER_generation_queue_wait_us")["count"] - q0 == 1
    rec = tracing.recent()[-1]
    assert rec["kind"] == "generation"
    assert rec["fields"]["finish_reason"] in ("eos", "length")
    assert rec["tokens"] == 4


# ---------------------------------------------------------------------------
# /tracez endpoint
# ---------------------------------------------------------------------------

def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_tracez_text_and_json():
    from paddle_tpu import introspect
    tr = tracing.begin("serving", deadline=1e-9)
    tr.stage("admit")
    tr.stage("execute")
    time.sleep(0.001)
    tr.finish()
    g = tracing.begin("generation")
    g.stage("prefill_start")
    g.token()
    g.token()
    g.finish(finish_reason="length")
    srv = introspect.start(port=0)
    try:
        code, text = _get(srv.url + "/tracez")
        assert code == 200
        assert "request traces" in text
        assert tr.trace_id in text
        assert "DEADLINE_MISSED" in text
        assert "rolling latency" in text
        code, body = _get(srv.url + "/tracez?format=json")
        assert code == 200
        payload = json.loads(body)
        assert payload["enabled"] is True
        ids = [r["trace_id"] for r in payload["recent"]]
        assert tr.trace_id in ids and g.trace_id in ids
        assert "generation_ttft" in payload["rolling_us"]
        # exemplars carry the full timeline
        ex = [r for r in payload["exemplars"]
              if r["trace_id"] == tr.trace_id]
        assert ex and ex[0]["deadline_missed"]
        # /statusz carries the rolling tracing summary
        code, body = _get(srv.url + "/statusz")
        st = json.loads(body)["tracing"]
        assert st["enabled"] is True
        assert st["completed"] >= 2
        # the index advertises /tracez
        code, body = _get(srv.url + "/")
        assert "/tracez" in body
    finally:
        introspect.stop()


# ---------------------------------------------------------------------------
# one-flag-lookup contract
# ---------------------------------------------------------------------------

def test_disabled_path_is_one_flag_lookup(monkeypatch):
    """begin() is the ONLY flag-lookup site: a pooled request threads
    the returned no-op trace everywhere, so disabling tracing costs
    exactly one dict lookup per request."""
    import paddle_tpu.tracing as tracing_mod
    set_flags({"FLAGS_request_tracing": False})
    calls = []
    real = tracing_mod.get_flag

    def counting(name, default=None):
        if name == "FLAGS_request_tracing":
            calls.append(name)
        return real(name, default)

    monkeypatch.setattr(tracing_mod, "get_flag", counting)
    tr = tracing_mod.begin("serving")
    assert tr is tracing.NOOP_TRACE
    tr.stage("admit")
    tr.token()
    tr.finish()
    assert len(calls) == 1
