"""FLAGS_dropout_storage strategies must be numerically IDENTICAL for
the same rng key — u8 and seed only change what the backward stores,
never the keep pattern or the math (ops/nn.py _drop_custom)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
from paddle_tpu.core.registry import REGISTRY, LowerCtx


def _run_strategy(strategy, key, x, p=0.3, impl="upscale_in_train"):
    prior = pt.get_flags(["FLAGS_dropout_storage"])
    pt.set_flags({"FLAGS_dropout_storage": strategy})
    try:
        class _Ctx(LowerCtx):
            def rng(self):
                return key

        def f(xx):
            outs = REGISTRY.get("dropout").lower(
                _Ctx(), {"X": [xx]},
                {"dropout_prob": p, "dropout_implementation": impl})
            return outs["Out"][0]

        out, vjp = jax.vjp(f, x)
        g = jnp.ones_like(out)
        (dx,) = vjp(g)
        return np.asarray(out), np.asarray(dx)
    finally:
        pt.set_flags(prior)


@pytest.mark.parametrize("impl", ["upscale_in_train",
                                  "downgrade_in_infer"])
def test_strategies_agree_forward_and_backward(impl):
    key = jax.random.PRNGKey(11)
    x = jnp.asarray(np.random.RandomState(0).randn(8, 64, 48),
                    jnp.float32)
    out_x, dx_x = _run_strategy("xla", key, x, impl=impl)
    out_u, dx_u = _run_strategy("u8", key, x, impl=impl)
    out_s, dx_s = _run_strategy("seed", key, x, impl=impl)
    np.testing.assert_array_equal(out_x, out_u)
    np.testing.assert_array_equal(out_x, out_s)
    np.testing.assert_array_equal(dx_x, dx_u)
    np.testing.assert_array_equal(dx_x, dx_s)
    # it actually dropped something and rescaled the rest
    assert (out_x == 0).mean() > 0.1
    kept = out_x != 0
    if impl == "upscale_in_train":
        np.testing.assert_allclose(out_x[kept],
                                   np.asarray(x)[kept] / 0.7, rtol=1e-6)
    # grad zero exactly where output is zero
    np.testing.assert_array_equal(dx_x == 0, out_x == 0)


def test_u8_and_seed_residual_sizes():
    """The point of the strategies: the jaxpr residual between fwd and
    bwd must be 1 byte/elem (u8) or just the key (seed) — not 4."""
    key = jax.random.PRNGKey(3)
    x = jnp.asarray(np.random.RandomState(1).randn(4, 4096), jnp.float32)

    for strategy, max_bytes in (("u8", x.size * 1 + 64),
                                ("seed", 1024)):
        prior = pt.get_flags(["FLAGS_dropout_storage"])
        pt.set_flags({"FLAGS_dropout_storage": strategy})
        try:
            class _Ctx(LowerCtx):
                def rng(self):
                    return key

            def loss(xx):
                outs = REGISTRY.get("dropout").lower(
                    _Ctx(), {"X": [xx]},
                    {"dropout_prob": 0.5,
                     "dropout_implementation": "upscale_in_train"})
                return jnp.sum(outs["Out"][0])

            # residuals = outputs of the fwd jaxpr beyond the primal:
            # measure via jax.linearize's consts
            _, f_vjp = jax.vjp(loss, x)
            leaves = jax.tree_util.tree_leaves(f_vjp)
            res_bytes = sum(
                leaf.size * leaf.dtype.itemsize for leaf in leaves
                if hasattr(leaf, "size") and not np.shares_memory(
                    np.asarray(leaf), np.asarray(x))
                and leaf.shape != x.shape)
            assert res_bytes <= max_bytes, (strategy, res_bytes)
        finally:
            pt.set_flags(prior)


def test_trainstep_runs_under_each_strategy():
    """End-to-end: a dropout-bearing layer trains under every strategy
    and the seeded runs are reproducible."""
    from paddle_tpu import nn
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.nn import functional as F

    for strategy in ("xla", "u8", "seed"):
        prior = pt.get_flags(["FLAGS_dropout_storage"])
        pt.set_flags({"FLAGS_dropout_storage": strategy})
        try:
            pt.seed(5)

            class Net(nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.fc = nn.Linear(16, 16)
                    self.drop = nn.Dropout(0.5)
                    self.head = nn.Linear(16, 4)

                def forward(self, x):
                    return self.head(self.drop(self.fc(x)))

            model = Net()
            opt = pt.optimizer.SGD(0.1, parameters=model.parameters())
            step = TrainStep(
                model, lambda o, y: F.cross_entropy(o, y), opt)
            x = np.random.RandomState(2).randn(8, 16).astype(np.float32)
            y = np.random.RandomState(3).randint(0, 4, (8, 1))
            losses = [float(step((x,), (y,))) for _ in range(3)]
            assert np.isfinite(losses).all(), (strategy, losses)
        finally:
            pt.set_flags(prior)


def test_trainstep_compiles_once():
    """The optimizer accumulator pytree is pre-built, so the jitted
    step must have exactly ONE cache entry after many calls — the old
    empty-then-populated opt_state structure compiled twice, paying
    double compile time and briefly holding two executables' buffers
    (jit.py _init_opt_state)."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.nn import functional as F

    pt.seed(0)
    model = nn.Linear(8, 4)
    opt = pt.optimizer.Adam(1e-3, parameters=model.parameters())
    step = TrainStep(model, lambda o, y: F.cross_entropy(o, y), opt)
    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 4, (4, 1))
    losses = [float(step((x,), (y,))) for _ in range(4)]
    assert np.isfinite(losses).all()
    assert step._step_fn._cache_size() == 1, step._step_fn._cache_size()
    # Adam accumulators exist and update from step 1 (not zeros-only)
    m = step._opt_state[step.param_names[0]]
    assert any(np.abs(np.asarray(v)).sum() > 0 for v in m.values())


def test_composed_attention_honors_storage_strategy():
    """The composed attention path's probs-dropout must produce the
    SAME output under every storage strategy for the same seed (the
    [B,H,S,S] keep decision is its biggest backward residual)."""
    from paddle_tpu import nn
    from paddle_tpu.nn import transformer as tr

    outs = {}
    for strategy in ("xla", "u8", "seed"):
        prior = pt.get_flags(["FLAGS_dropout_storage"])
        pt.set_flags({"FLAGS_dropout_storage": strategy})
        try:
            pt.seed(13)
            mha = nn.MultiHeadAttention(32, 4, dropout=0.3)
            x = pt.to_tensor(np.random.RandomState(0)
                             .randn(2, 6, 32).astype(np.float32))
            tr.reset_attention_path_log()
            y = mha(x, x, x)
            assert tr.attention_paths_taken() == ["composed"]
            loss = pt.tensor.mean(y)
            loss.backward()
            g = np.asarray(mha.q_proj.weight.grad)
            assert np.isfinite(g).all()
            outs[strategy] = np.asarray(y.value)
        finally:
            pt.set_flags(prior)
    np.testing.assert_array_equal(outs["xla"], outs["u8"])
    np.testing.assert_array_equal(outs["xla"], outs["seed"])
    # dropout actually engaged (same model/seed without dropout differs)
    pt.seed(13)
    mha2 = nn.MultiHeadAttention(32, 4, dropout=0.0)
    x = pt.to_tensor(np.random.RandomState(0)
                     .randn(2, 6, 32).astype(np.float32))
    y2 = np.asarray(mha2(x, x, x).value)
    assert not np.allclose(outs["xla"], y2)
