"""Quantized serving path (ISSUE 15, docs/quantization.md).

Tentpole coverage: the shared absmax scale contract (quantize /
dequantize round trips, dead channels included), the int8 x int8 ->
int32 -> scale matmul within the logit error budget across all four
samplers, the quantized KV block pool fused into the ragged mixed step
(reference AND Pallas-interpret forms), composition with PR 14's
prefix cache + copy-on-write + speculative decoding (greedy streams
agree with fp32 on short contexts), program-cache fingerprint
isolation (an fp32 entry can never serve a quantized checkpoint), and
fp32 purity (quant off keeps the EXACT pre-quant expressions at the
matmul/embed seams).

Error budgets mirror bench.py's quantized_serving block: max-abs logit
delta, MSE, and greedy-token agreement vs the fp32 oracle.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import layers, quant
from paddle_tpu.generation import (DecoderConfig, GenerationEngine,
                                   GenerationRequest, SamplingParams,
                                   init_params)
from paddle_tpu.generation.model import forward_full
from paddle_tpu.inference import Config, Predictor
from paddle_tpu.kernels import paged_attention as pa
from paddle_tpu.monitor import gauge_get, stat_get

CFG = DecoderConfig(vocab_size=64, hidden=32, layers=2, heads=4,
                    max_seq_len=64)

# the harness budget (bench.py quantized_serving uses the same gates,
# scaled): tiny-model logits live in ~[-4, 4]; int8 per-channel weights
# land well inside these
MAX_ABS_BUDGET = 0.25
MSE_BUDGET = 5e-3


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, seed=0)


@pytest.fixture(scope="module")
def qparams(params):
    return quant.quantize_decoder_params(params, "int8")


def _engine(params, **kw):
    kw.setdefault("num_blocks", 48)
    kw.setdefault("block_size", 4)
    kw.setdefault("decode_width", 2)
    kw.setdefault("prefill_chunk", 4)
    return GenerationEngine(CFG, params, **kw)


def _reqs(sampling_list, n_tok=8):
    return [GenerationRequest(request_id=i, prompt=[(i + 1) % 7 + 1] * 5,
                              max_new_tokens=n_tok, sampling=sp)
            for i, sp in enumerate(sampling_list)]


# ---------------------------------------------------------------------------
# scale contract
# ---------------------------------------------------------------------------

def test_quantize_roundtrip_and_dead_channel():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(16, 8)).astype(np.float32)
    w[:, 3] = 0.0                          # dead output channel
    q, s = quant.quantize_array(w, 1, "int8")
    assert np.asarray(q).dtype == np.int8 and s.shape == (8,)
    assert float(s[3]) == 1.0              # guarded, stored verbatim
    back = np.asarray(quant.dequantize_array(q, s, 1))
    assert np.abs(back - w).max() <= float(np.max(s)) / 254 + 1e-9
    assert np.all(back[:, 3] == 0.0)       # dead channel exact
    # idempotent conversion
    p = {"w": jnp.asarray(w), "w" + quant.SCALE_SUFFIX: s}
    assert quant.quantize_decoder_params(p, "int8") == p


def test_qat_adapters_are_lossless_inverses(qparams):
    back = quant.from_qat(quant.to_qat(qparams))
    assert set(back) == set(qparams)
    for k in qparams:
        np.testing.assert_array_equal(np.asarray(back[k]),
                                      np.asarray(qparams[k]))


def test_save_load_roundtrip(tmp_path, qparams):
    path = str(tmp_path / "q.npz")
    quant.save_quantized(path, qparams, "int8")
    back, mode = quant.load_quantized(path)
    assert mode == "int8" and set(back) == set(qparams)
    for k in qparams:
        np.testing.assert_array_equal(np.asarray(back[k]),
                                      np.asarray(qparams[k]))


def test_convert_cli_demo(tmp_path):
    from paddle_tpu.quant.convert import main
    out = str(tmp_path / "demo.npz")
    assert main(["--demo", "--out", out, "--mode", "int8"]) == 0
    p, mode = quant.load_quantized(out)
    assert mode == "int8" and quant.is_quantized(p)
    assert quant.weight_bytes_saved(p) > 0


# ---------------------------------------------------------------------------
# fp32 purity: absent scales keep the EXACT original expressions
# ---------------------------------------------------------------------------

def test_fp32_seams_are_bitwise_noops(params):
    x = jnp.asarray(np.random.default_rng(1).normal(
        size=(3, CFG.hidden)), jnp.float32)
    w = params["l0_wqkv"]
    np.testing.assert_array_equal(
        np.asarray(quant.matmul(params, "l0_wqkv", x)),
        np.asarray(x @ w))
    idx = jnp.asarray([0, 5, 2], jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(quant.embed(params, "tok_emb", idx)),
        np.asarray(params["tok_emb"][idx]))


def test_quant_off_engine_keeps_fp32_state(params):
    eng = _engine(params, quant_mode="off")
    assert eng.quant_mode == "off" and eng.kv_dtype == "fp32"
    assert eng.k_pools.dtype == jnp.float32 and eng.k_scales is None
    assert not quant.is_quantized(eng.params)
    assert gauge_get("GAUGE_quant_weight_bytes_saved") == 0


# ---------------------------------------------------------------------------
# logit error budget
# ---------------------------------------------------------------------------

def test_int8_logits_within_budget(params, qparams):
    rng = np.random.default_rng(2)
    toks = jnp.asarray(rng.integers(0, CFG.vocab_size, size=(4, 24)),
                       jnp.int32)
    lens = jnp.asarray([24, 13, 6, 1], jnp.int32)
    lf = np.asarray(forward_full(CFG, params, toks, lens)[0])
    lq = np.asarray(forward_full(CFG, qparams, toks, lens)[0])
    d = lf - lq
    assert np.abs(d).max() < MAX_ABS_BUDGET
    assert (d ** 2).mean() < MSE_BUDGET
    # greedy tokens agree everywhere on these short contexts
    assert np.array_equal(lf.argmax(-1), lq.argmax(-1))


# ---------------------------------------------------------------------------
# quantized KV fused into the mixed step
# ---------------------------------------------------------------------------

def test_kv_dequant_reference_vs_pallas_interpret():
    rng = np.random.default_rng(3)
    B, H, D, N, bs, M = 3, 4, 8, 16, 4, 4
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    kf = jnp.asarray(rng.normal(size=(N, bs, H, D)), jnp.float32)
    vf = jnp.asarray(rng.normal(size=(N, bs, H, D)), jnp.float32)
    kq, ks = quant.quantize_kv_rows(kf, jnp.int8)
    vq, vs = quant.quantize_kv_rows(vf, jnp.int8)
    tables = jnp.asarray(rng.integers(1, N, size=(B, M)), jnp.int32)
    ctx = jnp.asarray([5, 9, 1], jnp.int32)
    ref = pa.paged_attention_reference(q, kq, vq, tables, ctx,
                                       k_scales=ks, v_scales=vs)
    pal = pa.paged_attention_pallas(q, kq, vq, tables, ctx,
                                    k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                               rtol=0, atol=1e-5)
    # and the dequant error vs true fp32 K/V stays small
    f32 = pa.paged_attention_reference(q, kf, vf, tables, ctx)
    assert float(jnp.max(jnp.abs(ref - f32))) < 0.05


def test_quantized_kv_requires_chunked_mode(params):
    with pytest.raises(ValueError, match="chunked"):
        _engine(params, prefill_chunk=0, prefill_buckets="pow2:16",
                kv_dtype="int8")


def test_all_four_samplers_within_budget(params):
    """greedy / temperature / top-k / top-p: the quantized engine is
    deterministic per (seed, step) like fp32, stays within the logit
    budget (greedy agrees exactly on short contexts), and the
    stochastic samplers emit valid tokens through the int8 matmuls."""
    samplers = [SamplingParams(temperature=0.0),
                SamplingParams(temperature=0.8, seed=7),
                SamplingParams(temperature=0.9, top_k=8, seed=11),
                SamplingParams(temperature=0.9, top_p=0.8, seed=13)]

    def run(p, **kw):
        eng = _engine(p, decode_width=4, **kw)
        out = eng.generate(_reqs(samplers))
        return {r.request_id: r.tokens for r in out}, eng

    fp32, _ = run(params)
    q1, eng = run(params, quant_mode="int8")
    q2, _ = run(params, quant_mode="int8")
    assert eng.quant_mode == "int8" and eng.kv_dtype == "int8"
    assert q1 == q2                       # deterministic replay
    assert q1[0] == fp32[0]               # greedy agrees with fp32
    for i in range(len(samplers)):        # valid tokens everywhere
        assert all(0 <= t < CFG.vocab_size for t in q1[i])
    assert stat_get("STAT_generation_kv_quant_blocks") > 0
    assert gauge_get("GAUGE_kv_bytes_per_seq") == eng.kv_bytes_per_seq()
    assert gauge_get("GAUGE_quant_weight_bytes_saved") > 0


def test_composes_with_prefix_cache_cow_and_spec_decode(params):
    """The PR-14 stack (cross-request prefix cache, copy-on-write,
    speculative decoding) over a QUANTIZED pool: greedy streams match
    the fp32 engine running the same stack, COW clones carry the scale
    rows, and the prefix hits really happened."""
    shared = [3] * 8                       # shared prefix, 2 chunks
    def reqs():
        return [GenerationRequest(request_id=i,
                                  prompt=shared + [i + 1] * 2,
                                  max_new_tokens=8,
                                  sampling=SamplingParams(seed=i))
                for i in range(3)]

    def run(p, **kw):
        eng = _engine(p, prefix_cache=True, spec_tokens=2, **kw)
        out = eng.generate(reqs())
        return {r.request_id: r.tokens for r in out}, eng

    h0 = stat_get("STAT_generation_prefix_hits")
    c0 = stat_get("STAT_generation_prefix_cow_copies")
    fp32, _ = run(params)
    q, eng = run(params, quant_mode="int8")
    assert q == fp32
    assert eng.k_scales is not None
    assert stat_get("STAT_generation_prefix_hits") > h0
    assert stat_get("STAT_generation_prefix_cow_copies") > c0


def test_quantized_kv_capacity_headline(params):
    """At the same pool dims, int8 KV (payload + scales) costs under
    half the fp32 bytes per sequence — the >= 2x concurrent-sequence
    headline bench.py gates at a fixed byte budget."""
    e32 = _engine(params)
    e8 = _engine(params, quant_mode="int8")
    assert e8.kv_bytes_per_seq() * 2 <= e32.kv_bytes_per_seq()
    assert e8.kv_pool_bytes() * 2 <= e32.kv_pool_bytes()


# ---------------------------------------------------------------------------
# program-cache fingerprint isolation
# ---------------------------------------------------------------------------

def _trace_entries(cache_dir):
    d = os.path.join(cache_dir, "trace")
    return set(os.listdir(d)) if os.path.isdir(d) else set()


def test_fp32_and_int8_never_share_a_cache_entry(tmp_path, params):
    cache = str(tmp_path / "pcache")
    e32 = _engine(params, program_cache_dir=cache)
    e32.warmup()
    fp32_entries = _trace_entries(cache)
    assert fp32_entries                    # fp32 exported something
    e8 = _engine(params, quant_mode="int8", program_cache_dir=cache)
    e8.warmup()
    int8_entries = _trace_entries(cache) - fp32_entries
    assert int8_entries                    # int8 exported NEW entries
    assert not (fp32_entries & int8_entries)
    # steady state: a fresh engine of either flavor adds nothing
    before = _trace_entries(cache)
    _engine(params, quant_mode="int8", program_cache_dir=cache).warmup()
    _engine(params, program_cache_dir=cache).warmup()
    assert _trace_entries(cache) == before


# ---------------------------------------------------------------------------
# Predictor (program/scope) path
# ---------------------------------------------------------------------------

@pytest.fixture
def model_dir(tmp_path):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [6])
        h = layers.fc(x, 16, act="relu")
        y = layers.fc(h, 3, name="out")
    exe = pt.Executor()
    exe.run(startup)
    d = str(tmp_path / "model")
    pt.io.save_inference_model(d, ["x"], [y], exe, main_program=main)
    return d


def test_predictor_int8_within_budget(model_dir):
    xb = np.random.default_rng(4).normal(size=(5, 6)).astype(np.float32)
    ref = Predictor(Config(model_dir)).run([xb])[0]
    cfg = Config(model_dir)
    cfg.enable_quant("int8")
    pred = Predictor(cfg)
    # scope really holds int8 weights + persistable absmax scales
    int8_vars = [n for b in pred.program.blocks
                 for n, v in b.vars.items() if v.dtype == "int8"]
    assert int8_vars
    for n in int8_vars:
        s = np.asarray(pred.scope.find_var(n + ".quant_scale"))
        assert s.dtype == np.float32 and np.all(s > 0)
    assert gauge_get("GAUGE_quant_weight_bytes_saved") > 0
    out = pred.run([xb])[0]
    d = np.asarray(out) - np.asarray(ref)
    assert np.abs(d).max() < 0.1 and (d ** 2).mean() < 1e-3
    assert pred._prog_tag(8).endswith("_int8")   # /programz tag


def test_serialized_core_serves_quantized_export(tmp_path, model_dir):
    """export_serialized from a quantized Predictor: the traced
    computation already contains the int8 weights + dequant ops, so
    the framework-free SerializedCore serves the quantized model with
    no Program IR — and stays within the Predictor's own budget."""
    from paddle_tpu.inference import SerializedPredictor
    xb = np.random.default_rng(6).normal(size=(5, 6)).astype(np.float32)
    cfg = Config(model_dir)
    cfg.enable_quant("int8")
    pred = Predictor(cfg)
    ref = np.asarray(pred.run([xb])[0])
    art = str(tmp_path / "qart")
    pred.export_serialized(art, [xb])
    out = np.asarray(SerializedPredictor(art).run([xb])[0])
    np.testing.assert_allclose(out, ref, rtol=0, atol=1e-6)


def test_statusz_quant_section(params):
    from paddle_tpu.introspect import statusz
    _engine(params, quant_mode="int8")          # publishes the gauges
    s = statusz()["generation"]["quant"]
    assert set(s) >= {"mode", "kv_dtype", "kv_capacity_seqs",
                      "kv_bytes_per_seq", "weight_bytes_saved",
                      "kv_quant_blocks"}
    assert s["weight_bytes_saved"] > 0
