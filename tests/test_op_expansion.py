"""Oracle tests for the op-expansion waves: RNN, detection, vision,
losses (CTC/CRF/NCE/hsigmoid), beam search, fused ops.

Numpy/brute-force oracles per the reference's OpTest contract."""
import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu  # registers ops
from paddle_tpu.core.registry import REGISTRY, LowerCtx


def run_op(op, ins, attrs=None, rng=None):
    ctx = LowerCtx(jax.random.PRNGKey(0) if rng is None else rng)
    ins = {k: [jnp.asarray(v) for v in vs] for k, vs in ins.items()}
    return REGISTRY.get(op).lower(ctx, ins, attrs or {})


def _r(shape, seed=0, scale=1.0):
    return (np.random.RandomState(seed).randn(*shape) * scale) \
        .astype(np.float32)


# ---------------------------------------------------------------------------
# RNN
# ---------------------------------------------------------------------------

def _np_lstm(x, wx, wh, b, lens):
    B, T, _ = x.shape
    D = wh.shape[0]
    h = np.zeros((B, D), np.float32)
    c = np.zeros((B, D), np.float32)
    hs = np.zeros((B, T, D), np.float32)
    sig = lambda v: 1 / (1 + np.exp(-v))
    for t in range(T):
        g = x[:, t] @ wx + h @ wh + b
        i, f, cc, o = np.split(g, 4, axis=-1)
        c_new = sig(f) * c + sig(i) * np.tanh(cc)
        h_new = sig(o) * np.tanh(c_new)
        m = (t < lens)[:, None]
        h = np.where(m, h_new, h)
        c = np.where(m, c_new, c)
        hs[:, t] = h
    return hs, h, c


def test_lstm_matches_numpy():
    B, T, I, D = 2, 5, 3, 4
    x = _r((B, T, I), 0)
    wx = _r((I, 4 * D), 1, 0.5)
    wh = _r((D, 4 * D), 2, 0.5)
    b = _r((4 * D,), 3, 0.1)
    lens = np.array([5, 3], np.int32)
    outs = run_op("lstm", {"Input": [x], "WeightX": [wx], "WeightH": [wh],
                           "Bias": [b], "SeqLen": [lens]})
    hs_np, h_np, c_np = _np_lstm(x, wx, wh, b, lens)
    np.testing.assert_allclose(outs["Hidden"][0], hs_np, atol=1e-5)
    np.testing.assert_allclose(outs["LastH"][0], h_np, atol=1e-5)
    np.testing.assert_allclose(outs["LastC"][0], c_np, atol=1e-5)


def test_gru_shapes_and_mask_freeze():
    B, T, I, D = 2, 4, 3, 5
    outs = run_op("gru", {"Input": [_r((B, T, I))],
                          "WeightX": [_r((I, 3 * D), 1, 0.5)],
                          "WeightH": [_r((D, 3 * D), 2, 0.5)],
                          "SeqLen": [np.array([4, 2], np.int32)]})
    hs = np.asarray(outs["Hidden"][0])
    assert hs.shape == (B, T, D)
    # past its length, batch 1's hidden state is frozen
    np.testing.assert_allclose(hs[1, 1], hs[1, 3])
    assert not np.allclose(hs[0, 1], hs[0, 3])


def test_cudnn_lstm_bidirectional():
    B, T, I, D = 2, 3, 4, 5
    wl = []
    for _ in range(2):  # one layer, two directions
        wl += [_r((I, 4 * D), 1, 0.3), _r((D, 4 * D), 2, 0.3),
               _r((4 * D,), 3, 0.1), _r((4 * D,), 4, 0.1)]
    outs = run_op("cudnn_lstm", {"Input": [_r((B, T, I))],
                                 "WeightList": wl},
                  {"num_layers": 1, "is_bidirec": True})
    assert np.asarray(outs["Out"][0]).shape == (B, T, 2 * D)
    assert np.asarray(outs["LastH"][0]).shape == (2, B, D)


def test_lstm_unit_and_gru_unit():
    B, D = 3, 4
    outs = run_op("lstm_unit", {"X": [_r((B, 4 * D))],
                                "C_prev": [_r((B, D), 7)]},
                  {"forget_bias": 1.0})
    assert np.asarray(outs["H"][0]).shape == (B, D)
    outs = run_op("gru_unit", {"Input": [_r((B, 3 * D))],
                               "HiddenPrev": [_r((B, D), 8)],
                               "Weight": [_r((D, 3 * D), 9, 0.5)]})
    assert np.asarray(outs["Hidden"][0]).shape == (B, D)


# ---------------------------------------------------------------------------
# detection
# ---------------------------------------------------------------------------

def test_iou_similarity():
    a = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
    b = np.array([[0, 0, 2, 2], [10, 10, 11, 11]], np.float32)
    out = np.asarray(run_op("iou_similarity", {"X": [a], "Y": [b]})["Out"][0])
    np.testing.assert_allclose(out[0], [1.0, 0.0], atol=1e-6)
    assert abs(out[1, 0] - 1 / 7) < 1e-5  # inter 1, union 7


def test_prior_box_count_and_range():
    feat = np.zeros((1, 8, 4, 4), np.float32)
    img = np.zeros((1, 3, 64, 64), np.float32)
    outs = run_op("prior_box", {"Input": [feat], "Image": [img]},
                  {"min_sizes": [16.0], "max_sizes": [32.0],
                   "aspect_ratios": [2.0], "flip": True, "clip": True})
    boxes = np.asarray(outs["Boxes"][0])
    # 1 min + 1 max + 2 extra ratios = 4 priors per cell
    assert boxes.shape == (4, 4, 4, 4)
    assert boxes.min() >= 0 and boxes.max() <= 1


def test_box_coder_roundtrip():
    prior = np.array([[0, 0, 10, 10], [5, 5, 15, 19]], np.float32)
    gt = np.array([[1, 1, 8, 9]], np.float32)
    enc = np.asarray(run_op("box_coder",
                            {"PriorBox": [prior], "TargetBox": [gt]},
                            {"code_type": "encode_center_size"})["Out"][0])
    dec = np.asarray(run_op("box_coder",
                            {"PriorBox": [prior],
                             "TargetBox": [enc.transpose(0, 1, 2)]},
                            {"code_type": "decode_center_size"})["Out"][0])
    # decoding the encoding of gt against each prior recovers gt
    np.testing.assert_allclose(dec[0, 0], gt[0], atol=1e-4)
    np.testing.assert_allclose(dec[0, 1], gt[0], atol=1e-4)


def test_multiclass_nms_suppresses_overlaps():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                     np.float32)
    scores = np.array([[0.9, 0.85, 0.7]], np.float32)  # one class
    outs = run_op("multiclass_nms", {"BBoxes": [boxes],
                                     "Scores": [scores]},
                  {"score_threshold": 0.1, "nms_threshold": 0.5,
                   "keep_top_k": 3})
    out = np.asarray(outs["Out"][0])
    n = int(np.asarray(outs["NmsRoisNum"][0])[0])
    assert n == 2  # the two heavy overlaps collapse to one
    kept_scores = sorted(out[:n, 1].tolist(), reverse=True)
    assert abs(kept_scores[0] - 0.9) < 1e-6
    assert abs(kept_scores[1] - 0.7) < 1e-6


def test_bipartite_match_greedy():
    dist = np.array([[0.9, 0.1, 0.3],
                     [0.8, 0.7, 0.2]], np.float32)
    outs = run_op("bipartite_match", {"DistMat": [dist]})
    idx = np.asarray(outs["ColToRowMatchIndices"][0])[0]
    # greedy: (r0,c0)=0.9 then (r1,c1)=0.7; c2 unmatched
    assert idx[0] == 0 and idx[1] == 1 and idx[2] == -1


def test_roi_align_full_box_mean():
    # pooling the whole image into 1x1 with exact bilinear sampling
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 3, 3]], np.float32)  # full box, pixel coords
    out = np.asarray(run_op("roi_align", {"X": [x], "ROIs": [rois]},
                            {"pooled_height": 2, "pooled_width": 2,
                             "spatial_scale": 1.0,
                             "sampling_ratio": 2})["Out"][0])
    assert out.shape == (1, 1, 2, 2)
    # top-left bin mean < bottom-right bin mean, overall == image mean
    assert out[0, 0, 0, 0] < out[0, 0, 1, 1]
    assert abs(out.mean() - x.mean()) < 1e-4


def test_yolo_box_shapes():
    N, A, cls, H, W = 1, 2, 3, 2, 2
    x = _r((N, A * (5 + cls), H, W), 0)
    img = np.array([[64, 64]], np.int32)
    outs = run_op("yolo_box", {"X": [x], "ImgSize": [img]},
                  {"anchors": [10, 13, 16, 30], "class_num": cls,
                   "conf_thresh": 0.0, "downsample_ratio": 32})
    assert np.asarray(outs["Boxes"][0]).shape == (N, A * H * W, 4)
    assert np.asarray(outs["Scores"][0]).shape == (N, A * H * W, cls)


def test_sigmoid_focal_loss_positive():
    x = _r((4, 3), 0)
    label = np.array([0, 1, 2, 3], np.int64)
    fg = np.array([3], np.int32)
    out = np.asarray(run_op("sigmoid_focal_loss",
                            {"X": [x], "Label": [label], "FgNum": [fg]},
                            {})["Out"][0])
    assert out.shape == (4, 3) and (out >= 0).all()


# ---------------------------------------------------------------------------
# vision
# ---------------------------------------------------------------------------

def test_interp_v2_and_trilinear():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    out = np.asarray(run_op("bilinear_interp_v2", {"X": [x]},
                            {"out_h": 8, "out_w": 8,
                             "align_corners": False})["Out"][0])
    assert out.shape == (1, 1, 8, 8)
    assert abs(out.mean() - x.mean()) < 0.5
    x3 = np.ones((1, 1, 2, 2, 2), np.float32)
    out3 = np.asarray(run_op("trilinear_interp", {"X": [x3]},
                             {"out_d": 4, "out_h": 4, "out_w": 4,
                              "align_corners": False})["Out"][0])
    assert out3.shape == (1, 1, 4, 4, 4)


def test_unfold_matches_manual():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    out = np.asarray(run_op("unfold", {"X": [x]},
                            {"kernel_sizes": [2, 2]})["Out"][0])
    assert out.shape == (1, 4, 9)
    np.testing.assert_allclose(out[0, :, 0], [0, 1, 4, 5])  # first patch


def test_maxpool_with_index_unpool_roundtrip():
    x = _r((1, 2, 4, 4), 3)
    outs = run_op("max_pool2d_with_index", {"X": [x]},
                  {"ksize": [2, 2], "strides": [2, 2]})
    out, mask = np.asarray(outs["Out"][0]), np.asarray(outs["Mask"][0])
    assert out.shape == (1, 2, 2, 2)
    up = np.asarray(run_op("unpool", {"X": [out], "Indices": [mask]},
                           {"unpooled_size": [4, 4]})["Out"][0])
    # unpooled peaks equal the pooled maxima, rest zero
    assert np.isclose(np.sort(up[up != 0]),
                      np.sort(out.ravel())).all()


def test_temporal_shift_moves_channels():
    x = np.arange(2 * 4 * 1 * 1, dtype=np.float32).reshape(2, 4, 1, 1)
    out = np.asarray(run_op("temporal_shift", {"X": [x]},
                            {"seg_num": 2, "shift_ratio": 0.25})["Out"][0])
    # channel 0 shifts backward in time: frame0 gets frame1's value
    assert out[0, 0, 0, 0] == x[1, 0, 0, 0]
    assert out[1, 0, 0, 0] == 0  # padded


def test_conv_shift_circular():
    x = np.array([[1.0, 2.0, 3.0, 4.0]], np.float32)
    y = np.array([[0.0, 1.0, 0.0]], np.float32)  # identity kernel
    out = np.asarray(run_op("conv_shift", {"X": [x], "Y": [y]})["Out"][0])
    np.testing.assert_allclose(out, x)


def test_spectral_norm_unit_sigma():
    w = _r((4, 6), 0)
    u = _r((4,), 1)
    v = _r((6,), 2)
    out = np.asarray(run_op("spectral_norm",
                            {"Weight": [w], "U": [u], "V": [v]},
                            {"power_iters": 20})["Out"][0])
    assert abs(np.linalg.svd(out, compute_uv=False)[0] - 1.0) < 1e-3


def test_affine_grid_identity():
    theta = np.tile(np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32),
                    (1, 1, 1))
    out = np.asarray(run_op("affine_grid", {"Theta": [theta]},
                            {"output_shape": [1, 1, 2, 2],
                             "align_corners": True})["Out"][0])
    np.testing.assert_allclose(out[0, 0, 0], [-1, -1])
    np.testing.assert_allclose(out[0, 1, 1], [1, 1])


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def _np_ctc_loss(logp, labels, blank=0):
    """Brute force: sum over all alignments (tiny T only)."""
    T, C = logp.shape
    total = 0.0
    for path in itertools.product(range(C), repeat=T):
        # collapse path
        col = []
        prev = None
        for s in path:
            if s != prev and s != blank:
                col.append(s)
            prev = s
        if col == list(labels):
            total += np.exp(sum(logp[t, path[t]] for t in range(T)))
    return -np.log(total)


def test_warpctc_matches_bruteforce():
    T, C = 4, 3
    rng = np.random.RandomState(0)
    logits = rng.randn(1, T, C).astype(np.float32)
    labels = np.array([[1, 2]], np.int32)
    out = np.asarray(run_op("warpctc",
                            {"Logits": [logits], "Label": [labels]},
                            {"blank": 0})["Loss"][0])
    logp = np.log(np.exp(logits[0]) /
                  np.exp(logits[0]).sum(-1, keepdims=True))
    expect = _np_ctc_loss(logp, [1, 2])
    np.testing.assert_allclose(out[0, 0], expect, rtol=1e-4)


def test_warpctc_is_differentiable():
    logits = jnp.asarray(_r((1, 4, 3), 5))
    labels = jnp.asarray([[1, 2]], jnp.int32)

    def loss(lg):
        return run_op("warpctc", {"Logits": [lg], "Label": [labels]},
                      {"blank": 0})["Loss"][0].sum()
    g = jax.grad(loss)(logits)
    assert np.isfinite(np.asarray(g)).all() and np.abs(g).sum() > 0


def _np_crf_nll(em, tr, labels):
    """Brute force partition over all tag paths."""
    T, D = em.shape
    start, stop, w = tr[0], tr[1], tr[2:]
    scores = []
    for path in itertools.product(range(D), repeat=T):
        s = start[path[0]] + em[0, path[0]]
        for t in range(1, T):
            s += w[path[t - 1], path[t]] + em[t, path[t]]
        s += stop[path[-1]]
        scores.append(s)
    logZ = np.log(np.exp(scores).sum())
    gold = start[labels[0]] + em[0, labels[0]]
    for t in range(1, T):
        gold += w[labels[t - 1], labels[t]] + em[t, labels[t]]
    gold += stop[labels[-1]]
    return -(gold - logZ)


def test_linear_chain_crf_matches_bruteforce():
    T, D = 3, 3
    em = _r((1, T, D), 0)
    tr = _r((D + 2, D), 1, 0.5)
    labels = np.array([[0, 2, 1]], np.int32)
    out = np.asarray(run_op("linear_chain_crf",
                            {"Emission": [em], "Transition": [tr],
                             "Label": [labels]},
                            {})["LogLikelihood"][0])
    expect = _np_crf_nll(em[0], tr, labels[0])
    np.testing.assert_allclose(out[0, 0], expect, rtol=1e-4)


def test_nce_shapes_and_positive_cost():
    outs = run_op("nce", {"Input": [_r((4, 8), 0)],
                          "Label": [np.array([1, 2, 3, 4], np.int64)],
                          "Weight": [_r((10, 8), 1, 0.3)]},
                  {"num_neg_samples": 5, "num_total_classes": 10})
    cost = np.asarray(outs["Cost"][0])
    assert cost.shape == (4, 1) and (cost > 0).all()
    assert np.asarray(outs["SampleLogits"][0]).shape == (4, 6)


def test_hierarchical_sigmoid_positive_loss():
    out = np.asarray(run_op(
        "hierarchical_sigmoid",
        {"X": [_r((4, 8), 0)], "W": [_r((15, 8), 1, 0.3)],
         "Label": [np.array([0, 3, 7, 15], np.int64)]},
        {"num_classes": 16})["Out"][0])
    assert out.shape == (4, 1) and (out > 0).all()


def test_center_loss_updates_centers():
    x = _r((4, 3), 0)
    labels = np.array([0, 0, 1, 1], np.int64)
    centers = np.zeros((2, 3), np.float32)
    outs = run_op("center_loss",
                  {"X": [x], "Label": [labels], "Centers": [centers],
                   "CenterUpdateRate": [np.array([0.5], np.float32)]},
                  {"need_update": True})
    assert (np.asarray(outs["Loss"][0]) >= 0).all()
    assert not np.allclose(np.asarray(outs["CentersOut"][0]), 0)


def test_cvm():
    x = np.array([[3.0, 1.0, 5.0, 6.0]], np.float32)
    out = np.asarray(run_op("cvm", {"X": [x]}, {"use_cvm": True})["Out"][0])
    np.testing.assert_allclose(out[0, 0], np.log(4.0), rtol=1e-6)
    out2 = np.asarray(run_op("cvm", {"X": [x]},
                             {"use_cvm": False})["Out"][0])
    np.testing.assert_allclose(out2, [[5.0, 6.0]])


def test_dgc_topk_and_residual():
    g = np.array([4.0, -3.0, 0.1, 0.2], np.float32)
    outs = run_op("dgc", {"U": [np.zeros(4, np.float32)],
                          "V": [np.zeros(4, np.float32)],
                          "Grad": [g], "Param": [np.zeros(4, np.float32)]},
                  {"m": 0.9, "ratio": 0.25})
    enc = np.asarray(outs["EncodeGrad"][0])
    assert np.count_nonzero(enc) == 1 and enc[0] == 4.0
    v = np.asarray(outs["V_out"][0])
    assert v[1] == -3.0  # residual kept


# ---------------------------------------------------------------------------
# beam search
# ---------------------------------------------------------------------------

def test_beam_search_step_and_gather_tree():
    beam, V = 2, 4
    pre_ids = np.array([[1], [2]], np.int64)   # batch=1, beam=2
    pre_scores = np.array([[0.0], [-0.5]], np.float32)
    scores = np.log(np.array([[0.1, 0.6, 0.2, 0.1],
                              [0.7, 0.1, 0.1, 0.1]], np.float32))
    outs = run_op("beam_search",
                  {"pre_ids": [pre_ids], "pre_scores": [pre_scores],
                   "ids": [pre_ids], "scores": [scores]},
                  {"beam_size": beam, "end_id": 0})
    sel = np.asarray(outs["selected_ids"][0]).ravel()
    par = np.asarray(outs["parent_idx"][0]).ravel()
    # best continuation: beam0+token1 (0-0.51); then beam1+token0
    assert sel[0] == 1 and par[0] == 0

    # gather_tree on a hand-built 2-step history
    ids = np.array([[[1, 2]], [[3, 4]]], np.int64).transpose(0, 1, 2)
    ids = np.array([[[1, 2]], [[3, 4]]], np.int64)  # [T=2, B=1, K=2]
    parents = np.array([[[0, 0]], [[1, 0]]], np.int64)
    out = np.asarray(run_op("gather_tree",
                            {"Ids": [ids], "Parents": [parents]})["Out"][0])
    # final beam 0 came from step0-beam1: path [2, 3]
    assert out[:, 0, 0].tolist() == [2, 3]
    assert out[:, 0, 1].tolist() == [1, 4]


# ---------------------------------------------------------------------------
# fused
# ---------------------------------------------------------------------------

def test_multihead_matmul_packed_matches_manual():
    B, S, H, heads = 2, 8, 16, 2
    x = _r((B, S, H), 0)
    w = _r((H, 3 * H), 1, 0.2)
    b = _r((3 * H,), 2, 0.1)
    outs = run_op("multihead_matmul",
                  {"Input": [x], "W": [w], "Bias": [b]},
                  {"head_number": heads})
    out = np.asarray(outs["Out"][0])
    # manual
    qkv = (x @ w + b).reshape(B, S, 3, heads, H // heads)
    q = np.moveaxis(qkv[:, :, 0], 1, 2)
    k = np.moveaxis(qkv[:, :, 1], 1, 2)
    v = np.moveaxis(qkv[:, :, 2], 1, 2)
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(H // heads)
    p = np.exp(s) / np.exp(s).sum(-1, keepdims=True)
    ref = np.moveaxis(np.einsum("bhqk,bhkd->bhqd", p, v), 1, 2) \
        .reshape(B, S, H)
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_fused_fc_elementwise_layernorm_matches_composition():
    B, I, O = 4, 6, 8
    x, w = _r((B, I), 0), _r((I, O), 1, 0.4)
    b0, y = _r((O,), 2, 0.1), _r((B, O), 3)
    scale, b1 = _r((O,), 4, 0.2) + 1.0, _r((O,), 5, 0.1)
    outs = run_op("fused_fc_elementwise_layernorm",
                  {"X": [x], "W": [w], "Bias0": [b0], "Y": [y],
                   "Scale": [scale], "Bias1": [b1]}, {})
    h = x @ w + b0 + y
    mu = h.mean(-1, keepdims=True)
    var = h.var(-1)
    ref = (h - mu) / np.sqrt(var[:, None] + 1e-5) * scale + b1
    np.testing.assert_allclose(np.asarray(outs["Out"][0]), ref, atol=1e-4)


def test_fused_elemwise_activation():
    x, y = _r((3, 4), 0), _r((3, 4), 1)
    outs = run_op("fused_elemwise_activation", {"X": [x], "Y": [y]},
                  {"functor_list": ["elementwise_add", "relu"]})
    np.testing.assert_allclose(np.asarray(outs["Out"][0]),
                               np.maximum(x + y, 0), atol=1e-6)


def test_fusion_squared_mat_sub_fm_term():
    x, y = _r((2, 3), 0), _r((3, 4), 1)
    outs = run_op("fusion_squared_mat_sub", {"X": [x], "Y": [y]},
                  {"scalar": 0.5})
    ref = ((x @ y) ** 2 - (x ** 2) @ (y ** 2)) * 0.5
    np.testing.assert_allclose(np.asarray(outs["Out"][0]), ref, atol=1e-5)


def test_fusion_seqpool_concat_masks():
    x1 = _r((2, 3, 4), 0)
    lens = np.array([3, 1], np.int64)
    out = np.asarray(run_op("fusion_seqpool_concat",
                            {"X": [x1], "SeqLen": [lens]},
                            {"pooltype": "SUM"})["Out"][0])
    np.testing.assert_allclose(out[1], x1[1, 0], atol=1e-6)
