"""Pallas kernel tests (interpret mode on CPU) vs composed-jnp oracles.

Mirrors the reference's OpTest contract (numpy oracle + gradient check,
/root/reference/python/paddle/fluid/tests/unittests/op_test.py:948,1236)
for the fused kernels."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels.flash_attention import (attention_reference,
                                                flash_attention)
from paddle_tpu.kernels.layer_norm import layer_norm, layer_norm_reference


def _rand(shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape)
                       .astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_forward(causal):
    b, h, s, d = 2, 2, 256, 64
    q, k, v = _rand((b, h, s, d), 0), _rand((b, h, s, d), 1), \
        _rand((b, h, s, d), 2)
    out = flash_attention(q, k, v, causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_attention_causal_cross_length():
    # sq != sk: bottom-right-aligned causal mask must match the reference
    b, h, sq, sk, d = 1, 2, 128, 256, 64
    q = _rand((b, h, sq, d), 0)
    k, v = _rand((b, h, sk, d), 1), _rand((b, h, sk, d), 2)
    out = flash_attention(q, k, v, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    g_f = jax.grad(lambda q, k, v: flash_attention(q, k, v, causal=True)
                   .sum(), argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(lambda q, k, v: attention_reference(q, k, v, causal=True)
                   .sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_f, g_r):
        np.testing.assert_allclose(a, b_, atol=1e-4, rtol=1e-4)


def test_flash_attention_causal_sq_gt_sk():
    # sq > sk: leading q-rows see ZERO keys (bottom-right alignment);
    # their output is 0 and — the ADVICE r1 regression — their backward
    # must not blow up through exp(s - lse) with lse ~ -1e30
    b, h, sq, sk, d = 1, 2, 256, 128, 64
    q = _rand((b, h, sq, d), 0)
    k, v = _rand((b, h, sk, d), 1), _rand((b, h, sk, d), 2)
    out = flash_attention(q, k, v, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    # empty rows output exactly 0 in both paths
    np.testing.assert_allclose(out[:, :, :sq - sk], 0.0, atol=1e-6)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    g_f = jax.grad(lambda q, k, v: flash_attention(q, k, v, causal=True)
                   .sum(), argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(lambda q, k, v: attention_reference(q, k, v, causal=True)
                   .sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_f, g_r):
        assert np.isfinite(np.asarray(a)).all()
        np.testing.assert_allclose(a, b_, atol=1e-4, rtol=1e-4)


def test_flash_attention_key_axis_size1_bias():
    # bias [...,1] on the key axis broadcasts instead of failing at
    # pallas trace time (ADVICE r1)
    b, h, s, d = 1, 2, 128, 64
    q, k, v = _rand((b, h, s, d), 0), _rand((b, h, s, d), 1), \
        _rand((b, h, s, d), 2)
    bias = _rand((b, 1, s, 1), 3)
    out = flash_attention(q, k, v, bias=bias)
    ref = attention_reference(q, k, v, bias=bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_attention_bias_broadcast():
    b, h, s, d = 2, 2, 128, 64
    q, k, v = _rand((b, h, s, d), 0), _rand((b, h, s, d), 1), \
        _rand((b, h, s, d), 2)
    # key-padding style mask [B, 1, 1, S]
    bias = jnp.where(_rand((b, 1, 1, s), 3) > 0, 0.0, -1e9)
    out = flash_attention(q, k, v, bias=bias)
    ref = attention_reference(q, k, v, bias=bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grads(causal):
    b, h, s, d = 1, 2, 256, 64
    q, k, v = _rand((b, h, s, d), 0), _rand((b, h, s, d), 1), \
        _rand((b, h, s, d), 2)

    def f_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal) *
                _rand((b, h, s, d), 9)).sum()

    def f_ref(q, k, v):
        return (attention_reference(q, k, v, causal=causal) *
                _rand((b, h, s, d), 9)).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b_, atol=1e-4, rtol=1e-4)


def test_flash_attention_bias_grad():
    b, h, s, d = 1, 2, 128, 64
    q, k, v = _rand((b, h, s, d), 0), _rand((b, h, s, d), 1), \
        _rand((b, h, s, d), 2)
    bias = _rand((b, 1, 1, s), 3)

    def f_flash(q, k, v, bias):
        return (flash_attention(q, k, v, bias=bias)).sum()

    def f_ref(q, k, v, bias):
        return (attention_reference(q, k, v, bias=bias)).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2, 3))(q, k, v, bias)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for a, b_ in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b_, atol=1e-4, rtol=1e-4)


def test_flash_attention_unaligned_fallback():
    # S not multiple of 128 -> composed path, still correct
    b, h, s, d = 1, 2, 100, 32
    q, k, v = _rand((b, h, s, d), 0), _rand((b, h, s, d), 1), \
        _rand((b, h, s, d), 2)
    out = flash_attention(q, k, v)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_layer_norm_forward():
    x = _rand((4, 6, 256), 0)
    g, b = _rand((256,), 1), _rand((256,), 2)
    out = layer_norm(x, g, b)
    ref = layer_norm_reference(x, g, b)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_layer_norm_grads():
    x = _rand((8, 256), 0)
    g, b = _rand((256,), 1), _rand((256,), 2)
    w = _rand((8, 256), 5)

    gr_f = jax.grad(lambda x, g, b: (layer_norm(x, g, b) * w).sum(),
                    argnums=(0, 1, 2))(x, g, b)
    gr_r = jax.grad(lambda x, g, b: (layer_norm_reference(x, g, b) * w).sum(),
                    argnums=(0, 1, 2))(x, g, b)
    for a, b_ in zip(gr_f, gr_r):
        np.testing.assert_allclose(a, b_, atol=1e-4, rtol=1e-4)


def test_layer_norm_unaligned_fallback():
    x = _rand((4, 100), 0)
    g, b = _rand((100,), 1), _rand((100,), 2)
    np.testing.assert_allclose(layer_norm(x, g, b),
                               layer_norm_reference(x, g, b),
                               atol=1e-5, rtol=1e-5)


def test_flash_attention_dropout_forward_stats():
    # dropout keeps the softmax denominator undropped and rescales kept
    # values by 1/keep_prob, so E[out] matches the dropless output
    b, h, s, d = 2, 2, 256, 64
    q, k, v = _rand((b, h, s, d), 0), _rand((b, h, s, d), 1), \
        _rand((b, h, s, d), 2)
    rng = jax.random.PRNGKey(7)
    out = flash_attention(q, k, v, dropout_rate=0.3, dropout_rng=rng)
    base = flash_attention(q, k, v)
    # must actually drop something
    assert not np.allclose(np.asarray(out), np.asarray(base))
    # expectation check: averaged over the whole tensor the dropped
    # output tracks the dropless one
    np.testing.assert_allclose(float(out.mean()), float(base.mean()),
                               atol=5e-3)


def test_flash_attention_dropout_matches_masked_oracle():
    # the kernel consumes a precomputed keep-mask; rebuild the same mask
    # and apply the identical semantics composed to get an exact oracle
    from paddle_tpu.kernels.flash_attention import dropout_keep_mask
    b, h, s, d = 1, 2, 256, 64
    q, k, v = _rand((b, h, s, d), 3), _rand((b, h, s, d), 4), \
        _rand((b, h, s, d), 5)
    rate = 0.25
    rng = jax.random.PRNGKey(11)
    out = flash_attention(q, k, v, dropout_rate=rate, dropout_rng=rng)

    keep = dropout_keep_mask(rng, rate, (b, h, s, s), q.dtype)
    scale = 1.0 / np.sqrt(d)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    probs = jax.nn.softmax(scores, axis=-1)
    probs = probs * keep / (1.0 - rate)
    ref = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_attention_dropout_grads():
    from paddle_tpu.kernels.flash_attention import dropout_keep_mask
    b, h, s, d = 1, 2, 256, 64
    q, k, v = _rand((b, h, s, d), 6), _rand((b, h, s, d), 7), \
        _rand((b, h, s, d), 8)
    rate = 0.2
    rng = jax.random.PRNGKey(13)
    w = _rand((b, h, s, d), 9)

    def f_flash(q, k, v):
        return (flash_attention(q, k, v, dropout_rate=rate,
                                dropout_rng=rng) * w).sum()

    keep = dropout_keep_mask(rng, rate, (b, h, s, s), q.dtype)

    def f_ref(q, k, v):
        scale = 1.0 / np.sqrt(d)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        probs = jax.nn.softmax(scores, axis=-1)
        probs = probs * keep / (1.0 - rate)
        return (jnp.einsum("bhqk,bhkd->bhqd", probs, v) * w).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b_, atol=5e-4, rtol=5e-4)


def test_flash_block_divisor_fallback():
    """Non-512-divisible long seqs must still take the Pallas path: the
    entry shrinks blocks to divisors instead of bouncing S=1280 to the
    composed fallback (interpret mode exercises the same routing)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.kernels.flash_attention import (attention_reference,
                                                    flash_attention)
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 2, 1280, 64) * 0.1, jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 1280, 64) * 0.1, jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 1280, 64) * 0.1, jnp.float32)
    out = flash_attention(q, k, v)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="in-kernel PRNG dropout needs the real TPU "
                           "(pltpu.prng has no interpret-mode impl)")
def test_flash_inkernel_dropout_tpu():
    """Delegates to the standalone parity script so the run sheet can
    execute the SAME check outside pytest (tests/conftest.py forces the
    CPU backend for every pytest session, so on hardware this runs via
    `python scripts/inkernel_parity.py`)."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "inkernel_parity",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "inkernel_parity.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.check_inkernel_dropout_parity()


def test_flash_bias_needs_grad_false_matches_reference():
    """bias_needs_grad=False must not change q/k/v grads (the dbias
    recompute is skipped, its cotangent is zeros) — the padding-mask
    contract that makes in-kernel dropout eligible with a bias."""
    from paddle_tpu.kernels.flash_attention import (attention_reference,
                                                    flash_attention)
    rng = np.random.RandomState(2)
    B, H, S, D = 1, 2, 256, 64
    q = jnp.asarray(rng.randn(B, H, S, D) * 0.1, jnp.float32)
    k = jnp.asarray(rng.randn(B, H, S, D) * 0.1, jnp.float32)
    v = jnp.asarray(rng.randn(B, H, S, D) * 0.1, jnp.float32)
    mask = np.zeros((B, 1, 1, S), np.float32)
    mask[..., -32:] = -1e9
    bias = jnp.asarray(mask)

    def loss_flash(q, k, v, b):
        return jnp.sum(flash_attention(q, k, v, bias=b, sm_scale=0.125,
                                       block_q=128, block_k=128,
                                       bias_needs_grad=False) ** 2)

    def loss_ref(q, k, v, b):
        return jnp.sum(attention_reference(q, k, v, bias=b,
                                           sm_scale=0.125) ** 2)

    gq, gk, gv, gb = jax.grad(loss_flash, argnums=(0, 1, 2, 3))(q, k, v,
                                                                bias)
    rq, rk, rv, _ = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq),
                               atol=2e-4, rtol=2e-3)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk),
                               atol=2e-4, rtol=2e-3)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv),
                               atol=2e-4, rtol=2e-3)
    assert np.all(np.asarray(gb) == 0.0)  # declared non-differentiable


def test_attention_core_mask_is_stop_gradiented():
    """The nn router treats attn_mask as non-differentiable by contract
    (both composed and flash paths)."""
    from paddle_tpu.nn.transformer import _attention_core
    rng = np.random.RandomState(3)
    B, S, H, D = 2, 64, 4, 16
    q = jnp.asarray(rng.randn(B, S, H, D) * 0.1, jnp.float32)
    mask = jnp.zeros((B, 1, 1, S), jnp.float32)

    def loss(m):
        return jnp.sum(_attention_core(q, q, q, m, 0.0, False) ** 2)

    g = jax.grad(loss)(mask)
    assert np.all(np.asarray(g) == 0.0)


# ---------------------------------------------------------------------------
# in-kernel dropout parity-freshness stamp (ADVICE r5)
# ---------------------------------------------------------------------------

@pytest.fixture
def _stamp_env(tmp_path, monkeypatch):
    """Point the stamp at a throwaway path and reset the per-process
    memo around each test."""
    from paddle_tpu.kernels import flash_attention as fa
    p = tmp_path / "inkernel_parity.json"
    monkeypatch.setenv("PADDLE_TPU_PARITY_STAMP", str(p))
    fa._parity_memo = None
    yield str(p)
    fa._parity_memo = None


def test_parity_stamp_fresh_engages(_stamp_env):
    from paddle_tpu.kernels import flash_attention as fa
    written = fa.write_parity_stamp()
    assert written == _stamp_env
    import json
    with open(written) as f:
        stamp = json.load(f)
    assert stamp["kernel_hash"] == fa.kernel_parity_hash()
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any warning fails the test
        assert fa._inkernel_parity_ok() is True


def test_parity_stamp_missing_warns_once_and_falls_back(_stamp_env):
    from paddle_tpu.kernels import flash_attention as fa
    with pytest.warns(RuntimeWarning, match="parity stamp"):
        assert fa._inkernel_parity_ok() is False
    # memoized: the second call neither warns nor re-reads
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert fa._inkernel_parity_ok() is False


def test_parity_stamp_stale_hash_rejected(_stamp_env):
    from paddle_tpu.kernels import flash_attention as fa
    fa.write_parity_stamp()
    import json
    with open(_stamp_env) as f:
        stamp = json.load(f)
    stamp["kernel_hash"] = "0" * 64  # kernel edited since the run
    with open(_stamp_env, "w") as f:
        json.dump(stamp, f)
    fa._parity_memo = None
    with pytest.warns(RuntimeWarning, match="missing or stale"):
        assert fa._inkernel_parity_ok() is False


def test_parity_stamp_corrupt_json_rejected(_stamp_env):
    from paddle_tpu.kernels import flash_attention as fa
    with open(_stamp_env, "w") as f:
        f.write("{not json")
    with pytest.warns(RuntimeWarning):
        assert fa._inkernel_parity_ok() is False


def test_write_parity_stamp_resets_memo(_stamp_env):
    """The parity run un-sticks a previously-failed memo: after a pass
    writes a fresh stamp, the gate re-opens without a process restart."""
    from paddle_tpu.kernels import flash_attention as fa
    with pytest.warns(RuntimeWarning):
        assert fa._inkernel_parity_ok() is False
    fa.write_parity_stamp()
    assert fa._inkernel_parity_ok() is True


# ---------------------------------------------------------------------------
# ragged paged attention (PR 10): mixed prefill+decode batches
# ---------------------------------------------------------------------------

from paddle_tpu.generation import (DecoderConfig, KVCacheManager,  # noqa: E402
                                   forward_full, forward_paged,
                                   init_params)
from paddle_tpu.kernels.paged_attention import (  # noqa: E402
    paged_attention_reference, ragged_paged_attention,
    ragged_paged_attention_pallas, ragged_paged_attention_reference)


def _bits(a):
    return np.asarray(a, np.float32).view(np.uint32)


def _ragged_case(seed=0):
    rng = np.random.default_rng(seed)
    b, cq, h, d, bs, n, m = 4, 4, 4, 8, 4, 16, 4
    q = jnp.asarray(rng.normal(size=(b, cq, h, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(n, bs, h, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(n, bs, h, d)), jnp.float32)
    tbl = jnp.asarray(rng.integers(1, n, (b, m)), jnp.int32)
    # mixed batch: full chunk, decode single, short chunk, decode single
    q_lens = jnp.asarray([4, 1, 2, 1], jnp.int32)
    ctx = jnp.asarray([5, 9, 0, 3], jnp.int32)
    return q, kp, vp, tbl, q_lens, ctx


def test_ragged_reference_bitwise_matches_per_token_decode():
    """Every real query row of the ragged reference equals the Cq == 1
    decode path at the same absolute position, bit for bit — chunked
    prefill and single-token decode share one numerics contract."""
    q, kp, vp, tbl, q_lens, ctx = _ragged_case()
    out = ragged_paged_attention_reference(q, kp, vp, tbl, q_lens, ctx)
    for i in range(q.shape[0]):
        for j in range(int(q_lens[i])):
            one = paged_attention_reference(
                q[i:i + 1, j], kp, vp, tbl[i:i + 1], ctx[i:i + 1] + j + 1)
            assert np.array_equal(_bits(out[i, j]), _bits(one[0])), \
                "row %d query %d diverged" % (i, j)


def test_ragged_pallas_interpret_matches_reference():
    q, kp, vp, tbl, q_lens, ctx = _ragged_case(seed=3)
    ref = ragged_paged_attention_reference(q, kp, vp, tbl, q_lens, ctx)
    pal = ragged_paged_attention_pallas(q, kp, vp, tbl, q_lens, ctx)
    # compare only real rows: fully-masked rows intentionally differ
    # (reference degrades to a uniform average, the kernel emits 0)
    for i in range(q.shape[0]):
        for j in range(int(q_lens[i])):
            np.testing.assert_allclose(
                np.asarray(pal[i, j]), np.asarray(ref[i, j]),
                atol=2e-5, rtol=2e-5)


def test_ragged_flag_seam():
    """FLAGS_paged_attention_kernel routes the ragged entry exactly
    like the decode entry."""
    from paddle_tpu.flags import get_flags, set_flags
    q, kp, vp, tbl, q_lens, ctx = _ragged_case(seed=7)
    ref = ragged_paged_attention_reference(q, kp, vp, tbl, q_lens, ctx)
    prior = get_flags(["FLAGS_paged_attention_kernel"])
    try:
        set_flags({"FLAGS_paged_attention_kernel": "pallas"})
        pal = ragged_paged_attention(q, kp, vp, tbl, q_lens, ctx)
    finally:
        set_flags(prior)
    for i in range(q.shape[0]):
        for j in range(int(q_lens[i])):
            np.testing.assert_allclose(
                np.asarray(pal[i, j]), np.asarray(ref[i, j]),
                atol=2e-5, rtol=2e-5)
    routed = ragged_paged_attention(q, kp, vp, tbl, q_lens, ctx)
    assert np.array_equal(_bits(routed), _bits(ref))


def test_chunked_prefill_mixed_batch_bitwise_vs_forward_full():
    """PR-5's paged==full parity pin extended to chunked prefill: a
    prompt streamed through the mixed step in 4-token chunks — sharing
    its batch with a concurrently DECODING sequence — produces, at
    every prompt position and every decode step, logits bitwise equal
    to a full-context forward_full recompute."""
    cfg = DecoderConfig(vocab_size=64, hidden=32, layers=2, heads=4,
                        max_seq_len=32)
    params = init_params(cfg, seed=0)
    bs, nblocks, t_slots = 4, 32, 5
    m = -(-cfg.max_seq_len // bs)
    lanes = m * bs
    rng = np.random.default_rng(5)
    pa = [int(x) for x in rng.integers(1, cfg.vocab_size, 13)]
    pb = [int(x) for x in rng.integers(1, cfg.vocab_size, 5)]
    sb = 32
    ff = jax.jit(lambda p, t, l: forward_full(cfg, p, t, l,
                                              attn_lanes=lanes))

    def oracle(tokens):
        padded = np.zeros((1, sb), np.int32)
        padded[0, :len(tokens)] = tokens
        return ff(params, jnp.asarray(padded),
                  jnp.asarray([len(tokens)], np.int32))[0][0]

    step = jax.jit(lambda p, k, v, tb, c, x: forward_paged(
        cfg, p, k, v, tb, c, x))
    mgr = KVCacheManager(nblocks, bs)
    shape = (cfg.layers, nblocks, bs, cfg.heads, cfg.head_dim)
    kp = jnp.zeros(shape, jnp.float32)
    vp = jnp.zeros(shape, jnp.float32)
    mgr.alloc("A", mgr.blocks_for_tokens(len(pa) + 1))
    mgr.alloc("B", mgr.blocks_for_tokens(len(pb) + 6))
    ta = np.asarray(mgr.table("A", m), np.int32)
    tb_ = np.asarray(mgr.table("B", m), np.int32)

    tables = np.zeros((t_slots, m), np.int32)
    pos = np.zeros((t_slots,), np.int32)
    toks = np.zeros((t_slots,), np.int32)
    # step 0: B's whole prompt rides in as one chunk
    for j in range(len(pb)):
        tables[j], pos[j], toks[j] = tb_, j, pb[j]
    logits, kp, vp = step(params, kp, vp, jnp.asarray(tables),
                          jnp.asarray(pos), jnp.asarray(toks))
    for j in range(len(pb)):
        assert np.array_equal(_bits(logits[j]), _bits(oracle(pb[:j + 1])))
    btoks = pb + [int(np.argmax(np.asarray(logits[len(pb) - 1])))]
    # A's 13-token prompt streams in chunks of 4 while B greedy-decodes
    filled = 0
    while filled < len(pa):
        take = min(4, len(pa) - filled)
        tables[:], pos[:], toks[:] = 0, 0, 0
        tables[0], pos[0], toks[0] = tb_, len(btoks) - 1, btoks[-1]
        for j in range(take):
            tables[1 + j] = ta
            pos[1 + j] = filled + j
            toks[1 + j] = pa[filled + j]
        logits, kp, vp = step(params, kp, vp, jnp.asarray(tables),
                              jnp.asarray(pos), jnp.asarray(toks))
        assert np.array_equal(_bits(logits[0]), _bits(oracle(btoks))), \
            "decode lane diverged while chunk [%d:%d) prefilled" \
            % (filled, filled + take)
        for j in range(take):
            assert np.array_equal(
                _bits(logits[1 + j]),
                _bits(oracle(pa[:filled + j + 1]))), \
                "chunked prefill diverged at position %d" % (filled + j)
        btoks.append(int(np.argmax(np.asarray(logits[0]))))
        filled += take
