"""Round-5 detection additions: retinanet_target_assign and
deformable_roi_pooling (ops/detection.py)."""
import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import REGISTRY, LowerCtx


def _run(name, ins, attrs):
    return REGISTRY.get(name).lower(LowerCtx(), ins, attrs)


def test_retinanet_target_assign_basics():
    anchors = jnp.asarray([[0, 0, 10, 10], [20, 20, 30, 30],
                           [100, 100, 110, 110]], jnp.float32)
    gt = jnp.asarray([[[0, 0, 10, 10], [21, 21, 30, 30]]], jnp.float32)
    labels = jnp.asarray([[[3], [7]]], jnp.float32)
    outs = _run("retinanet_target_assign",
                {"Anchor": [anchors], "GtBoxes": [gt],
                 "GtLabels": [labels],
                 "GtNum": [jnp.asarray([2], jnp.int32)]},
                {"positive_overlap": 0.5, "negative_overlap": 0.4})
    tl = np.asarray(outs["TargetLabel"][0])[0]
    fg = int(np.asarray(outs["ForegroundNumber"][0])[0, 0])
    # anchor0 matches gt0 (class 3), anchor1 matches gt1 (class 7),
    # anchor2 is background (label 0)
    assert tl[0] == 3 and tl[1] == 7 and tl[2] == 0
    assert fg == 2
    li = np.asarray(outs["LocationIndex"][0])[0]
    assert set(li[li >= 0].tolist()) == {0, 1}


def test_retinanet_crowd_boxes_excluded():
    anchors = jnp.asarray([[0, 0, 10, 10]], jnp.float32)
    gt = jnp.asarray([[[0, 0, 10, 10]]], jnp.float32)
    labels = jnp.asarray([[[5]]], jnp.float32)
    outs = _run("retinanet_target_assign",
                {"Anchor": [anchors], "GtBoxes": [gt],
                 "GtLabels": [labels],
                 "IsCrowd": [jnp.asarray([[1]], jnp.int32)],
                 "GtNum": [jnp.asarray([1], jnp.int32)]}, {})
    # the only gt is crowd -> no positives; the anchor becomes
    # background (its max IoU vs valid gts is 0 < negative_overlap)
    assert int(np.asarray(outs["ForegroundNumber"][0])[0, 0]) == 0
    assert np.asarray(outs["TargetLabel"][0])[0, 0] == 0


def _ref_plain_roi_pool(x, roi, scale, ph, pw, spp):
    """Naive python oracle for the no-trans, non-PS path."""
    h, w = x.shape[1:]
    x1 = roi[0] * scale - 0.5
    y1 = roi[1] * scale - 0.5
    x2 = (roi[2] + 1.0) * scale - 0.5
    y2 = (roi[3] + 1.0) * scale - 0.5
    rw = max(x2 - x1, 0.1)
    rh = max(y2 - y1, 0.1)
    bw, bh = rw / pw, rh / ph
    out = np.zeros((x.shape[0], ph, pw), np.float32)
    for i in range(ph):
        for j in range(pw):
            acc = np.zeros(x.shape[0])
            cnt = 0
            for si in range(spp):
                for sj in range(spp):
                    yy = y1 + i * bh + (si + 0.5) * bh / spp
                    xx = x1 + j * bw + (sj + 0.5) * bw / spp
                    if not (-0.5 <= yy < h - 0.5
                            and -0.5 <= xx < w - 0.5):
                        continue
                    yc, xc = np.clip(yy, 0, h - 1), np.clip(xx, 0, w - 1)
                    y0, x0 = int(np.floor(yc)), int(np.floor(xc))
                    y1i, x1i = min(y0 + 1, h - 1), min(x0 + 1, w - 1)
                    wy, wx = yc - y0, xc - x0
                    acc += (x[:, y0, x0] * (1 - wy) * (1 - wx)
                            + x[:, y0, x1i] * (1 - wy) * wx
                            + x[:, y1i, x0] * wy * (1 - wx)
                            + x[:, y1i, x1i] * wy * wx)
                    cnt += 1
            out[:, i, j] = acc / max(cnt, 1)
    return out


def test_deformable_roi_pooling_matches_oracle():
    rng = np.random.RandomState(0)
    x = rng.randn(1, 8, 12, 12).astype(np.float32)
    rois = np.asarray([[1.0, 1.0, 8.0, 8.0]], np.float32)
    outs = _run("deformable_roi_pooling",
                {"Input": [jnp.asarray(x)], "ROIs": [jnp.asarray(rois)],
                 "BatchRoINums": [jnp.asarray([0], jnp.int32)]},
                {"no_trans": True, "spatial_scale": 1.0,
                 "pooled_height": 2, "pooled_width": 2,
                 "sample_per_part": 2})
    got = np.asarray(outs["Output"][0])[0]
    ref = _ref_plain_roi_pool(x[0], rois[0], 1.0, 2, 2, 2)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_deformable_roi_pooling_position_sensitive_and_grads():
    rng = np.random.RandomState(1)
    ph = pw = 2
    oc = 3
    x = jnp.asarray(rng.randn(1, oc * ph * pw, 10, 10), jnp.float32)
    rois = jnp.asarray([[0.0, 0.0, 7.0, 7.0]], jnp.float32)
    trans = jnp.asarray(rng.randn(1, 2, ph, pw) * 0.5, jnp.float32)

    def pooled_sum(xx, tt):
        outs = _run("deformable_roi_pooling",
                    {"Input": [xx], "ROIs": [rois], "Trans": [tt],
                     "BatchRoINums": [jnp.asarray([0], jnp.int32)]},
                    {"no_trans": False, "spatial_scale": 1.0,
                     "pooled_height": ph, "pooled_width": pw,
                     "sample_per_part": 2, "trans_std": 0.1,
                     "position_sensitive": True})
        return jnp.sum(outs["Output"][0]), outs["Output"][0]

    (s, out), grads = jax.value_and_grad(
        pooled_sum, argnums=(0, 1), has_aux=True)(x, trans)
    assert out.shape == (1, oc, ph, pw)
    gx, gt = grads
    assert np.isfinite(np.asarray(gx)).all()
    # offsets shift sample positions -> the Trans grad path is live
    # (matching the CUDA kernel's second grad output)
    assert np.abs(np.asarray(gt)).sum() > 0
    # PS channel routing: zeroing the channels of bin (0,0) must zero
    # ONLY that bin's outputs
    x0 = np.asarray(x).copy()
    x0[:, 0::ph * pw] = 0.0  # channel k*ph*pw + 0 feeds bin (0,0)
    _, out0 = pooled_sum(jnp.asarray(x0), trans)
    np.testing.assert_allclose(np.asarray(out0)[0, :, 0, 0], 0.0,
                               atol=1e-6)
    assert np.abs(np.asarray(out0)[0, :, 1, 1]).sum() > 0
