"""paddle.nn RNN API tests: cells vs manual oracles, RNN scan wrapper,
ragged masking, bidirection, multi-layer stacks, gradients.

Reference surface: fluid/layers/rnn.py RNNCell/rnn/birnn + the
paddle.nn SimpleRNN/LSTM/GRU family."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn


def _np(t):
    return np.asarray(t.value if hasattr(t, "value") else t)


def test_lstm_cell_oracle():
    cell = nn.LSTMCell(4, 3)
    x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
    out, (h, c) = cell(pt.to_tensor(x))
    # numpy oracle
    wi, wh, bi, bh = [_np(p) for p in cell._params()]
    g = x @ wi.T + bi + np.zeros((2, 3)) @ wh.T + bh
    i, f, gg, o = np.split(g, 4, axis=-1)
    sig = lambda v: 1 / (1 + np.exp(-v))
    c_ref = sig(f) * 0 + sig(i) * np.tanh(gg)
    h_ref = sig(o) * np.tanh(c_ref)
    np.testing.assert_allclose(_np(out), h_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(_np(c), c_ref, rtol=1e-5, atol=1e-6)


def test_rnn_wrapper_matches_stepwise():
    rng = np.random.RandomState(1)
    cell = nn.GRUCell(3, 5)
    x = rng.randn(2, 4, 3).astype(np.float32)
    out, final = nn.RNN(cell)(pt.to_tensor(x))
    # stepping the cell manually must match
    h = None
    for t in range(4):
        o, h = cell(pt.to_tensor(x[:, t]), h)
        np.testing.assert_allclose(_np(out)[:, t], _np(o),
                                   rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(_np(final), _np(h), rtol=1e-5,
                               atol=1e-5)


def test_rnn_sequence_length_masking():
    rng = np.random.RandomState(2)
    cell = nn.SimpleRNNCell(3, 4)
    x = rng.randn(2, 5, 3).astype(np.float32)
    lens = np.asarray([5, 3], np.int64)
    out, final = nn.RNN(cell)(pt.to_tensor(x),
                              sequence_length=pt.to_tensor(lens))
    # beyond its length, sequence 1's outputs are zero and the final
    # state equals the state at t=len-1
    assert np.abs(_np(out)[1, 3:]).max() == 0.0
    short, fs = nn.RNN(cell)(pt.to_tensor(x[1:2, :3]))
    np.testing.assert_allclose(_np(final)[1], _np(fs)[0], rtol=1e-5,
                               atol=1e-5)


def test_bidirect_and_stack_shapes_and_grads():
    rng = np.random.RandomState(3)
    m = nn.LSTM(4, 6, num_layers=2, direction="bidirect")
    x = pt.to_tensor(rng.randn(2, 5, 4).astype(np.float32))
    out, finals = m(x)
    assert _np(out).shape == (2, 5, 12)
    loss = (out ** 2).mean()
    loss.backward()
    g = m.layers[0].rnn_fw.cell.weight_ih.grad
    assert g is not None and np.abs(_np(g)).max() > 0


def test_reverse_rnn_is_time_flip():
    rng = np.random.RandomState(4)
    cell = nn.SimpleRNNCell(3, 4)
    x = rng.randn(1, 6, 3).astype(np.float32)
    out_r, _ = nn.RNN(cell, is_reverse=True)(pt.to_tensor(x))
    out_f, _ = nn.RNN(cell)(pt.to_tensor(x[:, ::-1].copy()))
    np.testing.assert_allclose(_np(out_r), _np(out_f)[:, ::-1],
                               rtol=1e-5, atol=1e-5)


def test_learned_initial_state_gets_grad():
    """Review regression: a learned h0 passed as initial_states must
    receive gradients through the scan."""
    rng = np.random.RandomState(5)
    cell = nn.GRUCell(3, 4)
    h0 = pt.to_tensor(rng.randn(2, 4).astype(np.float32))
    h0.stop_gradient = False
    x = pt.to_tensor(rng.randn(2, 5, 3).astype(np.float32))
    out, _ = nn.RNN(cell)(x, initial_states=h0)
    ((out ** 2).mean()).backward()
    assert h0.grad is not None
    assert np.abs(_np(h0.grad)).max() > 0


def test_multilayer_stacked_final_states():
    """Review regression: LSTM/GRU finals follow the reference stacked
    [L*D, B, H] form and round-trip as initial_states."""
    rng = np.random.RandomState(6)
    m = nn.LSTM(3, 4, num_layers=2, direction="bidirect")
    x = pt.to_tensor(rng.randn(2, 5, 3).astype(np.float32))
    out, (h, c) = m(x)
    assert _np(h).shape == (4, 2, 4) and _np(c).shape == (4, 2, 4)
    out2, _ = m(x, initial_states=(h, c))
    assert _np(out2).shape == (2, 5, 8)

    g = nn.GRU(3, 4, num_layers=2)
    _, hg = g(x)
    assert _np(hg).shape == (2, 2, 4)
    _, _ = g(x, initial_states=hg)


def test_beam_search_decoder_dynamic_decode():
    """BeamSearchDecoder + dynamic_decode (fluid rnn.py:856,1327):
    train a GRU seq2seq on the reversal task, then beam-decode."""
    from paddle_tpu.dygraph import tape
    tape.seed(13)  # hermetic init: the convergence bound is tight
    rng = np.random.RandomState(9)
    V, EMB, HID, T, BOS, EOS = 10, 12, 24, 4, 1, 0
    emb_src = nn.Embedding(V, EMB)
    emb_tgt = nn.Embedding(V, EMB)
    enc = nn.GRU(EMB, HID)
    dec_cell = nn.GRUCell(EMB, HID)
    out_fc = nn.Linear(HID, V)
    params = (emb_src.parameters() + emb_tgt.parameters()
              + enc.parameters() + dec_cell.parameters()
              + out_fc.parameters())
    opt = pt.optimizer.Adam(1e-2, parameters=params)

    def batch(n=32):
        src = rng.randint(2, V, (n, T)).astype(np.int64)
        tgt = src[:, ::-1].copy()
        tin = np.concatenate([np.full((n, 1), BOS), tgt[:, :-1]], 1)
        return src, tin.astype(np.int64), tgt

    import paddle_tpu.tensor as Tn
    for i in range(250):
        src, tin, tgt = batch()
        _, h = enc(emb_src(pt.to_tensor(src)))
        h = Tn.squeeze(h, 0)
        logits = []
        st = h
        for t in range(T):
            o, st = dec_cell(emb_tgt(pt.to_tensor(tin[:, t])), st)
            logits.append(out_fc(o))
        loss = nn.CrossEntropyLoss()(
            Tn.stack(logits, 1).reshape([-1, V]),
            pt.to_tensor(tgt.reshape(-1)[:, None]))
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss) < 0.5, float(loss)

    # decode 2 sources with beam 3
    src, _, tgt = batch(2)
    _, h = enc(emb_src(pt.to_tensor(src)))
    h = Tn.squeeze(h, 0)
    dec = nn.BeamSearchDecoder(dec_cell, BOS, EOS, beam_size=3,
                               embedding_fn=emb_tgt,
                               output_fn=out_fc)
    ids, scores = nn.dynamic_decode(dec, inits=h, max_step_num=T)
    top = np.asarray(ids.value)[:, 0, :]  # best beam per source
    acc = (top == tgt).mean()
    assert acc >= 0.75, (top.tolist(), tgt.tolist())
    assert np.asarray(scores.value).shape == (2, 3)
