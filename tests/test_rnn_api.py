"""paddle.nn RNN API tests: cells vs manual oracles, RNN scan wrapper,
ragged masking, bidirection, multi-layer stacks, gradients.

Reference surface: fluid/layers/rnn.py RNNCell/rnn/birnn + the
paddle.nn SimpleRNN/LSTM/GRU family."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn


def _np(t):
    return np.asarray(t.value if hasattr(t, "value") else t)


def test_lstm_cell_oracle():
    cell = nn.LSTMCell(4, 3)
    x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
    out, (h, c) = cell(pt.to_tensor(x))
    # numpy oracle
    wi, wh, bi, bh = [_np(p) for p in cell._params()]
    g = x @ wi.T + bi + np.zeros((2, 3)) @ wh.T + bh
    i, f, gg, o = np.split(g, 4, axis=-1)
    sig = lambda v: 1 / (1 + np.exp(-v))
    c_ref = sig(f) * 0 + sig(i) * np.tanh(gg)
    h_ref = sig(o) * np.tanh(c_ref)
    np.testing.assert_allclose(_np(out), h_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(_np(c), c_ref, rtol=1e-5, atol=1e-6)


def test_rnn_wrapper_matches_stepwise():
    rng = np.random.RandomState(1)
    cell = nn.GRUCell(3, 5)
    x = rng.randn(2, 4, 3).astype(np.float32)
    out, final = nn.RNN(cell)(pt.to_tensor(x))
    # stepping the cell manually must match
    h = None
    for t in range(4):
        o, h = cell(pt.to_tensor(x[:, t]), h)
        np.testing.assert_allclose(_np(out)[:, t], _np(o),
                                   rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(_np(final), _np(h), rtol=1e-5,
                               atol=1e-5)


def test_rnn_sequence_length_masking():
    rng = np.random.RandomState(2)
    cell = nn.SimpleRNNCell(3, 4)
    x = rng.randn(2, 5, 3).astype(np.float32)
    lens = np.asarray([5, 3], np.int64)
    out, final = nn.RNN(cell)(pt.to_tensor(x),
                              sequence_length=pt.to_tensor(lens))
    # beyond its length, sequence 1's outputs are zero and the final
    # state equals the state at t=len-1
    assert np.abs(_np(out)[1, 3:]).max() == 0.0
    short, fs = nn.RNN(cell)(pt.to_tensor(x[1:2, :3]))
    np.testing.assert_allclose(_np(final)[1], _np(fs)[0], rtol=1e-5,
                               atol=1e-5)


def test_bidirect_and_stack_shapes_and_grads():
    rng = np.random.RandomState(3)
    m = nn.LSTM(4, 6, num_layers=2, direction="bidirect")
    x = pt.to_tensor(rng.randn(2, 5, 4).astype(np.float32))
    out, finals = m(x)
    assert _np(out).shape == (2, 5, 12)
    loss = (out ** 2).mean()
    loss.backward()
    g = m.layers[0].rnn_fw.cell.weight_ih.grad
    assert g is not None and np.abs(_np(g)).max() > 0


def test_reverse_rnn_is_time_flip():
    rng = np.random.RandomState(4)
    cell = nn.SimpleRNNCell(3, 4)
    x = rng.randn(1, 6, 3).astype(np.float32)
    out_r, _ = nn.RNN(cell, is_reverse=True)(pt.to_tensor(x))
    out_f, _ = nn.RNN(cell)(pt.to_tensor(x[:, ::-1].copy()))
    np.testing.assert_allclose(_np(out_r), _np(out_f)[:, ::-1],
                               rtol=1e-5, atol=1e-5)


def test_learned_initial_state_gets_grad():
    """Review regression: a learned h0 passed as initial_states must
    receive gradients through the scan."""
    rng = np.random.RandomState(5)
    cell = nn.GRUCell(3, 4)
    h0 = pt.to_tensor(rng.randn(2, 4).astype(np.float32))
    h0.stop_gradient = False
    x = pt.to_tensor(rng.randn(2, 5, 3).astype(np.float32))
    out, _ = nn.RNN(cell)(x, initial_states=h0)
    ((out ** 2).mean()).backward()
    assert h0.grad is not None
    assert np.abs(_np(h0.grad)).max() > 0


def test_multilayer_stacked_final_states():
    """Review regression: LSTM/GRU finals follow the reference stacked
    [L*D, B, H] form and round-trip as initial_states."""
    rng = np.random.RandomState(6)
    m = nn.LSTM(3, 4, num_layers=2, direction="bidirect")
    x = pt.to_tensor(rng.randn(2, 5, 3).astype(np.float32))
    out, (h, c) = m(x)
    assert _np(h).shape == (4, 2, 4) and _np(c).shape == (4, 2, 4)
    out2, _ = m(x, initial_states=(h, c))
    assert _np(out2).shape == (2, 5, 8)

    g = nn.GRU(3, 4, num_layers=2)
    _, hg = g(x)
    assert _np(hg).shape == (2, 2, 4)
    _, _ = g(x, initial_states=hg)
