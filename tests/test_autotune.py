"""Adaptive kernel dispatch tests (ISSUE 16, docs/autotune.md).

Covers the tentpole contract: candidate enumeration (reference first,
pins respected, budget-bounded, Pallas last), the bitwise eligibility
gate, the one-dict-lookup steady-state resolve, winner persistence in
the program cache's policy/ sidecar (restart round-trip with ZERO new
trials and ZERO new compiles), corruption / version-skew self-healing,
fingerprint isolation across backend/quant-mode keys (mirroring the
PR-15 qm=/kvq= isolation tests), the autotune.measure failpoint
semantics (non-reference fault discards the candidate; reference fault
aborts with nothing persisted — the cache is never poisoned), override
precedence (explicit flags / ctor args pin knobs past any policy), the
scheduler's GAUGE_autotune_* retraction, the Predictor's pad-vs-exact
bucket dispatch, and the /statusz autotune section.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import autotune, failpoints, layers
from paddle_tpu.autotune import CandidateForm, generation_candidates
from paddle_tpu.core import program_cache
from paddle_tpu.generation import (DecoderConfig, GenerationEngine,
                                   GenerationPool, GenerationRequest,
                                   SamplingParams, init_params)
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.kernels import paged_attention as pa
from paddle_tpu.monitor import gauge_get, gauge_set, stat_get

CFG = DecoderConfig(vocab_size=64, hidden=32, layers=2, heads=4,
                    max_seq_len=32)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, seed=0)


@pytest.fixture(autouse=True)
def _clean_slate():
    """Small search budget + tiny probe so tunes stay test-sized, a
    fresh in-memory policy table per test, flags restored."""
    from paddle_tpu import flags as F
    saved, saved_exp = dict(F._values), set(F._EXPLICIT)
    F.set_flags({"FLAGS_autotune_candidates": 3,
                 "FLAGS_autotune_probe_tokens": 8})
    F.clear_explicit("FLAGS_autotune_candidates",
                     "FLAGS_autotune_probe_tokens")
    autotune.reset()
    failpoints.disarm()
    yield
    F._values.clear()
    F._values.update(saved)
    F._EXPLICIT.clear()
    F._EXPLICIT.update(saved_exp)
    autotune.reset()
    failpoints.disarm()


def _engine(params, **kw):
    # kernel + block_size pinned by default so tunes search the cheap
    # prefill_chunk dimension only (no Pallas-interpret trials)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("decode_width", 2)
    kw.setdefault("kernel", "reference")
    kw.setdefault("block_size", 4)
    kw.setdefault("autotune", True)
    return GenerationEngine(CFG, params, **kw)


def _gen(eng, n=2, new=4, seed=7):
    rng = np.random.default_rng(seed)
    streams = {}
    for i in range(n):
        prompt = [int(t) for t in rng.integers(0, CFG.vocab_size, 5)]
        eng.submit(GenerationRequest(
            prompt=prompt, max_new_tokens=new,
            sampling=SamplingParams(temperature=0.8, top_k=5,
                                    seed=100 + i),
            request_id="r%d" % i))
    for _ in range(200):
        if eng.idle:
            break
        for r in eng.step():
            streams[r.request_id] = tuple(r.tokens)
    assert eng.idle
    return streams


def _trace_entries(cache_dir):
    d = os.path.join(cache_dir, "trace")
    return set(os.listdir(d)) if os.path.isdir(d) else set()


def _policy_files(cache_dir):
    d = os.path.join(cache_dir, "policy")
    return [os.path.join(d, f) for f in sorted(os.listdir(d))] \
        if os.path.isdir(d) else []


# ---------------------------------------------------------------------------
# candidate enumeration
# ---------------------------------------------------------------------------

def test_candidates_reference_first_budget_and_pallas_last():
    d = CandidateForm("reference", 16, 8, 0)
    cands = generation_candidates(d, pins={}, budget=10)
    assert cands[0] == d                      # reference form is #1
    assert len(cands) == len(set(cands))      # deduped
    pallas = [c for c in cands if c.kernel == "pallas"]
    assert pallas == [cands[-1]]              # kernel flip ordered last
    # a small budget searches geometry only — Pallas never trialed
    small = generation_candidates(d, pins={}, budget=3)
    assert len(small) == 3
    assert all(c.kernel == "reference" for c in small)


def test_candidates_respect_pins():
    d = CandidateForm("reference", 16, 8, 0)
    cands = generation_candidates(
        d, pins={"prefill_chunk": 8, "kernel": "reference"}, budget=10)
    assert all(c.prefill_chunk == 8 for c in cands)
    assert all(c.kernel == "reference" for c in cands)
    assert any(c.block_size != 16 for c in cands)  # free dim varies


def test_two_phase_defaults_do_not_invent_chunking():
    # prefill_chunk=0 (two-phase mode) stays 0 across every candidate:
    # the tuner varies a knob's magnitude, never flips the mode
    d = CandidateForm("reference", 16, 0, 0)
    cands = generation_candidates(d, pins={}, budget=10)
    assert all(c.prefill_chunk == 0 for c in cands)


# ---------------------------------------------------------------------------
# steady-state resolve is ONE dict lookup
# ---------------------------------------------------------------------------

def test_resolve_is_one_dict_lookup():
    calls = []

    class Counting(dict):
        def get(self, *a, **kw):
            calls.append(a)
            return dict.get(self, *a, **kw)

    pol = autotune.policy()
    orig = pol._table
    try:
        pol._table = Counting(orig)
        pol._table["k"] = {"label": "x"}
        calls.clear()
        assert pol.resolve("k") == {"label": "x"}
        assert len(calls) == 1
    finally:
        pol._table = orig


# ---------------------------------------------------------------------------
# tune -> persist -> restart round-trip
# ---------------------------------------------------------------------------

def test_winner_survives_restart_zero_trials_zero_compiles(
        tmp_path, params):
    cache = str(tmp_path / "pcache")
    eng = _engine(params, program_cache_dir=cache)  # chunk left free
    assert eng._policy_entry is not None
    assert eng._policy_entry["source"] == "tuned"
    assert eng._policy_entry["trials"] >= 2
    assert len(_policy_files(cache)) == 1
    eng.warmup()
    streams = _gen(eng)
    traces = _trace_entries(cache)
    assert traces

    # "restart": clear the in-memory table; a fresh engine must reload
    # the winner from disk and re-tune / recompile NOTHING
    autotune.reset()
    t0 = stat_get("STAT_autotune_trials")
    m0 = stat_get("STAT_program_cache_trace_miss")
    eng2 = _engine(params, program_cache_dir=cache)
    assert stat_get("STAT_autotune_trials") == t0
    assert eng2._policy_entry["source"] == "disk"
    assert eng2._policy_entry["label"] == eng._policy_entry["label"]
    eng2.warmup()
    assert stat_get("STAT_program_cache_trace_miss") == m0
    assert _trace_entries(cache) == traces
    assert _gen(eng2) == streams              # bitwise across restart


def test_policy_entry_rides_program_fingerprint(tmp_path, params):
    """Two engines resolving DIFFERENT forms never share AOT entries:
    the resolved kernel+policy label is part of the v=4 program meta."""
    cache = str(tmp_path / "pcache")
    a = _engine(params, autotune=False, prefill_chunk=4,
                program_cache_dir=cache)
    a.warmup()
    ea = _trace_entries(cache)
    b = _engine(params, autotune=False, prefill_chunk=8,
                program_cache_dir=cache)
    b.warmup()
    assert _trace_entries(cache) - ea         # pc8 exported NEW entries


# ---------------------------------------------------------------------------
# corruption / version skew self-heal
# ---------------------------------------------------------------------------

def test_corrupt_policy_file_self_heals(tmp_path, params):
    cache = str(tmp_path / "pcache")
    eng = _engine(params, prefill_chunk=4, program_cache_dir=cache)
    label = eng._policy_entry["label"]
    [pf] = _policy_files(cache)
    with open(pf, "wb") as f:
        f.write(b"garbage\x00not json")
    autotune.reset()
    c0 = stat_get("STAT_program_cache_corrupt")
    t0 = stat_get("STAT_autotune_trials")
    eng2 = _engine(params, prefill_chunk=4, program_cache_dir=cache)
    assert stat_get("STAT_program_cache_corrupt") == c0 + 1
    assert stat_get("STAT_autotune_trials") > t0    # re-tuned
    assert eng2._policy_entry["source"] == "tuned"
    assert eng2._policy_entry["label"] == label
    # the healed file round-trips again
    autotune.reset()
    eng3 = _engine(params, prefill_chunk=4, program_cache_dir=cache)
    assert eng3._policy_entry["source"] == "disk"


def test_version_skewed_policy_file_retunes(tmp_path, params):
    cache = str(tmp_path / "pcache")
    _engine(params, prefill_chunk=4, program_cache_dir=cache)
    [pf] = _policy_files(cache)
    with open(pf, "rb") as f:
        blob = f.read()
    assert blob.startswith(program_cache.POLICY_MAGIC)
    rest = blob[len(program_cache.POLICY_MAGIC):]
    nl = rest.index(b"\n")
    hdr = json.loads(rest[:nl])
    hdr["format"] = program_cache.POLICY_FORMAT_VERSION + 1
    with open(pf, "wb") as f:
        f.write(program_cache.POLICY_MAGIC
                + json.dumps(hdr).encode() + b"\n" + rest[nl + 1:])
    autotune.reset()
    t0 = stat_get("STAT_autotune_trials")
    _engine(params, prefill_chunk=4, program_cache_dir=cache)
    assert stat_get("STAT_autotune_trials") > t0    # skew -> re-tune


# ---------------------------------------------------------------------------
# fingerprint isolation (mirrors the PR-15 qm=/kvq= tests)
# ---------------------------------------------------------------------------

def test_policy_fingerprint_isolates_backend_and_quant_keys():
    base = {"kind": "generation", "backend": "cpu", "qm": "off"}
    fp = program_cache.policy_fingerprint(base)
    assert fp != program_cache.policy_fingerprint(
        dict(base, backend="tpu"))
    assert fp != program_cache.policy_fingerprint(dict(base, qm="int8"))
    assert fp == program_cache.policy_fingerprint(dict(base))


def test_quant_modes_never_share_a_policy(tmp_path, params):
    cache = str(tmp_path / "pcache")
    e32 = _engine(params, prefill_chunk=4, program_cache_dir=cache)
    assert len(_policy_files(cache)) == 1
    t0 = stat_get("STAT_autotune_trials")
    e8 = _engine(params, prefill_chunk=4, quant_mode="int8",
                 program_cache_dir=cache)
    # the int8 key missed the fp32 policy: it tuned its own entry
    assert stat_get("STAT_autotune_trials") > t0
    assert len(_policy_files(cache)) == 2
    assert e8._policy_entry is not e32._policy_entry
    snap = autotune.policies()
    assert {s["qm"] for s in snap if s["kind"] == "generation"} == \
        {"off", "int8"}


def test_tuned_flags_excluded_from_policy_fingerprint():
    """The knobs the policy CHOOSES cannot fragment its key space —
    flipping FLAGS_paged_attention_kernel must not change the policy
    fingerprint (pins ride the key meta instead)."""
    from paddle_tpu.flags import set_flags
    meta = {"kind": "generation", "backend": "cpu"}
    fp = program_cache.policy_fingerprint(meta)
    set_flags({"FLAGS_paged_attention_kernel": "pallas"})
    assert program_cache.policy_fingerprint(meta) == fp


# ---------------------------------------------------------------------------
# autotune.measure failpoint
# ---------------------------------------------------------------------------

def test_reference_trial_fault_aborts_nothing_persisted(
        tmp_path, params):
    cache = str(tmp_path / "pcache")
    failpoints.arm("autotune.measure", "raise", "once")
    f0 = stat_get("STAT_autotune_fallbacks")
    w0 = stat_get("STAT_autotune_wins")
    eng = _engine(params, prefill_chunk=4, program_cache_dir=cache)
    assert stat_get("STAT_autotune_fallbacks") == f0 + 1
    assert stat_get("STAT_autotune_wins") == w0          # no winner
    assert eng._policy_entry is None
    assert _policy_files(cache) == []                    # not poisoned
    assert autotune.policies() == []
    # the engine still serves on the reference/default form
    eng.warmup()
    assert eng.prefill_chunk == 4
    assert _gen(eng)


def test_candidate_fault_discards_candidate_reference_wins(
        tmp_path, params):
    cache = str(tmp_path / "pcache")
    # fire on every trial AFTER the reference trial
    failpoints.arm("autotune.measure", "raise", "after(1)")
    f0 = stat_get("STAT_autotune_fallbacks")
    eng = _engine(params, program_cache_dir=cache)  # chunk free
    e = eng._policy_entry
    assert e is not None
    assert e["prefill_chunk"] == 8            # reference form won
    assert stat_get("STAT_autotune_fallbacks") > f0
    dead = [c for c in e["candidates"] if not c["eligible"]]
    assert dead and all("error" in c for c in dead)
    assert len(_policy_files(cache)) == 1     # winner still persisted


# ---------------------------------------------------------------------------
# override precedence: flags / ctor args pin past any policy
# ---------------------------------------------------------------------------

def test_explicit_flag_pins_knob_out_of_search(params):
    from paddle_tpu import flags as F
    F.set_flags({"FLAGS_generation_prefill_chunk": 4})
    assert F.explicitly_set("FLAGS_generation_prefill_chunk")
    eng = _engine(params)                     # no ctor chunk arg
    assert eng.prefill_chunk == 4             # the pin held
    e = eng._policy_entry
    assert e is not None
    assert all(c["prefill_chunk"] == 4 for c in e["candidates"])


def test_default_flag_is_not_a_pin(params):
    # a flag at its DEFAULT does not pin: the tuner varies the chunk
    from paddle_tpu import flags as F
    assert not F.explicitly_set("FLAGS_generation_prefill_chunk")
    eng = _engine(params)                     # chunk left free
    e = eng._policy_entry
    assert e is not None
    chunks = {c["prefill_chunk"] for c in e["candidates"]}
    assert len(chunks) > 1                    # search varied the knob


def test_autotune_off_is_legacy_behavior(params):
    t0 = stat_get("STAT_autotune_trials")
    eng = _engine(params, autotune=False, prefill_chunk=4)
    assert eng._policy_entry is None
    assert stat_get("STAT_autotune_trials") == t0
    assert eng.prefill_chunk == 4


# ---------------------------------------------------------------------------
# gauges + scheduler retraction
# ---------------------------------------------------------------------------

def test_engine_publishes_and_reset_engine_retracts_gauges(params):
    eng = _engine(params)
    assert gauge_get("GAUGE_autotune_active") == 1
    assert gauge_get("GAUGE_autotune_trials") >= 2
    plain = _engine(params, autotune=False, prefill_chunk=4)
    pool = GenerationPool(plain, _start=False)
    try:
        gauge_set("GAUGE_autotune_active", 1)
        gauge_set("GAUGE_autotune_step_time_us", 123.0)
        gauge_set("GAUGE_autotune_trials", 9)
        pool._reset_engine()
        assert gauge_get("GAUGE_autotune_active") == 0
        assert gauge_get("GAUGE_autotune_step_time_us") == 0
        assert gauge_get("GAUGE_autotune_trials") == 0
    finally:
        pool.close()


# ---------------------------------------------------------------------------
# kernel_form override is trace-scoped, not process-global
# ---------------------------------------------------------------------------

def test_kernel_form_override_scoped_and_restored():
    assert pa.resolved_form() == "reference"
    with pa.kernel_form("pallas"):
        assert pa.resolved_form() == "pallas"
        with pa.kernel_form(None):            # None passes through
            assert pa.resolved_form() == "pallas"
    assert pa.resolved_form() == "reference"


# ---------------------------------------------------------------------------
# Predictor bucket dispatch
# ---------------------------------------------------------------------------

@pytest.fixture
def model_dir(tmp_path):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [6])
        h = layers.fc(x, 16, act="relu")
        y = layers.fc(h, 3, name="out")
    exe = pt.Executor()
    exe.run(startup)
    d = str(tmp_path / "model")
    pt.io.save_inference_model(d, ["x"], [y], exe, main_program=main)
    return d


def test_predictor_bucket_dispatch_tunes_then_one_lookup(
        model_dir, tmp_path):
    cache = str(tmp_path / "pcache")
    cfg = Config(model_dir)
    cfg.switch_shape_bucketing(True, buckets=[1, 2, 4, 8])
    cfg.switch_autotune(True)
    cfg.enable_program_cache(cache)
    p = create_predictor(cfg)
    feed = np.random.RandomState(0).randn(3, 6).astype(np.float32)
    t0 = stat_get("STAT_autotune_trials")
    out1 = p.run([feed])[0]
    assert stat_get("STAT_autotune_trials") > t0
    snap = [s for s in autotune.policies() if s["kind"] == "predictor"]
    assert snap and snap[0]["form"] in ("bucket", "exact")
    assert snap[0]["rows"] == 3 and snap[0]["bucket"] == 4
    # steady state: policy hit, zero new trials, bitwise-stable output
    t1 = stat_get("STAT_autotune_trials")
    h0 = stat_get("STAT_autotune_cache_hits")
    out2 = p.run([feed])[0]
    assert stat_get("STAT_autotune_trials") == t1
    assert stat_get("STAT_autotune_cache_hits") == h0 + 1
    assert np.array_equal(out1, out2)
    # restart: a fresh predictor reloads the persisted winner
    autotune.reset()
    p2 = create_predictor(cfg)
    t2 = stat_get("STAT_autotune_trials")
    out3 = p2.run([feed])[0]
    assert stat_get("STAT_autotune_trials") == t2
    assert np.array_equal(out1, out3)


def test_predictor_autotune_off_never_tunes(model_dir):
    cfg = Config(model_dir)
    cfg.switch_shape_bucketing(True, buckets=[1, 2, 4, 8])
    p = create_predictor(cfg)
    t0 = stat_get("STAT_autotune_trials")
    p.run([np.zeros((3, 6), np.float32)])
    assert stat_get("STAT_autotune_trials") == t0


def test_predictor_reference_fault_keeps_bucket_form(model_dir):
    cfg = Config(model_dir)
    cfg.switch_shape_bucketing(True, buckets=[1, 2, 4, 8])
    cfg.switch_autotune(True)
    cfg.disable_program_cache()
    p = create_predictor(cfg)
    failpoints.arm("autotune.measure", "raise", "once")
    out = p.run([np.ones((3, 6), np.float32)])[0]
    assert out.shape[0] == 3
    assert autotune.policies() == []          # nothing installed
    failpoints.disarm()
    # exact-row b==bucket shapes never consult the policy at all
    t0 = stat_get("STAT_autotune_trials")
    p.run([np.ones((4, 6), np.float32)])
    assert stat_get("STAT_autotune_trials") == t0


# ---------------------------------------------------------------------------
# /statusz section
# ---------------------------------------------------------------------------

def test_statusz_autotune_section(params):
    from paddle_tpu import introspect
    _engine(params, prefill_chunk=4)
    s = introspect.statusz()["autotune"]
    assert set(s) >= {"enabled", "policies", "trials", "wins",
                      "cache_hits", "fallbacks"}
    assert s["trials"] >= 2 and s["wins"] >= 1
    forms = [p["form"] for p in s["policies"]]
    assert any("bs4" in f for f in forms)


# ---------------------------------------------------------------------------
# stat_diff cost family
# ---------------------------------------------------------------------------

def test_stat_diff_flags_retuning_loop_not_cache_hits():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "stat_diff", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "stat_diff.py"))
    sd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sd)
    assert sd._is_cost_counter("STAT_autotune_trials")
    assert sd._is_cost_counter("STAT_autotune_wins")
    assert sd._is_cost_counter("STAT_autotune_fallbacks")
    assert not sd._is_cost_counter("STAT_autotune_cache_hits")
