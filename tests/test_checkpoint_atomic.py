"""Crash-safe training: AtomicCheckpointer + auto-resume (ISSUE 9,
docs/robustness.md).

Covers the atomic commit protocol (tmp+fsync+rename payload, manifest
written AFTER the payload as the commit record), corrupt/torn-latest
fallback with STAT_checkpoint_corrupt_fallback, retention, and the
kill-and-resume pins: a TrainStep.run_loop (and a hapi Model.fit)
killed mid-run by an injected trainstep.step fault auto-resumes from
the newest valid checkpoint and finishes with BITWISE-identical state
to an uninterrupted run — params, optimizer slots, lr step, and the
host PRNG chain all restored.
"""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import failpoints
from paddle_tpu.failpoints import InjectedFault
from paddle_tpu.incubate.checkpoint import (AtomicCheckpointer,
                                            CheckpointCorrupt)
from paddle_tpu.monitor import stat_get


@pytest.fixture(autouse=True)
def _disarm_all():
    failpoints.disarm()
    yield
    failpoints.disarm()


@pytest.fixture
def flag_guard():
    from paddle_tpu import flags as F
    saved = dict(F._values)
    yield
    F._values.clear()
    F._values.update(saved)


def _arrays(seed, scale=1.0):
    rng = np.random.RandomState(seed)
    return {"w": (rng.randn(4, 3) * scale).astype(np.float32),
            "opt//w//velocity": rng.randn(4, 3).astype(np.float32),
            "lr_step": np.asarray(seed)}


def _assert_bitwise(a, b):
    assert sorted(a) == sorted(b)
    for k in a:
        assert np.asarray(a[k]).tobytes() == np.asarray(b[k]).tobytes(), k


# ---------------------------------------------------------------------------
# AtomicCheckpointer
# ---------------------------------------------------------------------------

def test_roundtrip_retention_and_manifest(tmp_path):
    ck = AtomicCheckpointer(str(tmp_path), keep=2)
    for step in (1, 2, 3):
        ck.save(step, _arrays(step))
    assert ck.steps() == [2, 3]  # keep=2 pruned step 1
    step, arrays, manifest = ck.load_latest()
    assert step == 3 and manifest["step"] == 3
    assert manifest["arrays"] == sorted(_arrays(3))
    _assert_bitwise(arrays, _arrays(3))


def test_commit_fsyncs_payload_rename_and_directory(tmp_path, monkeypatch):
    """Durability regression pin for _atomic_write: each publish must
    fsync the tmp file BEFORE the rename and fsync the DIRECTORY after
    it (a rename without the directory fsync can vanish on power loss
    — the payload would survive but the commit record could not be
    trusted), and the payload must be published before the manifest
    (the manifest is the commit record)."""
    import stat as stat_mod
    events = []
    real_fsync, real_replace = os.fsync, os.replace

    def rec_fsync(fd):
        kind = "dir" if stat_mod.S_ISDIR(os.fstat(fd).st_mode) else "file"
        events.append(("fsync", kind))
        return real_fsync(fd)

    def rec_replace(src, dst):
        events.append(("replace", os.path.basename(dst)))
        return real_replace(src, dst)

    monkeypatch.setattr(os, "fsync", rec_fsync)
    monkeypatch.setattr(os, "replace", rec_replace)
    AtomicCheckpointer(str(tmp_path)).save(1, _arrays(1))
    assert events == [
        ("fsync", "file"), ("replace", "ckpt_00000001.npz"),
        ("fsync", "dir"),
        ("fsync", "file"), ("replace", "ckpt_00000001.json"),
        ("fsync", "dir"),
    ]


def test_load_latest_none_on_empty(tmp_path):
    assert AtomicCheckpointer(str(tmp_path)).load_latest() is None
    assert AtomicCheckpointer(str(tmp_path / "nonexistent")) \
        .load_latest() is None


def test_payload_without_manifest_is_uncommitted(tmp_path):
    """The manifest is the commit record: a payload whose manifest
    never landed (crash between the two writes) must be invisible."""
    ck = AtomicCheckpointer(str(tmp_path))
    ck.save(1, _arrays(1))
    ck.save(2, _arrays(2))
    os.unlink(ck._manifest_path(2))
    assert ck.steps() == [1]
    step, arrays, _m = ck.load_latest()
    assert step == 1
    _assert_bitwise(arrays, _arrays(1))


def test_torn_write_falls_back_to_previous_step(tmp_path):
    """checkpoint.save=truncate tears the payload BEFORE it is
    fingerprinted — the manifest commits unreadable bytes, the worst
    crash shape. load_latest must skip it, count the fallback, and
    serve the previous step."""
    ck = AtomicCheckpointer(str(tmp_path))
    ck.save(1, _arrays(1))
    with failpoints.armed("checkpoint.save=truncate@once"):
        ck.save(2, _arrays(2))
    f0 = stat_get("STAT_checkpoint_corrupt_fallback")
    step, arrays, _m = ck.load_latest()
    assert step == 1
    _assert_bitwise(arrays, _arrays(1))
    assert stat_get("STAT_checkpoint_corrupt_fallback") == f0 + 1


def test_corrupt_on_load_falls_back(tmp_path):
    ck = AtomicCheckpointer(str(tmp_path))
    ck.save(1, _arrays(1))
    ck.save(2, _arrays(2))
    f0 = stat_get("STAT_checkpoint_corrupt_fallback")
    # @once: the newest payload reads corrupt (fingerprint mismatch),
    # the retry on step 1 reads clean
    with failpoints.armed("checkpoint.load=corrupt@once"):
        step, arrays, _m = ck.load_latest()
    assert step == 1
    _assert_bitwise(arrays, _arrays(1))
    assert stat_get("STAT_checkpoint_corrupt_fallback") == f0 + 1


def test_raises_when_no_checkpoint_validates(tmp_path):
    ck = AtomicCheckpointer(str(tmp_path))
    with failpoints.armed("checkpoint.save=truncate"):
        ck.save(1, _arrays(1))
    with pytest.raises(CheckpointCorrupt):
        ck.load_latest()


# ---------------------------------------------------------------------------
# kill-and-resume: TrainStep.run_loop
# ---------------------------------------------------------------------------

def _make_step(seed=11):
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.nn import functional as F
    pt.seed(seed)
    model = nn.Linear(4, 2)
    opt = pt.optimizer.SGD(0.1, parameters=model.parameters())

    def loss_fn(logits, label):
        return F.cross_entropy(logits, label, reduction="mean")

    return TrainStep(model, loss_fn, opt)


def _batches(n, seed=3):
    # the resume contract assumes a DETERMINISTIC batch stream: the
    # fast-forward replays the first k batches without dispatching
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        x = rng.rand(8, 4).astype(np.float32)
        y = rng.randint(0, 2, (8, 1)).astype(np.int64)
        out.append(([x], [y]))
    return out


def test_trainstep_kill_and_resume_bitwise(flag_guard, tmp_path):
    # reference: 8 uninterrupted steps
    step_a = _make_step()
    for h in step_a.run_loop(_batches(8), window=2):
        h.block_until_ready()
    ref = step_a.state_snapshot()

    ckdir = str(tmp_path / "ck")
    pt.set_flags({"FLAGS_auto_checkpoint_steps": 2,
                  "FLAGS_checkpoint_dir": ckdir})

    # "crash" at step 6 (checkpoints committed at steps 2 and 4)
    step_b = _make_step()
    with failpoints.armed("trainstep.step=raise@after(5)"):
        with pytest.raises(InjectedFault):
            for h in step_b.run_loop(_batches(8), window=2):
                h.block_until_ready()
    assert AtomicCheckpointer(ckdir).steps()[-1] == 4

    # a FRESH process (fresh TrainStep) auto-resumes from step 4,
    # fast-forwards the batch stream, and finishes steps 5..8
    r0 = stat_get("STAT_checkpoint_resumes")
    step_c = _make_step(seed=99)  # different init: must not matter
    losses = [np.asarray(h)
              for h in step_c.run_loop(_batches(8), window=2)]
    assert stat_get("STAT_checkpoint_resumes") == r0 + 1
    assert len(losses) == 4  # steps 1..4 skipped without dispatch
    _assert_bitwise(step_c.state_snapshot(), ref)


def test_trainstep_resume_survives_torn_latest(flag_guard, tmp_path):
    """Crash DURING a checkpoint write: the torn step-6 checkpoint
    must fall back to the committed step-4 one and still converge to
    the uninterrupted run's bits."""
    step_a = _make_step()
    for h in step_a.run_loop(_batches(8), window=2):
        h.block_until_ready()
    ref = step_a.state_snapshot()

    ckdir = str(tmp_path / "ck")
    pt.set_flags({"FLAGS_auto_checkpoint_steps": 2,
                  "FLAGS_checkpoint_dir": ckdir})
    step_b = _make_step()
    with failpoints.armed("checkpoint.save=truncate@after(2)"):
        for h in step_b.run_loop(_batches(6), window=2):
            h.block_until_ready()
    # steps 2,4 committed clean; step 6's payload is torn on disk
    assert AtomicCheckpointer(ckdir).steps()[-1] == 6

    f0 = stat_get("STAT_checkpoint_corrupt_fallback")
    step_c = _make_step()
    for h in step_c.run_loop(_batches(8), window=2):
        h.block_until_ready()
    assert stat_get("STAT_checkpoint_corrupt_fallback") > f0
    _assert_bitwise(step_c.state_snapshot(), ref)


# ---------------------------------------------------------------------------
# kill-and-resume: hapi Model.fit
# ---------------------------------------------------------------------------

def _hapi_model(seed=7):
    from paddle_tpu import nn

    class _Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Linear(4, 16)
            self.l2 = nn.Linear(16, 2)

        def forward(self, x):
            import paddle_tpu.nn.functional as F
            return self.l2(F.relu(self.l1(x)))

    def ce_loss(logits, label):
        import paddle_tpu.nn.functional as F
        return F.cross_entropy(logits, label)

    pt.seed(seed)
    model = pt.Model(_Net())
    model.prepare(pt.optimizer.SGD(0.05,
                                   parameters=model.parameters()),
                  ce_loss)
    return model


def _hapi_data(n=64, seed=0):
    from paddle_tpu.reader import TensorDataset
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int64).reshape(-1, 1)
    return TensorDataset(x, y)


def test_hapi_fit_kill_and_resume_bitwise(flag_guard, tmp_path):
    # resume requires a deterministic loader: shuffle=False
    fit_kw = dict(batch_size=16, epochs=2, verbose=0, shuffle=False)
    ds = _hapi_data()

    model_a = _hapi_model()
    model_a.fit(ds, **fit_kw)
    ref = model_a._train_step.state_snapshot()

    ckdir = str(tmp_path / "ck")
    pt.set_flags({"FLAGS_auto_checkpoint_steps": 2,
                  "FLAGS_checkpoint_dir": ckdir})

    # 4 steps/epoch x 2 epochs; crash at global step 6 of 8
    model_b = _hapi_model()
    with failpoints.armed("trainstep.step=raise@after(5)"):
        with pytest.raises(InjectedFault):
            model_b.fit(ds, **fit_kw)
    assert AtomicCheckpointer(ckdir).steps()[-1] == 4

    r0 = stat_get("STAT_checkpoint_resumes")
    model_c = _hapi_model(seed=1234)  # init must not matter
    model_c.fit(ds, **fit_kw)
    assert stat_get("STAT_checkpoint_resumes") == r0 + 1
    _assert_bitwise(model_c._train_step.state_snapshot(), ref)
