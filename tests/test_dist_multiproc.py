"""Multi-process distributed runtime tests.

The round-1 gap (VERDICT "What's missing" #1): ranks launched under the
cluster contract never formed a mesh. These tests exercise the real
bootstrap — jax.distributed coordination service + global mesh spanning
two localhost processes — and hold the reference's acceptance bar:
per-step loss parity between the local and the distributed run
(/root/reference/python/paddle/fluid/tests/unittests/
test_dist_base.py:594)."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

RUNNER = os.path.join(os.path.dirname(__file__), "dist_runner.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env(devcount, extra=None):
    env = dict(os.environ)
    env.pop("PADDLE_TRAINER_ID", None)
    env.pop("PADDLE_TRAINERS_NUM", None)
    env.pop("PADDLE_TRAINER_ENDPOINTS", None)
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devcount}"
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    if extra:
        env.update(extra)
    return env


def _parse_losses(out: bytes):
    for line in out.decode().splitlines():
        if line.startswith("LOSSES "):
            return json.loads(line[len("LOSSES "):])
    raise AssertionError(f"no LOSSES line in output:\n{out.decode()}")


def test_dist_vs_local_loss_parity():
    # local: 1 process x 4 devices
    local = subprocess.run([sys.executable, RUNNER, "local"],
                           env=_env(4), capture_output=True, timeout=300)
    assert local.returncode == 0, local.stderr.decode()
    local_losses = _parse_losses(local.stdout)

    # dist: 2 processes x 2 devices = same 4-way dp mesh
    port = _free_port()
    eps = f"127.0.0.1:{port},127.0.0.1:{port + 1}"
    procs = []
    for rank in range(2):
        env = _env(2, {"PADDLE_TRAINER_ID": str(rank),
                       "PADDLE_TRAINERS_NUM": "2",
                       "PADDLE_TRAINER_ENDPOINTS": eps,
                       "TRAINING_ROLE": "TRAINER"})
        procs.append(subprocess.Popen(
            [sys.executable, RUNNER, "dist"], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err.decode()
        outs.append(out)
    dist_losses = _parse_losses(outs[0])

    # the reference's bar: per-step loss parity within delta
    np.testing.assert_allclose(dist_losses, local_losses, atol=1e-5,
                               rtol=1e-5)
    # and training actually progressed
    assert dist_losses[-1] < dist_losses[0]


def test_init_distributed_runtime_requires_contract():
    import paddle_tpu.parallel as dist
    # without env vars and with nprocs<=1 this is a no-op returning False
    assert dist.init_distributed_runtime(num_processes=1) is False


def test_mp_across_processes_loss_parity():
    """Tensor-parallel (mp=4) axis spanning 2 processes vs the same mp
    mesh in one process — round-2 gap: multi-process coverage was dp
    only (VERDICT weak #5)."""
    local = subprocess.run([sys.executable, RUNNER, "mp_local"],
                           env=_env(4), capture_output=True, timeout=300)
    assert local.returncode == 0, local.stderr.decode()
    ref = _parse_losses(local.stdout)

    port = _free_port()
    eps = f"127.0.0.1:{port},127.0.0.1:{port + 1}"
    procs = []
    for rank in range(2):
        env = _env(2, {"PADDLE_TRAINER_ID": str(rank),
                       "PADDLE_TRAINERS_NUM": "2",
                       "PADDLE_TRAINER_ENDPOINTS": eps,
                       "TRAINING_ROLE": "TRAINER"})
        procs.append(subprocess.Popen(
            [sys.executable, RUNNER, "mp"], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err.decode()
        outs.append(out)
    got = _parse_losses(outs[0])
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)
    assert got[-1] < got[0]


def test_rank_failure_kills_pod():
    """When one rank dies mid-run the launch watchdog must kill the
    surviving ranks and report failure (fleet/launch.py; reference
    launch_utils.py TrainerProc watchdog)."""
    env = _env(2)
    # 420s budget: the rank processes each import jax from scratch,
    # which under an oversubscribed -n 8 host can take minutes before
    # the watchdog even gets a chance to observe the rank-1 death
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.fleet.launch",
         "--nproc_per_node", "2", RUNNER, "die"],
        env=env, capture_output=True, timeout=420)
    # rank 1 exits 17; the watchdog must kill hanging rank 0 and
    # report a nonzero pod exit — NOT run the full 120s sleep
    assert r.returncode != 0, r.stdout.decode() + r.stderr.decode()
