"""Quantized gradient collectives (ISSUE 17, docs/spmd.md "Quantized
collectives"): the int8 block-scaled exchange wire format and its
scale contract, bucket planning, the TrainStep threading behind
FLAGS_collective_quant (off = legacy, fp32 = explicit synchronous
oracle, int8 = accumulate-then-quantized-exchange), grad accumulation
with clip-on-the-averaged-gradient, the dist.collective_quant
failpoint's per-bucket fp32 fallback, AOT fingerprint isolation, and
the bytes-by-dtype census on /statusz."""
import contextlib
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import failpoints
from paddle_tpu.flags import get_flag, set_flags
from paddle_tpu.jit import TrainStep
from paddle_tpu.mesh import ShardingPlan
from paddle_tpu.mesh import collectives as coll
from paddle_tpu.mesh import compat as _compat
from paddle_tpu.monitor import reset_all, snapshot, stat_get


@contextlib.contextmanager
def _flags(**kv):
    old = {k: get_flag(k) for k in kv}
    set_flags(kv)
    try:
        yield
    finally:
        set_flags(old)


def _ts_loss(out, label):
    import paddle_tpu.nn.functional as F
    return F.cross_entropy(out, label)


def _build_step(mode, seed=42, accum=1, hidden=64, min_numel=16):
    from paddle_tpu import nn
    pt.dygraph.seed(seed)
    np.random.seed(seed)
    m = nn.Sequential(nn.Linear(8, hidden), nn.ReLU(),
                      nn.Linear(hidden, 4))
    o = pt.optimizer.SGD(0.1, parameters=m.parameters())
    set_flags({"FLAGS_collective_quant": mode,
               "FLAGS_collective_quant_min_numel": min_numel})
    return TrainStep(m, _ts_loss, o, plan=ShardingPlan("dp4"),
                     grad_accum_steps=accum)


def _run(step, steps=5, batch=16, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        x = rng.randn(batch, 8).astype(np.float32)
        y = rng.randint(0, 4, (batch, 1)).astype(np.int32)
        out.append(float(step((x,), (y,))))
    return out


# ---------------------------------------------------------------------------
# scale contract / wire format
# ---------------------------------------------------------------------------

def _wire_roundtrip(x_global, in_spec):
    """Run the int8 exchange over dp4 and return the per-rank result
    stack."""
    import jax
    from jax.sharding import PartitionSpec as P
    plan = coll.plan_buckets({"w": (1, x_global.shape[-1])}, "dp", 4,
                             mode="int8", bucket_mb=4, min_numel=1)
    (bucket,) = plan.buckets

    def body(x):
        flat = coll.bucket_concat([x.reshape(-1)], bucket)
        out = coll.exchange_bucket(flat, bucket, plan)
        return coll.bucket_split(out, bucket)[0]

    mesh = ShardingPlan("dp4").mesh
    f = _compat.shard_map(body, mesh=mesh, in_specs=in_spec,
                          out_specs=P(), check_vma=False)
    return np.asarray(jax.jit(f)(x_global)).ravel()


def test_wire_roundtrip_replicated_is_quantize_dequantize():
    """With the same value on every rank, the full wire (shared-scale
    quantize -> int8 ReduceScatter -> requantize -> AllGather ->
    dequant) must collapse to one quantize/dequantize round trip: the
    integer shard sum is exact and the mean requantizes losslessly
    onto the same grid."""
    from jax.sharding import PartitionSpec as P
    rng = np.random.RandomState(7)
    x = (rng.randn(coll.BLOCK * 8) * 3.0).astype(np.float32)
    got = _wire_roundtrip(x, P())
    # reference: per-block absmax contract from quant/ (PR 15)
    blocks = x.reshape(-1, coll.BLOCK)
    s = np.abs(blocks).max(axis=1)
    s = np.where(s > 0.0, s, 1.0)
    ref = (np.round(blocks * (127.0 / s[:, None])) *
           (s[:, None] / 127.0)).reshape(-1)
    np.testing.assert_allclose(got, ref, rtol=0, atol=1e-6)
    # quantization error bounded by half a grid step per block
    assert np.max(np.abs(got - x)) <= (s.max() / 127.0) * 0.5 + 1e-6


def test_wire_dead_block_guard_exact_zeros():
    """An all-zero scale block must round-trip to EXACT zeros: the
    dead-block guard pins its divisor to 1.0 before the store (the
    PR-15 contract), so no 0/0 NaN can enter the gradient stream."""
    from jax.sharding import PartitionSpec as P
    x = np.zeros(coll.BLOCK * 4, np.float32)
    x[coll.BLOCK:2 * coll.BLOCK] = 1.5  # one live block among dead ones
    got = _wire_roundtrip(x, P())
    assert np.all(np.isfinite(got))
    assert np.all(got[:coll.BLOCK] == 0.0)
    assert np.all(got[2 * coll.BLOCK:] == 0.0)


def test_wire_rank_varying_mean_within_grid_error():
    """Rank-varying inputs: the exchange must return the cross-rank
    mean within the shared-scale int8 grid error."""
    from jax.sharding import PartitionSpec as P
    rng = np.random.RandomState(11)
    x = rng.randn(4, coll.BLOCK * 4).astype(np.float32)
    got = _wire_roundtrip(x, P("dp"))
    want = x.mean(axis=0)
    # two rounding stages (per-rank quantize + requantized mean), each
    # at most half a step of the shared per-block grid
    step = np.abs(x).max() / 127.0
    assert np.max(np.abs(got - want)) <= 1.5 * step


# ---------------------------------------------------------------------------
# bucket planning
# ---------------------------------------------------------------------------

_SHAPES = {
    "layer0.w": (256, 64), "layer0.b": (64,),
    "layer1.w": (512, 512), "layer1.b": (512,),
    "head.w": (512, 128), "tiny.w": (4, 4),
}


def test_plan_small_grad_fp32_fallback_threshold():
    plan = coll.plan_buckets(_SHAPES, "dp", 4, mode="int8", bucket_mb=4,
                             min_numel=2048)
    small = dict(plan.small)
    # 1-D always small; below-threshold 2-D small; the rest bucketed
    assert set(small) == {"layer0.b", "layer1.b", "tiny.w"}
    bucketed = [n for b in plan.buckets for n in b.names]
    assert set(bucketed) == {"layer0.w", "layer1.w", "head.w"}
    assert all(b.quantized for b in plan.buckets)
    # raising the threshold demotes more tensors to the fp32 path
    plan2 = coll.plan_buckets(_SHAPES, "dp", 4, mode="int8", bucket_mb=4,
                              min_numel=100_000)
    assert [n for b in plan2.buckets for n in b.names] == ["layer1.w"]


def test_plan_deterministic_reverse_order_and_cap():
    a = coll.plan_buckets(_SHAPES, "dp", 4, mode="int8", bucket_mb=1,
                          min_numel=2048)
    b = coll.plan_buckets(_SHAPES, "dp", 4, mode="int8", bucket_mb=1,
                          min_numel=2048)
    assert a == b  # pure function of (shapes, axis, flags)
    # reverse-topological: last-constructed big tensor leads bucket 0
    assert a.buckets[0].names[0] == "head.w"
    cap = 1 * (1 << 20) // 4
    for bk in a.buckets:
        assert bk.numel <= cap or len(bk.names) == 1
        assert bk.padded % (coll.BLOCK * 4) == 0
        assert bk.padded >= bk.numel
    # every tensor lands exactly once
    names = [n for bk in a.buckets for n in bk.names] + \
        [n for n, _ in a.small]
    assert sorted(names) == sorted(_SHAPES)


def test_plan_fp32_mode_never_quantizes():
    plan = coll.plan_buckets(_SHAPES, "dp", 4, mode="fp32", bucket_mb=4,
                             min_numel=2048)
    assert plan.buckets and not any(b.quantized for b in plan.buckets)


# ---------------------------------------------------------------------------
# failpoint: per-bucket fp32 fallback (mirrors generation.kv_quant)
# ---------------------------------------------------------------------------

def test_collective_quant_failpoint_demotes_one_bucket():
    assert "dist.collective_quant" in failpoints.KNOWN_SITES
    shapes = {"a.w": (600, 512), "b.w": (600, 512)}  # 2 buckets @ 1MiB
    f0 = stat_get("STAT_collective_quant_fallbacks")
    failpoints.arm_spec("dist.collective_quant=raise@once")
    try:
        plan = coll.plan_buckets(shapes, "dp", 4, mode="int8",
                                 bucket_mb=1, min_numel=2048)
    finally:
        failpoints.disarm("dist.collective_quant")
    # the faulted bucket fell back to fp32; the other stayed quantized
    assert [b.quantized for b in plan.buckets] == [False, True]
    assert stat_get("STAT_collective_quant_fallbacks") == f0 + 1
    # disarmed: both quantize
    plan2 = coll.plan_buckets(shapes, "dp", 4, mode="int8",
                              bucket_mb=1, min_numel=2048)
    assert all(b.quantized for b in plan2.buckets)
    assert stat_get("STAT_collective_quant_fallbacks") == f0 + 1


def test_collective_quant_fault_step_still_converges():
    """Every bucket demoted by an armed fault -> the step runs the
    fp32 exchange and produces the SAME losses as the explicit fp32
    oracle (with accum=1 their traces coincide)."""
    with _flags(FLAGS_collective_quant="off",
                FLAGS_collective_quant_min_numel=2048):
        oracle = _run(_build_step("fp32"))
        failpoints.arm_spec("dist.collective_quant=raise")
        try:
            step = _build_step("int8")
            faulted = _run(step)
        finally:
            failpoints.disarm("dist.collective_quant")
        assert step._coll_manifest["buckets"] == 0  # nothing quantized
        assert all(np.isfinite(faulted))
        np.testing.assert_allclose(faulted, oracle, rtol=0, atol=1e-6)
        # fp32 engines never reach the site: armed 'raise' cannot fire
        failpoints.arm_spec("dist.collective_quant=raise")
        try:
            assert all(np.isfinite(_run(_build_step("fp32"), steps=2)))
        finally:
            failpoints.disarm("dist.collective_quant")


# ---------------------------------------------------------------------------
# TrainStep threading: modes, trajectory, recompiles, census
# ---------------------------------------------------------------------------

def test_trainstep_off_mode_untouched_and_uninstrumented():
    with _flags(FLAGS_collective_quant="off"):
        reset_all()
        s1 = _build_step("off")
        l1 = _run(s1)
        l2 = _run(_build_step("off"))
        assert l1 == l2  # deterministic legacy path
        assert s1._coll_manifest is None  # no manifest, no census
        snap = snapshot()
        assert not any("collective_quant" in k
                       for k in snap["counters"])
        assert not any(k.startswith("GAUGE_collective_quant")
                       for k in snap["gauges"])


def test_trainstep_int8_trajectory_and_zero_recompiles():
    with _flags(FLAGS_collective_quant="off",
                FLAGS_collective_quant_min_numel=16):
        reset_all()
        oracle = _run(_build_step("fp32"), steps=8)
        step = _build_step("int8")
        got = _run(step, steps=8)
        # int8 grads diverge only within the quantization error budget
        diff = max(abs(a - b) for a, b in zip(got, oracle))
        assert diff < 5e-3, (diff, got, oracle)
        assert step._step_fn._cache_size() == 1  # zero steady-state
        m = step._coll_manifest
        assert m["axis"] == "dp" and m["buckets"] >= 1
        assert m["bytes"]["int8"] > 0
        # fp32 oracle manifest carries no int8 wire at all
        fp_step = _build_step("fp32")
        fp_step._build()
        fp = fp_step._coll_manifest
        assert "int8" not in fp["bytes"] and fp["buckets"] == 0


def test_census_bytes_shrink_3x_on_bert_scale_shapes():
    """The >=3x wire-byte claim (ISSUE 17 acceptance) holds at
    realistic gradient sizes where the BLOCK*dp bucket padding is
    amortized — BERT-base-ish matrices, not toy Linear layers. (The
    executed-census version of this gate runs in bench.py's
    quantized_collectives block and the run_spmd_tests.sh smoke.)"""
    shapes = {}
    for i in range(12):
        shapes["l%d.qkv" % i] = (768, 2304)
        shapes["l%d.out" % i] = (768, 768)
        shapes["l%d.ffn_in" % i] = (768, 3072)
        shapes["l%d.ffn_out" % i] = (3072, 768)
        shapes["l%d.ln_g" % i] = (768,)
    kw = dict(bucket_mb=4, min_numel=2048)
    b8 = coll.census_bytes(
        coll.plan_buckets(shapes, "dp", 4, mode="int8", **kw))
    b32 = coll.census_bytes(
        coll.plan_buckets(shapes, "dp", 4, mode="fp32", **kw))
    assert sum(b32.values()) >= 3 * sum(b8.values()), (b32, b8)


def test_trainstep_census_and_statusz_section():
    with _flags(FLAGS_collective_quant="off",
                FLAGS_collective_quant_min_numel=16):
        reset_all()
        with _flags(FLAGS_collective_quant="int8"):
            step = _build_step("int8")
            _run(step, steps=3)
            from paddle_tpu.introspect import statusz
            sz = statusz()["mesh"]["collectives"]
            assert sz["quant"]["mode"] == "int8"
        assert sz["ops"].get("dp", 0) > 0
        assert sz["bytes"]["dp"]["int8"] == 3 * \
            step._coll_manifest["bytes"]["int8"]
        assert sz["quant"]["buckets"] >= 1
        assert sz["quant"]["bucket_exchanges"] == 3 * \
            step._coll_manifest["buckets"]
        assert sz["quant"]["fallbacks"] == 0
        # gauges retract when the step rebuilds with the flag off
        _build_step("off")._build()
        assert "GAUGE_collective_quant_buckets" not in \
            snapshot()["gauges"]


def test_host_collective_bytes_census_by_dtype():
    """Satellite: parallel/collective.py host-level calls count wire
    bytes by dtype under the same ring model."""
    import paddle_tpu.parallel as dist
    from paddle_tpu.mesh import use_plan
    reset_all()
    with use_plan(ShardingPlan("dp4")):
        x = np.ones((8, 4), np.float32)
        dist.all_reduce(x)
    key = 'STAT_mesh_collective_bytes{axis="dp",dtype="float32"}'
    # AllReduce rings twice: 2 * 128B * 3/4
    assert stat_get(key) == 2 * x.nbytes * 3 / 4


# ---------------------------------------------------------------------------
# grad accumulation: clip applies to the AVERAGED gradient
# ---------------------------------------------------------------------------

def _mse(out, label):
    d = out - label
    return (d * d).mean()


def _clip_step(accum, seed=5):
    from paddle_tpu import nn
    from paddle_tpu.optimizer import GradientClipByGlobalNorm
    pt.dygraph.seed(seed)
    np.random.seed(seed)
    m = nn.Linear(8, 4)
    o = pt.optimizer.SGD(
        0.2, parameters=m.parameters(),
        grad_clip=GradientClipByGlobalNorm(0.05))
    return TrainStep(m, _mse, o, grad_accum_steps=accum)


def test_grad_accum_clip_matches_big_batch():
    """grad_accum_steps=4 must match the equivalent big-batch step:
    global-norm clipping applies once to the averaged accumulated
    gradient — clipping per microbatch would rescale each microbatch
    by its own norm and the trajectories would split immediately (the
    0.05 clip_norm is tight enough that clipping is ACTIVE here)."""
    rng = np.random.RandomState(2)
    xs = [rng.randn(16, 8).astype(np.float32) for _ in range(4)]
    ys = [rng.randn(16, 4).astype(np.float32) for _ in range(4)]
    big = _clip_step(accum=1)
    acc = _clip_step(accum=4)
    for i, (x, y) in enumerate(zip(xs, ys)):
        lb = float(big((x,), (y,)))
        la = float(acc((x,), (y,)))
        assert abs(lb - la) < 1e-5, (i, lb, la)
    # clipping really engaged: an unclipped run must diverge from the
    # clipped one (grad norm of a fresh MSE head >> clip_norm=0.05)
    from paddle_tpu import nn
    pt.dygraph.seed(5)
    np.random.seed(5)
    m = nn.Linear(8, 4)
    o = pt.optimizer.SGD(0.2, parameters=m.parameters())
    unclipped = TrainStep(m, _mse, o, grad_accum_steps=1)
    lu = [float(unclipped((x,), (y,))) for x, y in zip(xs, ys)]
    lc = [float(_clip_step(accum=1)((x,), (y,))) for x, y in zip(xs, ys)]
    assert max(abs(a - b) for a, b in zip(lu, lc)) > 1e-3


def test_grad_accum_under_quantized_modes():
    """Accumulation composes with the explicit-exchange step: fp32
    mode (sync every microbatch) and the off-mode legacy loop agree to
    fp32 tolerance; int8 (one deferred quantized exchange) stays
    within the quantization budget."""
    with _flags(FLAGS_collective_quant="off",
                FLAGS_collective_quant_min_numel=16):
        base = _run(_build_step("off", accum=4), steps=4)
        fp32 = _run(_build_step("fp32", accum=4), steps=4)
        int8 = _run(_build_step("int8", accum=4), steps=4)
    d_fp = max(abs(a - b) for a, b in zip(base, fp32))
    d_i8 = max(abs(a - b) for a, b in zip(base, int8))
    assert d_fp < 1e-4, (d_fp, base, fp32)
    assert d_i8 < 5e-3, (d_i8, base, int8)


def test_grad_accum_rejects_indivisible_batch():
    step = _build_step("off", accum=3)
    with pytest.raises(ValueError, match="does not divide"):
        _run(step, steps=1, batch=16)


# ---------------------------------------------------------------------------
# fingerprint isolation: quant-on/off programs get disjoint AOT entries
# ---------------------------------------------------------------------------

def test_lowering_snapshot_isolates_quant_collectives():
    from paddle_tpu.flags import _LOWERING_FLAGS, lowering_snapshot
    for f in ("FLAGS_collective_quant", "FLAGS_collective_bucket_mb",
              "FLAGS_collective_quant_min_numel"):
        assert f in _LOWERING_FLAGS
    with _flags(FLAGS_collective_quant="off"):
        snap_off = lowering_snapshot()
        with _flags(FLAGS_collective_quant="int8"):
            snap_int8 = lowering_snapshot()
    assert snap_off != snap_int8


def test_program_fingerprint_disjoint_per_mode():
    prog = pt.Program()
    with _flags(FLAGS_collective_quant="off"):
        fp_off = prog.fingerprint(feed_sig=(), fetch_names=())
        with _flags(FLAGS_collective_quant="int8"):
            fp_int8 = prog.fingerprint(feed_sig=(), fetch_names=())
        with _flags(FLAGS_collective_bucket_mb=16):
            fp_bucket = prog.fingerprint(feed_sig=(), fetch_names=())
    assert fp_off and fp_int8 and fp_bucket
    assert len({fp_off, fp_int8, fp_bucket}) == 3


# ---------------------------------------------------------------------------
# stat_diff cost family
# ---------------------------------------------------------------------------

def test_stat_diff_flags_fallbacks_not_buckets():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "stat_diff", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "stat_diff.py"))
    sd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sd)
    assert sd._is_cost_counter("STAT_collective_quant_fallbacks")
    assert not sd._is_cost_counter("STAT_collective_quant_buckets")
    # labeled byte census diffs like its family, not as a cost
    assert not sd._is_cost_counter(
        'STAT_mesh_collective_bytes{axis="dp",dtype="int8"}')


# ---------------------------------------------------------------------------
# 12-layer BERT-shaped trajectory under dp4 (the bench-scale claim)
# ---------------------------------------------------------------------------

@pytest.mark.spmd
@pytest.mark.slow
def test_bert_dp4_fp32_vs_int8_loss_budget():
    """fp32-vs-int8 loss trajectory on a 12-layer BERT-shaped step
    under dp4 — the in-repo version of bench.py's 50-step
    quantized_collectives gate (budget stated in docs/spmd.md)."""
    from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                        pretraining_loss)
    cfg = BertConfig(vocab_size=128, hidden_size=64,
                     num_hidden_layers=12, num_attention_heads=2,
                     intermediate_size=128, max_position_embeddings=32,
                     hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    B, S, steps = 8, 16, 6
    rng = np.random.RandomState(0)
    batches = []
    for _ in range(steps):
        ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
        mlm = np.where(rng.rand(B, S) < 0.15, ids, -100).astype(np.int32)
        nsp = rng.randint(0, 2, (B, 1)).astype(np.int32)
        batches.append((ids, mlm, nsp))

    def run(mode):
        pt.dygraph.seed(0)
        np.random.seed(0)
        model = BertForPretraining(cfg)
        opt = pt.optimizer.Adam(1e-3, parameters=model.parameters())
        with _flags(FLAGS_collective_quant=mode):
            step = TrainStep(model, pretraining_loss, opt,
                             plan=ShardingPlan("dp4"))
            losses = [float(step((ids,), (mlm, nsp)))
                      for ids, mlm, nsp in batches]
        return losses, step._step_fn._cache_size()

    fp32, c_fp = run("fp32")
    int8, c_i8 = run("int8")
    diff = max(abs(a - b) for a, b in zip(fp32, int8))
    assert diff < 0.05, (diff, fp32, int8)
    assert c_fp == 1 and c_i8 == 1  # zero steady-state recompiles
