"""Custom-op loading tests: native .so via the ptcop_* C ABI and
python module loading — the load_op_library mechanism
(/root/reference/paddle/fluid/framework/load_op_lib.h,
pybind.cc:1654; reference test model: tests/custom_op/)."""
import os
import subprocess

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.registry import REGISTRY
from paddle_tpu.custom_op import load_op_library, load_op_module

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "csrc", "custom_op_demo.cc")


@pytest.fixture(scope="module")
def demo_so(tmp_path_factory):
    so = str(tmp_path_factory.mktemp("cop") / "libcustom_op_demo.so")
    subprocess.run(["g++", "-O2", "-shared", "-fPIC", "-o", so, _SRC],
                   check=True)
    return so


def test_load_op_library_registers_and_computes(demo_so):
    added = load_op_library(demo_so)
    assert set(added) == {"custom_axpby", "custom_count_positive"}
    # idempotent reload
    assert load_op_library(demo_so) == added

    from test_op_sweep_r3 import run_op
    x = np.asarray([[1.0, -2.0], [3.0, 4.0]], np.float32)
    y = np.ones((2, 2), np.float32)
    o = run_op("custom_axpby", {"X": [x, y]}, {"alpha": 2.0, "beta": 0.5})
    np.testing.assert_allclose(np.asarray(o["Out"][0]), 2 * x + 0.5 * y)

    o = run_op("custom_count_positive", {"X": [x]}, {})
    assert float(np.asarray(o["Out"][0])[0]) == 3.0


def test_custom_op_in_program(demo_so):
    load_op_library(demo_so)
    main = pt.Program()
    blk = main.global_block
    blk.create_var("a", shape=[2, 2], dtype="float32")
    blk.create_var("b", shape=[2, 2], dtype="float32")
    blk.create_var("o", shape=[2, 2], dtype="float32")
    blk.append_op("custom_axpby", {"X": ["a", "b"]}, {"Out": ["o"]},
                  {"alpha": 3.0, "beta": 1.0})
    exe = pt.Executor()
    a = np.full((2, 2), 2.0, np.float32)
    b = np.full((2, 2), 5.0, np.float32)
    out, = exe.run(main, feed={"a": a, "b": b}, fetch_list=["o"])
    np.testing.assert_allclose(np.asarray(out), 3 * a + b)


def test_load_op_module(tmp_path):
    mod = tmp_path / "my_ops.py"
    mod.write_text(
        "import jax.numpy as jnp\n"
        "from paddle_tpu.core.registry import register_op\n"
        "@register_op('custom_py_square', inputs=('X',))\n"
        "def _sq(ctx, ins, attrs):\n"
        "    return {'Out': [jnp.square(ins['X'][0])]}\n")
    added = load_op_module(str(mod))
    assert added == ["custom_py_square"]
    from test_op_sweep_r3 import run_op
    x = np.asarray([2.0, -3.0], np.float32)
    o = run_op("custom_py_square", {"X": x}, {})
    np.testing.assert_allclose(np.asarray(o["Out"][0]), [4.0, 9.0])
