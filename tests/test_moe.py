"""MoE + expert parallelism: routing semantics vs a per-token oracle,
capacity dropping, and exact sharded-vs-single-device parity on the
virtual ep mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from paddle_tpu.parallel.moe import (init_moe_params, moe_ffn,
                                     moe_ffn_sharded, router_topk)


def _params(E=4, M=8, F=16, seed=0):
    return init_moe_params(jax.random.PRNGKey(seed), E, M, F)


def test_top1_routing_matches_per_token_oracle():
    rng = np.random.RandomState(0)
    T, M, E, F = 6, 8, 4, 16
    p = _params(E, M, F)
    x = jnp.asarray(rng.randn(T, M).astype(np.float32))
    y, aux = moe_ffn(x, p, k=1, capacity=T)  # ample capacity: no drops
    logits = np.asarray(x @ p["router"])
    for t in range(T):
        e = int(np.argmax(logits[t]))
        w_in = np.asarray(p["w_in"][e])
        w_out = np.asarray(p["w_out"][e])
        h = np.asarray(jax.nn.gelu(jnp.asarray(
            np.asarray(x[t]) @ w_in)))
        exp = h @ w_out  # top-1 normalized gate == 1
        np.testing.assert_allclose(np.asarray(y[t]), exp,
                                   rtol=1e-4, atol=1e-5)
    assert float(aux) > 0


def test_top2_gates_normalized_and_combined():
    rng = np.random.RandomState(1)
    T, M, E, F = 5, 8, 4, 16
    p = _params(E, M, F, seed=1)
    x = jnp.asarray(rng.randn(T, M).astype(np.float32))
    y, _ = moe_ffn(x, p, k=2, capacity=T)
    logits = np.asarray(x @ p["router"])
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    for t in range(T):
        top2 = np.argsort(-probs[t])[:2]
        g = probs[t][top2] / probs[t][top2].sum()
        exp = 0.0
        for gi, e in zip(g, top2):
            h = np.asarray(jax.nn.gelu(jnp.asarray(
                np.asarray(x[t]) @ np.asarray(p["w_in"][int(e)]))))
            exp = exp + gi * (h @ np.asarray(p["w_out"][int(e)]))
        np.testing.assert_allclose(np.asarray(y[t]), exp,
                                   rtol=1e-4, atol=1e-5)


def test_capacity_overflow_drops_tokens():
    # route everything to one expert with capacity 2: tokens 3.. get 0
    T, M, E = 6, 4, 2
    p = _params(E, M, 8)
    # router forced: huge logit on expert 0
    p = dict(p)
    p["router"] = jnp.zeros((M, E)).at[:, 0].set(100.0)
    x = jnp.ones((T, M), jnp.float32)
    y, _ = moe_ffn(x, p, k=1, capacity=2)
    assert not np.allclose(np.asarray(y[0]), 0)
    np.testing.assert_allclose(np.asarray(y[2:]), 0.0, atol=1e-7)


def test_dispatch_combine_shapes_and_mass():
    dispatch, combine, (me, ce) = router_topk(
        jnp.asarray(np.random.RandomState(0).randn(10, 4)), 2, 8)
    assert dispatch.shape == (10, 4, 8) and combine.shape == (10, 4, 8)
    # tokens whose BOTH choices were kept carry combine mass exactly 1;
    # partially-dropped tokens carry strictly less
    mass = np.asarray(combine.sum(axis=(1, 2)))
    kept = np.asarray(dispatch.sum(axis=(1, 2)))
    assert np.all(np.abs(mass[kept == 2] - 1) < 1e-5)
    assert np.all(mass[kept < 2] < 1 - 1e-7) or np.all(kept == 2)


def _ep_mesh(n=4):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip("needs the virtual multi-device mesh")
    return Mesh(np.array(devs[:n]), ("ep",))


def test_sharded_matches_single_device_exactly():
    mesh = _ep_mesh(4)
    rng = np.random.RandomState(2)
    T, M, E, F = 16, 8, 4, 16
    p = _params(E, M, F, seed=2)
    x = jnp.asarray(rng.randn(T, M).astype(np.float32))
    # ample capacity so neither path drops: per-shard C = t_local
    y_ref, aux_ref = moe_ffn(x, p, k=2, capacity=T)
    y_sh, aux_sh = moe_ffn_sharded(x, p, mesh, "ep", k=2,
                                   capacity=T // 4)
    np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux_sh), float(aux_ref), rtol=1e-5)


def test_sharded_grads_flow_to_experts():
    mesh = _ep_mesh(4)
    rng = np.random.RandomState(3)
    T, M, E, F = 16, 8, 4, 16
    p = _params(E, M, F, seed=3)
    x = jnp.asarray(rng.randn(T, M).astype(np.float32))

    def loss(p):
        y, aux = moe_ffn_sharded(x, p, mesh, "ep", k=2, capacity=4)
        return jnp.sum(y ** 2) + 0.01 * aux

    g = jax.grad(loss)(p)
    for name in ("router", "w_in", "w_out"):
        arr = np.asarray(g[name])
        assert np.isfinite(arr).all(), name
        assert np.abs(arr).sum() > 0, name


def test_nn_moe_layer_trains_with_aux_loss():
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from paddle_tpu.dygraph import tape
    tape.seed(7)
    layer = nn.MoELayer(8, 16, num_experts=4, k=2)
    opt = pt.optimizer.Adam(1e-2, parameters=layer.parameters())
    rng = np.random.RandomState(5)
    x = rng.randn(2, 6, 8).astype(np.float32)
    target = rng.randn(2, 6, 8).astype(np.float32)
    l0 = None
    g_router = None
    for _ in range(15):
        out = layer(pt.to_tensor(x))
        loss = ((out - pt.to_tensor(target)) ** 2).mean() \
            + 0.01 * layer.aux_loss
        loss.backward()
        # snapshot BEFORE clear_grad: router must participate in
        # training (combine-weight + aux-loss gradients)
        g_router = np.asarray(layer.router.gradient)
        opt.step()
        opt.clear_grad()
        l0 = float(loss) if l0 is None else l0
    assert float(loss) < l0, (l0, float(loss))
    assert np.isfinite(g_router).all() and np.abs(g_router).sum() > 0
    assert out.shape == (2, 6, 8)
