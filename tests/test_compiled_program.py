"""CompiledProgram data-parallel tests on the 8-device CPU mesh.

Mirrors the reference's ParallelExecutor loss-parity contract
(test_dist_base.py:594 compares local vs distributed per-step losses)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _build():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        pred = layers.fc(x, 1, name="p")
        loss = layers.mean(layers.square_error_cost(pred, y))
        pt.optimizer.SGD(0.05).minimize(loss, startup_program=startup,
                                        program=main)
    return main, startup, loss


def _batches(n=6):
    rng = np.random.RandomState(0)
    w = np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)
    out = []
    for _ in range(n):
        xb = rng.randn(16, 4).astype(np.float32)
        out.append((xb, (xb @ w + 0.1).astype(np.float32)))
    return out


def test_compiled_program_dp_matches_single_device():
    batches = _batches()

    # single-device run
    main, startup, loss = _build()
    exe = pt.Executor()
    single = []
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        for xb, yb in batches:
            out, = exe.run(main, feed={"x": xb, "y": yb},
                           fetch_list=[loss])
            single.append(float(out))

    # data-parallel run over the full 8-device mesh
    main2, startup2, loss2 = _build()
    cp = pt.CompiledProgram(main2).with_data_parallel(
        loss_name=loss2.name)
    exe2 = pt.Executor()
    dp = []
    with pt.scope_guard(pt.Scope()):
        exe2.run(startup2)
        for xb, yb in batches:
            out, = exe2.run(cp, feed={"x": xb, "y": yb},
                            fetch_list=[loss2])
            dp.append(float(out))

    # same program, same seeds: per-step losses must match (the
    # reference's delta tolerance, test_dist_base.py)
    np.testing.assert_allclose(dp, single, rtol=1e-4, atol=1e-5)


def test_compiled_program_uneven_batch_falls_back_replicated():
    main, startup, loss = _build()
    cp = pt.CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        xb = np.random.randn(7, 4).astype(np.float32)  # 7 % 8 != 0
        yb = np.zeros((7, 1), np.float32)
        out, = exe.run(cp, feed={"x": xb, "y": yb}, fetch_list=[loss])
        assert np.isfinite(out).all()


def test_build_strategy_knobs():
    bs = pt.BuildStrategy()
    bs.fuse_all_reduce_ops = True
    bs.reduce_strategy = pt.BuildStrategy.ReduceStrategy.Reduce
    es = pt.ExecutionStrategy()
    es.num_threads = 4
    main, startup, loss = _build()
    cp = pt.CompiledProgram(main, build_strategy=bs).with_data_parallel(
        loss_name=loss.name, exec_strategy=es)
    assert cp._build_strategy.reduce_strategy == \
        pt.BuildStrategy.ReduceStrategy.Reduce
    with pytest.raises(ValueError):
        pt.CompiledProgram(cp)


def test_build_strategy_xla_flags_render():
    from paddle_tpu.compiler import BuildStrategy
    bs = BuildStrategy()
    assert bs.xla_flags_for() == ""  # defaults: XLA's own combiner
    bs.fuse_all_reduce_threshold_mb = 32
    assert "combine_threshold_bytes=33554432" in bs.xla_flags_for()
    bs.fuse_all_reduce_ops = False
    assert "combine_threshold_bytes=0" in bs.xla_flags_for()
