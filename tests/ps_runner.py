"""Subprocess runner for parameter-server transport tests.

Roles (argv[1]):
  local                — plain single-process SGD training, full batch
  trainer <id>         — transpiled trainer program over the RPC
                         transport (half batch per trainer)
  pserver <endpoint>   — transpiled pserver program (blocks until STOP)

Env: PS_ENDPOINTS (comma list), PS_TRAINERS (int), PS_STEPS, PS_SEED.
Mirrors the reference's test_dist_base.py:594 discipline: both runs
print "LOSSES [...]" for per-step parity checks — sync PS averages the
two trainers' half-batch grads, which equals the local full-batch grad.
"""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import layers  # noqa: E402

SEED = int(os.environ.get("PS_SEED", "7"))
STEPS = int(os.environ.get("PS_STEPS", "5"))
LR = 0.1
B = 8  # per-trainer batch


def build():
    main, startup = pt.Program(), pt.Program()
    main.random_seed = SEED
    startup.random_seed = SEED
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        pt.optimizer.SGD(LR).minimize(loss, startup_program=startup,
                                      program=main)
    return main, startup, loss


def batch(step, lo, hi):
    rng = np.random.RandomState(1234 + step)
    x = rng.randn(2 * B, 4).astype(np.float32)
    y = (x.sum(axis=1, keepdims=True) * 0.5 + 0.1).astype(np.float32)
    return {"x": x[lo:hi], "y": y[lo:hi]}


def main():
    role = sys.argv[1]
    main_prog, startup, loss = build()

    if role == "local":
        exe = pt.Executor()
        exe.run(startup)
        losses = []
        for s in range(STEPS):
            out, = exe.run(main_prog, feed=batch(s, 0, 2 * B),
                           fetch_list=[loss])
            losses.append(float(out))
        print("LOSSES " + json.dumps(losses), flush=True)
        return

    from paddle_tpu.transpiler import DistributeTranspiler
    eps = os.environ.get("PS_ENDPOINTS", "")
    trainers = int(os.environ.get("PS_TRAINERS", "2"))

    if role == "pserver":
        endpoint = sys.argv[2]
        t = DistributeTranspiler()
        t.transpile(0, program=main_prog, pservers=eps, trainers=trainers,
                    startup_program=startup)
        pprog = t.get_pserver_program(endpoint)
        print("PSERVER READY " + endpoint, flush=True)
        pt.Executor().run(pprog)  # blocks until a trainer sends STOP
        return

    if role == "trainer":
        tid = int(sys.argv[2])
        t = DistributeTranspiler()
        t.transpile(tid, program=main_prog, pservers=eps,
                    trainers=trainers, startup_program=startup)
        tprog = t.get_trainer_program()
        exe = pt.Executor()
        exe.run(startup)
        losses = []
        for s in range(STEPS):
            out, = exe.run(tprog, feed=batch(s, tid * B, (tid + 1) * B),
                           fetch_list=[loss])
            losses.append(float(out))
        print("LOSSES " + json.dumps(losses), flush=True)
        from paddle_tpu.ops.distributed_ps import get_ps_client
        cli = get_ps_client([e.strip() for e in eps.split(",")])
        cli.complete()
        if tid == 0:
            cli.stop_server()
        return

    ctr_main(role)


# ---------------------------------------------------------------------------
# Wide&Deep CTR over the transport (roles: ctr_local, ctr_trainer,
# ctr_pserver) — VERDICT r2 "Next round" #3's acceptance test
# ---------------------------------------------------------------------------

CTR_B = 8          # per-trainer batch
CTR_SLOTS = 4
CTR_VOCAB = 64
CTR_DIM = 4
CTR_DENSE = 6


def _ctr_model():
    from paddle_tpu.dygraph import tape
    from paddle_tpu.models.wide_deep import WideDeep
    tape.seed(SEED)
    return WideDeep(sparse_feature_number=CTR_VOCAB,
                    sparse_feature_dim=CTR_DIM,
                    dense_feature_dim=CTR_DENSE,
                    num_sparse_slots=CTR_SLOTS, fc_sizes=[16])


def _ctr_batch(step, lo, hi):
    rng = np.random.RandomState(99 + step)
    ids = rng.randint(0, CTR_VOCAB, (2 * CTR_B, CTR_SLOTS))
    dense = rng.randn(2 * CTR_B, CTR_DENSE).astype(np.float32)
    y = (dense.sum(1, keepdims=True) > 0).astype(np.float32)
    return ids[lo:hi], dense[lo:hi], y[lo:hi]


def _sparse_cfg():
    from paddle_tpu.distributed import SparseTableConfig
    return SparseTableConfig(name="emb", dim=CTR_DIM,
                             initializer="gaussian", init_scale=0.1,
                             optimizer="sgd", lr=LR, seed=3)


def _ctr_loop(server, n_trainers, tid, sync):
    """Transport-agnostic Downpour+sync-dense loop. `server` is a
    ParamServer or a (Sharded)PsClient; `sync()` runs the grad-window
    barrier."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.distributed import DownpourWorker
    from paddle_tpu.dygraph import tape

    model = _ctr_model()
    params = {n: p for n, p in model.named_parameters()}
    pnames = sorted(params)
    if tid == 0:
        for n in pnames:
            server.init_param(n, np.asarray(params[n].value))
    server.create_sparse_table(_sparse_cfg())
    worker = DownpourWorker(server, "emb")
    sync()  # everyone sees init

    losses = []
    for s in range(STEPS):
        # recv fresh dense params from the server (the transpiled
        # recv-op equivalent for the dygraph worker loop)
        for n in pnames:
            params[n].set_value(np.asarray(server.get_param(n)))
        share = 2 * CTR_B // n_trainers
        ids, dense, y = _ctr_batch(s, tid * share, (tid + 1) * share)
        rows = worker.pull(ids)
        sync()  # all pulls done before any push lands
        rows_t = tape.Tensor(jnp.asarray(rows), stop_gradient=False)
        logit = model.forward_from_rows(rows_t,
                                        tape.to_tensor(dense))
        loss = model.loss(logit, tape.to_tensor(y))
        loss.backward()
        worker.push(ids, np.asarray(rows_t.gradient) / n_trainers)
        for n in pnames:
            g = np.asarray(params[n].gradient, np.float32)
            params[n].clear_gradient()
            if hasattr(server, "send_grad_sync"):
                server.send_grad_sync(n, g)
            else:
                server.accumulate_grad(n, g)
        sync()  # dense window applies
        losses.append(float(loss.value))
    print("LOSSES " + json.dumps(losses), flush=True)


def ctr_main(role):
    eps_env = os.environ.get("PS_ENDPOINTS", "")
    trainers = int(os.environ.get("PS_TRAINERS", "2"))

    if role == "ctr_local":
        from paddle_tpu.distributed import ParamServer
        server = ParamServer(lr=LR)
        _ctr_loop(server, 1, 0, sync=server.apply_pending)
        return

    if role == "ctr_pserver":
        from paddle_tpu.distributed import ParamServer
        from paddle_tpu.distributed.rpc import PsServer
        srv = PsServer(ParamServer(lr=LR), endpoint=sys.argv[2],
                       n_trainers=trainers)
        print("PSERVER READY " + srv.endpoint, flush=True)
        srv.serve_forever()
        return

    if role == "ctr_trainer":
        tid = int(sys.argv[2])
        from paddle_tpu.ops.distributed_ps import get_ps_client
        cli = get_ps_client([e.strip() for e in eps_env.split(",")])
        _ctr_loop(cli, trainers, tid, sync=cli.barrier)
        cli.complete()
        if tid == 0:
            cli.stop_server()
        return

    raise SystemExit("unknown ctr role " + role)


if __name__ == "__main__":
    main()
