"""Subprocess runner for parameter-server transport tests.

Roles (argv[1]):
  local                — plain single-process SGD training, full batch
  trainer <id>         — transpiled trainer program over the RPC
                         transport (half batch per trainer)
  pserver <endpoint>   — transpiled pserver program (blocks until STOP)

Env: PS_ENDPOINTS (comma list), PS_TRAINERS (int), PS_STEPS, PS_SEED.
Mirrors the reference's test_dist_base.py:594 discipline: both runs
print "LOSSES [...]" for per-step parity checks — sync PS averages the
two trainers' half-batch grads, which equals the local full-batch grad.
"""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import layers  # noqa: E402

SEED = int(os.environ.get("PS_SEED", "7"))
STEPS = int(os.environ.get("PS_STEPS", "5"))
LR = 0.1
B = 8  # per-trainer batch


def build():
    main, startup = pt.Program(), pt.Program()
    main.random_seed = SEED
    startup.random_seed = SEED
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        pt.optimizer.SGD(LR).minimize(loss, startup_program=startup,
                                      program=main)
    return main, startup, loss


def batch(step, lo, hi):
    rng = np.random.RandomState(1234 + step)
    x = rng.randn(2 * B, 4).astype(np.float32)
    y = (x.sum(axis=1, keepdims=True) * 0.5 + 0.1).astype(np.float32)
    return {"x": x[lo:hi], "y": y[lo:hi]}


def main():
    role = sys.argv[1]
    main_prog, startup, loss = build()

    if role == "local":
        exe = pt.Executor()
        exe.run(startup)
        losses = []
        for s in range(STEPS):
            out, = exe.run(main_prog, feed=batch(s, 0, 2 * B),
                           fetch_list=[loss])
            losses.append(float(out))
        print("LOSSES " + json.dumps(losses), flush=True)
        return

    from paddle_tpu.transpiler import DistributeTranspiler
    eps = os.environ["PS_ENDPOINTS"]
    trainers = int(os.environ.get("PS_TRAINERS", "2"))

    if role == "pserver":
        endpoint = sys.argv[2]
        t = DistributeTranspiler()
        t.transpile(0, program=main_prog, pservers=eps, trainers=trainers,
                    startup_program=startup)
        pprog = t.get_pserver_program(endpoint)
        print("PSERVER READY " + endpoint, flush=True)
        pt.Executor().run(pprog)  # blocks until a trainer sends STOP
        return

    if role == "trainer":
        tid = int(sys.argv[2])
        t = DistributeTranspiler()
        t.transpile(tid, program=main_prog, pservers=eps,
                    trainers=trainers, startup_program=startup)
        tprog = t.get_trainer_program()
        exe = pt.Executor()
        exe.run(startup)
        losses = []
        for s in range(STEPS):
            out, = exe.run(tprog, feed=batch(s, tid * B, (tid + 1) * B),
                           fetch_list=[loss])
            losses.append(float(out))
        print("LOSSES " + json.dumps(losses), flush=True)
        from paddle_tpu.ops.distributed_ps import get_ps_client
        cli = get_ps_client([e.strip() for e in eps.split(",")])
        cli.complete()
        if tid == 0:
            cli.stop_server()
        return

    raise SystemExit("unknown role " + role)


if __name__ == "__main__":
    main()
