"""End-to-end static-graph tests: the 'book' analog of the reference
(/root/reference/python/paddle/fluid/tests/book/test_fit_a_line.py,
test_recognize_digits.py) — build program, run startup, train, assert loss
decreases.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _fresh_programs():
    main = pt.Program()
    startup = pt.Program()
    return main, startup


def test_fit_a_line():
    main, startup = _fresh_programs()
    rng = np.random.RandomState(0)
    true_w = rng.randn(13, 1).astype(np.float32)
    with pt.program_guard(main, startup):
        x = layers.data("x", [13], append_batch_size=True)
        y = layers.data("y", [1])
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.nn.square(
            layers.elementwise_sub(pred, y)))
        opt = pt.optimizer.SGD(learning_rate=0.01)
        opt.minimize(loss, startup_program=startup, program=main)

    scope = pt.Scope()
    exe = pt.Executor()
    with pt.scope_guard(scope):
        exe.run(startup)
        losses = []
        for i in range(50):
            xb = rng.randn(32, 13).astype(np.float32)
            yb = xb @ true_w + 0.01 * rng.randn(32, 1).astype(np.float32)
            out, = exe.run(main, feed={"x": xb, "y": yb},
                           fetch_list=[loss])
            losses.append(float(out))
    assert losses[-1] < losses[0] * 0.2, losses[::10]


def test_mnist_style_conv():
    main, startup = _fresh_programs()
    rng = np.random.RandomState(1)
    with pt.program_guard(main, startup):
        img = layers.data("img", [1, 28, 28])
        label = layers.data("label", [1], dtype="int64")
        conv1 = layers.conv2d(img, num_filters=8, filter_size=5, act="relu")
        pool1 = layers.pool2d(conv1, pool_size=2, pool_stride=2)
        conv2 = layers.conv2d(pool1, num_filters=16, filter_size=5,
                              act="relu")
        pool2 = layers.pool2d(conv2, pool_size=2, pool_stride=2)
        logits = layers.fc(pool2, size=10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        opt = pt.optimizer.Adam(learning_rate=1e-3)
        opt.minimize(loss, startup_program=startup, program=main)

    scope = pt.Scope()
    exe = pt.Executor()
    with pt.scope_guard(scope):
        exe.run(startup)
        # synthetic separable "digits": class k has bright k-th row block
        losses = []
        for i in range(30):
            lbl = rng.randint(0, 10, size=(16, 1)).astype(np.int64)
            imgs = 0.1 * rng.randn(16, 1, 28, 28).astype(np.float32)
            for b in range(16):
                imgs[b, 0, int(lbl[b, 0]) * 2: int(lbl[b, 0]) * 2 + 2, :] += 1.0
            lv, av = exe.run(main, feed={"img": imgs, "label": lbl},
                             fetch_list=[loss, acc])
            losses.append(float(lv))
        assert losses[-1] < losses[0], losses[::5]


def test_program_serialization_roundtrip():
    main, startup = _fresh_programs()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        h = layers.fc(x, size=3, act="relu")
        loss = layers.mean(h)
    js = main.to_json()
    prog2 = pt.Program.from_json(js)
    assert len(prog2.global_block.ops) == len(main.global_block.ops)
    assert set(prog2.global_block.vars) == set(main.global_block.vars)

    # the deserialized program must execute identically
    scope = pt.Scope()
    exe = pt.Executor()
    xb = np.ones((2, 4), np.float32)
    with pt.scope_guard(scope):
        exe.run(startup)
        a, = exe.run(main, feed={"x": xb}, fetch_list=[loss])
        b, = exe.run(prog2, feed={"x": xb}, fetch_list=[loss.name])
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_gradients_api():
    main, startup = _fresh_programs()
    with pt.program_guard(main, startup):
        x = layers.data("x", [3], append_batch_size=False)
        x.stop_gradient = False
        y = layers.nn.square(x)
        loss = layers.reduce_sum(y)
        (gx,) = pt.gradients(loss, x, program=main)
    exe = pt.Executor()
    scope = pt.Scope()
    with pt.scope_guard(scope):
        xv = np.array([1.0, 2.0, 3.0], np.float32)
        g, = exe.run(main, feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(g, 2 * xv, rtol=1e-6)


def test_lr_scheduler():
    main, startup = _fresh_programs()
    with pt.program_guard(main, startup):
        x = layers.data("x", [2])
        pred = layers.fc(x, size=1)
        loss = layers.mean(pred)
        sched = pt.optimizer.ExponentialDecay(
            learning_rate=0.1, decay_steps=10, decay_rate=0.5,
            staircase=True)
        opt = pt.optimizer.SGD(learning_rate=sched)
        opt.minimize(loss, startup_program=startup, program=main)
    lr_name = opt._lr_name
    exe = pt.Executor()
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup)
        lrs = []
        for i in range(21):
            lr, = exe.run(main, feed={"x": np.ones((2, 2), np.float32)},
                          fetch_list=[lr_name])
            lrs.append(float(lr))
    assert abs(lrs[0] - 0.1) < 1e-6
    assert abs(lrs[10] - 0.05) < 1e-6
    assert abs(lrs[20] - 0.025) < 1e-6


def test_pass_framework_and_dropout_prune():
    from paddle_tpu.core.passes import apply_pass, list_passes
    assert "amp_rewrite" in list_passes()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        h = layers.fc(x, 8, act="relu")
        d = layers.dropout(h, dropout_prob=0.5)
        out = layers.fc(d, 2)
    n_before = len(main.global_block.ops)
    pruned = apply_pass(main.clone(), "drop_dropout_eval")
    ops = [o.type for o in pruned.global_block.ops]
    # default downgrade_in_infer semantics: dropout -> scale(1-p)
    assert "dropout" not in ops and "scale" in ops
    assert len(pruned.global_block.ops) == n_before
    # consumers rewired: the program still runs and matches the
    # dropout-in-test-mode output
    exe = pt.Executor()
    exe.run(startup)
    feed = {"x": np.random.RandomState(0).randn(4, 4).astype(np.float32)}
    o1, = exe.run(main.clone(for_test=True), feed=feed, fetch_list=[out])
    o2, = exe.run(pruned, feed=feed, fetch_list=[out])
    np.testing.assert_allclose(o1, o2, atol=1e-6)


def test_var_builders_and_misc_layers():
    """create_tensor/create_global_var/create_parameter/
    autoincreased_step_counter + has_inf/has_nan/is_empty/rank/
    image_resize (fluid tensor.py + nn.py tail)."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [2, 4, 4])
        gv = layers.create_global_var([1], 3.5, persistable=True)
        p = layers.create_parameter([3], name="myparam")
        ctr = layers.autoincreased_step_counter()
        up = layers.resize_bilinear(x, out_shape=[8, 8])
        hi = layers.has_inf(x)
        hn = layers.has_nan(x)
        rk = layers.rank(x)
    scope = pt.Scope()
    exe = pt.Executor()
    with pt.scope_guard(scope):
        exe.run(startup)
        feed = {"x": np.ones((1, 2, 4, 4), np.float32)}
        g, c1, u, i1, n1, r1 = exe.run(
            main, feed=feed, fetch_list=[gv, ctr, up, hi, hn, rk])
        assert float(np.asarray(g)) == 3.5
        assert np.asarray(u).shape == (1, 2, 8, 8)
        assert not bool(np.asarray(i1)) and not bool(np.asarray(n1))
        assert int(np.asarray(r1)) == 4
        _, c2 = exe.run(main, feed=feed, fetch_list=[gv, ctr])
        assert int(np.asarray(c2)) == int(np.asarray(c1)) + 1
        bad = feed.copy()
        bad["x"] = np.full((1, 2, 4, 4), np.inf, np.float32)
        _, i2 = exe.run(main, feed=bad, fetch_list=[gv, hi])
        assert bool(np.asarray(i2))
    assert any(v.name == "myparam" for v in main.all_parameters())


def test_lookahead_optimizer():
    """fluid.optimizer.LookaheadOptimizer: fast params step every
    iteration, slow params sync every k; training still converges
    (reference test_lookahead.py discipline: mechanics + loss)."""
    main, startup = _fresh_programs()
    rng = np.random.RandomState(4)
    true_w = rng.randn(4, 1).astype(np.float32)
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.nn.square(
            layers.elementwise_sub(pred, y)))
        opt = pt.optimizer.LookaheadOptimizer(
            pt.optimizer.SGD(learning_rate=0.05), alpha=0.5, k=5)
        opt.minimize(loss, startup_program=startup, program=main)

    pname = next(v.name for v in main.all_parameters()
                 if "w" in v.name or v.shape == [4, 1])
    scope = pt.Scope()
    exe = pt.Executor()
    with pt.scope_guard(scope):
        exe.run(startup)
        slow0 = np.asarray(scope.find_var(pname + "@SLOW")).copy()
        fast0 = np.asarray(scope.find_var(pname)).copy()
        np.testing.assert_allclose(slow0, fast0)  # startup copy

        losses = []
        slow_base = None
        for i in range(1, 26):
            xb = rng.randn(32, 4).astype(np.float32)
            yb = xb @ true_w
            out, = exe.run(main, feed={"x": xb, "y": yb},
                           fetch_list=[loss])
            losses.append(float(out))
            fast = np.asarray(scope.find_var(pname))
            slow = np.asarray(scope.find_var(pname + "@SLOW"))
            if i == 1:
                # reference Switch first case: slow re-based to the
                # once-updated fast params at step 1 (optimizer.py:4959)
                np.testing.assert_allclose(slow, fast, rtol=1e-5,
                                           atol=1e-6)
                assert not np.allclose(slow, slow0)
                slow_base = slow.copy()
            elif i % 5 == 0:
                # sync step: fast reset to the updated slow
                np.testing.assert_allclose(fast, slow, rtol=1e-5,
                                           atol=1e-6)
            elif i < 5:
                # between step 1 and the first k-sync slow stays put
                np.testing.assert_allclose(slow, slow_base, rtol=1e-6)
                assert not np.allclose(fast, slow)
    assert losses[-1] < losses[0] * 0.5, losses[::5]
