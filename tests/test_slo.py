"""SLO engine tests (ISSUE 12, docs/observability.md "SLOs +
per-tenant accounting").

Covers the tentpole end to end: monitor.py windowed aggregation
(counter sums/rates, windowed timer quantiles and good-ratio, gauge
trends — all under a fake clock so bucket math is exact), Prometheus
label composition + single-# TYPE family grouping, objective
evaluation with error budgets and multi-window burn-rate alerts (trip
AND clear), autoscaling signal gauges, tenant attribution threaded
through a real PredictorPool into labeled series and the
/tracez?tenant= filter, the tenant-cardinality cap, the /sloz +
/statusz surfaces over live HTTP, a scrape-under-mutation storm, and
the disabled-path contracts (slo.evaluate() = ONE flag dict lookup;
windows off = the window recorder never runs).
"""
import json
import re
import threading
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import introspect, layers, monitor, serving, slo, tracing
from paddle_tpu.flags import set_flags
from paddle_tpu.monitor import gauge_get, labeled, stat_get, timer_get

PROM_LINE = re.compile(
    r"^(#.*|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [-+0-9.eEinfa]+)$")


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t


@pytest.fixture(autouse=True)
def _slo_isolation():
    """Rings, windows, objectives, and flags reset around every test
    (lifetime counters stay global — tests use deltas)."""
    tracing.reset()
    yield
    slo.disable()
    slo.clear_objectives()
    monitor.disable_windows()
    tracing.reset()
    set_flags({"FLAGS_slo": False, "FLAGS_slo_bucket_s": 10.0,
               "FLAGS_slo_buckets": 360})


@pytest.fixture
def model_dir(tmp_path):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [6])
        h = layers.fc(x, 16, act="relu")
        y = layers.fc(h, 3, name="out")
    exe = pt.Executor()
    exe.run(startup)
    d = str(tmp_path / "model")
    pt.io.save_inference_model(d, ["x"], [y], exe, main_program=main)
    return d


# ---------------------------------------------------------------------------
# windowed aggregation primitives (fake clock: bucket math is exact)
# ---------------------------------------------------------------------------

def test_windowed_counter_sum_rate_and_expiry():
    clk = FakeClock(5.0)
    monitor.enable_windows(bucket_s=10.0, n_buckets=6, clock=clk)
    name = "STAT_slo_w_counter"
    monitor.stat_add(name, 10)                      # bucket 0
    clk.t = 15.0
    monitor.stat_add(name, 20)                      # bucket 1
    assert monitor.counter_window_sum(name, 60.0, now=15.0) == 30.0
    # a 10s window sees only the current bucket
    assert monitor.counter_window_sum(name, 10.0, now=15.0) == 20.0
    # rate = in-window increments / elapsed in-window seconds:
    # lo bucket starts at -30s, so elapsed = 15-(-30) = 45s
    assert monitor.counter_rate(name, 60.0, now=15.0) \
        == pytest.approx(30.0 / 45.0)
    # both buckets expire once the window moves past them
    assert monitor.counter_window_sum(name, 60.0, now=90.0) == 0.0


def test_windowed_timer_quantiles_and_good_ratio():
    clk = FakeClock(5.0)
    monitor.enable_windows(10.0, 12, clock=clk)
    name = "TIMER_slo_w_us"
    for v in range(1, 51):                          # 1..50 in bucket 0
        monitor.timer_observe(name, float(v))
    clk.t = 15.0
    for v in range(51, 61):                         # 51..60 in bucket 1
        monitor.timer_observe(name, float(v))
    st = monitor.timer_window(name, 60.0, now=15.0)
    assert st["count"] == 60
    assert st["min"] == 1.0 and st["max"] == 60.0
    assert st["sum"] == pytest.approx(sum(range(1, 61)))
    assert st["p50"] == 31.0                        # nearest-rank, 1..60
    # good-ratio: 40 of the 60 in-window samples are <= 40
    assert monitor.timer_window_frac_le(name, 40.0, 60.0, now=15.0) \
        == pytest.approx(40.0 / 60.0)
    # a 10s window only sees bucket 1 (all samples above threshold)
    assert monitor.timer_window_frac_le(name, 40.0, 10.0, now=15.0) == 0.0
    # no in-window data -> None: an SLO must tell "good" from "idle"
    assert monitor.timer_window_frac_le(name, 40.0, 10.0, now=95.0) is None


def test_gauge_trend_slope():
    clk = FakeClock(5.0)
    monitor.enable_windows(10.0, 12, clock=clk)
    name = "GAUGE_slo_w_depth"
    monitor.gauge_set(name, 2.0)                    # bucket 0
    # a single in-window bucket has no computable slope
    assert monitor.gauge_trend(name, 60.0, now=5.0) == 0.0
    clk.t = 25.0
    monitor.gauge_set(name, 12.0)                   # bucket 2
    # (12 - 2) / (2 buckets * 10s) = 0.5/s
    assert monitor.gauge_trend(name, 60.0, now=25.0) == pytest.approx(0.5)


def test_enable_windows_idempotent_reconfigure_discards():
    clk = FakeClock(5.0)
    monitor.enable_windows(10.0, 6, clock=clk)
    monitor.stat_add("STAT_slo_w_cfg", 3)
    monitor.enable_windows(10.0, 6)                 # same config: keeps state
    assert monitor.counter_window_sum("STAT_slo_w_cfg", 60.0, now=5.0) == 3.0
    assert monitor.window_config() == {"bucket_s": 10.0, "n_buckets": 6,
                                       "span_s": 60.0}
    monitor.enable_windows(5.0, 6)                  # reconfigure: discards
    assert monitor.counter_window_sum("STAT_slo_w_cfg", 60.0) == 0.0


def test_windows_disabled_reads_are_inert():
    monitor.disable_windows()
    assert monitor.windows_enabled() is False
    assert monitor.window_config() is None
    monitor.stat_add("STAT_slo_w_off", 5)
    assert monitor.counter_window_sum("STAT_slo_w_off", 60.0) == 0.0
    assert monitor.counter_rate("STAT_slo_w_off", 60.0) == 0.0
    assert monitor.timer_window("TIMER_slo_w_off_us", 60.0)["count"] == 0
    assert monitor.timer_window_frac_le("TIMER_slo_w_off_us", 1.0,
                                        60.0) is None
    assert monitor.gauge_trend("GAUGE_slo_w_off", 60.0) == 0.0


def test_disabled_write_paths_touch_no_window_state(monkeypatch):
    """With windows off the recorder never runs: the hot-path cost is
    one `is not None` test under the already-held lock."""
    monitor.disable_windows()

    def boom(*a, **k):
        raise AssertionError("window recorder ran while disabled")

    monkeypatch.setattr(monitor._Windows, "record_counter", boom)
    monkeypatch.setattr(monitor._Windows, "record_timer", boom)
    monkeypatch.setattr(monitor._Windows, "record_gauge", boom)
    monitor.stat_add("STAT_slo_off_path", 1)
    monitor.gauge_set("GAUGE_slo_off_path", 1.0)
    monitor.timer_observe("TIMER_slo_off_path_us", 1.0)
    monitor.observe_many([("TIMER_slo_off_path_us", 2.0)],
                         [("STAT_slo_off_path", 1.0)])


# ---------------------------------------------------------------------------
# labels
# ---------------------------------------------------------------------------

def test_labeled_composition_sorted_and_escaped():
    assert labeled("STAT_x", {"tenant": "acme"}) == 'STAT_x{tenant="acme"}'
    assert labeled("STAT_x", {}) == "STAT_x"
    # keys sort so one label set always composes one registry key
    assert labeled("STAT_x", {"b": "1", "a": "2"}) == 'STAT_x{a="2",b="1"}'
    # exposition-format escapes: backslash, quote, newline
    assert labeled("STAT_x", {"t": 'a"b\\c\nd'}) \
        == 'STAT_x{t="a\\"b\\\\c\\nd"}'


def test_prometheus_labeled_family_grouping():
    monitor.stat_add(labeled("STAT_slo_lbl_req", {"tenant": "a"}), 2)
    monitor.stat_add(labeled("STAT_slo_lbl_req", {"tenant": "b"}), 3)
    monitor.stat_add("STAT_slo_lbl_req", 5)
    for v in (10.0, 20.0, 30.0):
        monitor.timer_observe(labeled("TIMER_slo_lbl_us", {"tenant": "a"}),
                              v)
    text = monitor.to_prometheus()
    lines = text.splitlines()
    for ln in lines:
        if ln:
            assert PROM_LINE.match(ln), ln
    # exactly ONE # TYPE line for the family; all series contiguous
    fam = "paddle_tpu_STAT_slo_lbl_req_total"
    at = [i for i, ln in enumerate(lines)
          if ln == "# TYPE %s counter" % fam]
    assert len(at) == 1
    assert set(lines[at[0] + 1:at[0] + 4]) == {
        "%s 5" % fam,
        '%s{tenant="a"} 2' % fam,
        '%s{tenant="b"} 3' % fam,
    }
    # a labeled summary merges quantile INTO the existing label block
    # (a second {...} block would not parse)
    assert re.search(
        r'paddle_tpu_TIMER_slo_lbl_us\{[^}]*quantile="0\.5"[^}]*'
        r'tenant="a"[^}]*\} |'
        r'paddle_tpu_TIMER_slo_lbl_us\{[^}]*tenant="a"[^}]*'
        r'quantile="0\.5"[^}]*\} ', text)
    assert 'paddle_tpu_TIMER_slo_lbl_us_count{tenant="a"} 3' in text


# ---------------------------------------------------------------------------
# objectives, budgets, burn-rate alerts
# ---------------------------------------------------------------------------

def _ratio_objective(**kw):
    d = dict(name="slo_test_ratio", kind="ratio", target=0.9,
             bad="STAT_slo_t_bad", total="STAT_slo_t_total",
             window_s=60.0, fast_window_s=60.0, slow_window_s=60.0,
             fast_burn=2.0, slow_burn=1000.0)
    d.update(kw)
    return slo.Objective(**d)


def test_objective_validation():
    with pytest.raises(ValueError):
        slo.Objective(name="x", kind="weird", target=0.5)
    with pytest.raises(ValueError):
        slo.Objective(name="x", kind="ratio", target=1.5,
                      bad="b", total="t")
    with pytest.raises(ValueError):
        slo.Objective(name="x", kind="latency", target=0.9)   # no timer
    with pytest.raises(ValueError):
        slo.Objective(name="x", kind="ratio", target=0.9,
                      bad="b")                                # no total


def test_burn_rate_alert_trips_and_clears():
    clk = FakeClock(5.0)
    slo.enable(bucket_s=10.0, n_buckets=60, clock=clk)
    slo.clear_objectives()
    obj = slo.register(_ratio_objective())
    olbl = {"objective": obj.name}
    fired0 = stat_get(labeled("STAT_slo_alert_fired",
                              dict(olbl, severity="page")))
    cleared0 = stat_get(labeled("STAT_slo_alert_cleared", olbl))

    monitor.stat_add("STAT_slo_t_total", 10)        # healthy bucket
    ev = slo.evaluate(now=clk.t)
    r = ev["objectives"][0]
    assert r["alert"]["firing"] is False
    assert r["good_ratio"] == 1.0
    assert r["error_budget_remaining"] == 1.0
    assert ev["firing"] == []

    clk.t = 15.0                                    # storm bucket
    monitor.stat_add("STAT_slo_t_total", 10)
    monitor.stat_add("STAT_slo_t_bad", 5)
    ev = slo.evaluate(now=clk.t)
    r = ev["objectives"][0]
    # long window: 5 bad / 20 total -> burn (1-0.75)/0.1 = 2.5 >= 2;
    # short confirmation window (one bucket): 5/10 -> burn 5.0 >= 2
    assert r["alert"]["firing"] is True
    assert r["alert"]["severity"] == "page"
    assert r["alert"]["trips"] == 1
    assert r["burn_rate"]["fast"] == pytest.approx(2.5)
    assert r["burn_rate"]["fast_short"] == pytest.approx(5.0)
    assert r["error_budget_remaining"] == 0.0       # 2.5x budget consumed
    assert ev["firing"] == [obj.name]
    assert stat_get(labeled("STAT_slo_alert_fired",
                            dict(olbl, severity="page"))) - fired0 == 1
    assert gauge_get(labeled("GAUGE_slo_alert_firing", olbl)) == 1.0
    assert gauge_get(labeled("GAUGE_slo_burn_rate",
                             dict(olbl, window="fast"))) \
        == pytest.approx(2.5)

    # re-evaluating while still bad must not re-trip
    ev = slo.evaluate(now=clk.t)
    assert ev["objectives"][0]["alert"]["trips"] == 1

    clk.t = 25.0                                    # recovery bucket
    monitor.stat_add("STAT_slo_t_total", 10)
    ev = slo.evaluate(now=clk.t)
    r = ev["objectives"][0]
    assert r["alert"]["firing"] is False
    assert r["alert"]["clears"] == 1
    assert ev["firing"] == []
    assert stat_get(labeled("STAT_slo_alert_cleared", olbl)) \
        - cleared0 == 1
    assert gauge_get(labeled("GAUGE_slo_alert_firing", olbl)) == 0.0


def test_latency_objective_good_ratio_and_idle_is_not_good():
    clk = FakeClock(5.0)
    slo.enable(bucket_s=10.0, n_buckets=60, clock=clk)
    slo.clear_objectives()
    slo.register(slo.Objective(
        name="slo_test_latency", kind="latency", target=0.9,
        timer="TIMER_slo_lat_us", threshold_us=100.0,
        window_s=60.0, fast_window_s=60.0, slow_window_s=60.0,
        fast_burn=3.0, slow_burn=1000.0))
    # idle: no data -> no good-ratio, no budget, no alert either way
    r = slo.evaluate(now=clk.t)["objectives"][0]
    assert r["good_ratio"] is None
    assert r["error_budget_remaining"] is None
    assert r["alert"]["firing"] is False
    for v in [50.0] * 8 + [500.0] * 2:              # 80% under threshold
        monitor.timer_observe("TIMER_slo_lat_us", v)
    r = slo.evaluate(now=clk.t)["objectives"][0]
    assert r["good_ratio"] == pytest.approx(0.8)
    # burn (1-0.8)/0.1 = 2.0 < fast_burn 3 -> over budget, not paging
    assert r["burn_rate"]["fast"] == pytest.approx(2.0)
    assert r["alert"]["firing"] is False
    assert r["error_budget_remaining"] == 0.0


def test_autoscaling_signals_exported():
    clk = FakeClock(5.0)
    slo.enable(bucket_s=10.0, n_buckets=60, clock=clk)
    monitor.gauge_set("GAUGE_serving_queue_depth", 0.0)
    clk.t = 25.0
    monitor.gauge_set("GAUGE_serving_queue_depth", 10.0)
    monitor.gauge_set("GAUGE_generation_blocks_free", 30.0)
    monitor.gauge_set("GAUGE_generation_blocks_used", 10.0)
    for v in (10_000.0, 20_000.0):
        monitor.timer_observe("TIMER_generation_tpot_us", v)
    sig = slo.evaluate(now=clk.t)["signals"]
    assert sig["queue_depth_trend_serving"] == pytest.approx(0.5)
    assert sig["kv_block_headroom"] == pytest.approx(0.75)
    assert sig["tpot_saturation"] == pytest.approx(20_000.0 / 50_000.0)
    assert gauge_get(labeled("GAUGE_slo_queue_depth_trend",
                             {"pool": "serving"})) == pytest.approx(0.5)
    assert gauge_get("GAUGE_slo_kv_block_headroom") == pytest.approx(0.75)
    assert gauge_get("GAUGE_slo_tpot_saturation") == pytest.approx(0.4)


# ---------------------------------------------------------------------------
# flag wiring + disabled-path contract
# ---------------------------------------------------------------------------

def test_flag_side_effect_enables_and_disables():
    assert slo.enabled() is False
    set_flags({"FLAGS_slo_bucket_s": 5.0, "FLAGS_slo_buckets": 24})
    set_flags({"FLAGS_slo": True})
    assert slo.enabled() is True
    assert monitor.windows_enabled() is True
    assert monitor.window_config() == {"bucket_s": 5.0, "n_buckets": 24,
                                       "span_s": 120.0}
    # first activation installs the stack's default objective set
    names = {o.name for o in slo.objectives()}
    assert {"serving_total_p95", "generation_ttft_p95",
            "serving_deadline_miss", "generation_deadline_miss"} <= names
    set_flags({"FLAGS_slo": False})
    assert slo.enabled() is False
    assert monitor.windows_enabled() is False


def test_disabled_evaluate_is_one_flag_lookup(monkeypatch):
    """evaluate() is the only flag-lookup site on the disabled path:
    the same one-dict-lookup contract as FLAGS_request_tracing and
    FLAGS_failpoints."""
    import paddle_tpu.slo as slo_mod
    set_flags({"FLAGS_slo": False})
    calls = []
    real = slo_mod.get_flag

    def counting(name, default=None):
        calls.append(name)
        return real(name, default)

    monkeypatch.setattr(slo_mod, "get_flag", counting)
    assert slo_mod.evaluate() is None
    assert calls == ["FLAGS_slo"]


# ---------------------------------------------------------------------------
# per-tenant attribution
# ---------------------------------------------------------------------------

def test_pool_tenant_threading_and_tracez_filter(model_dir):
    from paddle_tpu.inference import Config
    a_req = labeled("STAT_serving_requests", {"tenant": "acme"})
    a_tim = labeled("TIMER_serving_total_us", {"tenant": "acme"})
    c0 = stat_get(a_req)
    t0 = timer_get(a_tim)["count"]
    with serving.PredictorPool(Config(model_dir), max_batch=8) as pool:
        x = np.zeros((1, 6), np.float32)
        for _ in range(3):
            pool.run([x], timeout=60.0, tenant="acme")
        pool.run([x], timeout=60.0, tenant="bob")
        pool.run([x], timeout=60.0)                 # untenanted
    assert stat_get(a_req) - c0 == 3
    assert timer_get(a_tim)["count"] - t0 == 3
    z = tracing.tracez(tenant="acme")
    assert z["tenant"] == "acme"
    assert len(z["recent"]) == 3
    assert all(r["tenant"] == "acme" for r in z["recent"])
    assert "tenant=acme" in tracing.tracez_text(tenant="acme")
    t = slo.tenants()
    assert t["acme"]["serving_requests"] == stat_get(a_req)
    assert "bob" in t


def test_tenant_cardinality_cap():
    o0 = stat_get("STAT_tracing_tenant_overflow")
    other0 = stat_get(labeled("STAT_serving_requests",
                              {"tenant": "__other__"}))
    for i in range(70):
        tr = tracing.begin("serving", tenant="cap-tenant-%03d" % i)
        tr.stage("admit")
        tr.finish()
    # 64 distinct tenants admitted, the remaining 6 collapse
    assert stat_get("STAT_tracing_tenant_overflow") - o0 == 6
    assert stat_get(labeled("STAT_serving_requests",
                            {"tenant": "__other__"})) - other0 == 6
    # an overflowed tenant is cached: repeats don't re-count overflow
    tr = tracing.begin("serving", tenant="cap-tenant-069")
    tr.finish()
    assert stat_get("STAT_tracing_tenant_overflow") - o0 == 6
    assert stat_get(labeled("STAT_serving_requests",
                            {"tenant": "__other__"})) - other0 == 7


# ---------------------------------------------------------------------------
# HTTP surfaces
# ---------------------------------------------------------------------------

def _get_json(url):
    return json.load(urllib.request.urlopen(url, timeout=10))


def test_sloz_http_endpoints_and_statusz_section():
    srv = introspect.start(port=0)
    try:
        z = _get_json(srv.url + "/sloz?format=json")
        assert z["enabled"] is False
        txt = urllib.request.urlopen(srv.url + "/sloz",
                                     timeout=10).read().decode()
        assert "disabled" in txt
        st = _get_json(srv.url + "/statusz")
        assert st["slo"] == {"enabled": False}

        slo.enable(bucket_s=0.5, n_buckets=40)
        slo.clear_objectives()
        slo.register(_ratio_objective(name="http_ratio"))
        monitor.stat_add("STAT_slo_t_total", 10)
        tr = tracing.begin("serving", tenant="web")
        tr.finish()
        z = _get_json(srv.url + "/sloz?format=json")
        assert z["enabled"] is True
        assert z["windows"]["bucket_s"] == 0.5
        assert [o["name"] for o in z["objectives"]] == ["http_ratio"]
        assert z["objectives"][0]["good_ratio"] == 1.0
        assert "web" in z["tenants"]
        txt = urllib.request.urlopen(srv.url + "/sloz",
                                     timeout=10).read().decode()
        assert "http_ratio" in txt and "web" in txt
        st = _get_json(srv.url + "/statusz")
        assert st["slo"]["enabled"] is True
        assert st["slo"]["objectives"] == 1
        # the index advertises /sloz
        idx = urllib.request.urlopen(srv.url + "/",
                                     timeout=10).read().decode()
        assert "/sloz" in idx
        # /tracez honors the tenant query parameter over HTTP too
        tz = _get_json(srv.url + "/tracez?format=json&tenant=web")
        assert tz["tenant"] == "web"
        assert all(r["tenant"] == "web" for r in tz["recent"])
    finally:
        introspect.stop()


def test_scrape_under_labeled_mutation_storm():
    """to_prometheus() / /sloz stay valid while writer threads storm
    labeled observe_many: every exposition line parses mid-storm, and
    after quiesce each tenant's counter equals its timer count (both
    sides of every observe_many landed atomically — no torn buckets)."""
    slo.enable(bucket_s=0.25, n_buckets=40)
    stop = threading.Event()
    errors = []

    def writer(tid):
        lbl = {"tenant": "t%d" % (tid % 3)}
        t_name = labeled("TIMER_slo_storm_us", lbl)
        c_name = labeled("STAT_slo_storm_req", lbl)
        i = 0
        while not stop.is_set() and i < 200_000:
            try:
                monitor.observe_many([(t_name, float(i % 997))],
                                     [(c_name, 1.0)])
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)
                return
            i += 1

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    srv = introspect.start(port=0)
    try:
        for _ in range(8):
            body = urllib.request.urlopen(srv.url + "/metrics",
                                          timeout=10).read().decode()
            for ln in body.splitlines():
                if ln:
                    assert PROM_LINE.match(ln), ln
            z = _get_json(srv.url + "/sloz?format=json")
            assert z["enabled"] is True
    finally:
        stop.set()
        for t in threads:
            t.join()
        introspect.stop()
    assert not errors
    for tn in ("t0", "t1", "t2"):
        lbl = {"tenant": tn}
        c = stat_get(labeled("STAT_slo_storm_req", lbl))
        n = timer_get(labeled("TIMER_slo_storm_us", lbl))["count"]
        assert c == n and c > 0
        # the windowed view agrees with the lifetime view (the whole
        # storm fits inside the 10s span)
        assert monitor.counter_window_sum(
            labeled("STAT_slo_storm_req", lbl), 10.0) == c
