"""Persistent AOT program cache (core/program_cache.py) tests.

Covers the ISSUE-1 tentpole + satellites: stable program fingerprints,
the lowering-flag snapshot in the in-memory Executor cache key (the
stale-executable bugfix), the LRU capacity bound, disk trace-cache
hit/miss with bitwise-identical fetches, corruption/truncation/version
-skew fallback to a clean recompile, cross-process reuse through
subprocesses, Predictor wiring, and the bench.py `compile` block.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core import program_cache
from paddle_tpu.monitor import stat_get

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def flag_guard():
    from paddle_tpu import flags as F
    saved = dict(F._values)
    yield
    F._values.clear()
    F._values.update(saved)


@pytest.fixture(scope="module")
def cache_root(tmp_path_factory):
    # module-scoped: jax's persistent compilation cache dir is pointed
    # here once and pytest keeps the dir for the whole session
    return str(tmp_path_factory.mktemp("aot_cache"))


def _build(width=12, hidden=24, with_opt=True):
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [width])
        h = layers.fc(x, hidden, act="relu")
        loss = layers.mean(h)
        if with_opt:
            pt.optimizer.SGD(0.1).minimize(loss, startup_program=startup,
                                           program=main)
    return main, startup, loss


def _run_fresh(main, startup, loss, feed, cache_dir=None,
               use_program_cache=True):
    """Fresh Executor + fresh Scope: init, one train step, fetch."""
    exe = pt.Executor(program_cache_dir=cache_dir)
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    return exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope,
                   use_program_cache=use_program_cache)


# ---------------------------------------------------------------------------
# fingerprint
# ---------------------------------------------------------------------------
def test_fingerprint_stable_and_sensitive(flag_guard):
    m1, _, _ = _build()
    m2, _, _ = _build()
    sig = (("x", (4, 12), "float32"),)
    fp1 = m1.fingerprint(sig, ("loss",), ())
    assert fp1 and fp1 == m2.fingerprint(sig, ("loss",), ())
    # op attr change -> new fingerprint
    m2.global_block.ops[0].attrs["_salt"] = 1
    assert m2.fingerprint(sig, ("loss",), ()) != fp1
    # feed signature is pinned
    assert m1.fingerprint((("x", (8, 12), "float32"),), ("loss",), ()) \
        != fp1
    # lowering-relevant flag is pinned
    pt.set_flags({"FLAGS_dropout_storage": "u8"})
    assert m1.fingerprint(sig, ("loss",), ()) != fp1


def test_fingerprint_ndarray_attr_no_collision():
    m1, _, _ = _build()
    m2, _, _ = _build()
    # large ndarray attrs hash by content — numpy's elided repr must
    # never make two different programs collide
    a = np.arange(10000, dtype=np.float32)
    b = a.copy()
    b[7777] = -1.0
    m1.global_block.ops[0].attrs["table"] = a
    m2.global_block.ops[0].attrs["table"] = b
    assert m1.fingerprint() != m2.fingerprint()


# ---------------------------------------------------------------------------
# in-memory cache: flag snapshot in the key (stale-executable bugfix)
# ---------------------------------------------------------------------------
def test_inmemory_key_snapshots_lowering_flags(flag_guard):
    main, startup, loss = _build()
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    feed = {"x": np.ones((4, 12), np.float32)}
    exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
    before = stat_get("STAT_executor_compile")
    exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
    assert stat_get("STAT_executor_compile") == before  # cached
    # flipping a lowering-relevant flag must MISS (previously returned
    # the stale pre-flip executable)
    pt.set_flags({"FLAGS_embedding_onehot_grad": False})
    exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
    assert stat_get("STAT_executor_compile") == before + 1
    # flipping back returns to the still-cached original entry
    pt.set_flags({"FLAGS_embedding_onehot_grad": True})
    exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
    assert stat_get("STAT_executor_compile") == before + 1


def test_executor_cache_lru_capacity(flag_guard):
    pt.set_flags({"FLAGS_executor_cache_capacity": 2})
    main, startup, loss = _build()
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    evict0 = stat_get("STAT_executor_cache_evict")
    for b in (2, 3, 4, 5):
        exe.run(main, feed={"x": np.ones((b, 12), np.float32)},
                fetch_list=[loss.name], scope=scope)
    assert len(exe._cache) <= 2
    assert stat_get("STAT_executor_cache_evict") > evict0
    # the evicted batch=2 entry recompiles cleanly
    before = stat_get("STAT_executor_compile")
    exe.run(main, feed={"x": np.ones((2, 12), np.float32)},
            fetch_list=[loss.name], scope=scope)
    assert stat_get("STAT_executor_compile") == before + 1


# ---------------------------------------------------------------------------
# disk trace cache
# ---------------------------------------------------------------------------
def test_disk_cache_hit_bitwise_identical(cache_root):
    main, startup, loss = _build()
    feed = {"x": np.ones((4, 12), np.float32)}
    miss0 = stat_get("STAT_program_cache_trace_miss")
    out_cold = _run_fresh(main, startup, loss, feed, cache_dir=cache_root)
    assert stat_get("STAT_program_cache_trace_miss") > miss0
    assert stat_get("STAT_program_cache_bytes_written") > 0

    hit0 = stat_get("STAT_program_cache_trace_hit")
    out_warm = _run_fresh(main, startup, loss, feed, cache_dir=cache_root)
    assert stat_get("STAT_program_cache_trace_hit") > hit0
    assert out_cold[0].tobytes() == out_warm[0].tobytes()

    # an uncached run (disk cache off AND use_program_cache=False, the
    # plain jit path) produces the same bits
    out_plain = _run_fresh(main, startup, loss, feed, cache_dir="",
                           use_program_cache=False)
    assert out_plain[0].tobytes() == out_cold[0].tobytes()


def test_use_program_cache_false_bypasses_disk(cache_root):
    main, startup, loss = _build(width=13)  # unique program for stats
    feed = {"x": np.ones((4, 13), np.float32)}
    miss0 = stat_get("STAT_program_cache_trace_miss")
    hit0 = stat_get("STAT_program_cache_trace_hit")
    exe = pt.Executor(program_cache_dir=cache_root)
    scope = pt.Scope()
    exe.run(startup, scope=scope, use_program_cache=False)
    exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope,
            use_program_cache=False)
    assert stat_get("STAT_program_cache_trace_miss") == miss0
    assert stat_get("STAT_program_cache_trace_hit") == hit0


def _trace_entries(cache_root):
    d = os.path.join(cache_root, "trace")
    if not os.path.isdir(d):
        return []
    return sorted(os.path.join(d, f) for f in os.listdir(d)
                  if f.endswith(".stablehlo"))


_DAMAGES = ["garbage", "truncate", "version"]


@pytest.mark.parametrize("damage", _DAMAGES)
def test_damaged_entry_falls_back_and_heals(cache_root, damage):
    width = 30 + _DAMAGES.index(damage)  # unique program per case
    main, startup, loss = _build(width=width)
    feed = {"x": np.ones((4, width), np.float32)}
    before = set(_trace_entries(cache_root))
    out_cold = _run_fresh(main, startup, loss, feed, cache_dir=cache_root)
    # damage only THIS program's entries; the shared dir holds healthy
    # entries from other tests
    entries = sorted(set(_trace_entries(cache_root)) - before)
    assert entries
    for path in entries:
        if damage == "garbage":
            with open(path, "wb") as f:
                f.write(b"\x00garbage\xff" * 7)
        elif damage == "truncate":
            blob = open(path, "rb").read()
            with open(path, "wb") as f:
                f.write(blob[:10])
        else:  # valid container, wrong jax version in the header
            blob = open(path, "rb").read()
            rest = blob[len(program_cache.MAGIC):]
            nl = rest.index(b"\n")
            hdr = json.loads(rest[:nl])
            hdr["jax"] = "0.0.0"
            with open(path, "wb") as f:
                f.write(program_cache.MAGIC +
                        json.dumps(hdr, sort_keys=True).encode() + b"\n" +
                        rest[nl + 1:])
    corrupt0 = stat_get("STAT_program_cache_corrupt")
    out_recover = _run_fresh(main, startup, loss, feed,
                             cache_dir=cache_root)
    assert stat_get("STAT_program_cache_corrupt") > corrupt0
    assert out_recover[0].tobytes() == out_cold[0].tobytes()
    # the bad entries were overwritten with good ones: next run hits
    hit0 = stat_get("STAT_program_cache_trace_hit")
    corrupt1 = stat_get("STAT_program_cache_corrupt")
    out_warm = _run_fresh(main, startup, loss, feed, cache_dir=cache_root)
    assert stat_get("STAT_program_cache_trace_hit") > hit0
    assert stat_get("STAT_program_cache_corrupt") == corrupt1
    assert out_warm[0].tobytes() == out_cold[0].tobytes()


def test_int64_feed_warm_hit_no_corrupt(cache_root):
    # jit canonicalizes int64 feeds to int32 (x64 off): the stored
    # in_avals must compare equal to our avals or every warm process
    # with an int64 feed pays a spurious corrupt + re-export
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.data("y", [1], dtype="int64")
        logits = layers.fc(x, 10)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, y))
    feed = {"x": np.ones((8, 4), np.float32),
            "y": np.zeros((8, 1), np.int64)}
    out_cold = _run_fresh(main, startup, loss, feed, cache_dir=cache_root)
    corrupt0 = stat_get("STAT_program_cache_corrupt")
    hit0 = stat_get("STAT_program_cache_trace_hit")
    out_warm = _run_fresh(main, startup, loss, feed, cache_dir=cache_root)
    assert stat_get("STAT_program_cache_corrupt") == corrupt0
    assert stat_get("STAT_program_cache_trace_hit") > hit0
    assert out_cold[0].tobytes() == out_warm[0].tobytes()


# ---------------------------------------------------------------------------
# mesh-aware fingerprints (ISSUE-6 satellite: the mesh topology — axis
# names+sizes+device kind — rides in the in-memory key and the disk
# fingerprint, so entries never cross topologies)
# ---------------------------------------------------------------------------
@pytest.mark.spmd
def test_inmemory_key_includes_mesh_topology():
    from paddle_tpu.mesh import ShardingPlan, use_plan
    main, startup, loss = _build(width=18)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    feed = {"x": np.ones((8, 18), np.float32)}
    exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
    before = stat_get("STAT_executor_compile")
    exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
    assert stat_get("STAT_executor_compile") == before  # cached
    # same Executor under a dp4xmp2 plan: MISS (an executable
    # partitioned for one topology must never serve another)
    plan = ShardingPlan("dp4xmp2")
    with use_plan(plan):
        exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
        assert stat_get("STAT_executor_compile") == before + 1
        # identical mesh: steady state, no recompiles
        exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
        assert stat_get("STAT_executor_compile") == before + 1
    # chip-count flip (dp8): its own entry, never the dp4xmp2 one
    with use_plan(ShardingPlan("dp8")):
        exe.run(main, feed=feed, fetch_list=[loss.name], scope=scope)
    assert stat_get("STAT_executor_compile") == before + 2


@pytest.mark.spmd
def test_disk_cache_mesh_topology_round_trip(cache_root):
    """1-device and dp4xmp2 runs of the SAME program get distinct disk
    entries; an identical mesh in a fresh Executor hits its entry; a
    chip-count change misses (stale executables are structurally
    impossible — the topology is inside the fingerprint)."""
    from paddle_tpu.mesh import ShardingPlan, use_plan
    main, startup, loss = _build(width=19)
    feed = {"x": np.ones((8, 19), np.float32)}

    out_single = _run_fresh(main, startup, loss, feed,
                            cache_dir=cache_root)
    # dp4xmp2: same program + feed, distinct fingerprint -> trace MISS
    plan = ShardingPlan("dp4xmp2")
    miss0 = stat_get("STAT_program_cache_trace_miss")
    with use_plan(plan):
        out_mesh = _run_fresh(main, startup, loss, feed,
                              cache_dir=cache_root)
    assert stat_get("STAT_program_cache_trace_miss") > miss0
    # partitioned numerics match the single-device run
    np.testing.assert_allclose(np.asarray(out_mesh[0]),
                               np.asarray(out_single[0]),
                               rtol=1e-5, atol=1e-6)
    # identical mesh, fresh Executor/Scope: disk AOT HIT, same bits
    hit0 = stat_get("STAT_program_cache_trace_hit")
    with use_plan(plan):
        out_warm = _run_fresh(main, startup, loss, feed,
                              cache_dir=cache_root)
    assert stat_get("STAT_program_cache_trace_hit") > hit0
    assert out_warm[0].tobytes() == out_mesh[0].tobytes()
    # chip-count change (dp8): never served a dp4xmp2 entry
    miss1 = stat_get("STAT_program_cache_trace_miss")
    hit1 = stat_get("STAT_program_cache_trace_hit")
    with use_plan(ShardingPlan("dp8")):
        out_dp8 = _run_fresh(main, startup, loss, feed,
                             cache_dir=cache_root)
    assert stat_get("STAT_program_cache_trace_miss") > miss1
    assert stat_get("STAT_program_cache_trace_hit") == hit1
    np.testing.assert_allclose(np.asarray(out_dp8[0]),
                               np.asarray(out_single[0]),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# cross-process reuse (satellite: subprocess A populates, B hits)
# ---------------------------------------------------------------------------
_XPROC = """
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as pt
from paddle_tpu import layers

cache_dir, out_npy = sys.argv[1], sys.argv[2]
pt.set_flags({"FLAGS_program_cache_dir": cache_dir})
main, startup = pt.Program(), pt.Program()
with pt.program_guard(main, startup):
    x = layers.data("x", [10])
    h = layers.fc(x, 20, act="relu")
    loss = layers.mean(h)
    pt.optimizer.SGD(0.1).minimize(loss, startup_program=startup,
                                   program=main)
exe = pt.Executor()
exe.run(startup)
out = exe.run(main, feed={"x": np.ones((3, 10), np.float32)},
              fetch_list=[loss.name])
np.save(out_npy, out[0])
from paddle_tpu.monitor import get_float_stats
st = get_float_stats()
print(json.dumps({"hit": st.get("STAT_program_cache_trace_hit", 0),
                  "miss": st.get("STAT_program_cache_trace_miss", 0)}))
"""


def _spawn_xproc(cache_dir, out_npy, tmp):
    script = os.path.join(tmp, "xproc.py")
    with open(script, "w") as f:
        f.write(_XPROC)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, script, cache_dir, out_npy],
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_cross_process_reuse(tmp_path):
    tmp = str(tmp_path)
    cache_dir = os.path.join(tmp, "aot")
    a = _spawn_xproc(cache_dir, os.path.join(tmp, "a.npy"), tmp)
    b = _spawn_xproc(cache_dir, os.path.join(tmp, "b.npy"), tmp)
    assert a["hit"] == 0 and a["miss"] > 0      # A populated
    assert b["hit"] > 0 and b["miss"] == 0      # B reused the traces
    # uncached process ("" disables the disk cache)
    c = _spawn_xproc("", os.path.join(tmp, "c.npy"), tmp)
    assert c["hit"] == 0 and c["miss"] == 0
    va = np.load(os.path.join(tmp, "a.npy"))
    vb = np.load(os.path.join(tmp, "b.npy"))
    vc = np.load(os.path.join(tmp, "c.npy"))
    assert va.tobytes() == vb.tobytes() == vc.tobytes()


# ---------------------------------------------------------------------------
# Predictor wiring
# ---------------------------------------------------------------------------
def test_predictor_program_cache(cache_root, tmp_path):
    from paddle_tpu import layers
    from paddle_tpu.inference import Config, create_predictor
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [6])
        pred = layers.fc(x, 3)
    exe = pt.Executor()
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup)
        d = str(tmp_path / "model")
        pt.save_inference_model(d, ["x"], [pred], exe, main)

    xb = np.random.RandomState(0).randn(5, 6).astype(np.float32)

    def serve():
        cfg = Config(model_dir=d)
        cfg.enable_program_cache(cache_root)
        p = create_predictor(cfg)
        return p.run([xb])[0]

    miss0 = stat_get("STAT_program_cache_trace_miss")
    out1 = serve()
    assert stat_get("STAT_program_cache_trace_miss") > miss0
    hit0 = stat_get("STAT_program_cache_trace_hit")
    out2 = serve()
    assert stat_get("STAT_program_cache_trace_hit") > hit0
    assert out1.tobytes() == out2.tobytes()
    # disable_program_cache really opts out
    cfg = Config(model_dir=d)
    cfg.disable_program_cache()
    miss1 = stat_get("STAT_program_cache_trace_miss")
    hit1 = stat_get("STAT_program_cache_trace_hit")
    create_predictor(cfg).run([xb])
    assert stat_get("STAT_program_cache_trace_miss") == miss1
    assert stat_get("STAT_program_cache_trace_hit") == hit1


# ---------------------------------------------------------------------------
# bench.py `compile` block (cold/warm in subprocesses)
# ---------------------------------------------------------------------------
def _load_bench():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "pt_bench", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_compile_block_small(monkeypatch):
    # tiny shape: validates the block's plumbing (subprocess pair, hit
    # flag, bitwise fetch check) without the 12-layer compile cost
    monkeypatch.setenv("PT_COMPILE_BENCH_LAYERS_N", "2")
    monkeypatch.setenv("PT_COMPILE_BENCH_H", "64")
    monkeypatch.setenv("PT_COMPILE_BENCH_FF", "128")
    monkeypatch.setenv("PT_COMPILE_BENCH_HEADS", "4")
    monkeypatch.setenv("PT_COMPILE_BENCH_S", "16")
    monkeypatch.setenv("PT_COMPILE_BENCH_B", "2")
    block = _load_bench().bench_compile()
    assert "error" not in block, block
    assert block["warm_trace_cache_hit"] is True
    assert block["fetch_bitwise_identical"] is True
    assert block["cold_compile_s"] > 0 and block["warm_compile_s"] > 0


@pytest.mark.slow
def test_cold_warm_speedup_bert12_acceptance():
    """ISSUE-1 acceptance: warm-start Executor.run of the 12-layer
    BERT-shaped static train step reaches first results >= 3x faster
    than cold start on CPU, with bitwise-identical fetches."""
    block = _load_bench().bench_compile()
    assert "error" not in block, block
    assert block["warm_trace_cache_hit"] is True
    assert block["fetch_bitwise_identical"] is True
    assert block["speedup"] >= 3.0, block
