"""Double-grad (paddle.grad with create_graph) tests.

Reference bar: imperative/partial_grad_engine.cc enables
grad-of-grad for gradient-penalty training (test_imperative_double_grad.py
in the reference suite)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.dygraph import grad, to_tensor
from paddle_tpu.dygraph import tape


def test_grad_basic_no_accumulation():
    x = to_tensor(np.array([2.0, 3.0], np.float32), stop_gradient=False)
    y = x * x
    (g,) = grad([y], [x])
    np.testing.assert_allclose(np.asarray(g.value), [4.0, 6.0])
    assert x.grad is None  # grad() must not touch .grad


def test_grad_allow_unused():
    x = to_tensor(np.array([1.0], np.float32), stop_gradient=False)
    z = to_tensor(np.array([1.0], np.float32), stop_gradient=False)
    y = x * 2.0
    import pytest
    with pytest.raises(RuntimeError, match="allow_unused"):
        grad([y], [z], retain_graph=True)
    g = grad([y], [z], allow_unused=True)
    assert g[0] is None


def test_double_grad_polynomial():
    # y = x^3: dy/dx = 3x^2, d2y/dx2 = 6x
    x = to_tensor(np.array([2.0, -1.0], np.float32),
                  stop_gradient=False)
    y = x * x * x
    (dx,) = grad([y], [x], create_graph=True)
    np.testing.assert_allclose(np.asarray(dx.value), [12.0, 3.0],
                               rtol=1e-6)
    (ddx,) = grad([dx], [x])
    np.testing.assert_allclose(np.asarray(ddx.value), [12.0, -6.0],
                               rtol=1e-6)


def test_double_grad_through_matmul_and_nonlinearity():
    r = np.random.RandomState(0)
    xv = r.randn(3, 4).astype(np.float32)
    wv = r.randn(4, 2).astype(np.float32)
    x = to_tensor(xv, stop_gradient=False)
    w = to_tensor(wv, stop_gradient=False)
    h = tape.run_op("matmul", {"X": [x], "Y": [w]}, {})["Out"][0]
    y = tape.run_op("tanh", {"X": [h]}, {})["Out"][0]
    s = y.sum() if hasattr(y, "sum") else y
    (gx,) = grad([s], [x], create_graph=True)
    # second order vs jax oracle
    import jax
    import jax.numpy as jnp

    def first(xj):
        return jnp.tanh(xj @ wv).sum()

    def second(xj):
        return jax.grad(first)(xj).sum()

    (ggx,) = grad([gx.sum()], [x])
    oracle = jax.grad(second)(jnp.asarray(xv))
    np.testing.assert_allclose(np.asarray(ggx.value),
                               np.asarray(oracle), atol=1e-5)


def test_gradient_penalty_training_signal():
    # WGAN-GP style: penalty = (||d critic/d x||_2 - 1)^2 must give
    # finite, nonzero grads to the critic weights
    r = np.random.RandomState(1)
    xv = r.randn(4, 3).astype(np.float32)
    wv = (r.randn(3, 1) * 0.5).astype(np.float32)
    x = to_tensor(xv, stop_gradient=False)
    w = to_tensor(wv, stop_gradient=False)
    out = tape.run_op("matmul", {"X": [x], "Y": [w]}, {})["Out"][0]
    score = out.sum()
    (gx,) = grad([score], [x], create_graph=True)
    norm = ((gx * gx).sum() + 1e-12) ** 0.5
    penalty = (norm - 1.0) * (norm - 1.0)
    penalty.backward()
    gw = np.asarray(w.gradient)
    assert np.isfinite(gw).all() and np.abs(gw).sum() > 0
    # analytic: gx = w^T per row -> ||gx|| = 2*||w||; d pen/d w known
    import jax
    import jax.numpy as jnp

    def pen(wj):
        gxj = jax.grad(lambda xj: (xj @ wj).sum())(jnp.asarray(xv))
        n = jnp.sqrt((gxj * gxj).sum() + 1e-12)
        return (n - 1.0) ** 2
    oracle = jax.grad(pen)(jnp.asarray(wv))
    np.testing.assert_allclose(gw, np.asarray(oracle), atol=1e-5)


def test_dygraph_recompute_grad_parity():
    """distributed.recompute (fleet recompute analog): parameter grads
    through the jax.checkpoint segment equal the plain-forward grads."""
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed import recompute

    rng = np.random.RandomState(0)
    block = nn.Sequential(nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 6))
    x = pt.to_tensor(rng.randn(3, 6).astype(np.float32))

    out = recompute(block, x)
    ((out ** 2).mean()).backward()
    g_remat = [np.asarray(p.grad.value if hasattr(p.grad, "value")
                          else p.grad) for p in block.parameters()]
    for p in block.parameters():
        p.clear_grad() if hasattr(p, "clear_grad") else None

    block2 = nn.Sequential(nn.Linear(6, 12), nn.ReLU(),
                           nn.Linear(12, 6))
    block2.set_state_dict(block.state_dict())
    out2 = block2(x)
    ((out2 ** 2).mean()).backward()
    g_plain = [np.asarray(p.grad.value if hasattr(p.grad, "value")
                          else p.grad) for p in block2.parameters()]
    for a, b in zip(g_remat, g_plain):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    # pure-function form differentiates its args
    y = pt.to_tensor(rng.randn(3, 6).astype(np.float32))
    y.stop_gradient = False
    out3 = recompute(lambda a: (a * a).sum(), y)
    out3.backward()
    np.testing.assert_allclose(
        np.asarray(y.grad.value if hasattr(y.grad, "value") else y.grad),
        2 * np.asarray(y.value), rtol=1e-6)
