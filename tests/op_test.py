"""Per-op test harness: numpy oracle + numeric finite-difference grad check.

TPU-native analog of the reference's OpTest workhorse
(/root/reference/python/paddle/fluid/tests/unittests/op_test.py:170 —
check_output:948 runs the op through a tiny scope+executor and compares to
numpy-computed expected outputs; check_grad:1236 compares analytic gradients
against numeric gradients from get_numeric_gradient:57).

Differences from the reference, by design:
  - the op runs through the XLA-jitted block executor instead of a C++
    scope interpreter — which is exactly the production path here;
  - the numeric gradient is of the scalar L = sum(out * W) for a fixed
    random weighting W (mathematically the same contract: it checks the
    vector-Jacobian product the analytic path computes);
  - no place/layout sweep — XLA owns layout; dtype sweep is the caller's
    choice of input dtypes.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import paddle_tpu as pt
from paddle_tpu.core.backward import gradients
from paddle_tpu.core.program import VarDesc

__all__ = ["OpTest"]


def _norm_slot(value):
    """Normalize a slot value to [(var_name, ndarray), ...]."""
    if isinstance(value, (list, tuple)):
        return [(str(n), np.asarray(a)) for n, a in value]
    return None  # single-var slot; name assigned by caller


class OpTest:
    """Declarative single-op test.

    >>> t = OpTest("elementwise_add", inputs={"X": x, "Y": y},
    ...            outputs={"Out": x + y})
    >>> t.check_output()
    >>> t.check_grad(["X", "Y"])
    """

    def __init__(self, op_type: str,
                 inputs: Optional[Dict] = None,
                 outputs: Optional[Dict] = None,
                 attrs: Optional[Dict] = None):
        self.op_type = op_type
        self.attrs = dict(attrs or {})
        # slot -> [(name, array)]
        self.inputs: Dict[str, List] = {}
        for slot, v in (inputs or {}).items():
            multi = _norm_slot(v)
            if multi is None:
                multi = [(f"{op_type}_{slot.lower()}", np.asarray(v))]
            self.inputs[slot] = multi
        self.outputs: Dict[str, List] = {}
        for slot, v in (outputs or {}).items():
            multi = _norm_slot(v)
            if multi is None:
                multi = [(f"{op_type}_{slot.lower()}_out", np.asarray(v))]
            self.outputs[slot] = multi

    # ------------------------------------------------------------------
    def _build(self):
        """Fresh (program, scope, executor, feed, out_vars-by-slot)."""
        main = pt.Program()
        startup = pt.Program()
        feed = {}
        in_map, out_map = {}, {}
        with pt.program_guard(main, startup):
            block = main.global_block
            for slot, vars_ in self.inputs.items():
                names = []
                for name, arr in vars_:
                    block.create_var(name, shape=arr.shape,
                                     dtype=str(arr.dtype),
                                     stop_gradient=False)
                    feed[name] = arr
                    names.append(name)
                in_map[slot] = names
            for slot, vars_ in self.outputs.items():
                names = []
                for name, arr in vars_:
                    block.create_var(name, shape=arr.shape,
                                     dtype=str(arr.dtype),
                                     stop_gradient=False)
                    names.append(name)
                out_map[slot] = names
            block.append_op(self.op_type, inputs=in_map, outputs=out_map,
                            attrs=self.attrs)
        return main, startup, feed, out_map

    # ------------------------------------------------------------------
    def check_output(self, atol: float = 1e-5, rtol: float = 1e-4):
        main, startup, feed, out_map = self._build()
        exe = pt.Executor()
        scope = pt.Scope()
        fetch, expect = [], []
        for slot, vars_ in self.outputs.items():
            for name, arr in vars_:
                fetch.append(name)
                expect.append(arr)
        with pt.scope_guard(scope):
            got = exe.run(main, feed=feed, fetch_list=fetch)
        for name, e, g in zip(fetch, expect, got):
            g = np.asarray(g)
            assert g.shape == tuple(e.shape), (
                f"{self.op_type}/{name}: shape {g.shape} != {e.shape}")
            np.testing.assert_allclose(
                g, e, atol=atol, rtol=rtol,
                err_msg=f"{self.op_type} output {name!r} mismatch")

    # ------------------------------------------------------------------
    def check_grad(self, inputs_to_check: Sequence[str],
                   output_slot: str = "Out",
                   max_relative_error: float = 5e-3,
                   numeric_delta: float = 5e-3,
                   atol: float = 1e-4,
                   seed: int = 7):
        """Compare analytic d(sum(out*W))/dx against central differences.

        inputs_to_check: slot names; every var in the slot is checked.
        Only float inputs can be checked.
        """
        rng = np.random.RandomState(seed)
        out_vars = self.outputs[output_slot]
        weights = {name: rng.uniform(0.5, 1.5, arr.shape).astype(np.float32)
                   for name, arr in out_vars}

        check_names = []
        for slot in inputs_to_check:
            for name, arr in self.inputs[slot]:
                if not np.issubdtype(arr.dtype, np.floating):
                    raise ValueError(f"cannot grad-check non-float {name}")
                check_names.append(name)

        # --- analytic ---------------------------------------------------
        main, startup, feed, out_map = self._build()
        with pt.program_guard(main, startup):
            block = main.global_block
            layers = pt.layers
            terms = []
            for name, arr in out_vars:
                wname = "gradw_" + name
                block.create_var(wname, shape=arr.shape, dtype="float32",
                                 stop_gradient=True)
                feed[wname] = weights[name]
                prod = layers.elementwise_mul(block.var(name),
                                              block.var(wname))
                terms.append(layers.reduce_sum(prod))
            loss = terms[0] if len(terms) == 1 else layers.sums(terms)
            grad_vars = gradients(loss, [block.var(n) for n in check_names],
                                  program=main)
        exe = pt.Executor()
        with pt.scope_guard(pt.Scope()):
            analytic = exe.run(main, feed=feed,
                               fetch_list=[g.name for g in grad_vars])

        # --- numeric ----------------------------------------------------
        fmain, fstartup, ffeed, _ = self._build()
        fexe = pt.Executor()
        fscope = pt.Scope()
        fetch_outs = [name for name, _ in out_vars]

        def loss_of(feed_dict):
            with pt.scope_guard(fscope):
                outs = fexe.run(fmain, feed=feed_dict,
                                fetch_list=fetch_outs)
            return sum(float(np.sum(np.asarray(o) * weights[n]))
                       for n, o in zip(fetch_outs, outs))

        for name, g_analytic in zip(check_names, analytic):
            base = ffeed[name]
            num = np.zeros_like(base, dtype=np.float64).ravel()
            flat = base.ravel()
            for i in range(flat.size):
                orig = flat[i]
                flat[i] = orig + numeric_delta
                lp = loss_of(ffeed)
                flat[i] = orig - numeric_delta
                lm = loss_of(ffeed)
                flat[i] = orig
                num[i] = (lp - lm) / (2.0 * numeric_delta)
            num = num.reshape(base.shape)
            g_analytic = np.asarray(g_analytic, dtype=np.float64)
            denom = np.maximum(np.maximum(np.abs(num),
                                          np.abs(g_analytic)), 1e-3)
            rel = np.abs(num - g_analytic) / denom
            bad = rel > max_relative_error
            close = np.abs(num - g_analytic) < atol
            bad &= ~close
            assert not bad.any(), (
                f"{self.op_type} grad wrt {name}: max rel err "
                f"{rel.max():.4g} (numeric {num.ravel()[rel.argmax()]:.5g} "
                f"vs analytic {g_analytic.ravel()[rel.argmax()]:.5g})")
