"""Profiler spans/export, flags registry, enforce, nan/inf check tests.

Mirrors the reference's test_profiler.py, flag getter/setter tests and
nan_inf_utils debugging behavior."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, profiler
from paddle_tpu.core.enforce import EnforceNotMet, enforce


def test_record_event_nesting_and_summary():
    profiler.reset_profiler()
    profiler.start_profiler()
    with profiler.RecordEvent("outer"):
        with profiler.RecordEvent("inner"):
            pass
        with profiler.RecordEvent("inner"):
            pass
    rows = profiler.stop_profiler()
    by_name = {r["name"]: r for r in rows}
    assert by_name["inner"]["calls"] == 2
    assert by_name["outer"]["calls"] == 1
    assert by_name["outer"]["total_us"] >= by_name["inner"]["total_us"]


def test_chrome_tracing_export(tmp_path):
    profiler.reset_profiler()
    profiler.start_profiler()
    with profiler.RecordEvent("span_a"):
        pass
    path = str(tmp_path / "trace.json")
    profiler.stop_profiler(profile_path=path)
    with open(path) as f:
        trace = json.load(f)
    assert any(e["name"] == "span_a" for e in trace["traceEvents"])
    assert all(e["ph"] == "X" for e in trace["traceEvents"])


def test_executor_ops_produce_spans():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.fc(x, 8)
    profiler.reset_profiler()
    profiler.start_profiler()
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[y])
    rows = profiler.stop_profiler()
    names = {r["name"] for r in rows}
    assert "mul" in names  # fc lowers via mul


def test_flags_set_get_unknown():
    pt.set_flags({"FLAGS_check_nan_inf": True})
    assert pt.get_flags("check_nan_inf")["FLAGS_check_nan_inf"] is True
    pt.set_flags({"check_nan_inf": False})  # short name accepted
    with pytest.raises(ValueError):
        pt.set_flags({"FLAGS_not_a_flag": 1})
    with pytest.raises(ValueError):
        pt.get_flags("nope")


def test_enforce():
    enforce(True, "fine")
    with pytest.raises(EnforceNotMet, match="bad value 3"):
        enforce(False, "bad value %d", 3)


def test_check_nan_inf_reports(capfd):
    pt.set_flags({"FLAGS_check_nan_inf": True})
    try:
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [2])
            y = layers.log(x)  # log of a negative -> nan
        exe = pt.Executor()
        with pt.scope_guard(pt.Scope()):
            out, = exe.run(main,
                           feed={"x": np.array([[-1.0, 2.0]], np.float32)},
                           fetch_list=[y])
        assert np.isnan(out).any()
        captured = capfd.readouterr()
        assert "check_nan_inf" in captured.out
        assert "log" in captured.out
    finally:
        pt.set_flags({"FLAGS_check_nan_inf": False})


def test_op_bench_harness():
    from paddle_tpu.incubate.op_bench import bench_op
    r = bench_op("softmax", {"X": (8, 32)}, repeat=5, warmup=1)
    assert r["op"] == "softmax" and r["mean_us"] > 0
    assert r["min_us"] <= r["p50_us"] <= r["p99_us"] + 1e-9
    g = bench_op("matmul", {"X": (8, 16), "Y": (16, 4)}, repeat=3,
                 warmup=1, grad=True)
    assert g["mean_us"] > 0


def test_monitor_stats():
    """platform/monitor STAT registry analog (pybind get_float_stats)."""
    import paddle_tpu as pt
    from paddle_tpu.monitor import (get_float_stats, get_int_stats,
                                    stat_add, stat_get, stat_reset)
    stat_reset("STAT_test_counter")
    stat_add("STAT_test_counter", 2)
    stat_add("STAT_test_counter")
    assert stat_get("STAT_test_counter") == 3.0
    assert get_int_stats()["STAT_test_counter"] == 3
    # executor compiles bump the stat
    before = stat_get("STAT_executor_compile")
    main = pt.Program()
    main.global_block.create_var("z", shape=[2], dtype="float32")
    main.global_block.append_op("fill_constant", {}, {"Out": ["z"]},
                                {"shape": [2], "value": 1.0,
                                 "dtype": "float32"})
    pt.Executor().run(main, feed={}, fetch_list=["z"])
    assert stat_get("STAT_executor_compile") >= before + 1


def test_fast_check_nan_inf_and_unused_var(caplog):
    import logging
    import numpy as np
    import pytest
    import paddle_tpu as pt
    from paddle_tpu.core.enforce import EnforceNotMet
    main = pt.Program()
    blk = main.global_block
    blk.create_var("x", shape=[2], dtype="float32")
    blk.create_var("y", shape=[2], dtype="float32")
    blk.create_var("dead", shape=[2], dtype="float32")
    blk.append_op("scale", {"X": ["x"]}, {"Out": ["y"]},
                  {"scale": 1.0, "bias": 0.0})
    blk.append_op("scale", {"X": ["x"]}, {"Out": ["dead"]},
                  {"scale": 2.0, "bias": 0.0})
    exe = pt.Executor()
    pt.set_flags({"FLAGS_fast_check_nan_inf": True,
                  "FLAGS_enable_unused_var_check": True})
    try:
        with caplog.at_level(logging.WARNING, logger="paddle_tpu"):
            out, = exe.run(main, feed={"x": np.ones(2, np.float32)},
                           fetch_list=["y"])
        assert any("dead" in r.message for r in caplog.records)
        with pytest.raises(EnforceNotMet, match="nan/inf"):
            exe.run(main, feed={"x": np.asarray([np.inf, 1.0],
                                                np.float32)},
                    fetch_list=["y"])
    finally:
        pt.set_flags({"FLAGS_fast_check_nan_inf": False,
                      "FLAGS_enable_unused_var_check": False})


def test_program_to_dot():
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.utils_viz import program_to_dot
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.fc(x, size=2)
    dot = program_to_dot(main, title="net")
    assert dot.startswith("digraph")
    assert "mul" in dot or "matmul" in dot
    assert "lightblue" in dot  # parameters shaded


def test_fleet_metrics_local():
    import numpy as np
    from paddle_tpu.fleet import metrics as fm
    assert fm.acc(np.asarray(3.0), np.asarray(4.0)) == 0.75
    assert fm.mae(np.asarray(2.0), np.asarray(4.0)) == 0.5
    assert abs(fm.rmse(np.asarray(8.0), np.asarray(2.0)) - 2.0) < 1e-9
    # AUC oracle: perfect separation -> 1.0; random histograms -> 0.5ish
    pos = np.zeros(10); neg = np.zeros(10)
    pos[9] = 100; neg[0] = 100
    assert fm.auc(pos, neg) == 1.0
    pos2 = np.ones(10); neg2 = np.ones(10)
    assert abs(fm.auc(pos2, neg2) - 0.5) < 1e-6
