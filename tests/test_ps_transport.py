"""Parameter-server RPC transport + DistributeTranspiler tests.

The round-2 gap (VERDICT missing #1): the PS path never crossed a
process boundary. These tests exercise the real transport — trainer and
pserver PROCESSES over sockets with binary serde — and hold the
reference's bar: per-step loss parity between local SGD and 1-pserver +
2-trainer sync PS training
(/root/reference/python/paddle/fluid/tests/unittests/
test_dist_base.py:594; transpiler semantics per
distribute_transpiler.py:256)."""
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

RUNNER = os.path.join(os.path.dirname(__file__), "ps_runner.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env(extra=None):
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    if extra:
        env.update(extra)
    return env


def _losses(out: bytes):
    for line in out.decode().splitlines():
        if line.startswith("LOSSES "):
            return json.loads(line[len("LOSSES "):])
    raise AssertionError("no LOSSES line:\n" + out.decode())


def _wait_for_listeners(procs, endpoints, timeout=120.0):
    """Retry-connect until every pserver listens (fleet-launch style);
    kill the procs and surface their stderr on timeout."""
    deadline = time.time() + timeout
    for ep in endpoints:
        host, port = ep.rsplit(":", 1)
        while True:
            try:
                socket.create_connection((host, int(port)),
                                         timeout=1).close()
                break
            except OSError:
                if time.time() > deadline:
                    for s in procs:
                        s.kill()
                    raise AssertionError(
                        "pserver never listened: "
                        + procs[0].stderr.read().decode())
                time.sleep(0.2)


# ---------------------------------------------------------------------------
# wire protocol unit tests (in-process server, real sockets)
# ---------------------------------------------------------------------------

def test_rpc_roundtrip_dense_and_sparse():
    from paddle_tpu.distributed.communicator import ParamServer
    from paddle_tpu.distributed.large_scale_kv import SparseTableConfig
    from paddle_tpu.distributed.rpc import PsClient, PsServer

    srv = PsServer(ParamServer(lr=0.5), "127.0.0.1:0",
                   n_trainers=1).start()
    cli = PsClient(srv.endpoint)
    try:
        w0 = np.arange(12, dtype=np.float32).reshape(3, 4)
        cli.init_param("w", w0)
        np.testing.assert_array_equal(cli.get_param("w"), w0)
        g = np.ones((3, 4), np.float32)
        cli.send_grad("w", g)  # async apply: w -= 0.5 * g
        np.testing.assert_allclose(cli.get_param("w"), w0 - 0.5)

        cli.create_sparse_table(SparseTableConfig(
            name="emb", dim=4, initializer="fill", fill_value=0.0,
            lr=1.0))
        rows = cli.pull_sparse("emb", np.array([3, 9], np.int64))
        assert rows.shape == (2, 4)
        cli.push_sparse("emb", np.array([3], np.int64),
                        np.ones((1, 4), np.float32))
        rows2 = cli.pull_sparse("emb", np.array([3], np.int64))
        # sgd push: row -= lr * grad
        np.testing.assert_allclose(rows2[0], rows[0] - 1.0)

        with pytest.raises(RuntimeError, match="pserver error"):
            cli.get_param("nonexistent")
        assert cli.ping()
    finally:
        cli.stop_server()
        cli.close()


def test_rpc_sync_window_averages_trainer_grads():
    from paddle_tpu.distributed.communicator import ParamServer
    from paddle_tpu.distributed.rpc import PsClient, PsServer
    import threading

    srv = PsServer(ParamServer(lr=1.0), "127.0.0.1:0",
                   n_trainers=2).start()
    c1, c2 = PsClient(srv.endpoint), PsClient(srv.endpoint)
    try:
        c1.init_param("w", np.zeros(4, np.float32))
        c1.send_grad_sync("w", np.full(4, 2.0, np.float32))
        c2.send_grad_sync("w", np.full(4, 4.0, np.float32))
        # both must sit at the barrier before the merged window applies
        t = threading.Thread(target=c1.barrier)
        t.start()
        c2.barrier()
        t.join(timeout=10)
        # w -= lr * mean(2, 4) = -3
        np.testing.assert_allclose(c1.get_param("w"),
                                   np.full(4, -3.0, np.float32))
    finally:
        c1.stop_server()
        c1.close()
        c2.close()


def test_slice_variable_blocks():
    from paddle_tpu.transpiler import slice_variable
    blocks = slice_variable({"w": (100, 200)}, n_pservers=3,
                            min_block_size=4096)
    assert sum(rows for _, _, rows in blocks["w"]) == 100
    starts = [s for _, s, _ in blocks["w"]]
    assert starts == sorted(starts) and starts[0] == 0
    assert len(blocks["w"]) == 3
    # small var: never sliced
    small = slice_variable({"b": (16,)}, n_pservers=3)
    assert small["b"] == [("b.block0", 0, 16)]


# ---------------------------------------------------------------------------
# end-to-end: 1 pserver + 2 trainer processes vs local
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n_pservers", [1, 2])
def test_ps_training_loss_parity(n_pservers):
    local = subprocess.run([sys.executable, RUNNER, "local"],
                           env=_env(), capture_output=True, timeout=300)
    assert local.returncode == 0, local.stderr.decode()
    ref = _losses(local.stdout)

    eps = ",".join("127.0.0.1:%d" % _free_port()
                   for _ in range(n_pservers))
    env = _env({"PS_ENDPOINTS": eps, "PS_TRAINERS": "2"})
    servers = [subprocess.Popen(
        [sys.executable, RUNNER, "pserver", ep], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for ep in eps.split(",")]
    _wait_for_listeners(servers, eps.split(","))

    trainers = [subprocess.Popen(
        [sys.executable, RUNNER, "trainer", str(i)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for i in range(2)]
    touts = []
    try:
        for p in trainers:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, err.decode()
            touts.append(out)
        for s in servers:
            out, err = s.communicate(timeout=60)
            assert s.returncode == 0, err.decode()
    finally:
        for p in trainers + servers:
            if p.poll() is None:
                p.kill()

    # sync PS averaging two half-batch grads == local full-batch grad;
    # losses differ only in which half each trainer reports, so compare
    # the MEAN of the two trainers' losses to local
    l0, l1 = _losses(touts[0]), _losses(touts[1])
    mean_losses = [(a + b) / 2 for a, b in zip(l0, l1)]
    np.testing.assert_allclose(mean_losses, ref, atol=1e-5, rtol=1e-5)
    assert mean_losses[-1] < mean_losses[0]


def test_wide_deep_ctr_over_transport_loss_parity():
    """BASELINE config 4 acceptance: Wide&Deep CTR, 1 pserver + 2
    trainer processes (Downpour sparse pull/push + sync dense window)
    vs single-process local — per-step loss parity."""
    env = _env({"PS_STEPS": "4"})
    local = subprocess.run([sys.executable, RUNNER, "ctr_local"],
                           env=env, capture_output=True, timeout=300)
    assert local.returncode == 0, local.stderr.decode()
    ref = _losses(local.stdout)

    ep = "127.0.0.1:%d" % _free_port()
    env = _env({"PS_ENDPOINTS": ep, "PS_TRAINERS": "2", "PS_STEPS": "4"})
    server = subprocess.Popen(
        [sys.executable, RUNNER, "ctr_pserver", ep], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    _wait_for_listeners([server], [ep])

    trainers = [subprocess.Popen(
        [sys.executable, RUNNER, "ctr_trainer", str(i)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for i in range(2)]
    touts = []
    try:
        for p in trainers:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, err.decode()
            touts.append(out)
        out, err = server.communicate(timeout=60)
        assert server.returncode == 0, err.decode()
    finally:
        for p in trainers + [server]:
            if p.poll() is None:
                p.kill()

    l0, l1 = _losses(touts[0]), _losses(touts[1])
    mean_losses = [(a + b) / 2 for a, b in zip(l0, l1)]
    np.testing.assert_allclose(mean_losses, ref, atol=1e-5, rtol=1e-5)


def test_dead_trainer_releases_barrier():
    """A trainer whose connection drops without OP_COMPLETE is dead:
    its barrier party is removed so survivors keep training
    (heart_beat_monitor.h:54 analog — connection = heartbeat)."""
    import threading
    from paddle_tpu.distributed.communicator import ParamServer
    from paddle_tpu.distributed.rpc import PsClient, PsServer

    srv = PsServer(ParamServer(lr=1.0), "127.0.0.1:0",
                   n_trainers=2).start()
    alive = PsClient(srv.endpoint)
    dead = PsClient(srv.endpoint)
    try:
        alive.init_param("w", np.zeros(2, np.float32))
        released = []

        def wait_barrier():
            alive.send_grad_sync("w", np.ones(2, np.float32))
            alive.barrier()   # would block forever with 2 live parties
            released.append(True)

        # the dying trainer DID trainer traffic (so its connection
        # counts as a heartbeat; a pull-only client closing must not
        # shrink the barrier) and staged a grad before dying
        dead.send_grad_sync("w", np.full(2, 3.0, np.float32))
        t = threading.Thread(target=wait_barrier, daemon=True)
        t.start()
        time.sleep(0.5)
        assert not released  # still waiting on the second trainer
        dead.close()         # trainer dies WITHOUT complete()
        t.join(timeout=20)
        assert released, "surviving trainer stayed deadlocked"
        # window applied with both staged grads: mean(1, 3) = 2
        np.testing.assert_allclose(alive.get_param("w"),
                                   -np.full(2, 2.0, np.float32))
    finally:
        alive.stop_server()
        alive.close()


def test_geo_communicator_over_transport():
    """GeoSGD across the process boundary: the GeoCommunicator is
    transport-agnostic (send_delta/get_param duck-typing), so local
    training with periodic delta pushes works against a remote pserver
    exactly as against the in-process object (communicator.cc:403
    GeoCommunicator semantics)."""
    from paddle_tpu.distributed import GeoCommunicator, ParamServer
    from paddle_tpu.distributed.rpc import PsClient, PsServer

    srv = PsServer(ParamServer(), "127.0.0.1:0", n_trainers=1).start()
    cli = PsClient(srv.endpoint)
    try:
        cli.init_param("w", np.zeros(4, np.float32))
        geo = GeoCommunicator(cli, trainer_push_step=3)
        geo.init_local("w")
        g = np.ones(4, np.float32)
        for i in range(6):
            geo.local_step("w", g, lr=0.1)
        # 6 local sgd steps pushed as 2 delta windows of -0.3 each
        np.testing.assert_allclose(cli.get_param("w"),
                                   np.full(4, -0.6, np.float32),
                                   atol=1e-6)
        np.testing.assert_allclose(geo.local_param("w"),
                                   np.full(4, -0.6, np.float32),
                                   atol=1e-6)
    finally:
        cli.stop_server()
        cli.close()


def test_uds_second_transport(tmp_path):
    """uds:// endpoints select the unix-domain transport behind the same
    PsServer/PsClient interface — the reference's interchangeable
    grpc/brpc dual-stack contract."""
    from paddle_tpu.distributed import ParamServer
    from paddle_tpu.distributed.rpc import PsClient, PsServer
    ep = "uds://%s" % (tmp_path / "ps.sock")
    srv = PsServer(ParamServer(lr=0.1), endpoint=ep, n_trainers=1)
    srv.start()
    try:
        c = PsClient(ep)
        c.init_param("w", np.ones(4, np.float32))
        c.send_grad("w", np.ones(4, np.float32))
        out = c.get_param("w")
        np.testing.assert_allclose(out, 0.9 * np.ones(4), rtol=1e-6)
        c.complete()
        c.close()
        # a second server on the SAME live path must fail loudly, and
        # stop() must remove the socket file
        import pytest as _pt
        srv2 = None
        with _pt.raises(OSError, match="in use"):
            from paddle_tpu.distributed import ParamServer as _PS
            srv2 = PsServer(_PS(), endpoint=ep)
        assert srv2 is None
    finally:
        srv.stop()
    import os
    assert not os.path.exists(str(tmp_path / "ps.sock"))
