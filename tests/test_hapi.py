"""hapi Model.fit/evaluate/predict + metrics + callbacks tests.

Mirrors the reference's test_model.py (fit on a small classifier, metric
accumulation, checkpoint save/load, early stopping)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.hapi.callbacks import Callback, EarlyStopping
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall
from paddle_tpu.reader import TensorDataset


def _make_data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    w = np.array([[1.0, -1.0], [2.0, 0.0], [-1.0, 1.0], [0.5, 0.5]],
                 np.float32)
    logits = x @ w
    y = logits.argmax(1).astype(np.int64).reshape(-1, 1)
    return x, y


class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.l1 = nn.Linear(4, 16)
        self.l2 = nn.Linear(16, 2)

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        return self.l2(F.relu(self.l1(x)))


def _ce_loss(logits, label):
    import paddle_tpu.nn.functional as F
    return F.cross_entropy(logits, label)


def test_metrics_standalone():
    acc = Accuracy(topk=(1, 2))
    pred = np.array([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4]])
    label = np.array([[0], [1], [1]])
    acc.update(*acc.compute(pred, label))
    a1, a2 = acc.accumulate()
    assert abs(a1 - 2 / 3) < 1e-6 and a2 == 1.0

    p = Precision()
    p.update(np.array([0.9, 0.8, 0.2]), np.array([1, 0, 1]))
    assert abs(p.accumulate() - 0.5) < 1e-6
    r = Recall()
    r.update(np.array([0.9, 0.8, 0.2]), np.array([1, 0, 1]))
    assert abs(r.accumulate() - 0.5) < 1e-6

    auc = Auc()
    scores = np.concatenate([np.random.RandomState(0).rand(100) * 0.5,
                             np.random.RandomState(1).rand(100) * 0.5
                             + 0.5])
    labels = np.concatenate([np.zeros(100), np.ones(100)])
    auc.update(scores, labels)
    assert auc.accumulate() > 0.95


def test_model_fit_reduces_loss_and_evaluates():
    x, y = _make_data(128)
    model = pt.Model(_MLP())
    model.prepare(pt.optimizer.Adam(0.01,
                                    parameters=model.parameters()),
                  _ce_loss, metrics=Accuracy())
    hist = model.fit(TensorDataset(x, y), batch_size=16, epochs=4,
                     verbose=0, shuffle=True)
    assert hist["loss"][-1] < hist["loss"][0]
    logs = model.evaluate(TensorDataset(x, y), batch_size=32)
    assert logs["acc"] > 0.7
    assert "loss" in logs


def test_model_predict_shapes():
    x, y = _make_data(20)
    model = pt.Model(_MLP())
    model.prepare(pt.optimizer.SGD(0.01, parameters=model.parameters()),
                  _ce_loss)
    out, = model.predict(TensorDataset(x), batch_size=8)
    assert out.shape == (20, 2)


def test_model_save_load_roundtrip(tmp_path):
    x, y = _make_data(32)
    model = pt.Model(_MLP())
    model.prepare(pt.optimizer.SGD(0.05, parameters=model.parameters()),
                  _ce_loss)
    model.fit(TensorDataset(x, y), batch_size=8, epochs=1, verbose=0)
    before, = model.predict(TensorDataset(x), batch_size=32)
    path = str(tmp_path / "m")
    model.save(path)

    model2 = pt.Model(_MLP())
    model2.prepare(pt.optimizer.SGD(0.05,
                                    parameters=model2.parameters()),
                   _ce_loss)
    model2.load(path)
    after, = model2.predict(TensorDataset(x), batch_size=32)
    np.testing.assert_allclose(before, after, atol=1e-5)


def test_callbacks_order_and_early_stopping():
    x, y = _make_data(32)

    class Recorder(Callback):
        def __init__(self):
            super().__init__()
            self.events = []

        def on_train_begin(self, logs=None):
            self.events.append("train_begin")

        def on_epoch_begin(self, epoch, logs=None):
            self.events.append("epoch_begin")

        def on_train_batch_end(self, step, logs=None):
            self.events.append("batch")

        def on_epoch_end(self, epoch, logs=None):
            self.events.append("epoch_end")

        def on_train_end(self, logs=None):
            self.events.append("train_end")

    rec = Recorder()
    model = pt.Model(_MLP())
    model.prepare(pt.optimizer.SGD(0.01, parameters=model.parameters()),
                  _ce_loss)
    model.fit(TensorDataset(x, y), batch_size=16, epochs=2, verbose=0,
              callbacks=[rec])
    assert rec.events[0] == "train_begin" and rec.events[-1] == "train_end"
    assert rec.events.count("epoch_begin") == 2

    # early stopping: patience 0 on a non-improving metric stops training
    class ConstantMetricStop(EarlyStopping):
        def on_epoch_end(self, epoch, logs=None):
            self.on_eval_end({"loss": 1.0})  # never improves after 1st

    model2 = pt.Model(_MLP())
    model2.prepare(pt.optimizer.SGD(0.01,
                                    parameters=model2.parameters()),
                   _ce_loss)
    stopper = ConstantMetricStop(monitor="loss", patience=0)
    model2.fit(TensorDataset(x, y), batch_size=16, epochs=10, verbose=0,
               callbacks=[stopper])
    assert model2.stop_training


def test_hapi_eval_batch_with_labels_and_metric_contract():
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.hapi import Model
    from paddle_tpu.metric import Accuracy
    from paddle_tpu.nn import functional as F
    from paddle_tpu.dygraph import tape
    tape.seed(3)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    model = Model(net)
    model.prepare(pt.optimizer.Adam(1e-2, parameters=net.parameters()),
                  loss=lambda out, lab: F.cross_entropy(
                      out, lab, reduction="mean"),
                  metrics=Accuracy())
    rng = np.random.RandomState(0)
    x = rng.randn(8, 4).astype(np.float32)
    y = rng.randint(0, 3, (8, 1)).astype(np.int64)
    model.train_batch([x], [y])
    res = model.eval_batch([x], [y])
    assert len(res) == 2  # loss + accuracy
    assert 0.0 <= float(np.asarray(res[1])) <= 1.0


def test_hapi_save_load_with_optimizer_state(tmp_path):
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.hapi import Model
    from paddle_tpu.nn import functional as F
    from paddle_tpu.dygraph import tape
    tape.seed(4)
    net = nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 1))
    model = Model(net)
    model.prepare(pt.optimizer.Adam(1e-2,
                                    parameters=net.parameters()),
                  loss=lambda out, lab: F.mse_loss(out, lab))
    rng = np.random.RandomState(1)
    x = rng.randn(8, 4).astype(np.float32)
    y = rng.randn(8, 1).astype(np.float32)
    for _ in range(3):
        model.train_batch([x], [y])
    path = str(tmp_path / "ckpt")
    model.save(path)
    assert os.path.exists(path + ".pdopt.npz")

    # a fresh model restores params AND Adam moments: its next step
    # must match the original's next step exactly
    l_ref = float(model.train_batch([x], [y])[0])

    tape.seed(4)
    net2 = nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 1))
    model2 = Model(net2)
    model2.prepare(pt.optimizer.Adam(1e-2,
                                     parameters=net2.parameters()),
                   loss=lambda out, lab: F.mse_loss(out, lab))
    model2.load(path)
    l_new = float(model2.train_batch([x], [y])[0])
    np.testing.assert_allclose(l_new, l_ref, rtol=1e-5)


def test_hapi_summary(capsys):
    from paddle_tpu import nn
    from paddle_tpu.hapi import Model
    net = nn.Linear(3, 2)
    info = Model(net).summary()
    assert info["total_params"] == 3 * 2 + 2
    assert "Total params" in capsys.readouterr().out


def test_metric_long_tail():
    """CompositeMetric / ChunkEvaluator / EditDistance / DetectionMAP
    (fluid metrics.py:199,513,611,805)."""
    import numpy as np
    from paddle_tpu.metric import (Accuracy, ChunkEvaluator,
                                   CompositeMetric, DetectionMAP,
                                   EditDistance, Precision)

    ce = ChunkEvaluator()
    ce.update(10, 8, 6)
    p, r, f1 = ce.accumulate()
    assert abs(p - 0.6) < 1e-9 and abs(r - 0.75) < 1e-9
    assert abs(f1 - 2 * 0.6 * 0.75 / 1.35) < 1e-9

    ed = EditDistance()
    ed.update(np.asarray([[0.0], [2.0]]), 2)
    avg, err = ed.accumulate()
    assert avg == 1.0 and err == 0.5

    m = DetectionMAP(map_type="11point")
    # one perfect detection, one missed gt
    m.update(np.asarray([[0, 0.9, 0, 0, 10, 10]], np.float64),
             np.asarray([[0, 0, 0, 10, 10], [0, 20, 20, 30, 30]],
                        np.float64))
    ap = m.accumulate()
    assert 0.4 < ap < 0.6  # recall caps at 0.5 with full precision

    comp = CompositeMetric()
    comp.add_metric(ChunkEvaluator())
    comp.update(4, 4, 4)   # varargs forwarded to every child
    res = comp.accumulate()
    assert res[0][2] == 1.0
    comp.reset()
    assert comp.accumulate()[0] == (0.0, 0.0, 0.0)

    # the threshold guard: a zero-IoU detection is never a TP even at
    # overlap_threshold=0
    m0 = DetectionMAP(overlap_threshold=0.0)
    m0.update(np.asarray([[0, 0.9, 100, 100, 110, 110]], np.float64),
              np.asarray([[0, 0, 0, 10, 10]], np.float64))
    assert m0.accumulate() == 0.0
