"""Fleet API + meta-optimizer chain + AMP rewrite + launch tests.

Mirrors the reference's test_fleet_base.py, test_dist_strategy
(meta-optimizer wiring), test_mixed_precision and test_launch semantics."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.fleet import (DistributedStrategy, Fleet,
                              PaddleCloudRoleMaker, UserDefinedRoleMaker)
from paddle_tpu.fleet.role_maker import Role


def _build(seed_w=None):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        pred = layers.fc(x, 1, name="p")
        loss = layers.mean(layers.square_error_cost(pred, y))
    return main, startup, pred, loss


def _batches(n=12, seed=0):
    rng = np.random.RandomState(seed)
    w = np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)
    for _ in range(n):
        xb = rng.randn(16, 4).astype(np.float32)
        yield xb, (xb @ w + 0.1).astype(np.float32)


def _train(main, startup, loss, steps=12):
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        losses = []
        for xb, yb in _batches(steps):
            out, = exe.run(main, feed={"x": xb, "y": yb},
                           fetch_list=[loss])
            losses.append(float(out))
    return losses


# ---------------------------------------------------------------------------
# role maker / facade
# ---------------------------------------------------------------------------

def test_cloud_role_maker_trainer_env(monkeypatch):
    monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    monkeypatch.setenv("PADDLE_TRAINER_ENDPOINTS",
                       "h0:1,h1:2,h2:3,h3:4")
    rm = PaddleCloudRoleMaker()
    assert rm.is_worker() and not rm.is_server()
    assert rm.worker_index() == 2
    assert rm.worker_num() == 4
    assert not rm.is_first_worker()


def test_cloud_role_maker_pserver_env(monkeypatch):
    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    monkeypatch.setenv("POD_IP", "10.0.0.2")
    monkeypatch.setenv("PADDLE_PORT", "7164")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST",
                       "10.0.0.1:7164,10.0.0.2:7164")
    rm = PaddleCloudRoleMaker()
    assert rm.is_server()
    assert rm.server_index() == 1
    assert rm.server_num() == 2


def test_fleet_facade_and_strategy_roundtrip():
    f = Fleet()
    f.init(UserDefinedRoleMaker(current_id=0, role=Role.WORKER,
                                worker_num=2))
    assert f.is_first_worker() and f.worker_num() == 2
    st = DistributedStrategy()
    st.amp = True
    st.recompute = True
    d = st.to_dict()
    st2 = DistributedStrategy.from_dict(d)
    assert st2.amp and st2.recompute and not st2.dgc
    with pytest.raises(ValueError):
        DistributedStrategy.from_dict({"bogus_flag": True})


# ---------------------------------------------------------------------------
# meta-optimizer chain over static programs
# ---------------------------------------------------------------------------

def test_fleet_minimize_plain_sgd_converges():
    main, startup, pred, loss = _build()
    f = Fleet().init(UserDefinedRoleMaker())
    with pt.program_guard(main, startup):
        f.distributed_optimizer(pt.optimizer.SGD(0.05),
                                DistributedStrategy())
        f.minimize(loss, startup_program=startup, program=main)
    losses = _train(main, startup, loss)
    assert losses[-1] < losses[0]


def test_fleet_amp_rewrite_inserts_casts_and_trains():
    main, startup, pred, loss = _build()
    st = DistributedStrategy()
    st.amp = True
    f = Fleet().init(UserDefinedRoleMaker())
    with pt.program_guard(main, startup):
        f.distributed_optimizer(pt.optimizer.SGD(0.05), st)
        f.minimize(loss, startup_program=startup, program=main)
    types = [op.type for op in main.global_block.ops]
    assert "cast" in types, types
    assert "check_finite_and_unscale" in types
    losses = _train(main, startup, loss)
    assert losses[-1] < losses[0]


def test_fleet_recompute_trains():
    main, startup, pred, loss = _build()
    st = DistributedStrategy()
    st.recompute = True
    # checkpoint at the fc output
    st.recompute_configs = {"checkpoints": [pred.name]}
    f = Fleet().init(UserDefinedRoleMaker())
    with pt.program_guard(main, startup):
        f.distributed_optimizer(pt.optimizer.SGD(0.05), st)
        f.minimize(loss, startup_program=startup, program=main)
    losses = _train(main, startup, loss)
    assert losses[-1] < losses[0]


def test_gradient_merge_applies_every_k_steps():
    main, startup, pred, loss = _build()
    st = DistributedStrategy()
    st.gradient_merge = True
    st.gradient_merge_configs = {"k_steps": 4, "avg": True}
    f = Fleet().init(UserDefinedRoleMaker())
    with pt.program_guard(main, startup):
        f.distributed_optimizer(pt.optimizer.SGD(0.1), st)
        f.minimize(loss, startup_program=startup, program=main)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        wname = main.all_parameters()[0].name
        w0 = np.asarray(pt.global_scope().find_var(wname)).copy()
        batches = list(_batches(4))
        # 3 steps: no parameter change yet
        for xb, yb in batches[:3]:
            exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        w3 = np.asarray(pt.global_scope().find_var(wname))
        np.testing.assert_allclose(w3, w0)
        # 4th step applies the merged update
        exe.run(main, feed={"x": batches[3][0], "y": batches[3][1]},
                fetch_list=[loss])
        w4 = np.asarray(pt.global_scope().find_var(wname))
        assert not np.allclose(w4, w0)


def test_lamb_lars_meta_swap():
    for flag, op_type in [("lamb", "lamb"), ("lars", "lars_momentum")]:
        main, startup, pred, loss = _build()
        st = DistributedStrategy()
        setattr(st, flag, True)
        f = Fleet().init(UserDefinedRoleMaker())
        inner = pt.optimizer.Adam(0.001) if flag == "lamb" else \
            pt.optimizer.Momentum(0.001, momentum=0.9)
        with pt.program_guard(main, startup):
            f.distributed_optimizer(inner, st)
            f.minimize(loss, startup_program=startup, program=main)
        types = {op.type for op in main.global_block.ops}
        assert op_type in types, (flag, types)


def test_dgc_compress_topk_and_residual():
    from paddle_tpu.fleet import DGCMomentumOptimizer
    dgc = DGCMomentumOptimizer(pt.optimizer.Momentum(0.1, momentum=0.9),
                               rampup_begin_step=0, sparsity=0.75)
    g = np.array([4.0, -3.0, 0.1, 0.2], np.float32)
    out = dgc.compress("w", g)
    assert np.count_nonzero(out) == 1 and out[0] == 4.0
    # residual carries the dropped mass into the next step
    out2 = dgc.compress("w", np.zeros(4, np.float32))
    assert out2[1] == -3.0


# ---------------------------------------------------------------------------
# PS wiring through the facade
# ---------------------------------------------------------------------------

def test_fleet_ps_worker_server_flow():
    f = Fleet().init(UserDefinedRoleMaker())
    st = DistributedStrategy()
    st.a_sync = True
    f._strategy = st
    server = f.init_server()
    server.init_param("w", np.zeros(2, np.float32))
    comm = f.init_worker()
    comm.send("w", np.ones(2, np.float32))
    f.barrier_worker()
    f.stop_worker()
    assert comm.recv("w")[0] < 0


# ---------------------------------------------------------------------------
# launch CLI
# ---------------------------------------------------------------------------

def test_launch_collective_env_contract(tmp_path):
    script = tmp_path / "probe.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        rank = os.environ["PADDLE_TRAINER_ID"]
        n = os.environ["PADDLE_TRAINERS_NUM"]
        eps = os.environ["PADDLE_TRAINER_ENDPOINTS"]
        assert len(eps.split(",")) == int(n), eps
        print("rank", rank, "of", n)
    """))
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.fleet.launch",
         "--nproc_per_node", "2", str(script)],
        capture_output=True, text=True, cwd="/root/repo", timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr


def test_launch_propagates_failure(tmp_path):
    script = tmp_path / "boom.py"
    script.write_text("import os, sys; "
                      "sys.exit(3 if os.environ['PADDLE_TRAINER_ID'] == '1' "
                      "else 0)")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.fleet.launch",
         "--nproc_per_node", "2", str(script)],
        capture_output=True, text=True, cwd="/root/repo", timeout=120)
    assert r.returncode == 3


# ---------------------------------------------------------------------------
# device-path DGC + LocalSGD (dp=4 CPU mesh, reference dgc_op.cc /
# localsgd_optimizer.py:78-140 semantics as SPMD steps)
# ---------------------------------------------------------------------------

def _reg_task(seed=7, d=6):
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    true_w = rng.randn(d, 1).astype(np.float32)

    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    params = {"w": jnp.zeros((d, 1), jnp.float32),
              "b": jnp.zeros((1,), jnp.float32)}
    def make_batch(n):
        x = rng.randn(n, d).astype(np.float32)
        return x, (x @ true_w).astype(np.float32)
    return loss_fn, params, make_batch


def test_dgc_spmd_convergence_parity():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from paddle_tpu.fleet import DGCMomentumOptimizer

    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    loss_fn, params0, make_batch = _reg_task()

    def train(sparsity, rampup):
        dgc = DGCMomentumOptimizer(pt.optimizer.Momentum(0.05, 0.9),
                                   rampup_begin_step=rampup,
                                   sparsity=sparsity)
        step, init = dgc.build_spmd_step(loss_fn, mesh, lr=0.05,
                                         momentum=0.9)
        params, state = dict(params0), init(params0)
        losses = []
        for i in range(60):
            params, state, loss = step(params, state, make_batch(32))
            losses.append(float(loss))
        return losses, state

    dense_losses, _ = train(sparsity=0.75, rampup=10 ** 9)  # never ramps
    dgc_losses, state = train(sparsity=0.75, rampup=0)
    assert dense_losses[-1] < dense_losses[0] * 0.05
    # convergence parity: compressed training still converges
    assert dgc_losses[-1] < dgc_losses[0] * 0.05, dgc_losses[::10]
    # the residuals actually carry mass (compression really happened)
    (u, v), step_cnt = state
    assert int(step_cnt) == 60
    assert float(jnp.abs(v["w"]).sum()) > 0.0


def test_localsgd_spmd_round():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from paddle_tpu.fleet import LocalSGDOptimizer

    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    loss_fn, params, make_batch = _reg_task(seed=9)
    lsgd = LocalSGDOptimizer(pt.optimizer.SGD(0.05), k_steps=4)
    round_fn = lsgd.build_spmd_round(loss_fn, mesh, lr=0.05)

    losses = []
    for r in range(12):
        x, y = zip(*[make_batch(16) for _ in range(4)])  # k local steps
        batches = (np.stack(x), np.stack(y))
        params, loss = round_fn(params, batches)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1, losses
    # params come back replicated (the pmean re-sync): every device's
    # shard holds the same full array
    w = params["w"]
    assert w.sharding.is_fully_replicated
    shards = [np.asarray(s.data) for s in w.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    # and a wrong microbatch count is rejected, not silently run
    with pytest.raises(ValueError, match="k_steps"):
        x, y = make_batch(16)
        round_fn(params, (np.stack([x] * 3), np.stack([y] * 3)))


def test_dgc_rampup_transition():
    """rampup>0 exercises the lax.cond dense->sparse switch: residuals
    stay zero through the dense phase and carry mass after ramping."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from paddle_tpu.fleet import DGCMomentumOptimizer

    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    loss_fn, params, make_batch = _reg_task(seed=5)
    dgc = DGCMomentumOptimizer(pt.optimizer.Momentum(0.05, 0.9),
                               rampup_begin_step=5, sparsity=0.75)
    step, init = dgc.build_spmd_step(loss_fn, mesh, lr=0.05, momentum=0.9)
    state = init(params)
    for i in range(1, 11):
        params, state, loss = step(params, state, make_batch(32))
        (u, v), cnt = state
        vmass = float(jnp.abs(v["w"]).sum())
        assert np.isfinite(float(loss))
        if i <= 5:
            assert vmass == 0.0, (i, vmass)  # dense phase: no residual
    assert vmass > 0.0  # compression engaged after rampup


def test_fleet_pipeline_strategy_runs_schedule():
    """strategy.pipeline=True routes minimize through the real
    pipeline_train rewrite (pipeline_optimizer.py analog)."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        with pt.device_guard("gpu:0"):
            h = layers.fc(x, 8, act="tanh")
        with pt.device_guard("gpu:1"):
            loss = layers.mean(layers.square_error_cost(
                layers.fc(h, 1), y))
        st = DistributedStrategy()
        st.pipeline = True
        st.pipeline_configs = {"accumulate_steps": 2}
        f = Fleet().init(UserDefinedRoleMaker())
        f.distributed_optimizer(pt.optimizer.SGD(0.05), st)
        f.minimize(loss, startup_program=startup, program=main)
    assert "pipeline_train" in [op.type for op in main.global_block.ops]
    exe = pt.Executor()
    rng = np.random.RandomState(0)
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        losses = []
        for i in range(6):
            xb = rng.randn(8, 4).astype(np.float32)
            out, = exe.run(main, feed={"x": xb, "y": xb[:, :1].copy()},
                           fetch_list=[loss])
            losses.append(float(out))
    assert losses[-1] < losses[0], losses
