"""API-compat guard (SURVEY §4.6 — the reference's check_op_desc.py
golden-spec diffing): the live registry must not silently drop ops or
change signatures vs tools/op_registry_golden.json."""
import json
import os
import subprocess
import sys


def test_registry_matches_golden():
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "check_op_registry.py")
    proc = subprocess.run([sys.executable, tools], capture_output=True,
                          text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_golden_has_full_surface():
    golden = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "op_registry_golden.json")
    ops = json.load(open(golden))
    assert len(ops) >= 476
    # spot-check signature capture of a mutating optimizer op
    assert ops["sgd"]["inplace_map"].get("ParamOut") == "Param"
    assert ops["lookup_table_v2"]["non_diff_inputs"] == ["Ids"]


def test_api_surface_matches_reference():
    """Top-level name parity with the reference's python/paddle
    __init__ (tools/check_api_surface.py; reference analog:
    tools/check_api_compatible.py)."""
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "check_api_surface.py")
    env = dict(os.environ, PT_FORCE_CPU="1")
    proc = subprocess.run([sys.executable, tool], capture_output=True,
                          text=True, timeout=300, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_tpu_scripts_parse():
    """The run-sheet scripts are TPU-only (never executed in CI); at
    least guarantee they stay syntactically valid (.py via ast, .sh via
    bash -n)."""
    import ast
    import shutil
    import subprocess
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    checked = 0
    for fn in sorted(os.listdir(root)):
        path = os.path.join(root, fn)
        if fn.endswith(".py"):
            ast.parse(open(path).read(), filename=fn)
            checked += 1
        elif fn.endswith(".sh") and shutil.which("bash"):
            subprocess.run(["bash", "-n", path], check=True,
                           capture_output=True)
            checked += 1
    assert checked >= 3


def test_tpu_scripts_import():
    """ast.parse let a broken run sheet through in round 5: the scripts
    were invoked as `python scripts/x.py` (so the repo root was NOT on
    sys.path) and one used the nonexistent np.bfloat16 — every section
    died on the live tunnel. Actually EXECUTE the scripts' import +
    setup surface on CPU, from a cwd that is not the repo root, exactly
    how the run sheet launches them."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # PT_FORCE_CPU, not JAX_PLATFORMS: the axon sitecustomize overrides
    # the env var, and a stray TPU job from CI would wedge a concurrent
    # run-sheet session on the tunnel (observed in round 5)
    env = dict(os.environ, PT_FORCE_CPU="1")
    env.pop("PYTHONPATH", None)  # scripts must self-insert the repo root

    # tpu_experiments --selftest runs imports + tiny-shape jits, rc=0
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "tpu_experiments.py"),
         "--selftest"], capture_output=True, text=True, timeout=300,
        cwd="/tmp", env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "selftest OK" in proc.stdout

    # the TPU-asserting scripts must die on the backend check (meaning
    # all their imports resolved), not on any import failure. NB: a bare
    # `'tpu' in err` would match 'paddle_tpu' inside any traceback — the
    # checks must pin the actual backend-assert message.
    for script in ("inkernel_parity.py", "profile_bert.py",
                   "profile_resnet.py"):
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "scripts", script)],
            capture_output=True, text=True, timeout=300, cwd="/tmp",
            env=env)
        assert proc.returncode != 0
        err = proc.stdout + proc.stderr
        assert "ModuleNotFoundError" not in err, (script, err)
        assert "ImportError" not in err, (script, err)
        assert ("AssertionError: cpu" in err
                or "real TPU backend" in err), (script, err)
