"""API-compat guard (SURVEY §4.6 — the reference's check_op_desc.py
golden-spec diffing): the live registry must not silently drop ops or
change signatures vs tools/op_registry_golden.json."""
import json
import os
import subprocess
import sys


def test_registry_matches_golden():
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "check_op_registry.py")
    proc = subprocess.run([sys.executable, tools], capture_output=True,
                          text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_golden_has_full_surface():
    golden = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "op_registry_golden.json")
    ops = json.load(open(golden))
    assert len(ops) >= 476
    # spot-check signature capture of a mutating optimizer op
    assert ops["sgd"]["inplace_map"].get("ParamOut") == "Param"
    assert ops["lookup_table_v2"]["non_diff_inputs"] == ["Ids"]


def test_tpu_scripts_parse():
    """The run-sheet scripts are TPU-only (never executed in CI); at
    least guarantee they stay syntactically valid (.py via ast, .sh via
    bash -n)."""
    import ast
    import shutil
    import subprocess
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    checked = 0
    for fn in sorted(os.listdir(root)):
        path = os.path.join(root, fn)
        if fn.endswith(".py"):
            ast.parse(open(path).read(), filename=fn)
            checked += 1
        elif fn.endswith(".sh") and shutil.which("bash"):
            subprocess.run(["bash", "-n", path], check=True,
                           capture_output=True)
            checked += 1
    assert checked >= 3
