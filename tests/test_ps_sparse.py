"""PS/sparse path tests: sharded embedding lookup, host KV table,
communicator modes, Downpour-style CTR training.

Mirrors the reference's communicator_test.cc, large_scale_kv semantics
and the dist_fleet_ctr convergence tests (loss must decrease)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from paddle_tpu.distributed import (AsyncCommunicator, DownpourWorker,
                                    GeoCommunicator, LargeScaleKV,
                                    ParamServer, ShardedEmbedding,
                                    SparseTableConfig, SyncCommunicator,
                                    sharded_lookup)


def _mesh(n=4, axis="mp"):
    return Mesh(np.array(jax.devices()[:n]), (axis,))


# ---------------------------------------------------------------------------
# sharded embedding (HBM path)
# ---------------------------------------------------------------------------

def test_sharded_lookup_matches_dense():
    mesh = _mesh(4)
    emb = ShardedEmbedding(vocab_size=10, dim=8, mesh=mesh, seed=1)
    dense = emb.dense_view()
    ids = np.array([[0, 3, 9], [7, 7, 1]], np.int32)
    out = np.asarray(emb.lookup(ids))
    np.testing.assert_allclose(out, dense[ids], atol=1e-6)


def test_sharded_lookup_grad_is_row_sparse():
    mesh = _mesh(4)
    emb = ShardedEmbedding(vocab_size=12, dim=4, mesh=mesh, seed=2)
    ids = jnp.asarray([1, 5, 1], jnp.int32)

    def loss(tbl):
        rows = sharded_lookup(tbl, ids, mesh)
        return (rows * rows).sum()

    g = jax.grad(loss)(emb.table)
    g_dense = np.zeros_like(np.asarray(emb.table))
    n = 4
    rows_per = g_dense.shape[0] // n
    dense = emb.dense_view()
    for i in np.asarray(ids):
        phys = (i % n) * rows_per + i // n
        g_dense[phys] += 2 * dense[i]
    np.testing.assert_allclose(np.asarray(g), g_dense, atol=1e-5)


def test_sharded_lookup_in_jit_train_step():
    mesh = _mesh(2)
    emb = ShardedEmbedding(vocab_size=50, dim=4, mesh=mesh, seed=3)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 50, (8, 3)))
    y = jnp.asarray(np.random.RandomState(1).rand(8) > 0.5, jnp.float32)

    @jax.jit
    def step(tbl):
        def loss(tbl):
            feat = sharded_lookup(tbl, ids, mesh).sum(axis=(1, 2))
            p = jax.nn.sigmoid(feat)
            return -jnp.mean(y * jnp.log(p + 1e-7) +
                             (1 - y) * jnp.log(1 - p + 1e-7))
        l, g = jax.value_and_grad(loss)(tbl)
        return l, tbl - 0.5 * g

    tbl = emb.table
    losses = []
    for _ in range(30):
        l, tbl = step(tbl)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.8


# ---------------------------------------------------------------------------
# host KV
# ---------------------------------------------------------------------------

def test_kv_pull_creates_rows_and_is_stable():
    kv = LargeScaleKV(SparseTableConfig(dim=4, initializer="gaussian"))
    r1 = kv.pull([3, 5])
    r2 = kv.pull([5, 3])
    np.testing.assert_allclose(r1[0], r2[1])
    np.testing.assert_allclose(r1[1], r2[0])
    assert kv.size() == 2


def test_kv_push_sgd_merges_duplicates():
    kv = LargeScaleKV(SparseTableConfig(dim=2, initializer="fill",
                                        fill_value=0.0, optimizer="sgd",
                                        lr=1.0))
    kv.pull([7])
    kv.push([7, 7], np.array([[1.0, 0.0], [0.0, 2.0]]))
    np.testing.assert_allclose(kv.pull([7])[0], [-1.0, -2.0])


@pytest.mark.parametrize("opt", ["adagrad", "adam"])
def test_kv_optimizers_reduce_loss(opt):
    kv = LargeScaleKV(SparseTableConfig(dim=1, initializer="fill",
                                        fill_value=5.0, optimizer=opt,
                                        lr=0.5))
    # minimize x^2 on a single row
    for _ in range(60):
        x = kv.pull([0])[0]
        kv.push([0], 2 * x[None])
    assert abs(kv.pull([0])[0][0]) < 1.0


def test_kv_save_load(tmp_path):
    kv = LargeScaleKV(SparseTableConfig(name="t", dim=3))
    kv.pull([1, 2, 3])
    kv.save(str(tmp_path))
    kv2 = LargeScaleKV(SparseTableConfig(name="t", dim=3))
    kv2.load(str(tmp_path))
    np.testing.assert_allclose(kv.pull([2]), kv2.pull([2]))


# ---------------------------------------------------------------------------
# communicators
# ---------------------------------------------------------------------------

def test_sync_communicator_applies_grads():
    server = ParamServer(lr=0.1)
    server.init_param("w", np.zeros(3, np.float32))
    comm = SyncCommunicator(server)
    comm.start()
    comm.send("w", np.ones(3, np.float32))
    comm.barrier()
    comm.stop()
    np.testing.assert_allclose(comm.recv("w"), -0.1 * np.ones(3))


def test_async_communicator_eventually_applies():
    server = ParamServer(lr=1.0)
    server.init_param("w", np.zeros(1, np.float32))
    comm = AsyncCommunicator(server, merge_steps=2)
    comm.start()
    for _ in range(10):
        comm.send("w", np.ones(1, np.float32))
    comm.barrier()
    comm.stop()
    # 10 grads merged in >=1-sized averaged batches: total update in
    # [-10, -5] (each merged batch of k averages to 1.0 -> -1.0 * batches)
    w = float(comm.recv("w")[0])
    assert -10.0 <= w <= -5.0 + 1e-6


def test_geo_communicator_delta_sync():
    server = ParamServer()
    server.init_param("w", np.zeros(2, np.float32))
    t1 = GeoCommunicator(server, trainer_push_step=5)
    t2 = GeoCommunicator(server, trainer_push_step=5)
    t1.init_local("w")
    t2.init_local("w")
    # each trainer does 5 local steps with constant grad
    for _ in range(5):
        t1.local_step("w", np.array([1.0, 0.0]), lr=0.1)
        t2.local_step("w", np.array([0.0, 1.0]), lr=0.1)
    # both deltas (-0.5 each direction) accumulate on the server
    np.testing.assert_allclose(server.get_param("w"), [-0.5, -0.5],
                               atol=1e-6)
    # t2 pushed last, so its refresh saw the fully-merged state; t1 is
    # one push stale (it catches up at its next push) — geo semantics
    np.testing.assert_allclose(t2.local_param("w"), server.get_param("w"))
    np.testing.assert_allclose(t1.local_param("w"), [-0.5, 0.0])


# ---------------------------------------------------------------------------
# Downpour CTR end-to-end (Wide&Deep-ish on host KV + device dense step)
# ---------------------------------------------------------------------------

def test_downpour_ctr_training_converges():
    rng = np.random.RandomState(0)
    vocab, dim, B, T = 100, 4, 32, 3
    server = ParamServer()
    server.create_sparse_table(SparseTableConfig(
        name="emb", dim=dim, initializer="gaussian", init_scale=0.1,
        optimizer="adagrad", lr=0.5, seed=0))
    worker = DownpourWorker(server, "emb")

    true_w = rng.randn(vocab) * 2

    def make_batch():
        ids = rng.randint(0, vocab, (B, T))
        logits = true_w[ids].sum(1)
        y = (logits > 0).astype(np.float32)
        return ids, y

    @jax.jit
    def step(rows, y):
        def loss_fn(rows):
            logit = rows.sum(axis=(1, 2))
            p = jax.nn.sigmoid(logit)
            return -jnp.mean(y * jnp.log(p + 1e-7) +
                             (1 - y) * jnp.log(1 - p + 1e-7))
        l, g = jax.value_and_grad(loss_fn)(rows)
        return l, g

    losses = []
    for i in range(60):
        ids, y = make_batch()
        l = worker.train_batch(ids, lambda rows, y=y: [
            np.asarray(v) for v in step(jnp.asarray(rows),
                                        jnp.asarray(y))])
        losses.append(float(np.asarray(l)))
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.6, losses[:3]


def test_heter_worker_pipeline_matches_serial():
    """HeterWorker's double-buffered pipeline must produce the same
    training trajectory as the serial DownpourWorker loop when batches
    touch disjoint ids (pipelining never reorders a batch's pull after
    its own push)."""
    from paddle_tpu.distributed import HeterWorker
    rng = np.random.RandomState(5)
    dim, B, T = 4, 8, 2
    nb = 6

    def build_server():
        s = ParamServer()
        s.create_sparse_table(SparseTableConfig(
            name="emb", dim=dim, initializer="gaussian", init_scale=0.1,
            optimizer="sgd", lr=0.3, seed=9))
        return s

    # disjoint id ranges per batch -> pipeline == serial exactly
    batches = []
    for b in range(nb):
        ids = rng.randint(b * 10, (b + 1) * 10, (B, T))
        y = rng.rand(B).astype(np.float32)
        batches.append((ids, y))

    @jax.jit
    def step(rows, y):
        def loss_fn(rows):
            return ((rows.sum(axis=(1, 2)) - y) ** 2).mean()
        l, g = jax.value_and_grad(loss_fn)(rows)
        return l, g

    def np_step(rows, y):
        l, g = step(jnp.asarray(rows), jnp.asarray(y))
        return float(l), np.asarray(g)

    s1 = build_server()
    serial = DownpourWorker(s1, "emb")
    ref = [serial.train_batch(ids, lambda r, yy=y: np_step(r, yy))
           for ids, y in batches]

    s2 = build_server()
    heter = HeterWorker(s2, "emb", depth=2)
    got = heter.run_pipeline(batches, np_step)
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    # final tables identical
    all_ids = np.concatenate([b[0].reshape(-1) for b in batches])
    np.testing.assert_allclose(s2.pull_sparse("emb", all_ids),
                               s1.pull_sparse("emb", all_ids))


def test_multi_trainer_drains_channel_and_trains():
    """MultiTrainer fan-out (multi_trainer.cc analog): N worker threads
    drain one batch channel; every batch is consumed exactly once and
    CTR training still converges."""
    from paddle_tpu.distributed import MultiTrainer
    rng = np.random.RandomState(11)
    vocab, dim, B, T = 60, 4, 16, 3
    server = ParamServer()
    server.create_sparse_table(SparseTableConfig(
        name="emb", dim=dim, initializer="gaussian", init_scale=0.1,
        optimizer="adagrad", lr=0.5, seed=2))
    worker = DownpourWorker(server, "emb")
    true_w = rng.randn(vocab) * 2

    batches = []
    for _ in range(40):
        ids = rng.randint(0, vocab, (B, T))
        y = (true_w[ids].sum(1) > 0).astype(np.float32)
        batches.append((ids, y))

    @jax.jit
    def step(rows, y):
        def loss_fn(rows):
            logit = rows.sum(axis=(1, 2))
            p = jax.nn.sigmoid(logit)
            return -jnp.mean(y * jnp.log(p + 1e-7) +
                             (1 - y) * jnp.log(1 - p + 1e-7))
        return jax.value_and_grad(loss_fn)(rows)

    consumed = []

    def worker_fn(batch):
        ids, y = batch
        consumed.append(1)
        return worker.train_batch(ids, lambda rows, yy=y: [
            np.asarray(v) for v in step(jnp.asarray(rows),
                                        jnp.asarray(yy))])

    losses = MultiTrainer(thread_num=3).run(batches, worker_fn)
    assert len(losses) == len(batches) == len(consumed)
    assert np.mean(losses[-8:]) < np.mean(losses[:8]) * 0.7


def test_multi_trainer_propagates_worker_error():
    from paddle_tpu.distributed import MultiTrainer

    def bad(batch):
        raise ValueError("worker exploded")

    with pytest.raises(ValueError, match="exploded"):
        MultiTrainer(thread_num=2).run([1, 2, 3], bad)


# ---------------------------------------------------------------------------
# PSLib descriptor layer (pslib/node.py + optimizer_factory.py)
# ---------------------------------------------------------------------------

def test_pslib_descriptor_validation_and_text():
    from paddle_tpu.distributed import DownpourDescriptor
    d = DownpourDescriptor()
    with pytest.raises(ValueError, match="not support"):
        d.server.add_sparse_table(0, {"bogus_key": 1})
    with pytest.raises(ValueError, match="accessor_class"):
        d.server.add_sparse_table(0, {"sparse_accessor_class": "Nope"})
    d.sparse_table("emb", strategy={
        "sparse_accessor_class": "DownpourCtrAccessor",
        "sparse_learning_rate": 0.5, "sparse_embedx_dim": 4})
    txt = d.server.to_text()
    assert "DownpourCtrAccessor" in txt and "PS_SPARSE_TABLE" in txt
    assert "embedx_dim: 4" in txt


def test_pslib_descriptor_drives_wide_deep_ctr():
    """Wide&Deep-style CTR run configured entirely through the Downpour
    descriptor (optimizer_factory.py DistributedAdam builds protos ->
    pslib runtime; here desc -> LargeScaleKV + DownpourWorker): sparse
    wide embedding on the PS, dense deep tower on-device."""
    from paddle_tpu.distributed import DownpourDescriptor
    rng = np.random.RandomState(2)
    vocab, dim, B, T = 200, 4, 32, 3

    desc = DownpourDescriptor()
    desc.sparse_table("wide_emb", strategy={
        "sparse_accessor_class": "DownpourCtrAccessor",
        "sparse_learning_rate": 0.5,
        "sparse_initial_range": 0.1,
        "sparse_embedx_dim": dim,
        "sparse_seed": 0})
    server, workers = desc.build_runtime()
    worker = workers["wide_emb"]
    assert server.sparse["wide_emb"].cfg.optimizer == "adagrad"

    true_w = rng.randn(vocab) * 2
    deep_w = jnp.zeros((dim * T, 8))
    deep_v = jnp.zeros((8,))

    def make_batch():
        ids = rng.randint(0, vocab, (B, T))
        y = (true_w[ids].sum(1) > 0).astype(np.float32)
        return ids, y

    @jax.jit
    def step(rows, deep_w, deep_v, y):
        def loss_fn(rows, deep_w, deep_v):
            wide = rows.sum(axis=(1, 2))
            h = jax.nn.relu(rows.reshape(rows.shape[0], -1) @ deep_w)
            deep = h @ deep_v
            p = jax.nn.sigmoid(wide + deep)
            return -jnp.mean(y * jnp.log(p + 1e-7) +
                             (1 - y) * jnp.log(1 - p + 1e-7))
        l, g = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
            rows, deep_w, deep_v)
        return l, g

    losses = []
    for i in range(60):
        ids, y = make_batch()
        rows = worker.pull(ids)
        l, (g_rows, g_w, g_v) = step(jnp.asarray(rows), deep_w, deep_v,
                                     jnp.asarray(y))
        worker.push(ids, np.asarray(g_rows))
        deep_w = deep_w - 0.1 * g_w
        deep_v = deep_v - 0.1 * g_v
        losses.append(float(l))
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.6, losses[:5]


# ---------------------------------------------------------------------------
# heter service split (heterxpu_trainer.cc RegisterServiceHandler +
# hetercpu_worker.cc): sparse stage in THIS process, dense stage in a
# real accelerator-service subprocess over the framed-socket wire
# ---------------------------------------------------------------------------

def test_heter_service_two_process_training():
    import os
    import socket
    import subprocess
    import sys
    import time

    runner = os.path.join(os.path.dirname(__file__), "heter_runner.py")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen([sys.executable, runner, str(port)],
                            stdout=subprocess.PIPE, text=True, env=env)
    try:
        line = proc.stdout.readline()
        assert line, "service died on startup"
        from paddle_tpu.distributed import (HeterClient, HeterCpuWorker,
                                            ParamServer)
        client = HeterClient("127.0.0.1:%d" % port)
        assert client.output_names == ["loss", "row_grads"]

        server = ParamServer()
        server.create_sparse_table(SparseTableConfig(
            name="emb", dim=4, initializer="gaussian", init_scale=0.1,
            optimizer="adagrad", lr=0.5, seed=0))
        worker = HeterCpuWorker(server, "emb", client)

        rng = np.random.RandomState(0)
        true_w = rng.randn(100) * 2
        losses = []
        for i in range(50):
            ids = rng.randint(0, 100, (32, 3))
            y = (true_w[ids].sum(1) > 0).astype(np.float32)
            loss = worker.train_batch(ids, {"y": y})
            losses.append(float(np.asarray(loss)))
        client.end_pass()
        client.stop()
        assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.6, \
            losses[:5]
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
