"""Quantized collectives on the mp axis (ISSUE 19, docs/spmd.md
"Quantized collectives on the mp axis"): the composed gather-compute
path that lets Megatron-sharded params ride the quantized wire instead
of demoting to legacy GSPMD — per-SHARD scale blocks on the mp
all-gather, the fp8-e4m3 wire (GRID_FP8=448) where the probe admits
it, axis-aware spec-grouped bucket planning, the
dist.collective_quant_mp failpoint, warn-once demotion accounting, and
the TrainStep threading behind FLAGS_collective_quant_mp (dp2xmp2:
zero demotions, loss-budget parity with the composed fp32 oracle, zero
steady-state recompiles)."""
import contextlib
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import failpoints, quant
from paddle_tpu.flags import get_flag, set_flags
from paddle_tpu.jit import TrainStep
from paddle_tpu.mesh import ShardingPlan
from paddle_tpu.mesh import collectives as coll
from paddle_tpu.mesh import compat as _compat
from paddle_tpu.monitor import reset_all, snapshot, stat_get

fp8_only = pytest.mark.skipif(not quant.supports_fp8(),
                              reason="backend has no fp8-e4m3")


@contextlib.contextmanager
def _flags(**kv):
    old = {k: get_flag(k) for k in kv}
    set_flags(kv)
    try:
        yield
    finally:
        set_flags(old)


def _mesh22():
    return ShardingPlan("dp2xmp2").mesh


# ---------------------------------------------------------------------------
# wire primitives: quantized_all_gather / gather_param / reduce_scatter
# ---------------------------------------------------------------------------

_SHAPES = {"w1": (8, 16), "b1": (16,), "w2": (16, 8)}
_SPECS = {"w1": (None, "mp"), "w2": ("mp", None)}


def _mp_plan(mp_mode, min_numel=4):
    return coll.plan_buckets(_SHAPES, "dp", 2, mode="int8", bucket_mb=4,
                             min_numel=min_numel, specs=_SPECS,
                             axis_sizes={"mp": 2}, mp_mode=mp_mode)


def _gather(full, mp_mode, gather_idx=0):
    """Run gather_param over the mp axis of a dp2xmp2 mesh, feeding
    the FULL tensor sharded per its spec; returns the reassembled
    full-value as seen inside the body."""
    import jax
    from jax.sharding import PartitionSpec as P
    plan = _mp_plan(mp_mode)
    g = plan.gathers[gather_idx]
    f = _compat.shard_map(
        lambda w: coll.gather_param(w, g, plan), mesh=_mesh22(),
        in_specs=(P(*_SPECS[g.name]),), out_specs=P(),
        check_vma=False)
    return np.asarray(jax.jit(f)(full)), g


def test_gather_param_fp32_oracle_exact():
    """mp_mode fp32 is the wire-parity oracle: the gathered value is
    BITWISE the resident full tensor."""
    rng = np.random.RandomState(3)
    x = rng.randn(8, 16).astype(np.float32)
    got, g = _gather(x, "fp32")
    assert not g.quantized
    assert np.array_equal(got, x)


def test_gather_param_int8_per_shard_scales():
    """int8 gather error is bounded by each SHARD's own grid step —
    the per-shard scale rule: rank 1's outlier must not widen rank
    0's grid."""
    rng = np.random.RandomState(4)
    x = rng.randn(8, 16).astype(np.float32)
    x[:, 8:] *= 100.0  # rank 1's shard carries the outliers
    got, g = _gather(x, "int8")
    assert g.quantized
    # per-shard bound: each half against ITS OWN absmax grid
    for lo, hi in ((0, 8), (8, 16)):
        step = np.abs(x[:, lo:hi]).max() / 127.0
        assert np.max(np.abs(got[:, lo:hi] - x[:, lo:hi])) <= \
            0.5 * step + 1e-6
    # shared-scale wire could not meet rank 0's bound (grid 100x wider)
    shared_step = np.abs(x).max() / 127.0
    assert np.abs(x[:, :8]).max() / 127.0 < shared_step / 50


def test_gather_param_row_split_dim0():
    """Row-parallel (dim-0) shards reassemble in rank order through
    the moveaxis layout."""
    rng = np.random.RandomState(5)
    x = rng.randn(16, 8).astype(np.float32)
    got, g = _gather(x, "int8", gather_idx=1)
    assert g.dim == 0 and g.name == "w2"
    step = np.abs(x).max() / 127.0
    assert np.max(np.abs(got - x)) <= 0.5 * step + 1e-6


@fp8_only
def test_gather_param_fp8_wire_roundtrip():
    """fp8-e4m3 wire: ~2 mantissa-bit relative error on the 448 grid,
    never worse than a few percent of each block's absmax."""
    rng = np.random.RandomState(6)
    x = (rng.randn(8, 16) * 2.0).astype(np.float32)
    got, g = _gather(x, "fp8")
    assert g.quantized
    assert np.all(np.isfinite(got))
    assert np.max(np.abs(got - x)) <= 0.07 * np.abs(x).max()


@fp8_only
def test_fp8_wire_dead_block_exact_zeros():
    """An all-zero scale block must round-trip to EXACT zeros on the
    fp8 wire too: the dead-block guard pins the divisor to 1.0 (PR-15
    contract), so no 0/0 NaN can enter the gathered params."""
    x = np.zeros((8, 16), np.float32)
    x[0, 0] = 3.0  # one live value on rank 0's shard
    got, _ = _gather(x, "fp8")
    assert np.all(np.isfinite(got))
    assert got[0, 0] != 0.0
    assert np.all(got[1:, :] == 0.0) and np.all(got[0, 8:] == 0.0)


@fp8_only
def test_fp8_reduce_scatter_replicated_is_qdq():
    """Replicated input through the fp8 reduce-scatter must collapse
    to one quantize-dequantize round trip: payloads upcast to fp32
    before summing (fp8 addition is not exact), so the mean of n
    identical encodings IS the encoding."""
    import jax
    from jax.sharding import PartitionSpec as P
    rng = np.random.RandomState(7)
    x = rng.randn(4 * coll.BLOCK).astype(np.float32)
    f = _compat.shard_map(
        lambda v: coll.quantized_reduce_scatter(v, "mp", 2, mode="fp8",
                                                mean=True),
        mesh=_mesh22(), in_specs=(P(),), out_specs=P(None),
        check_vma=False)
    got = np.asarray(jax.jit(f)(x))
    # reference via the same encode/decode path, scales shared (input
    # replicated -> pmax is identity)
    import jax.numpy as jnp
    x2 = jnp.asarray(x.reshape(-1, coll.BLOCK))
    s = coll._block_scales(x2)
    ref = np.asarray(coll._wire_decode(
        coll._wire_encode(x2, s, "fp8"), s, "fp8")).reshape(-1)
    seg = got.size
    assert np.allclose(got, ref[:seg], atol=1e-6) or \
        np.allclose(got, ref[seg:], atol=1e-6)


def test_int8_reduce_scatter_rank_varying_mean():
    """Rank-varying input: each rank's segment returns the cross-rank
    mean within the shared-scale grid error (scales pmax over the
    REDUCTION axis — the mirror image of the gather's per-shard
    rule)."""
    import jax
    from jax.sharding import PartitionSpec as P
    rng = np.random.RandomState(8)
    x = rng.randn(2, 2 * coll.BLOCK).astype(np.float32)

    def body(v):
        mine = v[jax.lax.axis_index("mp")]
        return coll.quantized_reduce_scatter(
            mine, "mp", 2, mode="int8", mean=True)

    f = _compat.shard_map(body, mesh=_mesh22(), in_specs=(P(),),
                          out_specs=P(None), check_vma=False)
    got = np.asarray(jax.jit(f)(x))
    want = x.mean(axis=0)
    step = np.abs(x).max() / 127.0
    seg = got.size
    err0 = np.max(np.abs(got - want[:seg]))
    err1 = np.max(np.abs(got - want[seg:]))
    assert min(err0, err1) <= 1.5 * step


# ---------------------------------------------------------------------------
# resolve_wire_mode: fp8 probe fallback
# ---------------------------------------------------------------------------

def test_resolve_wire_mode_passthrough_and_unknown():
    assert quant.resolve_wire_mode("fp32") == "fp32"
    assert quant.resolve_wire_mode("int8") == "int8"
    with pytest.raises(ValueError):
        quant.resolve_wire_mode("int4")


def test_resolve_wire_mode_probe_off_falls_back_int8(monkeypatch):
    monkeypatch.setattr(quant, "supports_fp8", lambda: False)
    monkeypatch.setattr(quant, "_WIRE_WARNED", False)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert quant.resolve_wire_mode("fp8") == "int8"
        assert quant.resolve_wire_mode("fp8") == "int8"
    assert len([x for x in w if "fp8" in str(x.message)]) == 1


# ---------------------------------------------------------------------------
# axis-aware planner
# ---------------------------------------------------------------------------

def test_plan_spec_grouped_buckets_never_mix_domains():
    shapes = {"a": (64, 64), "b": (64, 64), "c": (64, 64),
              "d": (64, 64)}
    specs = {"a": (None, "mp"), "c": (None, "mp"), "d": ("mp", None)}
    plan = coll.plan_buckets(shapes, "dp", 2, mode="int8", bucket_mb=4,
                             min_numel=4, specs=specs,
                             axis_sizes={"mp": 2}, mp_mode="int8")
    # one bucket per distinct spec, members never mixed
    by_spec = {b.spec: set(b.names) for b in plan.buckets}
    assert by_spec[()] == {"b"}
    assert by_spec[(None, "mp")] == {"a", "c"}
    assert by_spec[("mp", None)] == {"d"}
    # sharded members carry LOCAL geometry
    for b in plan.buckets:
        if b.spec == (None, "mp"):
            assert set(b.shapes) == {(64, 32)}
    assert [g.name for g in plan.gathers] == ["a", "c", "d"]
    # determinism: pure function of inputs
    plan2 = coll.plan_buckets(shapes, "dp", 2, mode="int8", bucket_mb=4,
                              min_numel=4, specs=specs,
                              axis_sizes={"mp": 2}, mp_mode="int8")
    assert plan == plan2


def test_plan_small_threshold_applies_to_local_shard():
    shapes = {"w": (4, 1024)}  # full 4096 elems, shard 2048
    specs = {"w": (None, "mp")}
    plan = coll.plan_buckets(shapes, "dp", 2, mode="int8", bucket_mb=4,
                             min_numel=3000, specs=specs,
                             axis_sizes={"mp": 2}, mp_mode="int8")
    # the SHARD (2048) is under threshold: per-tensor fp32 dp sync,
    # but the gather still rides the quantized wire
    assert dict(plan.small) == {"w": 2048}
    assert plan.gathers and plan.gathers[0].quantized


def test_plan_bad_specs_raise():
    with pytest.raises(ValueError):  # two sharded dims
        coll._local_shape((8, 8), ("mp", "mp"), {"mp": 2})
    with pytest.raises(ValueError):  # tuple axis entry
        coll._local_shape((8, 8), (("dp", "mp"), None), {"mp": 2})
    with pytest.raises(ValueError):  # axis outside non-data axes
        coll._local_shape((8, 8), ("dp", None), {"mp": 2})
    with pytest.raises(ValueError):  # indivisible dim
        coll._local_shape((9, 8), ("mp", None), {"mp": 2})


def test_plan_mp_failpoint_demotes_one_gather_group():
    assert "dist.collective_quant_mp" in failpoints.KNOWN_SITES
    f0 = stat_get("STAT_collective_quant_mp_fallbacks")
    failpoints.arm_spec("dist.collective_quant_mp=raise@once")
    try:
        plan = _mp_plan("int8")
    finally:
        failpoints.disarm("dist.collective_quant_mp")
    # planning walks reverse-topologically: w2's (mp, None) group is
    # offered first and faulted to the fp32 wire; w1's group stays
    # quantized. Fired once per GROUP, not per tensor.
    quantized = {g.name: g.quantized for g in plan.gathers}
    assert quantized == {"w1": True, "w2": False}
    assert stat_get("STAT_collective_quant_mp_fallbacks") == f0 + 1
    # disarmed: both quantize again
    plan2 = _mp_plan("int8")
    assert all(g.quantized for g in plan2.gathers)


def test_census_by_axis_and_gather_entries():
    plan = _mp_plan("int8")
    ca = coll.census_by_axis(plan)
    assert set(ca) == {"dp", "mp"}
    assert ca["mp"].get("int8", 0) > 0       # quantized gather payload
    assert ca["mp"].get("float32", 0) > 0    # fp32 scale rows
    # flat census (legacy shape) is the axis sum
    flat = coll.census_bytes(plan)
    for dt in flat:
        assert flat[dt] == sum(ca[a].get(dt, 0) for a in ca)
    # fp32 oracle wire: no one-byte payloads on the mp axis
    ca32 = coll.census_by_axis(_mp_plan("fp32"))
    assert "int8" not in ca32["mp"] and "float8_e4m3fn" not in ca32["mp"]


# ---------------------------------------------------------------------------
# TrainStep: composed Megatron path on dp2xmp2
# ---------------------------------------------------------------------------

def _ts_loss(out, label):
    import paddle_tpu.nn.functional as F
    return F.cross_entropy(out, label)


def _megatron_rule(name, shape):
    from jax.sharding import PartitionSpec as P
    if shape == (8, 16):
        return P(None, "mp")   # column-parallel
    if shape == (16, 4):
        return P("mp", None)   # row-parallel
    return None


def _build_mp_step(mode, mp, accum=1, seed=42):
    from paddle_tpu import nn
    pt.dygraph.seed(seed)
    np.random.seed(seed)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    o = pt.optimizer.SGD(0.1, parameters=m.parameters())
    set_flags({"FLAGS_collective_quant": mode,
               "FLAGS_collective_quant_mp": mp,
               "FLAGS_collective_quant_min_numel": 16})
    return TrainStep(m, _ts_loss, o,
                     plan=ShardingPlan("dp2xmp2", params=_megatron_rule),
                     grad_accum_steps=accum)


def _run(step, steps=5, batch=16, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        x = rng.randn(batch, 8).astype(np.float32)
        y = rng.randint(0, 4, (batch, 1)).astype(np.int32)
        out.append(float(step((x,), (y,))))
    return out


def test_composed_int8_zero_demotions_budget_and_recompiles():
    with _flags(FLAGS_collective_quant="off",
                FLAGS_collective_quant_mp="off",
                FLAGS_collective_quant_min_numel=16):
        reset_all()
        oracle = _run(_build_mp_step("fp32", "fp32"))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            step = _build_mp_step("int8", "int8")
            got = _run(step)
        # ZERO demotions: no GSPMD fallback warning, counter untouched
        assert not [x for x in w if "GSPMD" in str(x.message)]
        assert stat_get("STAT_collective_quant_demotions") == 0
        diff = max(abs(a - b) for a, b in zip(got, oracle))
        assert diff < 0.05, (diff, got, oracle)
        assert step._step_fn._cache_size() == 1  # zero steady-state
        m = step._coll_manifest
        assert m["gathers"] == 2
        assert m["axes"]["mp"]["bytes"].get("int8", 0) > 0
        assert m["axes"]["dp"]["bytes"].get("int8", 0) > 0
        assert stat_get("STAT_collective_quant_mp_gathers") >= 10
        # params stay SHARDED at rest through the whole run
        for n, v in step._state.items():
            if tuple(v.shape) == (8, 16):
                assert tuple(v.sharding.spec)[:2] == (None, "mp")


def test_composed_fp32_oracle_matches_legacy_gspmd():
    """The composed fp32 wire is a PARITY oracle: same math as the
    legacy GSPMD sync (gather is exact, grad slice is exact, same
    batch/rng split), so losses agree to float tolerance."""
    with _flags(FLAGS_collective_quant="off",
                FLAGS_collective_quant_mp="off",
                FLAGS_collective_quant_min_numel=16):
        legacy = _run(_build_mp_step("off", "off"))
        composed = _run(_build_mp_step("fp32", "fp32"))
        diff = max(abs(a - b) for a, b in zip(composed, legacy))
        assert diff < 1e-5, (diff, composed, legacy)


@fp8_only
def test_composed_fp8_within_budget():
    with _flags(FLAGS_collective_quant="off",
                FLAGS_collective_quant_mp="off",
                FLAGS_collective_quant_min_numel=16):
        oracle = _run(_build_mp_step("fp32", "fp32"))
        step = _build_mp_step("int8", "fp8")
        got = _run(step)
        diff = max(abs(a - b) for a, b in zip(got, oracle))
        assert diff < 0.05, (diff, got, oracle)
        assert step._coll_manifest["axes"]["mp"]["bytes"].get(
            "float8_e4m3fn", 0) > 0


def test_composed_fp8_probe_off_pins_int8(monkeypatch):
    """Where the probe does NOT admit fp8, the build lands on the int8
    wire — same geometry, no crash, counted as int8 in the census."""
    monkeypatch.setattr(quant, "supports_fp8", lambda: False)
    monkeypatch.setattr(quant, "_WIRE_WARNED", True)  # silence
    with _flags(FLAGS_collective_quant="off",
                FLAGS_collective_quant_mp="off",
                FLAGS_collective_quant_min_numel=16):
        step = _build_mp_step("int8", "fp8")
        got = _run(step, steps=2)
        assert all(np.isfinite(got))
        assert step._coll_plan.mp_mode == "int8"
        assert step._coll_manifest["axes"]["mp"]["bytes"].get(
            "int8", 0) > 0


def test_flag_off_demotes_warn_once_and_counts():
    """FLAGS_collective_quant_mp=off pins PR-17 behavior: sharded
    params keep the legacy GSPMD sync — but the diagnostic now fires
    ONCE per TrainStep (not per param, not per rebuild) and every
    demoted param lands in STAT_collective_quant_demotions."""
    with _flags(FLAGS_collective_quant="off",
                FLAGS_collective_quant_mp="off",
                FLAGS_collective_quant_min_numel=16):
        reset_all()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            step = _build_mp_step("int8", "off")
            _run(step, steps=2)
            step._step_fn = None    # force a rebuild
            _run(step, steps=1)
        demo = [x for x in w if "GSPMD" in str(x.message)]
        assert len(demo) == 1, [str(x.message) for x in w]
        # 2 sharded params x 2 builds
        assert stat_get("STAT_collective_quant_demotions") == 4
        assert step._coll_manifest is None  # legacy path, no census


def test_composed_grad_accum_finite():
    with _flags(FLAGS_collective_quant="off",
                FLAGS_collective_quant_mp="off",
                FLAGS_collective_quant_min_numel=16):
        got = _run(_build_mp_step("int8", "int8", accum=2), steps=3)
        assert all(np.isfinite(got))


def test_statusz_mp_section():
    with _flags(FLAGS_collective_quant="off",
                FLAGS_collective_quant_mp="off",
                FLAGS_collective_quant_min_numel=16):
        reset_all()
        with _flags(FLAGS_collective_quant="int8",
                    FLAGS_collective_quant_mp="int8"):
            step = _build_mp_step("int8", "int8")
            _run(step, steps=3)
            from paddle_tpu.introspect import statusz
            sz = statusz()["mesh"]["collectives"]
            assert sz["quant"]["mode_mp"] == "int8"
        assert sz["quant"]["gathers"] == 2
        assert sz["quant"]["gather_exchanges"] == 3 * 2
        assert sz["quant"]["demotions"] == 0
        assert sz["quant"]["mp_fallbacks"] == 0
        assert sz["bytes"]["mp"]["int8"] == 3 * \
            step._coll_manifest["axes"]["mp"]["bytes"]["int8"]


def test_mp_flag_is_a_lowering_flag():
    """Flipping FLAGS_collective_quant_mp reshapes the traced program
    (gather ops, wire dtype, shard-shaped exchange) — it must miss the
    AOT cache, i.e. live in the lowering fingerprint."""
    from paddle_tpu.flags import _LOWERING_FLAGS, lowering_snapshot
    assert "FLAGS_collective_quant_mp" in _LOWERING_FLAGS
    with _flags(FLAGS_collective_quant_mp="off"):
        a = lowering_snapshot()
        with _flags(FLAGS_collective_quant_mp="int8"):
            b = lowering_snapshot()
    assert a != b


def test_stat_diff_families():
    """The new counters classify correctly in the regression gate:
    _mp_gathers is the healthy composed steady state (exchanges
    dispatched per step — exempt); _demotions and _mp_fallbacks growth
    mean builds or gather groups fell off the quantized wire — cost."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "stat_diff", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "stat_diff.py"))
    sd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sd)
    assert not sd._is_cost_counter("STAT_collective_quant_mp_gathers")
    assert sd._is_cost_counter("STAT_collective_quant_demotions")
    assert sd._is_cost_counter("STAT_collective_quant_mp_fallbacks")
    assert not sd._is_cost_counter("STAT_mesh_collective_bytes"
                                   '{axis="mp",dtype="int8"}')


# ---------------------------------------------------------------------------
# trace_merge: wire-byte annotation of exchange slices from digests
# ---------------------------------------------------------------------------

def _phase_event(pid, step, name="phase/exchange", ts=None):
    return {"name": name, "ph": "X", "pid": pid, "tid": 1,
            "ts": float(100 * step if ts is None else ts), "dur": 50.0,
            "cat": "phase", "args": {"step": step}}


def test_trace_merge_annotates_exchange_slices_with_wire_bytes(
        tmp_path):
    """Digest ``coll`` deltas divided by their step span land on every
    exchange slice the span covers — per dtype plus a total — and
    slices outside any span (or on ranks without a digest log) stay
    untouched."""
    from tools import trace_merge
    r0 = {"traceEvents": [_phase_event(0, s) for s in (1, 2, 3, 4)] +
          [_phase_event(0, 2, name="phase/compute")]}
    r1 = {"traceEvents": [_phase_event(1, s) for s in (1, 2, 3, 4)]}
    merged = trace_merge.merge_traces([r0, r1], align_step=1)
    # rank 0's digests: steps 1-2 moved 2000 int8 + 200 fp32, steps
    # 3-4 moved only 1000 int8; rank 1 logs nothing
    digs = [{"v": 1, "step": 2, "coll": {"int8": 2000,
                                         "float32": 200}},
            {"v": 1, "step": 4, "coll": {"int8": 1000}}]
    n = trace_merge.annotate_wire_bytes(merged, {0: digs})
    assert n == 4
    got = {(_e["pid"], _e["args"]["step"]): _e["args"]
           for _e in merged["traceEvents"]
           if _e.get("name") == "phase/exchange"}
    assert got[(0, 1)]["wire_bytes"] == {"int8": 1000, "float32": 100}
    assert got[(0, 2)]["wire_bytes_total"] == 1100
    assert got[(0, 3)]["wire_bytes"] == {"int8": 500}
    assert "wire_bytes" not in got[(1, 2)]
    # the compute slice is never annotated
    comp = [e for e in merged["traceEvents"]
            if e.get("name") == "phase/compute"]
    assert all("wire_bytes" not in (e.get("args") or {}) for e in comp)


def test_trace_merge_digests_cli_roundtrip(tmp_path):
    import json
    from tools import trace_merge
    p0 = str(tmp_path / "r0.json")
    with open(p0, "w") as f:
        json.dump({"traceEvents": [_phase_event(0, 1),
                                   _phase_event(0, 2)]}, f)
    dpath = str(tmp_path / "digests_rank0.jsonl")
    with open(dpath, "w") as f:
        f.write(json.dumps({"v": 1, "step": 2,
                            "coll": {"int8": 800}}) + "\n")
        f.write("{corrupt\n")  # torn tail write must be skipped
    out = str(tmp_path / "merged.json")
    assert trace_merge.main([p0, "-o", out,
                             "--digests", "0=%s" % dpath]) == 0
    with open(out) as f:
        merged = json.load(f)
    ex = [e for e in merged["traceEvents"]
          if e.get("name") == "phase/exchange"]
    assert all(e["args"]["wire_bytes"] == {"int8": 400} for e in ex)
