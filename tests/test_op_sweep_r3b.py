"""Round-3 second op batch: registry long-tail (hsigmoid, pool3d-index,
correlation, bilateral_slice, collectives-to-root, PS helper ops,
detection labels, dgc_momentum)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.ops  # noqa: F401
import paddle_tpu.parallel.collective  # noqa: F401  (registers c_*)
from paddle_tpu.core.registry import REGISTRY, LowerCtx

from test_op_sweep_r3 import run_op  # reuse the harness


def test_hsigmoid_matches_loop_oracle():
    r = np.random.RandomState(0)
    n, d, c = 4, 6, 8
    x = r.randn(n, d).astype(np.float32)
    w = r.randn(c - 1, d).astype(np.float32)
    b = r.randn(c - 1).astype(np.float32)
    label = r.randint(0, c, (n, 1)).astype(np.int64)
    o = run_op("hsigmoid", {"X": x, "W": w, "Label": label, "Bias": b},
               {"num_classes": c})
    out = np.asarray(o["Out"][0]).reshape(-1)
    # oracle: complete-binary-tree SimpleCode walk
    import math
    depth = int(math.ceil(math.log2(c)))
    for i in range(n):
        full = int(label[i, 0]) + c
        loss = 0.0
        for dd in range(depth):
            node = (full >> (dd + 1)) - 1
            if node < 0:
                continue
            code = (full >> dd) & 1
            pre = float(x[i] @ w[node] + b[node])
            loss += math.log1p(math.exp(-abs(pre))) + max(pre, 0) \
                - code * pre
        np.testing.assert_allclose(out[i], loss, rtol=1e-5, atol=1e-5)

    def f(xv):
        return run_op("hsigmoid", {"X": xv, "W": w, "Label": label,
                                   "Bias": b},
                      {"num_classes": c})["Out"][0].sum()
    g = jax.grad(f)(jnp.asarray(x))
    assert np.isfinite(np.asarray(g)).all() and np.abs(g).sum() > 0


def test_empty_and_inplace_abn():
    o = run_op("empty", {}, {"shape": [2, 3], "dtype": "float32"})
    assert np.asarray(o["Out"][0]).shape == (2, 3)
    r = np.random.RandomState(1)
    x = r.randn(2, 3, 4, 4).astype(np.float32)
    args = {"X": x, "Scale": np.ones(3, np.float32),
            "Bias": np.zeros(3, np.float32),
            "Mean": np.zeros(3, np.float32),
            "Variance": np.ones(3, np.float32)}
    bn = run_op("batch_norm", dict(args))["Y"][0]
    abn = run_op("inplace_abn", dict(args),
                 {"activation": "leaky_relu", "alpha": 0.1})["Y"][0]
    ref = np.where(np.asarray(bn) >= 0, np.asarray(bn),
                   0.1 * np.asarray(bn))
    np.testing.assert_allclose(np.asarray(abn), ref, atol=1e-6)


def test_max_pool3d_with_index():
    r = np.random.RandomState(2)
    x = r.randn(1, 2, 4, 4, 4).astype(np.float32)
    o = run_op("max_pool3d_with_index", {"X": x},
               {"ksize": [2, 2, 2], "strides": [2, 2, 2]})
    out = np.asarray(o["Out"][0])
    mask = np.asarray(o["Mask"][0])
    assert out.shape == (1, 2, 2, 2, 2)
    for ci in range(2):
        blk = x[0, ci, :2, :2, :2]
        assert out[0, ci, 0, 0, 0] == blk.max()
        d, h, w = np.unravel_index(blk.argmax(), blk.shape)
        assert mask[0, ci, 0, 0, 0] == d * 16 + h * 4 + w


def test_correlation_zero_displacement():
    r = np.random.RandomState(3)
    x1 = r.randn(1, 3, 5, 5).astype(np.float32)
    x2 = r.randn(1, 3, 5, 5).astype(np.float32)
    o = np.asarray(run_op("correlation",
                          {"Input1": x1, "Input2": x2},
                          {"pad_size": 1, "max_displacement": 1,
                           "stride2": 1})["Output"][0])
    assert o.shape == (1, 9, 5, 5)
    # center channel (d=(0,0)) == mean over C of x1*x2
    np.testing.assert_allclose(o[0, 4], (x1[0] * x2[0]).mean(0),
                               rtol=1e-5)


def test_bilateral_slice_constant_grid():
    # grid holding constant multiplier m per output channel: out = m*x
    n, cin, h, w = 1, 2, 4, 4
    cout = 2
    grid = np.zeros((n, cout * cin, 4, 3, 3), np.float32)
    grid[:, 0] = 2.0  # out0 = 2*x0
    grid[:, 3] = 3.0  # out1 = 3*x1
    x = np.random.RandomState(4).randn(n, cin, h, w).astype(np.float32)
    guide = np.full((n, h, w), 0.5, np.float32)
    o = np.asarray(run_op("bilateral_slice",
                          {"X": x, "Grid": grid, "Guide": guide},
                          {"has_offset": False})["Out"][0])
    np.testing.assert_allclose(o[0, 0], 2 * x[0, 0], atol=1e-5)
    np.testing.assert_allclose(o[0, 1], 3 * x[0, 1], atol=1e-5)


def test_c_reduce_and_scatter_shardmap():
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("dp",))
    import paddle_tpu.parallel as dist
    dist.init_parallel_env({"dp": 4})
    x = np.arange(8, dtype=np.float32)

    def body(xs):
        o = run_op("c_reduce_sum", {"X": xs}, {"ring_id": 0})
        return o["Out"][0]

    out = shard_map(body, mesh=mesh, in_specs=P("dp"),
                    out_specs=P("dp"))(jnp.asarray(x))
    # every shard holds the global sum of its position across shards
    np.testing.assert_allclose(np.asarray(out)[:2],
                               [0 + 2 + 4 + 6, 1 + 3 + 5 + 7])


def test_split_merge_ids_roundtrip():
    ids = np.array([5, 2, 9, 4, 2], np.int64)
    o = run_op("split_ids", {"Ids": ids}, {"n_parts": 3})
    parts = [np.asarray(p) for p in o["Out"]]
    for i, p in enumerate(parts):
        valid = p[p >= 0]
        assert (valid % 3 == i).all()
    rows = [np.stack([np.full(4, float(v), np.float32) if v >= 0
                      else np.zeros(4, np.float32) for v in p])
            for p in parts]
    m = run_op("merge_ids", {"Ids": ids, "Rows": list(parts),
                             "X": rows}, {})
    out = np.asarray(m["Out"][0])
    np.testing.assert_allclose(out[:, 0], ids.astype(np.float32))


def test_split_selected_rows():
    from paddle_tpu.core.selected_rows import SelectedRows
    sr = SelectedRows(np.array([1, 7, 4]),
                      np.arange(12, dtype=np.float32).reshape(3, 4), 10)
    opdef = REGISTRY.get("split_selected_rows")
    outs = opdef.lower(LowerCtx(), {"X": [sr]},
                       {"height_sections": [5, 5]})["Out"]
    a, b = outs
    assert sorted(np.asarray(a.rows).tolist()) == [1, 4]
    assert np.asarray(b.rows).tolist() == [2]  # 7 - 5


def test_lookup_sparse_table_ops():
    run_op("lookup_sparse_table_init", {},
           {"name": "t1", "dim": 4, "initializer": "fill",
            "init_scale": 0.0, "optimizer": "sgd", "lr": 0.1, "seed": 0})
    ids = np.array([3, 8], np.int64)
    vals = np.ones((2, 4), np.float32) * 7
    run_op("lookup_sparse_table_write", {"Ids": ids, "Value": vals},
           {"table_name": "t1"})
    out = np.asarray(run_op("lookup_sparse_table_read", {"Ids": ids},
                            {"table_name": "t1"})["Out"][0])
    np.testing.assert_allclose(out, vals)


def test_checkpoint_notify_over_transport(tmp_path):
    from paddle_tpu.distributed import ParamServer, SparseTableConfig
    from paddle_tpu.distributed.rpc import PsClient, PsServer
    srv = PsServer(ParamServer(), "127.0.0.1:0", n_trainers=1).start()
    cli = PsClient(srv.endpoint)
    try:
        cli.create_sparse_table(SparseTableConfig(
            name="ck", dim=2, initializer="fill", fill_value=1.5))
        cli.pull_sparse("ck", np.array([0, 1], np.int64))
        d = str(tmp_path / "snap")
        import os
        os.makedirs(d, exist_ok=True)
        run_op("checkpoint_notify", {},
               {"endpoints": [srv.endpoint], "dirname": d})
        assert (tmp_path / "snap" / "ck.kv").exists()
    finally:
        cli.stop_server()
        cli.close()
        from paddle_tpu.ops.distributed_ps import reset_ps_clients
        reset_ps_clients()


def test_box_decoder_and_assign():
    prior = np.array([[0, 0, 10, 10]], np.float32)
    deltas = np.zeros((1, 8), np.float32)  # 2 classes, zero deltas
    scores = np.array([[0.2, 0.8]], np.float32)
    o = run_op("box_decoder_and_assign",
               {"PriorBox": prior, "TargetBox": deltas,
                "BoxScore": scores}, {})
    dec = np.asarray(o["DecodeBox"][0])
    assign = np.asarray(o["OutputAssignBox"][0])
    # zero deltas decode back to the prior (xyxy with -1 width conv)
    np.testing.assert_allclose(assign[0], dec[0, 4:])
    np.testing.assert_allclose(dec[0, :4], [0, 0, 10, 10], atol=1e-4)


def test_generate_proposal_labels():
    rois = np.array([[0, 0, 10, 10], [20, 20, 30, 30],
                     [0, 0, 9, 9], [40, 40, 50, 50]], np.float32)
    gt = np.array([[[0, 0, 10, 10]]], np.float32)
    cls = np.array([[3]], np.int32)
    o = run_op("generate_proposal_labels",
               {"RpnRois": rois, "GtClasses": cls, "GtBoxes": gt,
                "RpnRoisNum": np.array([4], np.int32),
                "GtNum": np.array([1], np.int32)},
               {"batch_size_per_im": 4, "fg_fraction": 0.5,
                "fg_thresh": 0.5, "bg_thresh_hi": 0.5,
                "bg_thresh_lo": 0.0},
               rng=jax.random.PRNGKey(3))
    labels = np.asarray(o["LabelsInt32"][0])
    # the exact-overlap roi must be fg with class 3; far rois bg (0)
    assert (labels == 3).sum() >= 1
    assert (labels == 0).sum() >= 1
    wi = np.asarray(o["BboxInsideWeights"][0])
    assert (wi[labels == 3] == 1).all()
    assert (wi[labels == 0] == 0).all()


def test_roi_perspective_transform_identity():
    r = np.random.RandomState(6)
    x = r.randn(1, 1, 6, 6).astype(np.float32)
    ph = pw = 4
    # axis-aligned quad covering [0,3]x[0,3] -> identity sampling
    rois = np.array([[0, 0, 3, 0, 3, 3, 0, 3]], np.float32)
    o = run_op("roi_perspective_transform", {"X": x, "ROIs": rois},
               {"transformed_height": ph, "transformed_width": pw,
                "spatial_scale": 1.0})
    out = np.asarray(o["Out"][0])
    np.testing.assert_allclose(out[0, 0], x[0, 0, :4, :4], atol=1e-4)


def test_dgc_momentum_matches_momentum_rule():
    r = np.random.RandomState(7)
    p = r.randn(5).astype(np.float32)
    g = r.randn(5).astype(np.float32)
    v = r.randn(5).astype(np.float32)
    # dgc_momentum_op.h: step < rampup_begin_step -> momentum
    o = run_op("dgc_momentum",
               {"Param": p, "Grad": g, "Velocity": v,
                "LearningRate": np.asarray([0.1], np.float32),
                "CurrentStep": np.asarray([0], np.float32)},
               {"mu": 0.9, "rampup_begin_step": 10.0})
    v_ref = 0.9 * v + g
    np.testing.assert_allclose(np.asarray(o["VelocityOut"][0]), v_ref,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(o["ParamOut"][0]),
                               p - 0.1 * v_ref, rtol=1e-6)
    # step >= rampup (incl. the default -1.0 from step 0): plain SGD,
    # velocity untouched
    o2 = run_op("dgc_momentum",
                {"Param": p, "Grad": g, "Velocity": v,
                 "LearningRate": np.asarray([0.1], np.float32),
                 "CurrentStep": np.asarray([0], np.float32)},
                {"mu": 0.9})
    np.testing.assert_allclose(np.asarray(o2["VelocityOut"][0]), v,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(o2["ParamOut"][0]), p - 0.1 * g,
                               rtol=1e-6)
