"""Reader decorators + samplers (round-5 io parity; reference
reader/decorator.py and fluid/dataloader/sampler.py semantics)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import reader as R


def _r(vals):
    def rd():
        return iter(vals)
    return rd


def test_map_chain_compose_firstn_cache():
    assert list(R.map_readers(lambda a, b: a + b,
                              _r([1, 2]), _r([10, 20]))()) == [11, 22]
    assert list(R.chain(_r([1]), _r([2, 3]))()) == [1, 2, 3]
    assert list(R.compose(_r([1, 2]), _r([(3, 4), (5, 6)]))()) == \
        [(1, 3, 4), (2, 5, 6)]
    with pytest.raises(ValueError):
        list(R.compose(_r([1]), _r([2, 3]))())
    assert list(R.firstn(_r(range(100)), 3)()) == [0, 1, 2]
    calls = []

    def counting():
        calls.append(1)
        return iter([7, 8])
    c = R.cache(counting)
    assert list(c()) == [7, 8]
    assert list(c()) == [7, 8]
    assert len(calls) == 1  # second pass replays from memory


def test_samplers():
    data = list(range(10))
    assert list(R.SequenceSampler(data)) == data
    np.random.seed(0)
    rs = list(R.RandomSampler(data))
    assert sorted(rs) == data
    assert len(list(R.RandomSampler(data, replacement=True,
                                    num_samples=4))) == 4


def test_distributed_batch_sampler_partitions():
    data = list(range(12))
    seen = []
    for rank in (0, 1):
        s = R.DistributedBatchSampler(data, batch_size=2,
                                      num_replicas=2, rank=rank)
        batches = list(s)
        assert all(len(b) == 2 for b in batches)
        seen.extend(i for b in batches for i in b)
    # the two ranks together cover the dataset exactly once
    assert sorted(seen) == data
    # shuffle reshuffles per epoch deterministically
    s = R.DistributedBatchSampler(data, batch_size=3, num_replicas=2,
                                  rank=0, shuffle=True)
    s.set_epoch(0)
    e0 = [i for b in s for i in b]
    s.set_epoch(1)
    e1 = [i for b in s for i in b]
    assert e0 != e1
    assert R.get_worker_info() is None
    # dataset smaller than nranks: every rank still gets len(sampler)
    # batches (wrapping pad), so lockstep SPMD loops stay in sync
    for rank in range(4):
        tiny = R.DistributedBatchSampler([42], batch_size=1,
                                         num_replicas=4, rank=rank)
        assert len(list(tiny)) == len(tiny) == 1


def test_io_program_state_roundtrip():
    import tempfile
    pt.enable_static()
    try:
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = pt.layers.data("x", [4])
            pt.layers.fc(x, 3)
        exe = pt.Executor()
        exe.run(startup)
        with tempfile.TemporaryDirectory() as d:
            pt.io.save_persistables(exe, d, main_program=main)
            state = pt.io.load_program_state(d)
            assert state  # the fc weight + bias
            changed = {k: np.zeros_like(v) for k, v in state.items()}
            missing = pt.io.set_program_state(main, changed)
            assert missing == []
            state2 = pt.io.load_program_state(d)
            assert set(state2) == set(state)
    finally:
        pt.disable_static()
