"""Transformer/BERT model-path tests (previously only covered indirectly
via __graft_entry__). Oracle: composed numpy/jnp attention; contract:
fused-QKV self-attention must match the unfused projections, and the
masked-position MLM gather must equal slicing the full-logits path."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.dygraph import Tensor, seed


def _rand(shape, s=0):
    return np.random.RandomState(s).randn(*shape).astype(np.float32)


def test_mha_fused_qkv_matches_manual():
    import jax.numpy as jnp
    from paddle_tpu.nn.transformer import MultiHeadAttention
    seed(0)
    mha = MultiHeadAttention(32, 4)
    mha.eval()
    x = _rand((2, 8, 32), 1)
    out = mha(Tensor(x))

    # manual composed attention with the same projection weights
    q = x @ np.asarray(mha.q_proj.weight.value) + np.asarray(mha.q_proj.bias.value)
    k = x @ np.asarray(mha.k_proj.weight.value) + np.asarray(mha.k_proj.bias.value)
    v = x @ np.asarray(mha.v_proj.weight.value) + np.asarray(mha.v_proj.bias.value)
    b, s, h, d = 2, 8, 4, 8
    qh = q.reshape(b, s, h, d).transpose(0, 2, 1, 3)
    kh = k.reshape(b, s, h, d).transpose(0, 2, 1, 3)
    vh = v.reshape(b, s, h, d).transpose(0, 2, 1, 3)
    sc = qh @ kh.transpose(0, 1, 3, 2) / np.sqrt(d)
    p = np.exp(sc - sc.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    o = (p @ vh).transpose(0, 2, 1, 3).reshape(b, s, 32)
    ref = o @ np.asarray(mha.out_proj.weight.value) + \
        np.asarray(mha.out_proj.bias.value)
    np.testing.assert_allclose(np.asarray(out.value), ref, atol=1e-4)


def test_mha_fused_qkv_grads_flow_to_all_projections():
    from paddle_tpu.nn.transformer import MultiHeadAttention
    seed(1)
    mha = MultiHeadAttention(16, 2)
    x = Tensor(_rand((2, 4, 16), 2), stop_gradient=False)
    loss = mha(x).sum()
    loss.backward()
    for name in ("q_proj", "k_proj", "v_proj", "out_proj"):
        p = getattr(mha, name)
        assert p.weight.grad is not None, name
        assert float(np.abs(np.asarray(p.weight.grad)).sum()) > 0, name


def test_bert_masked_position_gather_parity():
    from paddle_tpu.models.bert import BertConfig, BertForPretraining
    seed(2)
    cfg = BertConfig(vocab_size=300, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=2, intermediate_size=64,
                     max_position_embeddings=32)
    model = BertForPretraining(cfg)
    model.eval()
    B, S, M = 2, 16, 4
    rng = np.random.RandomState(3)
    ids = rng.randint(0, 300, (B, S)).astype(np.int32)
    pos = np.stack([np.sort(rng.choice(S, M, replace=False))
                    for _ in range(B)]).astype(np.int32)
    mlm_all, _ = model(Tensor(ids))
    mlm_g, _ = model(Tensor(ids), masked_positions=Tensor(pos))
    a, g = np.asarray(mlm_all.value), np.asarray(mlm_g.value)
    assert g.shape == (B, M, 300)
    for b in range(B):
        np.testing.assert_allclose(g[b], a[b, pos[b]], atol=1e-5)


def test_bert_trainstep_masked_positions_converges():
    from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                        pretraining_loss)
    from paddle_tpu.jit import TrainStep
    seed(3)
    cfg = BertConfig(vocab_size=200, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=2, intermediate_size=64,
                     max_position_embeddings=32)
    model = BertForPretraining(cfg)
    opt = pt.optimizer.Adam(1e-3, parameters=model.parameters())
    step = TrainStep(model, pretraining_loss, opt)
    B, S, M = 4, 16, 4
    rng = np.random.RandomState(4)
    ids = rng.randint(0, 200, (B, S)).astype(np.int32)
    pos = np.stack([np.sort(rng.choice(S, M, replace=False))
                    for _ in range(B)]).astype(np.int32)
    lbl = rng.randint(0, 200, (B, M)).astype(np.int32)
    nsp = rng.randint(0, 2, (B, 1)).astype(np.int32)
    losses = [float(step((ids, None, None, pos), (lbl, nsp)))
              for _ in range(5)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_transformer_encoder_decoder():
    """paddle.nn.Transformer parity: full encoder-decoder forward,
    causal decoder self-attention, gradient flow through cross
    attention."""
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.nn as nn

    model = nn.Transformer(d_model=32, nhead=4, num_encoder_layers=2,
                           num_decoder_layers=2, dim_feedforward=64,
                           dropout=0.0)
    rng = np.random.RandomState(0)
    src = pt.to_tensor(rng.randn(2, 6, 32).astype(np.float32))
    tgt = pt.to_tensor(rng.randn(2, 5, 32).astype(np.float32))
    causal = nn.Transformer.generate_square_subsequent_mask(5)
    out = model(src, tgt, tgt_mask=causal)
    assert tuple(np.asarray(out.value).shape) == (2, 5, 32)

    # causality: decoder output at position t must not depend on
    # tgt positions > t
    tgt2 = np.asarray(tgt.value).copy()
    tgt2[:, -1] += 100.0  # perturb the LAST target position
    model.eval()
    out_a = np.asarray(model(src, tgt, tgt_mask=causal).value)
    out_b = np.asarray(model(src, pt.to_tensor(tgt2),
                             tgt_mask=causal).value)
    np.testing.assert_allclose(out_a[:, :-1], out_b[:, :-1],
                               rtol=1e-4, atol=1e-5)
    assert np.abs(out_a[:, -1] - out_b[:, -1]).max() > 1e-3

    # grads reach encoder params through cross attention
    model.train()
    loss = (model(src, tgt, tgt_mask=causal) ** 2).mean()
    loss.backward()
    enc_p = model.encoder.layers[0].self_attn.q_proj.weight
    assert enc_p.grad is not None
    assert float(np.abs(np.asarray(enc_p.grad.value
                                   if hasattr(enc_p.grad, "value")
                                   else enc_p.grad)).max()) > 0
