"""Front-door tests (ISSUE 20, docs/frontdoor.md).

Covers the tentpole: catalog registration, priority-ordered dispatch,
per-tenant token-bucket quotas, predicted-deadline and queue-full
shedding with attributed counters, graceful hot-swap (in-flight
finishes on the OLD version; an armed frontdoor.swap failpoint aborts
with the pointer unflipped and no future hung), the autoscaler's
up/down/veto decisions from the /sloz signal gauges, /modelz + the
/statusz section, per-model SLO objective install/retract, and the
flag-off one-read disabled path. Plus the satellites: ServingQueueFull
parity across BOTH pool families and monitor.gauge_retract.
"""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import failpoints, frontdoor, monitor, slo
from paddle_tpu import tracing
from paddle_tpu.frontdoor import (EndpointSpec, FrontDoor, ModelCatalog,
                                  QuotaExceeded, SwapFailed,
                                  UnknownModel)
from paddle_tpu.monitor import get_float_stats, labeled, stat_get
from paddle_tpu.serving import (DeadlineBurned, PredictorPool,
                                ServingQueueFull)


class _Core:
    """Predictor-like dummy: records the marker value of every request
    it executes, tagged with this core's version."""

    feed_names = ["x"]
    fetch_names = ["y"]

    def __init__(self, version=1, delay_s=0.0):
        self.version = version
        self.delay_s = delay_s
        self.seen = []
        self.lock = threading.Lock()

    def run(self, feeds):
        if self.delay_s:
            time.sleep(self.delay_s)
        x = np.asarray(feeds[0])
        with self.lock:
            self.seen.append(float(x.flat[0]))
        return [x * float(self.version)]


def _spec(core, version="v1", **kw):
    kw.setdefault("pool_kwargs", {"max_batch": 4,
                                  "batch_timeout_ms": 1.0})
    return EndpointSpec(name="toy", kind="predictor", version=version,
                        factory=lambda: core, **kw)


def _req(v=1.0, rows=1):
    return [np.full((rows, 2), v, np.float32)]


@pytest.fixture(autouse=True)
def _clean():
    yield
    assert frontdoor.active() is None, \
        "a test leaked a live FrontDoor"
    monitor.reset_all()
    tracing.reset()
    slo.disable()
    slo.clear_objectives()


# ---------------------------------------------------------------------------
# flag-off pin + catalog
# ---------------------------------------------------------------------------

def test_flag_off_is_one_read_and_dark():
    """FLAGS_frontdoor unset: no FrontDoor exists, active() is one
    module-global read returning None, /modelz reports disabled, and
    nothing frontdoor-related reaches the registry when the pools are
    used directly (the opt-in contract of docs/MIGRATION.md)."""
    assert not pt.get_flags(["FLAGS_frontdoor"])["FLAGS_frontdoor"]
    assert frontdoor.active() is None
    assert frontdoor.modelz() == {"enabled": False, "models": {}}
    assert frontdoor.status_summary() == {"enabled": False}
    assert "disabled" in frontdoor.modelz_text()
    pool = PredictorPool(_Core(), max_batch=2, batch_timeout_ms=1.0)
    try:
        pool.run(_req())
    finally:
        pool.close()
    assert not [k for k in get_float_stats() if "frontdoor" in k]


def test_catalog_and_spec_validation():
    c = ModelCatalog([_spec(_Core(), "v1"), _spec(_Core(2), "v2")])
    assert c.names() == ["toy"]
    assert c.versions("toy") == ["v1", "v2"]
    assert c.get("toy").version == "v1"          # first registered
    assert c.get("toy", "v2").version == "v2"
    with pytest.raises(UnknownModel):
        c.get("toy", "v9")
    with pytest.raises(UnknownModel):
        c.get("nope")
    with pytest.raises(ValueError):
        EndpointSpec(name="a", kind="bogus")
    with pytest.raises(ValueError):
        EndpointSpec(name="a", kind="generation")    # no factory
    with pytest.raises(ValueError):
        EndpointSpec(name="a", kind="predictor")     # no dir/factory


# ---------------------------------------------------------------------------
# admission: priority, quotas, shedding
# ---------------------------------------------------------------------------

def test_strict_priority_dequeue():
    """With dispatch parked (0 workers), mixed-priority submissions
    drain highest-priority-first, FIFO within a class — not FIFO
    overall."""
    core = _Core()
    door = FrontDoor(ModelCatalog([_spec(core, workers=0,
                                         workers_min=0)]),
                     autoscale=False)
    try:
        futs = [door.submit("toy", _req(v), priority=p)
                for v, p in [(1.0, 0), (2.0, 5), (3.0, 1), (4.0, 5)]]
        door.set_workers("toy", 1)
        for f in futs:
            f.result(timeout=10.0)
        assert core.seen == [2.0, 4.0, 3.0, 1.0], core.seen
    finally:
        door.close()


def test_tenant_quota_token_bucket():
    """burst = rate * FLAGS_frontdoor_quota_burst_s tokens up front,
    then QuotaExceeded with a refill hint; other tenants unaffected;
    rejections attributed per (model, tenant, reason)."""
    door = FrontDoor(ModelCatalog([_spec(
        _Core(), tenant_quota_rps={"limited": 1.0})]), autoscale=False)
    try:
        for _ in range(2):   # burst_s default 2.0 -> 2 tokens
            door.run("toy", _req(), tenant="limited")
        with pytest.raises(QuotaExceeded) as ei:
            door.submit("toy", _req(), tenant="limited")
        assert ei.value.tenant == "limited"
        assert ei.value.retry_after_s > 0
        door.run("toy", _req(), tenant="other")   # unlimited
        assert stat_get(labeled("STAT_frontdoor_quota_rejected",
                                {"model": "toy",
                                 "tenant": "limited"})) == 1
        assert stat_get(labeled(
            "STAT_frontdoor_shed",
            {"model": "toy", "tenant": "limited",
             "reason": "quota"})) == 1
        assert stat_get(labeled("STAT_frontdoor_shed_total",
                                {"model": "toy"})) == 1
        z = frontdoor.modelz()["models"]["toy"]
        assert z["counters"]["quota_rejected"] == 1
        assert z["counters"]["shed"] == {"quota": 1}
    finally:
        door.close()


def test_predicted_deadline_shed_at_admit():
    """A deadline the measured service distribution says cannot be met
    is shed AT THE DOOR (DeadlineBurned), before occupying a queue
    slot."""
    door = FrontDoor(ModelCatalog([_spec(_Core())]), autoscale=False)
    try:
        door.run("toy", _req())          # prime the service EWMA
        ep = door._endpoints["toy"]
        ep.ewma_service_s = 0.5          # measured: ~500ms a request
        with pytest.raises(DeadlineBurned):
            door.submit("toy", _req(), deadline=0.01)
        # a generous deadline still admits
        assert door.run("toy", _req(), deadline=30.0)
        assert stat_get(labeled(
            "STAT_frontdoor_shed",
            {"model": "toy", "tenant": "",
             "reason": "deadline_predicted"})) == 1
    finally:
        door.close()


def test_queue_full_rejects_immediately():
    """The front door never blocks the caller: at the admission bound
    submit() raises ServingQueueFull NOW, with the depth and a backoff
    hint (the PR-9 contract)."""
    door = FrontDoor(ModelCatalog([_spec(_Core(), workers=0,
                                         workers_min=0,
                                         queue_depth=2)]),
                     autoscale=False)
    try:
        door.submit("toy", _req())
        door.submit("toy", _req())
        t0 = time.monotonic()
        with pytest.raises(ServingQueueFull) as ei:
            door.submit("toy", _req())
        assert time.monotonic() - t0 < 0.2   # decided now, no wait
        assert ei.value.queue_depth == 2
        assert ei.value.retry_after_s > 0
        assert stat_get(labeled(
            "STAT_frontdoor_shed",
            {"model": "toy", "tenant": "",
             "reason": "queue_full"})) == 1
        door.set_workers("toy", 1)           # drain before close
    finally:
        door.close()


def test_admit_failpoint_counts_as_shed():
    door = FrontDoor(ModelCatalog([_spec(_Core())]), autoscale=False)
    try:
        with failpoints.armed("frontdoor.admit=raise@once"):
            with pytest.raises(failpoints.InjectedFault):
                door.submit("toy", _req(), tenant="acme")
        assert stat_get(labeled(
            "STAT_frontdoor_shed",
            {"model": "toy", "tenant": "acme",
             "reason": "admit_fault"})) == 1
        door.run("toy", _req())   # disarmed: serving again
    finally:
        door.close()


def test_unknown_model():
    door = FrontDoor(ModelCatalog([_spec(_Core())]), autoscale=False)
    try:
        with pytest.raises(UnknownModel):
            door.submit("nope", _req())
    finally:
        door.close()


# ---------------------------------------------------------------------------
# hot-swap
# ---------------------------------------------------------------------------

def test_hot_swap_in_flight_finishes_on_old_version():
    """deploy(name, v2) warms off-path, flips the pointer, drains v1:
    a request in flight ON v1 completes with v1's output (never
    dropped, never rerouted), and the next request runs on v2."""
    v1, v2 = _Core(1, delay_s=0.3), _Core(2)
    door = FrontDoor(ModelCatalog([_spec(v1, "v1")]), autoscale=False)
    try:
        fut = door.submit("toy", _req(7.0))
        time.sleep(0.1)      # dispatcher is now inside v1's pool
        door.register(_spec(v2, "v2"))    # register + hot-swap
        out = fut.result(timeout=10.0)
        assert np.allclose(out[0], 7.0), "in-flight must finish on v1"
        assert 7.0 in v1.seen and 7.0 not in v2.seen
        out2 = door.run("toy", _req(9.0))
        assert np.allclose(out2[0], 18.0), "post-swap routes to v2"
        z = frontdoor.modelz()["models"]["toy"]
        assert z["active_version"] == "v2"
        assert z["counters"]["swaps"] == 1
        assert [h["version"] for h in z["history"]] == ["v1"]
        assert z["history"][0]["state"] == "retired"
        assert stat_get(labeled("STAT_frontdoor_swaps",
                                {"model": "toy"})) == 1
    finally:
        door.close()


def test_swap_failpoint_leaves_old_serving_nothing_hung():
    """Satellite: an armed frontdoor.swap fault mid-deploy (after
    warmup, before the flip) must leave the OLD version serving, the
    routing pointer unflipped, and every in-flight future resolved —
    typed error or completed result, never a hang."""
    v1, v2 = _Core(1, delay_s=0.25), _Core(2)
    door = FrontDoor(ModelCatalog([_spec(v1, "v1")]), autoscale=False)
    try:
        door.catalog.add(_spec(v2, "v2"))
        fut = door.submit("toy", _req(5.0))
        time.sleep(0.05)     # in flight on v1
        with failpoints.armed("frontdoor.swap=raise@once"):
            with pytest.raises(SwapFailed) as ei:
                door.deploy("toy", "v2")
        assert isinstance(ei.value.cause, failpoints.InjectedFault)
        # the in-flight future resolved with v1's result — no hang
        out = fut.result(timeout=10.0)
        assert np.allclose(out[0], 5.0)
        # pointer unflipped, v1 still serving, v2 never saw traffic
        z = frontdoor.modelz()["models"]["toy"]
        assert z["active_version"] == "v1"
        assert z["history"][0].get("aborted") is True
        out2 = door.run("toy", _req(3.0))
        assert np.allclose(out2[0], 3.0)
        assert v2.seen == []
        assert stat_get(labeled("STAT_frontdoor_swap_aborted",
                                {"model": "toy"})) == 1
        assert stat_get(labeled("STAT_frontdoor_swaps",
                                {"model": "toy"})) == 0
        # the catalog still has v2: a later deploy succeeds
        door.deploy("toy", "v2")
        assert np.allclose(door.run("toy", _req(3.0))[0], 6.0)
    finally:
        door.close()


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------

def test_autoscaler_scales_up_and_down():
    """Queue pressure grows the worker count toward max; confirmed
    idleness (two consecutive intervals) shrinks it toward min. Every
    decision lands in STAT_frontdoor_scale_{up,down} + the decision
    ring."""
    pt.set_flags({"FLAGS_frontdoor_scale_cooldown_s": 0.0})
    try:
        door = FrontDoor(ModelCatalog([_spec(_Core(), workers=0,
                                             workers_min=0,
                                             workers_max=3)]),
                         autoscale=False)
        try:
            futs = [door.submit("toy", _req(float(i)))
                    for i in range(6)]
            d1 = door.autoscale_once()   # depth 6 > 2*0 -> up
            assert [d["action"] for d in d1] == ["scale_up"]
            for f in futs:
                f.result(timeout=10.0)
            assert door.autoscale_once() == []   # idle streak 1
            d3 = door.autoscale_once()           # streak 2 -> down
            assert [d["action"] for d in d3] == ["scale_down"]
            assert stat_get(labeled("STAT_frontdoor_scale_up",
                                    {"model": "toy"})) == 1
            assert stat_get(labeled("STAT_frontdoor_scale_down",
                                    {"model": "toy"})) == 1
            z = frontdoor.modelz()["models"]["toy"]
            acts = [d["action"] for d in z["decisions"]]
            assert acts == ["scale_up", "scale_down"]
            assert z["counters"]["scale_up"] == 1
            assert z["counters"]["scale_down"] == 1
        finally:
            door.close()
    finally:
        pt.set_flags({"FLAGS_frontdoor_scale_cooldown_s": 10.0})


def test_autoscaler_generation_kv_veto():
    """A generation endpoint with saturated TPOT but <10% KV-block
    headroom must NOT scale up (more decode concurrency with no blocks
    thrashes the KV pool) — the decision is recorded as a veto. With
    headroom back, the same signals scale up."""
    from paddle_tpu.generation import (DecoderConfig, GenerationEngine,
                                       init_params)

    def engine():
        cfg = DecoderConfig(vocab_size=64, hidden=32, layers=2,
                            heads=4, max_seq_len=32)
        eng = GenerationEngine(cfg, init_params(cfg, seed=0),
                               num_blocks=16, block_size=4,
                               decode_width=2)
        eng._warmed = True   # no compile-ahead in this test
        return eng

    pt.set_flags({"FLAGS_frontdoor_scale_cooldown_s": 0.0})
    try:
        door = FrontDoor(ModelCatalog([EndpointSpec(
            name="lm", kind="generation", factory=engine,
            quant_mode="int8", workers=1, workers_max=3,
            pool_kwargs={})]), autoscale=False)
        try:
            monitor.gauge_set("GAUGE_slo_tpot_saturation", 2.0)
            monitor.gauge_set("GAUGE_slo_kv_block_headroom", 0.05)
            d1 = door.autoscale_once()
            assert [d["action"] for d in d1] == ["up_vetoed_kv"]
            assert door._endpoints["lm"].workers_target == 1
            monitor.gauge_set("GAUGE_slo_kv_block_headroom", 0.9)
            d2 = door.autoscale_once()
            assert [d["action"] for d in d2] == ["scale_up"]
            assert door._endpoints["lm"].workers_target == 2
        finally:
            door.close()
    finally:
        pt.set_flags({"FLAGS_frontdoor_scale_cooldown_s": 10.0})


# ---------------------------------------------------------------------------
# surfaces: /modelz, /statusz, SLO objectives, labeled series
# ---------------------------------------------------------------------------

def test_modelz_http_and_statusz_section():
    from paddle_tpu import introspect
    door = FrontDoor(ModelCatalog([_spec(_Core())]), autoscale=False)
    srv = introspect.start(port=0)
    try:
        door.run("toy", _req(), tenant="acme")
        txt = urllib.request.urlopen(srv.url + "/modelz",
                                     timeout=5).read().decode()
        assert "toy@v1" in txt and "routed=1" in txt
        z = json.loads(urllib.request.urlopen(
            srv.url + "/modelz?format=json", timeout=5).read())
        assert z["enabled"] and z["models"]["toy"]["kind"] == "predictor"
        st = json.loads(urllib.request.urlopen(
            srv.url + "/statusz", timeout=5).read())
        assert st["frontdoor"]["enabled"]
        assert st["frontdoor"]["models"]["toy"]["version"] == "v1"
        idx = urllib.request.urlopen(srv.url + "/",
                                     timeout=5).read().decode()
        assert "/modelz" in idx
    finally:
        introspect.stop()
        door.close()
    # closed: the surface goes dark
    assert frontdoor.modelz() == {"enabled": False, "models": {}}


def test_slo_objectives_installed_and_retracted():
    """Satellite: registration installs per-model p95 + shed-ratio
    objectives; retirement unregisters them AND retracts their gauges
    (they used to accrete forever)."""
    slo.enable(bucket_s=0.5, n_buckets=20)
    door = FrontDoor(ModelCatalog([_spec(_Core())]), autoscale=False)
    try:
        names = {o.name for o in slo.objectives()}
        assert {"frontdoor_toy_p95", "frontdoor_toy_shed"} <= names
        door.run("toy", _req())
        assert slo.evaluate() is not None   # evaluates cleanly
        snap = monitor.snapshot()["gauges"]
        assert any("frontdoor_toy" in k for k in snap), snap.keys()
    finally:
        door.close()
    names = {o.name for o in slo.objectives()}
    assert not [n for n in names if n.startswith("frontdoor_toy")]
    snap = monitor.snapshot()["gauges"]
    assert not [k for k in snap if "frontdoor_toy" in k], \
        "objective gauges must be retracted on retirement"
    assert not [k for k in snap
                if k.startswith("GAUGE_frontdoor_")], \
        "endpoint gauges must be retracted on retirement"


def test_model_version_tenant_labeled_series():
    """Routing through the front door flushes {model,version,tenant}
    labeled series from the pool trace (tracing._model_names path)."""
    door = FrontDoor(ModelCatalog([_spec(_Core())]), autoscale=False)
    try:
        door.run("toy", _req(), tenant="acme")
        stats = get_float_stats()
        key = labeled("STAT_serving_requests",
                      {"model": "toy", "version": "v1",
                       "tenant": "acme"})
        assert stats.get(key) == 1, [k for k in stats if "toy" in k]
        assert stat_get(labeled("STAT_frontdoor_routed",
                                {"model": "toy",
                                 "version": "v1"})) == 1
    finally:
        door.close()


# ---------------------------------------------------------------------------
# satellites: queue-full parity across pool families, gauge_retract
# ---------------------------------------------------------------------------

def _full_predictor_pool():
    pool = PredictorPool(_Core(), max_batch=2, batch_timeout_ms=1.0,
                         queue_depth=2, _start=False)
    pool.submit(_req())
    pool.submit(_req())
    return pool, lambda: pool.submit(_req(), timeout=0.05)


def _full_generation_pool():
    from paddle_tpu.generation import GenerationRequest
    from paddle_tpu.generation.scheduler import GenerationPool

    class _Eng:   # ctor only touches .on_request_error before start
        decode_width = 2

    pool = GenerationPool(_Eng(), queue_depth=2, _start=False)
    req = GenerationRequest(prompt=[1, 2], max_new_tokens=2)
    pool.submit(req)
    pool.submit(req)
    return pool, lambda: pool.submit(req, timeout=0.05)


@pytest.mark.parametrize("make", [_full_predictor_pool,
                                  _full_generation_pool],
                         ids=["predictor", "generation"])
def test_queue_full_carries_depth_and_retry_hint(make):
    """ONE shared pin for BOTH pool families: ServingQueueFull carries
    queue_depth + retry_after_s (PR 9 added it serving-side; the
    generation pool must stay in parity — the front door's backoff
    hints depend on it)."""
    pool, overflow = make()
    try:
        with pytest.raises(ServingQueueFull) as ei:
            overflow()
        assert ei.value.queue_depth == 2
        assert ei.value.retry_after_s > 0
    finally:
        pool.close()


def test_gauge_retract():
    monitor.gauge_set("GAUGE_t_retract_a", 1.0)
    monitor.gauge_set("GAUGE_t_retract_b", 2.0)
    assert monitor.gauge_retract("GAUGE_t_retract_a",
                                 "GAUGE_t_retract_missing") == 1
    snap = monitor.snapshot()["gauges"]
    assert "GAUGE_t_retract_a" not in snap
    assert snap["GAUGE_t_retract_b"] == 2.0
    monitor.gauge_retract("GAUGE_t_retract_b")
