"""dygraph->static control-flow conversion tests (reference
test_program_translator / test_ifelse / test_loop discipline)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.dygraph import (ConversionError, ProgramTranslator,
                                convert_to_static, declarative)
from paddle_tpu.jit import to_static


def test_data_dependent_if_both_branches_execute():
    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x - 10.0
        return y

    import jax.numpy as jnp
    g = to_static(f)
    pos = jnp.ones((3,))
    neg = -jnp.ones((3,))
    np.testing.assert_allclose(np.asarray(g(pos)), 2 * np.ones(3))
    np.testing.assert_allclose(np.asarray(g(neg)), -11 * np.ones(3))


def test_data_dependent_while_loop():
    def f(x):
        s = x * 0.0
        while s.sum() < 10.0:
            s = s + x
        return s

    import jax.numpy as jnp
    g = to_static(f)
    out = g(jnp.ones((2,)) * 3.0)  # 3,6,9,12 -> stops at 12
    np.testing.assert_allclose(np.asarray(out), [6.0, 6.0])


def test_python_condition_stays_python():
    calls = []

    def f(x, flag=True):
        if flag:
            calls.append("t")
            return x + 1
        return x - 1

    conv = convert_to_static(f)
    assert float(np.asarray(conv(np.zeros(()), True))) == 1.0
    assert calls == ["t"]


def test_layer_with_branch_through_to_static():
    class Gated(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = pt.nn.Linear(4, 4)

        def forward(self, x):
            h = self.lin(x)
            if h.sum() > 0:
                out = h * 2.0
            else:
                out = h * 0.5
            return out

    pt.seed(0)
    layer = Gated()
    run = to_static(layer)
    x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
    out_pos = np.asarray(run(pt.to_tensor(x)))
    out_neg = np.asarray(run(pt.to_tensor(-x * 100)))
    # eager references (python branching on concrete values)
    ref_pos = np.asarray(layer(pt.to_tensor(x)).value)
    ref_neg = np.asarray(layer(pt.to_tensor(-x * 100)).value)
    np.testing.assert_allclose(out_pos, ref_pos, rtol=1e-5)
    np.testing.assert_allclose(out_neg, ref_neg, rtol=1e-5)


def test_undefined_var_sentinel_raises_on_use():
    def f(x):
        if x.sum() > 0:
            only_true = x * 2
        else:
            pass
        return only_true  # noqa: F821

    import jax.numpy as jnp
    g = to_static(f)
    with pytest.raises(Exception, match="undefined|mismatch"):
        g(jnp.ones((2,)))


def test_return_inside_branch_stays_python_and_fails_loudly():
    # `if` with an early return is NOT converted (reference needs its
    # return transformer): concrete predicates keep exact python
    # semantics; a data-dependent one fails loudly at trace time.
    def f(x):
        if x.sum() > 0:
            return x
        return -x

    import jax
    import jax.numpy as jnp
    conv = convert_to_static(f)
    np.testing.assert_allclose(np.asarray(conv(np.ones(2))), np.ones(2))
    with pytest.raises(jax.errors.TracerBoolConversionError):
        jax.jit(conv)(jnp.ones(2))


def test_translator_disable_restores_trace_behavior():
    def f(x):
        if x.sum() > 0:
            y = x
        else:
            y = -x
        return y

    import jax
    import jax.numpy as jnp
    ProgramTranslator().enable(False)
    try:
        g = to_static(f)
        with pytest.raises(jax.errors.TracerBoolConversionError):
            g(jnp.ones((2,)))
    finally:
        ProgramTranslator().enable(True)


def test_declarative_decorator():
    @declarative
    def f(x):
        s = x
        while s.sum() < 5:
            s = s * 2
        return s

    import jax
    out = jax.jit(f)(np.ones(2, np.float32))
    np.testing.assert_allclose(np.asarray(out), [4.0, 4.0])


def test_read_modify_inside_branch():
    # the read-modify accumulator: y read and assigned in the branch
    def f(x):
        y = x + 1.0
        if x.sum() > 0:
            y = y * 2.0
        return y

    import jax.numpy as jnp
    conv = convert_to_static(f)
    np.testing.assert_allclose(np.asarray(conv(np.ones(2))), [4.0, 4.0])
    g = to_static(f)
    np.testing.assert_allclose(np.asarray(g(jnp.ones(2))), [4.0, 4.0])
    np.testing.assert_allclose(np.asarray(g(-jnp.ones(2))), [0.0, 0.0])
