"""dygraph->static control-flow conversion tests (reference
test_program_translator / test_ifelse / test_loop discipline)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.dygraph import (ConversionError, ProgramTranslator,
                                convert_to_static, declarative)
from paddle_tpu.jit import to_static


def test_data_dependent_if_both_branches_execute():
    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x - 10.0
        return y

    import jax.numpy as jnp
    g = to_static(f)
    pos = jnp.ones((3,))
    neg = -jnp.ones((3,))
    np.testing.assert_allclose(np.asarray(g(pos)), 2 * np.ones(3))
    np.testing.assert_allclose(np.asarray(g(neg)), -11 * np.ones(3))


def test_data_dependent_while_loop():
    def f(x):
        s = x * 0.0
        while s.sum() < 10.0:
            s = s + x
        return s

    import jax.numpy as jnp
    g = to_static(f)
    out = g(jnp.ones((2,)) * 3.0)  # 3,6,9,12 -> stops at 12
    np.testing.assert_allclose(np.asarray(out), [6.0, 6.0])


def test_python_condition_stays_python():
    calls = []

    def f(x, flag=True):
        if flag:
            calls.append("t")
            return x + 1
        return x - 1

    conv = convert_to_static(f)
    assert float(np.asarray(conv(np.zeros(()), True))) == 1.0
    assert calls == ["t"]


def test_layer_with_branch_through_to_static():
    class Gated(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = pt.nn.Linear(4, 4)

        def forward(self, x):
            h = self.lin(x)
            if h.sum() > 0:
                out = h * 2.0
            else:
                out = h * 0.5
            return out

    pt.seed(0)
    layer = Gated()
    run = to_static(layer)
    x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
    out_pos = np.asarray(run(pt.to_tensor(x)))
    out_neg = np.asarray(run(pt.to_tensor(-x * 100)))
    # eager references (python branching on concrete values)
    ref_pos = np.asarray(layer(pt.to_tensor(x)).value)
    ref_neg = np.asarray(layer(pt.to_tensor(-x * 100)).value)
    np.testing.assert_allclose(out_pos, ref_pos, rtol=1e-5)
    np.testing.assert_allclose(out_neg, ref_neg, rtol=1e-5)


def test_undefined_var_sentinel_raises_on_use():
    def f(x):
        if x.sum() > 0:
            only_true = x * 2
        else:
            pass
        return only_true  # noqa: F821

    import jax.numpy as jnp
    g = to_static(f)
    with pytest.raises(Exception, match="undefined|mismatch"):
        g(jnp.ones((2,)))


def test_return_inside_branch_stays_python_and_fails_loudly():
    # `if` with an early return is NOT converted (reference needs its
    # return transformer): concrete predicates keep exact python
    # semantics; a data-dependent one fails loudly at trace time.
    def f(x):
        if x.sum() > 0:
            return x
        return -x

    import jax
    import jax.numpy as jnp
    conv = convert_to_static(f)
    np.testing.assert_allclose(np.asarray(conv(np.ones(2))), np.ones(2))
    with pytest.raises(jax.errors.TracerBoolConversionError):
        jax.jit(conv)(jnp.ones(2))


def test_translator_disable_restores_trace_behavior():
    def f(x):
        if x.sum() > 0:
            y = x
        else:
            y = -x
        return y

    import jax
    import jax.numpy as jnp
    ProgramTranslator().enable(False)
    try:
        g = to_static(f)
        with pytest.raises(jax.errors.TracerBoolConversionError):
            g(jnp.ones((2,)))
    finally:
        ProgramTranslator().enable(True)


def test_declarative_decorator():
    @declarative
    def f(x):
        s = x
        while s.sum() < 5:
            s = s * 2
        return s

    import jax
    out = jax.jit(f)(np.ones(2, np.float32))
    np.testing.assert_allclose(np.asarray(out), [4.0, 4.0])


def test_read_modify_inside_branch():
    # the read-modify accumulator: y read and assigned in the branch
    def f(x):
        y = x + 1.0
        if x.sum() > 0:
            y = y * 2.0
        return y

    import jax.numpy as jnp
    conv = convert_to_static(f)
    np.testing.assert_allclose(np.asarray(conv(np.ones(2))), [4.0, 4.0])
    g = to_static(f)
    np.testing.assert_allclose(np.asarray(g(jnp.ones(2))), [4.0, 4.0])
    np.testing.assert_allclose(np.asarray(g(-jnp.ones(2))), [0.0, 0.0])


def test_for_range_tensor_bound_converts():
    """for i in range(tensor) lowers through the while conversion to
    lax.while_loop (reference loop_transformer for-range path)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.dygraph.dygraph_to_static import convert_to_static

    def f(n, x):
        acc = x * 0.0
        for i in range(n):
            acc = acc + x * (i + 1)
        return acc

    conv = convert_to_static(f)
    x = jnp.asarray([1.0, 2.0])
    # concrete bound: matches python
    np.testing.assert_allclose(np.asarray(conv(3, x)),
                               np.asarray(f(3, x)), rtol=1e-6)
    # traced (tensor) bound: must compile and match
    out = jax.jit(conv)(jnp.asarray(4), x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(f(4, x)),
                               rtol=1e-6)


def test_for_range_start_stop_step_and_descending():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.dygraph.dygraph_to_static import convert_to_static

    def f(n, x):
        acc = x * 0.0
        for i in range(2, n, 2):
            acc = acc + x * i
        return acc

    conv = convert_to_static(f)
    x = jnp.asarray([1.0])
    out = jax.jit(conv)(jnp.asarray(9), x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(f(9, x)),
                               rtol=1e-6)

    def g(x):
        acc = x * 0.0
        for i in range(5, 0, -1):
            acc = acc + x * i
        return acc

    convg = convert_to_static(g)
    np.testing.assert_allclose(np.asarray(convg(x)), np.asarray(g(x)),
                               rtol=1e-6)


def test_for_nonrange_stays_python():
    from paddle_tpu.dygraph.dygraph_to_static import convert_to_static

    def f(xs):
        total = 0.0
        for v in xs:       # list iterable: unrolls, stays python
            total = total + v
        return total

    conv = convert_to_static(f)
    assert conv([1.0, 2.0, 3.0]) == 6.0


def test_for_with_break_stays_python():
    from paddle_tpu.dygraph.dygraph_to_static import convert_to_static

    def f(n):
        total = 0
        for i in range(n):
            if i == 2:
                break
            total += i
        return total

    conv = convert_to_static(f)
    assert conv(5) == f(5) == 1


def test_for_body_fresh_temp_var():
    """A temp assigned only inside the loop body must not crash the
    conversion (python dispatch overwrites the Undefined sentinel)."""
    from paddle_tpu.dygraph.dygraph_to_static import convert_to_static
    import jax.numpy as jnp

    def f(x):
        acc = x * 0.0
        for i in range(3):
            tmp = x * (i + 1)
            acc = acc + tmp
        return acc

    conv = convert_to_static(f)
    np.testing.assert_allclose(np.asarray(conv(jnp.ones(2))), [6.0, 6.0])


def test_for_traced_bound_with_fresh_temp_raises_named_error():
    from paddle_tpu.dygraph.dygraph_to_static import (ConversionError,
                                                      convert_to_static)
    import jax
    import jax.numpy as jnp

    def f(n, x):
        acc = x * 0.0
        for i in range(n):
            tmp = x * i
            acc = acc + tmp
        return acc

    conv = convert_to_static(f)
    with pytest.raises(ConversionError, match="tmp"):
        jax.jit(conv)(jnp.asarray(3), jnp.ones(2))


def test_for_variable_step_stays_python():
    from paddle_tpu.dygraph.dygraph_to_static import convert_to_static

    def f(s):
        acc = 0
        for i in range(5, 0, s):
            acc += i
        return acc

    conv = convert_to_static(f)
    assert conv(-1) == f(-1) == 15
    assert conv(-2) == f(-2) == 5 + 3 + 1


def test_for_bound_references_loop_var_prior_value():
    from paddle_tpu.dygraph.dygraph_to_static import convert_to_static

    def h(i):
        acc = 0
        for i in range(0, i):
            acc += 1
        return acc

    conv = convert_to_static(h)
    assert conv(5) == h(5) == 5


def test_for_starred_range_args_stay_python():
    from paddle_tpu.dygraph.dygraph_to_static import convert_to_static
    import jax.numpy as jnp

    def f(x, n):
        acc = x * 0.0
        for i in range(*(0, 3)):
            acc = acc + x
        # a convertible data-dependent while must STILL convert even
        # though the starred-range loop stays python
        while (acc < n).all():
            acc = acc + 1.0
        return acc

    import jax
    conv = convert_to_static(f)
    out = jax.jit(conv)(jnp.asarray([0.0]), jnp.asarray(5.0))
    assert float(out[0]) >= 5.0
