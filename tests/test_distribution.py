"""Distribution tests vs closed-form oracles
(fluid/layers/distributions.py parity)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distribution import (Categorical, MultivariateNormalDiag,
                                     Normal, Uniform, kl_divergence)


def _np(t):
    return np.asarray(t.value if hasattr(t, "value") else t)


def test_uniform():
    d = Uniform(np.asarray([0.0, 2.0]), np.asarray([1.0, 6.0]))
    s = _np(d.sample((1000,)))
    assert s.shape == (1000, 2)
    assert (s[:, 0] >= 0).all() and (s[:, 0] < 1).all()
    assert (s[:, 1] >= 2).all() and (s[:, 1] < 6).all()
    np.testing.assert_allclose(_np(d.entropy()), [0.0, np.log(4.0)],
                               atol=1e-6)
    lp = _np(d.log_prob(np.asarray([0.5, 10.0])))
    assert abs(lp[0] - 0.0) < 1e-6 and lp[1] < -1e30


def test_normal_logprob_entropy_kl():
    d = Normal(0.0, 2.0)
    lp = float(_np(d.log_prob(np.asarray([1.0]))))
    ref = -0.5 * (1.0 / 4.0) - np.log(2.0) - 0.5 * np.log(2 * np.pi)
    assert abs(lp - ref) < 1e-5
    ent = float(_np(d.entropy()))
    assert abs(ent - (0.5 + 0.5 * np.log(2 * np.pi) + np.log(2.0))) \
        < 1e-5
    q = Normal(1.0, 1.0)
    kl = float(_np(kl_divergence(d, q)))
    # closed form: log(s2/s1) + (s1^2 + (m1-m2)^2)/(2 s2^2) - 1/2
    ref_kl = np.log(1.0 / 2.0) + (4.0 + 1.0) / 2.0 - 0.5
    assert abs(kl - ref_kl) < 1e-5
    assert _np(d.sample((64,))).shape == (64,)


def test_categorical():
    logits = np.log(np.asarray([[0.2, 0.3, 0.5]], np.float32))
    d = Categorical(logits)
    ent = float(_np(d.entropy())[0])
    ref = -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5))
    assert abs(ent - ref) < 1e-5
    lp = float(_np(d.log_prob(np.asarray([2], np.int64)))[0])
    assert abs(lp - np.log(0.5)) < 1e-5
    q = Categorical(np.log(np.asarray([[1 / 3] * 3], np.float32)))
    kl = float(_np(d.kl_divergence(q))[0])
    ref_kl = (0.2 * np.log(0.2 * 3) + 0.3 * np.log(0.3 * 3)
              + 0.5 * np.log(0.5 * 3))
    assert abs(kl - ref_kl) < 1e-5
    s = _np(d.sample((500,)))
    assert set(np.unique(s)) <= {0, 1, 2}


def test_mvn_diag():
    # the scale argument is a diagonal COVARIANCE (reference
    # distributions.py:461); its docstring example is the oracle:
    loc = np.asarray([0.0, 1.0], np.float32)
    d_doc = MultivariateNormalDiag(
        np.asarray([0.3, 0.5], np.float32),
        np.diag([0.4, 0.5]).astype(np.float32))
    assert abs(float(_np(d_doc.entropy())) - 2.033158) < 1e-4

    cov1 = np.diag([1.0, 4.0]).astype(np.float32)
    d = MultivariateNormalDiag(loc, cov1)
    q = MultivariateNormalDiag(loc, np.eye(2, dtype=np.float32))
    kl = float(_np(kl_divergence(d, q)))
    # 0.5*(tr(S2^-1 S1) - k + log det(S2)/det(S1)), means equal
    ref_kl = 0.5 * ((1 + 4) - 2 + np.log(1.0 / 4.0))
    assert abs(kl - ref_kl) < 1e-4
    s = _np(d.sample((2000,)))
    assert abs(s[:, 1].std() - 2.0) < 0.2  # std = sqrt(var 4)
