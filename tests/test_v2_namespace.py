"""v2 namespace parity tests: paddle.tensor-style top-level functions
(dual-mode) and paddle.static re-exports.

Reference surface: /root/reference/python/paddle/tensor/ (creation/
linalg/logic/manipulation/math/random/search/stat) and
python/paddle/static."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.tensor as T


@pytest.fixture
def x():
    return pt.to_tensor(np.asarray([[1.0, -2.0], [3.0, 4.0]], np.float32))


@pytest.fixture
def y():
    return pt.to_tensor(np.ones((2, 2), np.float32))


def _np(t):
    return np.asarray(t.value if hasattr(t, "value") else t)


def test_creation(x):
    assert _np(T.zeros([2, 3])).shape == (2, 3)
    assert float(_np(T.full([2], 7.0))[0]) == 7.0
    assert float(_np(T.full_like(x, 5))[0, 0]) == 5.0
    np.testing.assert_array_equal(_np(T.arange(4)), [0, 1, 2, 3])
    assert abs(float(_np(T.linspace(0, 1, 5))[-1]) - 1.0) < 1e-6
    assert float(_np(T.eye(3)).trace()) == 3.0
    assert _np(T.diag(pt.to_tensor(np.asarray([1.0, 2.0])))).shape == (2, 2)


def test_manipulation(x, y):
    assert _np(T.concat([x, y], 1)).shape == (2, 4)
    parts = T.split(x, 2, 1)
    assert len(parts) == 2 and _np(parts[0]).shape == (2, 1)
    assert _np(T.stack([x, y])).shape == (2, 2, 2)
    assert len(T.unstack(x)) == 2
    assert _np(T.reshape(x, [4])).shape == (4,)
    assert float(_np(T.transpose(x, [1, 0]))[0, 1]) == 3.0
    assert _np(T.unsqueeze(x, 0)).shape == (1, 2, 2)
    assert _np(T.squeeze(T.reshape(x, [1, 4]))).shape == (4,)
    assert _np(T.flatten(x)).shape == (4,)
    assert _np(T.tile(x, [2, 1])).shape == (4, 2)
    assert _np(T.cast(x, "int32")).dtype == np.int32
    assert float(_np(T.flip(x, 0))[0, 0]) == 3.0
    assert float(_np(T.roll(x, 1, 0))[0, 0]) == 3.0
    idx = pt.to_tensor(np.asarray([0], np.int64))
    assert _np(T.gather(x, idx)).shape == (1, 2)
    u = T.unique(pt.to_tensor(np.asarray([3, 1, 1, 2], np.int64)))
    np.testing.assert_array_equal(_np(u), [1, 2, 3])


def test_math_linalg(x, y):
    assert float(_np(T.add(x, y))[0, 0]) == 2.0
    assert float(_np(T.pow(x, 2))[0, 1]) == 4.0
    assert float(_np(T.clip(x, 0, 2))[0, 1]) == 0.0
    assert float(_np(T.sum(x))) == 6.0
    assert _np(T.mean(x, 1)).shape == (2,)
    assert float(_np(T.cumsum(x, 0))[1, 0]) == 4.0
    assert _np(T.matmul(x, y)).shape == (2, 2)
    assert float(_np(T.tril(x))[0, 1]) == 0.0
    assert float(_np(T.triu(x))[1, 0]) == 0.0
    assert _np(T.norm(x, 2, 1)).shape == (2,)
    assert _np(T.kron(x, y)).shape == (4, 4)
    v = pt.to_tensor(np.ones(3, np.float32))
    assert float(_np(T.dot(v, v)).reshape(-1)[0]) == 3.0
    # std/var vs numpy (unbiased)
    xv = _np(x)
    np.testing.assert_allclose(float(_np(T.var(x))), xv.var(ddof=1),
                               rtol=1e-6)


def test_logic_search(x, y):
    assert _np(T.equal(x, y)).dtype == bool
    assert bool(_np(T.isfinite(x)).all())
    assert bool(_np(T.allclose(x, x)))
    assert not bool(_np(T.isnan(x)).any())
    np.testing.assert_array_equal(_np(T.argmax(x, 1)), [0, 1])
    assert float(_np(T.sort(x, 1))[0, 0]) == -2.0
    out, idx = T.topk(x, 1, 1)
    assert _np(out).shape == (2, 1)
    w = T.where(T.greater_than(x, T.zeros_like(x)), x, y)
    assert float(_np(w)[0, 1]) == 1.0
    m = T.masked_select(x, T.greater_than(x, T.zeros_like(x)))
    assert _np(m).shape == (3,)
    assert int(_np(T.numel(x))) == 4


def test_random_shapes():
    assert _np(T.rand([3])).shape == (3,)
    assert _np(T.randn([3])).shape == (3,)
    r = _np(T.randint(0, 5, [10]))
    assert r.shape == (10,) and (r >= 0).all() and (r < 5).all()
    assert sorted(_np(T.randperm(5))) == [0, 1, 2, 3, 4]


def test_tensor_namespace_in_static_mode():
    """The same functions append ops when building a Program (the v2
    contract: paddle.enable_static() switches the dispatch)."""
    from paddle_tpu import layers
    from paddle_tpu.core.program import disable_static, enable_static
    main, startup = pt.Program(), pt.Program()
    enable_static()
    try:
        with pt.program_guard(main, startup):
            a = layers.data("a", [4])
            b = T.reshape(T.add(a, a), [2, 2])
            out = T.matmul(b, b)
    finally:
        disable_static()
    exe = pt.Executor()
    got, = exe.run(main, feed={"a": np.ones((1, 4), np.float32) * 2},
                   fetch_list=[out])
    np.testing.assert_allclose(np.asarray(got), np.full((2, 2), 32.0))


def test_static_namespace(tmp_path):
    import paddle_tpu.static as static
    from paddle_tpu.core.program import disable_static, enable_static
    enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [3], dtype="float32")
            w = static.nn.fc(x, size=2)
        exe = static.Executor()
        scope = static.Scope()
        with static.scope_guard(scope):
            exe.run(startup)
            out, = exe.run(main, feed={"x": np.ones((2, 3), np.float32)},
                           fetch_list=[w])
            assert np.asarray(out).shape == (2, 2)
            # save/load round trip restores parameters
            static.save(main, str(tmp_path / "model"))
            pname = [v.name for v in main.all_parameters()][0]
            orig = np.asarray(scope.find_var(pname)).copy()
            scope.set(pname, np.zeros_like(orig))
            static.load(main, str(tmp_path / "model"), exe)
            np.testing.assert_allclose(np.asarray(scope.find_var(pname)),
                                       orig)
    finally:
        disable_static()
    spec = static.InputSpec([None, 8], "float32", "inp")
    assert spec.shape == (-1, 8)


def test_no_grad_context(x):
    with pt.no_grad():
        z = T.add(x, x)
    assert z.stop_gradient


def test_review_regressions(x):
    # isnan: inf is NOT nan
    v = pt.to_tensor(np.asarray([np.inf, np.nan, 1.0], np.float32))
    np.testing.assert_array_equal(_np(T.isnan(v)), [False, True, False])
    # L1 norm over all elements
    v2 = pt.to_tensor(np.asarray([3.0, -4.0], np.float32))
    assert abs(float(_np(T.norm(v2, p=1))) - 7.0) < 1e-6
    assert abs(float(_np(T.norm(v2, p=2))) - 5.0) < 1e-6
    # float arange infers float dtype
    r = _np(T.arange(0.0, 1.0, 0.25))
    np.testing.assert_allclose(r, [0.0, 0.25, 0.5, 0.75])
    # unique with inverse in dygraph
    u, inv = T.unique(pt.to_tensor(np.asarray([2, 1, 2], np.int64)),
                      return_inverse=True)
    np.testing.assert_array_equal(_np(u), [1, 2])
    np.testing.assert_array_equal(_np(inv), [1, 0, 1])


def test_clip_preserves_int_dtype():
    v = pt.to_tensor(np.asarray([1, 5], np.int32))
    r = _np(T.clip(v, 0, 2))
    assert r.dtype == np.int32
    np.testing.assert_array_equal(r, [1, 2])


def test_norm_fro_multi_axis(x):
    got = float(_np(T.norm(x, "fro", [0, 1])))
    assert abs(got - np.sqrt((_np(x) ** 2).sum())) < 1e-5
    with pytest.raises(ValueError, match="fro"):
        T.norm(x, 1, [0, 1])


def test_ema_and_model_average_eager():
    from paddle_tpu.optimizer import (ExponentialMovingAverage,
                                      ModelAverage)
    import paddle_tpu.nn as nn
    lin = nn.Linear(3, 2)
    w = lin.weight
    # thres_steps=None -> constant decay (reference optimizer.py:3575)
    ema = ExponentialMovingAverage(0.5, parameters=[w])
    v0 = np.asarray(w.value).copy()
    ema.update()
    w.set_value(v0 + 1.0)
    ema.update()
    with ema.apply():
        shown = np.asarray(w.value)
        np.testing.assert_allclose(shown, 0.5 * v0 + 0.5 * (v0 + 1),
                                   rtol=1e-6)
    np.testing.assert_allclose(np.asarray(w.value), v0 + 1.0)
    # thres_steps given -> warmup decay min(d, (1+t)/(10+t))
    w.set_value(v0)
    ema2 = ExponentialMovingAverage(0.5, thres_steps=True,
                                    parameters=[w])
    ema2.update()
    w.set_value(v0 + 1.0)
    ema2.update()
    with ema2.apply():
        np.testing.assert_allclose(
            np.asarray(w.value), 0.25 * v0 + 0.75 * (v0 + 1), rtol=1e-6)

    ma = ModelAverage(0.5, min_average_window=2, max_average_window=4,
                      parameters=[w])
    for i in range(3):
        w.set_value(np.full_like(v0, float(i)))
        ma.update()
    with ma.apply():
        avg = np.asarray(w.value)
        np.testing.assert_allclose(avg, np.full_like(v0, 1.0),
                                   rtol=1e-6)  # mean(0,1,2)
    np.testing.assert_allclose(np.asarray(w.value), 2.0)


def test_dataloader_from_generator():
    from paddle_tpu.reader import DataLoader
    dl = DataLoader.from_generator(capacity=4, return_list=True)
    dl.set_batch_generator(
        lambda: iter([[np.ones((2, 3)), np.zeros((2, 1))]
                      for _ in range(3)]))
    batches = list(dl)
    assert len(batches) == 3 and batches[0][0].shape == (2, 3)
    dl2 = DataLoader.from_generator(capacity=4, return_list=True)
    dl2.set_sample_list_generator(
        lambda: iter([[(np.ones(3), np.zeros(1)) for _ in range(4)]
                      for _ in range(2)]))
    b2 = list(dl2)
    assert len(b2) == 2 and b2[0][0].shape == (4, 3)
