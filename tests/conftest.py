"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of exercising distributed paths with local
processes (/root/reference/python/paddle/fluid/tests/unittests/
test_dist_base.py:594) — except on TPU we use XLA's host-platform device
virtualization so multi-chip sharding tests run single-process.

Note: the axon TPU sitecustomize imports jax at interpreter startup with
JAX_PLATFORMS=axon baked into jax.config, so setting os.environ here is not
enough — jax.config must be updated directly.
"""
import os
import re

flags = os.environ.get("XLA_FLAGS", "")
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Hermetic tests: the persistent AOT program cache (default
# ~/.cache/paddle_tpu/aot) must not leak state between CI runs or
# pollute the user's home; "" disables it. Cache tests opt back in with
# explicit tmp dirs via FLAGS_program_cache_dir / Executor kwarg, which
# both take precedence over this env default.
os.environ.setdefault("PADDLE_TPU_PROGRAM_CACHE_DIR", "")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavyweight perf/compile tests excluded from "
        "the tier-1 `-m 'not slow'` run")
    config.addinivalue_line(
        "markers", "spmd: mesh-native SPMD runtime tests (docs/spmd.md) "
        "— need the 8-device virtual mesh; scripts/run_spmd_tests.sh "
        "runs just these and emits MULTICHIP_r11.json")


def pytest_sessionstart(session):
    n = len(jax.devices())
    assert jax.default_backend() == "cpu", jax.default_backend()
    assert n == 8, f"expected 8 virtual CPU devices, got {n}"
