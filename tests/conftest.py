"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of exercising distributed paths with local
processes (/root/reference/python/paddle/fluid/tests/unittests/
test_dist_base.py:594) — except on TPU we use XLA's host-platform device
virtualization so multi-chip sharding tests run single-process.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
