"""Accelerator-side process for the heter service test: hosts a
HeterService around a jitted dense logistic-regression stage."""
import json
import sys

import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from paddle_tpu.distributed import HeterService  # noqa: E402


def main():
    port = sys.argv[1]
    @jax.jit
    def step(rows, y):
        def loss_fn(rows):
            logit = rows.sum(axis=(1, 2))
            p = jax.nn.sigmoid(logit)
            return -jnp.mean(y * jnp.log(p + 1e-7)
                             + (1 - y) * jnp.log(1 - p + 1e-7))
        loss, g = jax.value_and_grad(loss_fn)(rows)
        return loss, g

    def dense_fn(feeds):
        loss, g_rows = step(jnp.asarray(feeds["rows"]),
                            jnp.asarray(feeds["y"]))
        return {"loss": np.asarray(loss), "row_grads": np.asarray(g_rows)}

    svc = HeterService(dense_fn, ["loss", "row_grads"],
                       endpoint="127.0.0.1:%s" % port)
    print(json.dumps({"endpoint": svc.endpoint}), flush=True)
    svc.serve_forever()


if __name__ == "__main__":
    main()
