"""Introspection server tests (paddle_tpu/introspect.py, PR 7).

What is pinned here:
- /metrics is valid Prometheus text exposition: every sample belongs
  to a declared ``# TYPE`` family, and a summary family contains ONLY
  {quantile}/_sum/_count samples — the timer min/max must ship as
  separate gauge families (the monitor.to_prometheus fix this PR).
- /readyz flips 503 -> 200 only when warmup actually completes
  (PredictorPool probe) and when an installed process-global
  ShardingPlan has placed state.
- /statusz carries the mesh topology and the KV block-pool occupancy.
- concurrent scrapes during executor load all succeed with parseable
  payloads.
- FLAGS_introspect_port=0 (the default) spawns NO thread and NO
  socket: constructing Executors/pools must not start a server.
"""
import json
import re
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import introspect, layers, monitor


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _get(url, timeout=10):
    """(status, body) — 4xx/5xx return their status instead of raising."""
    try:
        r = urllib.request.urlopen(url, timeout=timeout)
        return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _parse_exposition(text):
    """(families, samples) with format assertions. families maps name
    -> kind; samples are (name, labels, value_str)."""
    fams = {}
    samples = []
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# TYPE "):
            parts = ln.split()
            assert len(parts) == 4, "bad TYPE line: %r" % ln
            _, _, name, kind = parts
            assert name not in fams, "family %s declared twice" % name
            assert kind in ("counter", "gauge", "summary"), ln
            fams[name] = kind
        elif ln.startswith("#"):
            continue
        else:
            m = re.match(
                r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (\S+)$", ln)
            assert m, "unparseable sample: %r" % ln
            float(m.group(3))  # value must parse (inf/nan included)
            samples.append((m.group(1), m.group(2) or "", m.group(3)))
    return fams, samples


def _check_family_membership(fams, samples):
    """Every sample belongs to a declared family, in a role its kind
    allows. This is exactly what a strict scraper enforces: counter and
    gauge samples may carry a label block (the labeled() series of
    monitor.py — per-tenant families, the per-axis/dtype collective
    bytes census), and so may a labeled summary series' _sum/_count
    (the per-rank gang phase timers) — but every quantile sample must
    carry quantile=, _sum/_count must NOT, and any label block must be
    well-formed key="value" pairs."""
    label_re = re.compile(
        r'^\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\}$')
    for name, labels, _ in samples:
        if labels:
            assert label_re.match(labels), \
                "malformed label block on %s: %r" % (name, labels)
        if name in fams:
            fam, kind = name, fams[name]
            if kind == "summary":
                assert "quantile=" in labels, \
                    "bare %s sample inside summary family" % name
            continue
        base = next((name[:-len(s)] for s in ("_sum", "_count")
                     if name.endswith(s)
                     and fams.get(name[:-len(s)]) == "summary"), None)
        assert base is not None, \
            "sample %s belongs to no declared family" % name
        assert "quantile=" not in labels, \
            "summary %s sample carries a quantile label" % name


@pytest.fixture
def server():
    """Ephemeral-port server, torn down (with its socket) per test."""
    srv = introspect.start(port=0)
    try:
        yield srv
    finally:
        introspect.stop()


@pytest.fixture
def fc_model_dir(tmp_path):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [6])
        y = layers.fc(x, 3, name="out")
    exe = pt.Executor()
    exe.run(startup)
    d = str(tmp_path / "model")
    pt.io.save_inference_model(d, ["x"], [y], exe, main_program=main)
    return d


def _run_small_program(steps=1):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8])
        y = layers.fc(x, 4)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        for _ in range(steps):
            exe.run(main, feed={"x": np.ones((4, 8), np.float32)},
                    fetch_list=[y])


# ---------------------------------------------------------------------------
# /metrics exposition validity
# ---------------------------------------------------------------------------

def test_metrics_every_family_valid(server):
    _run_small_program()
    monitor.timer_observe("TIMER_test_introspect_us", 100.0)
    monitor.timer_observe("TIMER_test_introspect_us", 300.0)
    code, body = _get(server.url + "/metrics")
    assert code == 200
    fams, samples = _parse_exposition(body)
    assert fams, "no families scraped"
    assert samples, "no samples scraped"
    _check_family_membership(fams, samples)
    # all three instrument kinds present
    assert "counter" in fams.values()
    assert "gauge" in fams.values()
    assert "summary" in fams.values()


def test_timer_min_max_are_separate_gauge_families(server):
    """Regression for the summary-family bug: min/max samples may not
    live inside the summary — they must be their own gauge families."""
    monitor.timer_observe("TIMER_test_minmax_us", 5.0)
    monitor.timer_observe("TIMER_test_minmax_us", 25.0)
    _, body = _get(server.url + "/metrics")
    fams, samples = _parse_exposition(body)
    base = "paddle_tpu_TIMER_test_minmax_us"
    assert fams[base] == "summary"
    assert fams[base + "_min"] == "gauge"
    assert fams[base + "_max"] == "gauge"
    by_name = {n: v for n, labels, v in samples if not labels}
    assert float(by_name[base + "_min"]) == 5.0
    assert float(by_name[base + "_max"]) == 25.0
    # and the summary family itself holds only quantile/_sum/_count
    _check_family_membership(fams, samples)


def test_program_accounting_gauges_scraped(server):
    _run_small_program()
    _, body = _get(server.url + "/metrics")
    fams, samples = _parse_exposition(body)
    names = {n for n, _, _ in samples}
    assert "paddle_tpu_GAUGE_programs_count" in names
    assert "paddle_tpu_GAUGE_programs_hbm_bytes" in names
    assert any(n.startswith("paddle_tpu_GAUGE_program_flops_executor")
               for n in names), sorted(names)


# ---------------------------------------------------------------------------
# readiness
# ---------------------------------------------------------------------------

def test_readyz_flips_only_after_pool_warmup(server, fc_model_dir):
    from paddle_tpu import serving
    from paddle_tpu.inference import Config
    code, body = _get(server.url + "/readyz")
    assert code == 200, body  # nothing registered -> trivially ready

    cfg = Config(fc_model_dir)
    cfg.switch_shape_bucketing(True, buckets=[2, 4])
    with serving.PredictorPool(cfg, max_batch=4) as pool:
        code, body = _get(server.url + "/readyz")
        checks = json.loads(body)["checks"]
        assert code == 503 and any(
            k.startswith("serving_pool_") and not v
            for k, v in checks.items()), body
        pool.warmup([np.zeros((1, 6), np.float32)])
        code, body = _get(server.url + "/readyz")
        assert code == 200, body
        assert all(json.loads(body)["checks"].values())
    # close() unregisters the probe
    code, body = _get(server.url + "/readyz")
    assert code == 200 and json.loads(body)["checks"] == {}


def test_readyz_requires_installed_plan_placed(server):
    from paddle_tpu.mesh import ShardingPlan
    from paddle_tpu.mesh.plan import install_plan
    plan = ShardingPlan("dp4xmp2")
    install_plan(plan)
    try:
        code, body = _get(server.url + "/readyz")
        assert code == 503
        assert json.loads(body)["checks"]["mesh_plan_placed"] is False
        plan.place_state({"w": np.ones((8, 2), np.float32)})
        code, body = _get(server.url + "/readyz")
        assert code == 200
        assert json.loads(body)["checks"]["mesh_plan_placed"] is True
    finally:
        install_plan(None)


# ---------------------------------------------------------------------------
# /statusz and /programz payloads
# ---------------------------------------------------------------------------

def test_statusz_mesh_topology_and_kv_occupancy(server):
    from paddle_tpu.generation.kv_cache import KVCacheManager
    from paddle_tpu.mesh import ShardingPlan
    from paddle_tpu.mesh.plan import install_plan
    kv = KVCacheManager(16, 4)
    blocks = kv.alloc("seq0", kv.blocks_for_tokens(8))
    install_plan(ShardingPlan("dp4xmp2"))
    try:
        code, body = _get(server.url + "/statusz")
        assert code == 200
        d = json.loads(body)
        assert d["jax"]["device_count"] == 8
        assert d["mesh"]["active"] is True
        assert ["dp", 4] in d["mesh"]["topology"]
        assert d["mesh"]["devices"] == 8
        kvb = d["generation"]["kv_blocks"]
        assert kvb["used"] >= len(blocks)
        assert kvb["total"] == kvb["free"] + kvb["used"]
        assert d["uptime_s"] >= 0
        assert "readiness" in d
    finally:
        install_plan(None)
        kv.free("seq0")


def test_programz_lists_accounted_programs(server):
    _run_small_program(steps=2)
    code, body = _get(server.url + "/programz")
    assert code == 200
    d = json.loads(body)
    assert d["totals"]["count"] >= 1
    assert d["totals"]["hbm_bytes"] > 0
    tags = [p["tag"] for p in d["programs"]]
    assert any(t.startswith("executor_") for t in tags), tags
    for p in d["programs"]:
        assert p["flops"] >= 0
        assert p["hbm_bytes"] >= 0
        assert p["calls"] >= 0
    # repeat executions bump calls without adding entries
    ent = next(p for p in d["programs"]
               if p["tag"].startswith("executor_") and p["calls"] >= 2)
    assert ent["key"]


def test_healthz_flightz_and_404(server):
    assert _get(server.url + "/healthz")[0] == 200
    assert _get(server.url + "/flightz")[0] == 200
    code, body = _get(server.url + "/flightz?format=json")
    assert code == 200
    json.loads(body)
    assert _get(server.url + "/nope")[0] == 404


# ---------------------------------------------------------------------------
# concurrency + lifecycle
# ---------------------------------------------------------------------------

def test_concurrent_scrape_under_load(server):
    errors = []

    def scrape(n):
        for _ in range(n):
            try:
                code, body = _get(server.url + "/metrics")
                assert code == 200
                _parse_exposition(body)
                code, body = _get(server.url + "/statusz")
                assert code == 200
                json.loads(body)
            except Exception as e:  # noqa: BLE001 - collected for the assert
                errors.append(e)
                return
    threads = [threading.Thread(target=scrape, args=(5,))
               for _ in range(4)]
    for t in threads:
        t.start()
    _run_small_program(steps=10)   # executor load during the scrapes
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors


def test_flag_port_zero_spawns_nothing():
    """The off-by-default contract: flag 0 means maybe_start is a
    no-op — no server object, no pt-introspect thread — even as
    Executors (which call maybe_start) are constructed."""
    introspect.stop()
    assert introspect.maybe_start() is None
    _run_small_program()
    assert introspect.server() is None
    assert not [t for t in threading.enumerate()
                if t.name == "pt-introspect"]


def test_start_idempotent_and_stop_releases():
    srv = introspect.start(port=0)
    try:
        assert introspect.start(port=0) is srv
        assert introspect.maybe_start() is srv
        assert _get(srv.url + "/healthz")[0] == 200
    finally:
        introspect.stop()
    assert introspect.server() is None
    with pytest.raises(Exception):
        urllib.request.urlopen(srv.url + "/healthz", timeout=2)
