"""E2E "book" convergence tests for the five BASELINE configs.

Analog of the reference's book suite
(/root/reference/python/paddle/fluid/tests/book/ — test_recognize_digits,
test_image_classification, test_recommender_system, ...): each config
trains on synthetic data shaped like the real task, asserts the loss
decreases, and round-trips its parameters through save/load.

Configs (BASELINE.json):
  1. MNIST LeNet     — static-graph Executor
  2. ResNet/CIFAR    — CompiledProgram with_data_parallel (GSPMD DP)
  3. BERT-small      — TrainStep + bf16 AMP + masked positions
  4. Wide&Deep CTR   — Dataset (csrc MultiSlot parser) + in-process PS
                       (the cross-process transport has its own parity
                       suite, tests/test_ps_transport.py)
  5. ERNIE-ish finetune — sequence classification, AMP autocast +
                       dygraph DataParallel-style allreduce via DP mesh
"""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _seeded(main, startup, seed=11):
    main.random_seed = seed
    startup.random_seed = seed


# ---------------------------------------------------------------------------
# 1. MNIST LeNet via static Executor
# ---------------------------------------------------------------------------

def test_book_mnist_lenet_static(tmp_path):
    main, startup = pt.Program(), pt.Program()
    _seeded(main, startup)
    with pt.program_guard(main, startup):
        img = layers.data("img", [1, 28, 28])
        label = layers.data("label", [1], dtype="int64")
        c1 = layers.conv2d(img, 6, 5, padding=2, act="relu")
        p1 = layers.pool2d(c1, 2, pool_stride=2)
        c2 = layers.conv2d(p1, 16, 5, act="relu")
        p2 = layers.pool2d(c2, 2, pool_stride=2)
        fc = layers.fc(layers.flatten(p2), 64, act="relu")
        logits = layers.fc(fc, 10)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.Adam(1e-3).minimize(loss, startup_program=startup,
                                         program=main)
    exe = pt.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    # learnable synthetic digits: class = strongest quadrant pattern
    protos = rng.randn(10, 1, 28, 28).astype(np.float32)
    losses = []
    for step in range(30):
        y = rng.randint(0, 10, (32, 1))
        x = protos[y[:, 0]] + 0.3 * rng.randn(32, 1, 28, 28) \
            .astype(np.float32)
        out, = exe.run(main, feed={"img": x, "label": y},
                       fetch_list=[loss])
        losses.append(float(out))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.5, losses

    # save/load round trip restores the exact parameters: compare an
    # EVAL program's loss (main fetches pre-update loss, so the raw
    # losses[-1] reflects params before the final optimizer step)
    test_prog = main.clone(for_test=True)
    ref, = exe.run(test_prog, feed={"img": x, "label": y},
                   fetch_list=[loss])
    path = str(tmp_path / "lenet")
    pt.save_persistables(exe, path, main)
    scope2 = pt.Scope()
    with pt.scope_guard(scope2):
        exe2 = pt.Executor()
        exe2.run(startup)
        pt.load_persistables(exe2, path, main)
        out2, = exe2.run(test_prog, feed={"img": x, "label": y},
                         fetch_list=[loss])
    np.testing.assert_allclose(float(out2), float(ref), rtol=1e-4)


# ---------------------------------------------------------------------------
# 2. CIFAR ResNet via CompiledProgram DP
# ---------------------------------------------------------------------------

def test_book_cifar_resnet_compiled_dp():
    from paddle_tpu.compiler import CompiledProgram
    main, startup = pt.Program(), pt.Program()
    _seeded(main, startup)
    with pt.program_guard(main, startup):
        img = layers.data("img", [3, 32, 32])
        label = layers.data("label", [1], dtype="int64")
        # resnet-ish: conv -> 2 residual blocks -> pool -> fc
        h = layers.conv2d(img, 8, 3, padding=1, act="relu")
        for _ in range(2):
            r = layers.conv2d(h, 8, 3, padding=1, act="relu")
            r = layers.conv2d(r, 8, 3, padding=1)
            h = layers.relu(layers.elementwise_add(h, r))
        pool = layers.pool2d(h, 4, pool_stride=4, pool_type="avg")
        logits = layers.fc(layers.flatten(pool), 10)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.Momentum(0.05, 0.9).minimize(
            loss, startup_program=startup, program=main)
    exe = pt.Executor()
    exe.run(startup)
    compiled = CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    rng = np.random.RandomState(1)
    protos = rng.randn(10, 3, 32, 32).astype(np.float32)
    losses = []
    for step in range(25):
        y = rng.randint(0, 10, (16, 1))
        x = protos[y[:, 0]] + 0.3 * rng.randn(16, 3, 32, 32) \
            .astype(np.float32)
        out, = exe.run(compiled, feed={"img": x, "label": y},
                       fetch_list=[loss])
        losses.append(float(out))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.6, losses


# ---------------------------------------------------------------------------
# 3. BERT-small pretrain via TrainStep + AMP + masked positions
# ---------------------------------------------------------------------------

def test_book_bert_small_amp_trainstep(tmp_path):
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                        pretraining_loss)
    from paddle_tpu.dygraph import tape
    tape.seed(5)
    cfg = BertConfig(vocab_size=211, hidden_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     intermediate_size=128, max_position_embeddings=64)
    model = BertForPretraining(cfg)
    opt = pt.optimizer.Adam(2e-3, parameters=model.parameters())
    step = TrainStep(model, pretraining_loss, opt, amp_dtype="bfloat16")

    rng = np.random.RandomState(2)
    B, S, M = 8, 32, 6
    losses = []
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    pos = np.stack([rng.choice(S, M, replace=False) for _ in range(B)]
                   ).astype(np.int32)
    mlm = np.take_along_axis(ids, pos, axis=1).astype(np.int32)
    nsp = rng.randint(0, 2, (B, 1)).astype(np.int32)
    for _ in range(60):
        loss = step((ids, None, None, pos), (mlm, nsp))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]

    # save/load round trip through dygraph state dicts
    step.sync_model()
    sd = model.state_dict()
    path = str(tmp_path / "bert")
    pt.save_dygraph(sd, path)
    loaded, _ = pt.load_dygraph(path)
    for k, v in sd.items():
        np.testing.assert_array_equal(np.asarray(loaded[k]),
                                      np.asarray(v.value if
                                                 hasattr(v, "value")
                                                 else v))


# ---------------------------------------------------------------------------
# 4. Wide&Deep CTR via Dataset (csrc parser) + PS worker
# ---------------------------------------------------------------------------

def test_book_wide_deep_dataset_ps(tmp_path):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.distributed import (DownpourWorker, ParamServer,
                                        SparseTableConfig)

    # MultiSlot text files for the csrc parser: per line
    # "<n> id ... <n> val ..." per slot (sparse uint64 + dense float)
    rng = np.random.RandomState(3)
    nslots, dim = 3, 4
    true_w = rng.randn(50) * 2
    files = []
    for f in range(2):
        lines = []
        for _ in range(64):
            ids = rng.randint(0, 50, nslots)
            logit = true_w[ids].sum()
            label = 1 if logit > 0 else 0
            parts = ["1 %d" % label]
            for s in ids:
                parts.append("1 %d" % s)
            lines.append(" ".join(parts))
        p = str(tmp_path / ("part-%d.txt" % f))
        with open(p, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        files.append(p)

    ds = pt.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(16)
    ds.set_use_var(["label"] + ["slot%d" % i for i in range(nslots)])
    ds.set_filelist(files)
    ds.set_thread(2)
    ds.load_into_memory()
    ds.local_shuffle(seed=0)

    server = ParamServer()
    server.create_sparse_table(SparseTableConfig(
        name="emb", dim=dim, initializer="gaussian", init_scale=0.1,
        optimizer="adagrad", lr=0.5, seed=4))
    worker = DownpourWorker(server, "emb")

    @jax.jit
    def step(rows, y):
        def loss_fn(rows):
            logit = rows.sum(axis=(1, 2))
            return jnp.mean(
                jnp.maximum(logit, 0) - logit * y
                + jnp.log1p(jnp.exp(-jnp.abs(logit))))
        return jax.value_and_grad(loss_fn)(rows)

    losses = []
    for epoch in range(8):
        for batch in ds:
            label = batch["label"][:, 0].astype(np.float32)
            ids = np.stack([batch["slot%d" % i][:, 0]
                            for i in range(nslots)], axis=1)
            l = worker.train_batch(
                ids, lambda rows, y=label: [np.asarray(v) for v in
                                            step(jnp.asarray(rows),
                                                 jnp.asarray(y))])
            losses.append(float(np.asarray(l)))
    assert np.mean(losses[-8:]) < np.mean(losses[:8]) * 0.75, \
        (losses[:4], losses[-4:])

    # sparse table save/load round trip
    server.sparse["emb"].save(str(tmp_path / "table"))
    from paddle_tpu.distributed import LargeScaleKV
    kv2 = LargeScaleKV(SparseTableConfig(name="emb", dim=dim))
    kv2.load(str(tmp_path / "table"))
    some = worker.pull(ids[:2])
    np.testing.assert_allclose(
        kv2.pull(ids[:2].reshape(-1)).reshape(some.shape), some)


# ---------------------------------------------------------------------------
# 5. ERNIE-ish finetune: AMP autocast + DP-mesh allreduce
# ---------------------------------------------------------------------------

def test_book_ernie_finetune_amp_dp():
    import jax
    from jax.sharding import Mesh
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.bert import (BertConfig,
                                        BertForSequenceClassification)
    from paddle_tpu.nn import functional as F
    from paddle_tpu.dygraph import tape
    tape.seed(6)
    cfg = BertConfig(vocab_size=97, hidden_size=32,
                     num_hidden_layers=2, num_attention_heads=2,
                     intermediate_size=64, max_position_embeddings=32)
    model = BertForSequenceClassification(cfg, num_classes=2)
    opt = pt.optimizer.Adam(1e-3, parameters=model.parameters())

    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))

    def loss_fn(logits, label):
        return F.cross_entropy(logits, label, reduction="mean")

    step = TrainStep(model, loss_fn, opt, mesh=mesh,
                     amp_dtype="bfloat16")
    rng = np.random.RandomState(7)
    B, S = 8, 16
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    # learnable: label = parity of first token
    y = (ids[:, :1] % 2).astype(np.int64)
    losses = []
    for _ in range(30):
        losses.append(float(step((ids,), (y,))))
    assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]
